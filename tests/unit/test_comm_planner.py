"""Collective planner (comm/planner): plan IR, mesh fingerprint, cost-model
pruning, disk cache keying/round-trip, static-mode determinism, and the five
consumer wirings (engine DP grads, TP linears, Ulysses, MoE EP, ZeRO++)."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu.comm as dist
from deepspeed_tpu.comm.compressed import configure_compression
from deepspeed_tpu.comm.planner import (CollectivePlanner, CostModel,
                                        IMPLEMENTATIONS, MeshFingerprint,
                                        Plan, PlanCache, PlanDecision,
                                        configure_planner, get_planner,
                                        make_site, planner_active,
                                        reset_planner, resolve_site)
from deepspeed_tpu.parallel import Topology, TopologySpec, set_topology

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")


@pytest.fixture(autouse=True)
def _reset_planner_state():
    yield
    reset_planner()
    configure_compression("none")
    set_topology(Topology(TopologySpec()))
    dist.get_comms_logger().plan_records.clear()


# ---------------------------------------------------------------------------
# plan IR
# ---------------------------------------------------------------------------


def test_site_signature_and_validation():
    s = make_site(op="all_reduce", shape=(1024,), dtype=jnp.float32,
                  axes=("dp_outer", "ep"), consumer="dp-grad")
    assert s.signature() == "dp-grad:all_reduce:1024:float32@dp_outer,ep"
    assert s.nbytes == 4096
    s2 = make_site(op="all_gather", shape=[256], dtype="float32",
                   axes=["dp"], consumer="zeropp", axis_size=4)
    assert s2.signature().endswith("@dp*4")  # foreign-mesh size is identity
    with pytest.raises(ValueError, match="unknown collective op"):
        make_site(op="gossip", shape=(1,), dtype="float32", axes=("dp",),
                  consumer="dp-grad")
    with pytest.raises(ValueError, match="unknown consumer"):
        make_site(op="all_reduce", shape=(1,), dtype="float32", axes=("dp",),
                  consumer="mystery")
    with pytest.raises(ValueError, match="unknown implementation"):
        PlanDecision(impl="telepathy")


def test_plan_json_roundtrip():
    site = make_site(op="all_to_all", shape=(2, 8, 4, 16), dtype="float32",
                     axes=("sp",), consumer="ulysses")
    plan = Plan(fingerprint="abc123")
    plan.set(site, PlanDecision(impl="int8", block=512, source="measured",
                                est_us=12.5))
    back = Plan.from_json(plan.to_json())
    assert back == plan
    assert back.get(site).impl == "int8" and back.get(site).block == 512


# ---------------------------------------------------------------------------
# fingerprint + cost model
# ---------------------------------------------------------------------------


def test_fingerprint_captures_mesh_and_is_stable():
    set_topology(Topology(TopologySpec(ep=2, tp=2)))
    fp1 = MeshFingerprint.capture()
    fp2 = MeshFingerprint.capture()
    assert fp1 == fp2 and fp1.digest() == fp2.digest()
    sizes = dict(fp1.axis_sizes)
    assert sizes["ep"] == 2 and sizes["tp"] == 2 and fp1.n_devices == 8
    assert fp1.dcn_axes == ()  # single host: every axis is local
    # a different mesh shape keys a different plan file
    set_topology(Topology(TopologySpec()))
    assert MeshFingerprint.capture().digest() != fp1.digest()


def _tpu_fp(dcn=("dp_outer",), ep=1):
    return MeshFingerprint(platform="tpu", device_kind="TPU v5e",
                           n_devices=64, n_processes=8,
                           axis_sizes=(("pp", 1), ("dp_outer", 8), ("ep", ep),
                                       ("sp", 1), ("tp", 8 // max(1, ep))),
                           dcn_axes=tuple(dcn))


def test_decode_attn_site_and_cost_regime():
    """The serving decode_attn op: a first-class plan-IR site with a
    decode-shape cost regime — pallas (resident-pool kernel) wins on the
    TPU fingerprint, the einsum reference wins off-TPU (interpret-mode
    pallas is never a win), and int8 storage widens the pallas margin (the
    einsum path pays the dequant + a 4x-wider materialized copy)."""
    site = make_site(op="decode_attn", shape=(16, 1024, 4, 128),
                     dtype="float32", axes=(), consumer="decode")
    assert site.signature() == "decode:decode_attn:16x1024x4x128:float32@"
    tpu = CostModel(_tpu_fp())
    assert tpu.estimate(site, "pallas") < tpu.estimate(site, "einsum")
    q = make_site(op="decode_attn", shape=(16, 1024, 4, 128), dtype="int8",
                  axes=(), consumer="decode")
    assert (tpu.estimate(q, "einsum") / tpu.estimate(q, "pallas")
            > tpu.estimate(site, "einsum") / tpu.estimate(site, "pallas"))
    assert tpu.decide(site).impl == "pallas"
    cpu = CostModel(MeshFingerprint.capture())
    assert cpu.estimate(site, "pallas") == float("inf")
    assert cpu.decide(site).impl == "einsum"


def test_decode_attn_static_resolution_ignores_compression_knob():
    """Static mode resolves decode_attn on the cost model, records it in
    the plan table, and the compressed_collectives knob (which maps every
    OTHER site to an impl) must not hijack it onto an off-menu impl."""
    configure_planner("static", use_cache=False,
                      knobs={"compression": {"mode": "int8", "block": 2048,
                                             "hierarchical": False,
                                             "sites": {}}})
    site = make_site(op="decode_attn", shape=(8, 512, 2, 64),
                     dtype="float32", axes=(), consumer="decode")
    d = get_planner().resolve(site)
    assert d.impl == "einsum"            # CPU fingerprint: kernel loses
    assert d.source == "cost-model"
    assert site.signature() in dist.get_comms_logger().plan_records


def test_decode_attn_microbench_probe_runs():
    """measure-mode ground truth: the decode_attn probe builds and times
    the einsum reference (single-device, no mesh axis) — and the pallas
    probe runs the real kernel in interpret mode."""
    from deepspeed_tpu.comm.planner.microbench import benchmark_site

    site = make_site(op="decode_attn", shape=(2, 64, 2, 16), dtype="float32",
                     axes=(), consumer="decode")
    t = benchmark_site(site, "einsum", reps=2, repeats=1, max_elems=1 << 10)
    assert np.isfinite(t) and t > 0
    t_p = benchmark_site(site, "pallas", reps=2, repeats=1, max_elems=1 << 10)
    assert np.isfinite(t_p) and t_p > 0


def test_decode_tp_gather_matmul_resolution():
    """The decode-TP projections resolve through the planner under the
    'decode' consumer (op=gather_matmul) — a big row gather picks the
    overlapped fused_matmul on the cost model and lands in the plan table,
    so the static auditor reconciles decode collectives against the plan."""
    from deepspeed_tpu.inference.v2.model import resolve_decode_tp_impl

    set_topology(Topology(TopologySpec(tp=4)))
    reset_planner()
    assert resolve_decode_tp_impl("tp", (64, 4096), "float32") == "xla"
    configure_planner("static", use_cache=False)
    impl = resolve_decode_tp_impl("tp", (1 << 16, 128), "float32")
    assert impl == "fused_matmul"
    recs = dist.get_comms_logger().plan_records
    sig = [s for s in recs if s.startswith("decode:gather_matmul")]
    assert sig and recs[sig[0]]["impl"] == "fused_matmul"


def test_cost_model_prefers_int8_on_dcn_and_exact_for_tiny():
    cm = CostModel(_tpu_fp())
    big = make_site(op="all_reduce", shape=(128 * 2**20,), dtype="float32",
                    axes=("dp_outer", "ep"), consumer="dp-grad")
    assert cm.estimate(big, "int8") < cm.estimate(big, "xla")
    assert cm.decide(big).impl in ("int8", "int8_sr", "hierarchical")
    tiny = make_site(op="all_reduce", shape=(64,), dtype="float32",
                     axes=("dp_outer", "ep"), consumer="dp-grad")
    assert cm.decide(tiny).impl == "xla"  # alpha-dominated: quant can't pay


def test_cost_model_candidate_gating_and_pruning():
    # hierarchical needs BOTH split levels real
    cm_flat = CostModel(_tpu_fp(ep=1))
    site = make_site(op="all_reduce", shape=(2**20,), dtype="float32",
                     axes=("dp_outer", "ep"), consumer="dp-grad")
    assert "hierarchical" not in cm_flat.candidates(site)
    cm_two = CostModel(_tpu_fp(ep=2))
    assert "hierarchical" in cm_two.candidates(site)
    # stochastic rounding never offered to activation exchanges
    act = make_site(op="reduce_scatter", shape=(2**20,), dtype="float32",
                    axes=("ep",), consumer="moe-a2a")
    assert "int8_sr" not in cm_two.candidates(act)
    grad = make_site(op="reduce_scatter", shape=(2**20,), dtype="float32",
                     axes=("dp_outer",), consumer="zeropp")
    assert "int8_sr" in cm_two.candidates(grad)
    # pruning drops dominated candidates and keeps rank order
    ranked = cm_two.prune(site, margin=1.05)
    assert ranked == sorted(ranked, key=lambda kv: kv[1])
    assert len(ranked) < len(cm_two.candidates(site))


# ---------------------------------------------------------------------------
# cache keying + round-trip, static determinism (tier-1 smoke)
# ---------------------------------------------------------------------------


def _site_list():
    return [
        make_site(op="all_reduce", shape=(2**18,), dtype="float32",
                  axes=("dp_outer", "ep"), consumer="dp-grad"),
        make_site(op="all_to_all", shape=(1, 8, 4, 8), dtype="float32",
                  axes=("sp",), consumer="ulysses"),
        make_site(op="all_to_all", shape=(4, 1, 16, 32), dtype="float32",
                  axes=("ep",), consumer="moe-a2a"),
        make_site(op="all_gather", shape=(2**15,), dtype="float32",
                  axes=("dp_outer", "ep"), consumer="zeropp"),
        make_site(op="gather_matmul", shape=(2, 64, 32), dtype="float32",
                  axes=("tp",), consumer="tp-linear"),
    ]


def test_static_mode_resolves_every_site_deterministically():
    """The acceptance smoke: static mode on the CPU mesh resolves every
    wired-site shape to a concrete implementation, and two consecutive
    fresh planners resolve the IDENTICAL plan."""
    set_topology(Topology(TopologySpec(ep=2, sp=2, tp=2)))
    a = CollectivePlanner("static", use_cache=False)
    b = CollectivePlanner("static", use_cache=False)
    for site in _site_list():
        da, db = a.resolve(site), b.resolve(site)
        assert da.impl in IMPLEMENTATIONS and da.source == "cost-model"
        assert (da.impl, da.block) == (db.impl, db.block)
    assert a.plan.decisions == b.plan.decisions


def test_plan_cache_roundtrips_to_fresh_planner(tmp_path):
    set_topology(Topology(TopologySpec(ep=2, sp=2, tp=2)))
    site = _site_list()[0]
    a = CollectivePlanner("static", cache_dir=str(tmp_path))
    da = a.resolve(site)
    path = a.cache.path_for(a.fingerprint)
    assert os.path.exists(path)
    body = json.load(open(path))
    assert site.signature() in body["sites"]  # keyed by site signature
    assert body["fingerprint"] == a.fingerprint.digest()
    # a FRESH planner instance loads the decision from disk
    b = CollectivePlanner("static", cache_dir=str(tmp_path))
    db = b.resolve(site)
    assert db.source == "cache" and db.impl == da.impl
    # a corrupt cache file reads as a miss, not an error
    open(path, "w").write("not json{")
    c = CollectivePlanner("static", cache_dir=str(tmp_path))
    assert c.resolve(site).source == "cost-model"


def test_cache_ignores_foreign_fingerprint(tmp_path):
    set_topology(Topology(TopologySpec(ep=2)))
    a = CollectivePlanner("static", cache_dir=str(tmp_path))
    a.resolve(_site_list()[0])
    set_topology(Topology(TopologySpec(tp=2)))  # different mesh shape
    b = CollectivePlanner("static", cache_dir=str(tmp_path))
    assert b.cache.load(b.fingerprint) is None  # plan keyed off-topology


def test_explicit_knobs_win_over_planning():
    set_topology(Topology(TopologySpec()))
    p = CollectivePlanner("static", use_cache=False, knobs={
        "compression": {"mode": "int8_sr", "block": 512,
                        "hierarchical": False,
                        "sites": {"dp_gradients": True, "ulysses": False,
                                  "moe": True, "zero_weights": True,
                                  "zero_gradients": True}}})
    d = p.resolve(make_site(op="all_reduce", shape=(64,), dtype="float32",
                            axes=("dp_outer", "ep"), consumer="dp-grad"))
    assert d.impl == "int8_sr" and d.source == "knob"  # even for a tiny site
    # site toggled off -> exact, still by knob
    d2 = p.resolve(make_site(op="all_to_all", shape=(2, 8, 4, 8),
                             dtype="float32", axes=("sp",),
                             consumer="ulysses"))
    assert d2.impl == "xla" and d2.source == "knob"
    p_ov = CollectivePlanner("static", use_cache=False,
                             knobs={"overlap": True})
    d3 = p_ov.resolve(make_site(op="gather_matmul", shape=(2, 8, 32),
                                dtype="float32", axes=("tp",),
                                consumer="tp-linear"))
    assert d3.impl == "fused_matmul" and d3.source == "knob"


def test_hierarchical_knob_resolves_when_split_is_real():
    """The explicit hierarchical knob: two-level only when BOTH split
    levels are real (same gate as the engine wiring), flat int8 otherwise."""
    knobs = {"compression": {"mode": "int8", "block": 2048,
                             "hierarchical": True,
                             "sites": {"dp_gradients": True}}}
    site = make_site(op="all_reduce", shape=(2**16,), dtype="float32",
                     axes=("dp_outer", "ep"), consumer="dp-grad")
    set_topology(Topology(TopologySpec(ep=2)))  # dp_outer=4, ep=2: real split
    d = CollectivePlanner("static", use_cache=False, knobs=knobs).resolve(site)
    assert d.impl == "hierarchical" and d.source == "knob"
    set_topology(Topology(TopologySpec()))  # ep=1: no inner level
    d2 = CollectivePlanner("static", use_cache=False, knobs=knobs).resolve(site)
    assert d2.impl == "int8" and d2.source == "knob"


def test_measure_probes_foreign_mesh_site():
    """A zeropp-style site on a mesh axis the fleet topology doesn't have:
    the probe builds its own mesh from the declared axis_size instead of
    silently degrading to the cost model."""
    set_topology(Topology(TopologySpec()))
    p = CollectivePlanner("measure", use_cache=False, measure_reps=2,
                          measure_max_elems=1 << 12, margin=50.0)
    d = p.resolve(make_site(op="all_gather", shape=(4096,), dtype="float32",
                            axes=("dp",), consumer="zeropp", axis_size=8))
    assert d.source == "measured", d


def test_log_summary_prints_plan_table(capsys):
    set_topology(Topology(TopologySpec(ep=2)))
    configure_planner("static", use_cache=False)
    resolve_site(op="all_reduce", shape=(2**16,), dtype="float32",
                 axes=("dp_outer", "ep"), consumer="dp-grad")
    totals = dist.log_summary()
    out = capsys.readouterr().out
    assert "Collective plan" in out and "dp-grad" in out
    assert isinstance(totals, dict)  # the PR2 contract is unchanged
    recs = dist.get_comms_logger().plan_records
    assert any(v["consumer"] == "dp-grad" for v in recs.values())


# ---------------------------------------------------------------------------
# consumer wirings
# ---------------------------------------------------------------------------


def _simple_problem(dim=64):
    rng = np.random.default_rng(0)
    params = {"w": jnp.asarray(rng.normal(size=(dim, 10)) * 0.1, jnp.float32),
              "b": jnp.zeros((10,), jnp.float32)}

    def loss_fn(p, batch):
        logits = batch["x"] @ p["w"] + p["b"]
        one_hot = jax.nn.one_hot(batch["y"], 10)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * one_hot, -1))

    def batch(i, n):
        r = np.random.default_rng(100 + i)
        return {"x": jnp.asarray(r.normal(size=(n, dim)), jnp.float32),
                "y": jnp.asarray(r.integers(0, 10, n), jnp.int32)}

    return loss_fn, params, batch


def _run_engine(extra_cfg, steps=3, dim=64):
    import deepspeed_tpu as ds

    loss_fn, params, batch = _simple_problem(dim)
    set_topology(Topology(TopologySpec()))
    cfg = {"train_micro_batch_size_per_gpu": 16,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 0}, "steps_per_print": 10**9}
    cfg.update(extra_cfg or {})
    eng, *_ = ds.initialize(model=loss_fn,
                            model_parameters=jax.tree.map(jnp.copy, params),
                            config=cfg)
    return eng, [float(eng.train_batch(batch(i, 16 * 8))) for i in range(steps)]


def test_engine_planner_off_bit_identical_and_inert():
    eng_ref, ref = _run_engine(None)
    assert not planner_active()  # default config leaves the planner off
    assert eng_ref._compressed_dp is False
    eng_off, off = _run_engine({"comm_planner": "off"})
    assert ref == off  # off IS the default path, bit for bit


def test_engine_dp_grad_site_resolves_under_static():
    eng, losses = _run_engine({"comm_planner": {"mode": "static",
                                                "use_cache": False}})
    recs = dist.get_comms_logger().plan_records
    dp = [v for v in recs.values() if v["consumer"] == "dp-grad"]
    assert dp and dp[0]["impl"] in IMPLEMENTATIONS and dp[0]["mode"] == "static"
    # the engine's compiled path matches the recorded decision
    quant = dp[0]["impl"] in ("int8", "int8_sr", "hierarchical")
    assert eng._compressed_dp == quant
    assert all(np.isfinite(losses))


def test_engine_dp_grad_cached_plan_drives_compression(tmp_path):
    """A plan cache written for this mesh fingerprint is loaded by the
    engine's fresh planner and switches the DP-grad reduction to int8; the
    losses track the exact run (the PR2 tolerance)."""
    loss_fn, params, _ = _simple_problem()
    n_elems = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    topo = Topology(TopologySpec())
    fp = MeshFingerprint.capture(topo)
    site = make_site(op="all_reduce", shape=(n_elems,), dtype="float32",
                     axes=topo.dp_axes, consumer="dp-grad")
    plan = Plan(fingerprint=fp.digest())
    plan.set(site, PlanDecision(impl="int8", block=512, source="measured"))
    PlanCache(str(tmp_path)).store(fp, plan)

    _, ref = _run_engine(None)
    eng, got = _run_engine({"comm_planner": {"mode": "static",
                                             "cache_dir": str(tmp_path)}})
    assert eng._compressed_dp is True
    assert eng._dp_grad_impl == ("int8", 512, False)
    assert got[0] == ref[0]  # first loss predates any reduction effect
    for a, b in zip(ref, got):
        assert abs(a - b) < 0.02 * abs(a) + 1e-3, (ref, got)


def test_tp_linear_site_fused_by_plan_matches_declarative():
    """tp-linear wiring: a planner decision of fused_matmul engages the
    ring-overlapped linears with NO knob set, and the logits/grads match
    the declarative model at the PR1 tolerances."""
    import dataclasses

    from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                  TransformerLM, init_params,
                                                  make_loss_fn)

    cfg = TransformerConfig(vocab_size=128, hidden_size=32,
                            intermediate_size=64, num_layers=2, num_heads=4,
                            num_kv_heads=2, max_seq_len=64, dtype=jnp.float32)
    assert not cfg.overlap_collective_matmul  # the knob stays untouched
    set_topology(Topology(TopologySpec(tp=4)))
    model = TransformerLM(cfg)
    params = init_params(model, batch=1, seq=32)
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 128, (2, 32)),
                         jnp.int32)

    reset_planner()
    logits_off = jax.jit(lambda p, t: model.apply({"params": p}, t))(
        params, tokens)
    g_off = jax.jit(jax.grad(make_loss_fn(model)))(params, {"tokens": tokens})

    planner = configure_planner("static", use_cache=False)
    site = make_site(op="gather_matmul", shape=(2, 32, 32), dtype="float32",
                     axes=("tp",), consumer="tp-linear")
    planner.plan.decisions[site.signature()] = PlanDecision(
        impl="fused_matmul", source="measured")
    logger = dist.get_comms_logger()
    logger.configure(enabled=True, prof_all=True)
    logger.reset()
    try:
        logits_on = jax.jit(lambda p, t: model.apply({"params": p}, t))(
            params, tokens)
        # the ring primitives actually ran (ledger sees the chunk traffic)
        assert "all_gather_matmul" in logger.totals()
    finally:
        logger.configure(enabled=False)
        logger.reset()
    np.testing.assert_allclose(np.asarray(logits_on), np.asarray(logits_off),
                               rtol=2e-5, atol=2e-5)
    g_on = jax.jit(jax.grad(make_loss_fn(model)))(params, {"tokens": tokens})
    for a, b in zip(jax.tree.leaves(g_on), jax.tree.leaves(g_off)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=1e-5)


def test_ulysses_site_planner_picks_int8_when_transport_bound():
    """ulysses wiring: with the quantizer modeled as free (transport-bound
    regime) the planner resolves int8 for the sp exchange and the quantized
    a2a actually runs; output tracks the exact exchange."""
    from deepspeed_tpu.models.transformer import attention_core
    from deepspeed_tpu.sequence.layer import ulysses_attention

    set_topology(Topology(TopologySpec(sp=2)))
    rng = np.random.default_rng(7)
    b, s, h, d = 4, 16, 4, 8
    q, k, v = (jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
               for _ in range(3))

    def local_attn(q_, k_, v_, pos):
        return attention_core(q_, k_, v_, causal=True, impl="xla")

    def run():
        return np.asarray(jax.jit(
            lambda a, b_, c: ulysses_attention(local_attn, a, b_, c))(q, k, v))

    reset_planner()
    exact = run()

    planner = configure_planner("static", use_cache=False)
    # transport-bound regime: quantization modeled as free -> int8 wins
    planner.cost.quant_cost = 0.0
    planner.cost.quant_fixed = 0.0
    logger = dist.get_comms_logger()
    logger.configure(enabled=True, prof_all=True)
    logger.reset()
    try:
        planned = run()
        assert "quantized_all_to_all" in logger.totals()
    finally:
        logger.configure(enabled=False)
        logger.reset()
    recs = dist.get_comms_logger().plan_records
    assert any(v["consumer"] == "ulysses" and v["impl"] == "int8"
               for v in recs.values())
    assert np.abs(exact - planned).max() < 0.05 * max(np.abs(exact).max(), 1.0)


def test_moe_site_gates_quantized_ep_through_planner():
    from deepspeed_tpu.moe.sharded_moe import quantized_ep_ready

    set_topology(Topology(TopologySpec(ep=4)))
    shape = (8, 8, 16, 32)
    reset_planner()
    assert not quantized_ep_ready(8, 8, site_shape=shape)  # planner off
    planner = configure_planner("static", use_cache=False)
    planner.cost.quant_cost = 0.0
    planner.cost.quant_fixed = 0.0
    assert quantized_ep_ready(8, 8, site_shape=shape)
    recs = dist.get_comms_logger().plan_records
    assert any(v["consumer"] == "moe-a2a" for v in recs.values())
    # structural gates still bind regardless of the plan
    assert not quantized_ep_ready(9, 8, site_shape=shape)  # 9 % ep != 0


def test_zeropp_sites_resolve_at_init_and_train():
    import optax
    from jax.sharding import Mesh

    from deepspeed_tpu.runtime.zero.zeropp import zeropp_train_step_factory

    rng = np.random.default_rng(0)
    params = {"w1": jnp.asarray(rng.normal(size=(32, 16)) * 0.3, jnp.float32),
              "w2": jnp.asarray(rng.normal(size=(16, 8)) * 0.3, jnp.float32)}

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((jnp.tanh(x @ p["w1"]) @ p["w2"] - y) ** 2)

    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))
    planner = configure_planner("static", use_cache=False)
    init, step, _ = zeropp_train_step_factory(
        loss_fn, optax.adam(1e-2), mesh, dp_axis="dp")
    state = init(params)
    recs = dist.get_comms_logger().plan_records
    zp = [v for v in recs.values() if v["consumer"] == "zeropp"]
    assert len(zp) == 2  # the qwZ gather and qgZ scatter sites
    assert {v["op"] for v in zp} == {"all_gather", "reduce_scatter"}
    x = jnp.asarray(rng.normal(size=(8, 32)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(8, 8)), jnp.float32)
    state, loss = step(state, (x, y))
    assert np.isfinite(float(loss))


def test_zeropp_planner_inactive_keeps_legacy_default():
    """Without a planner the factory's legacy default (qwZ+qgZ on) is
    untouched — the off mode changes nothing."""
    import optax
    from jax.sharding import Mesh

    from deepspeed_tpu.runtime.zero import zeropp as zpp

    reset_planner()
    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))
    captured = {}
    orig = zpp.quantized_all_gather

    def spy(x, axis, block=None, **kw):
        captured["hit"] = True
        return orig(x, axis, block=block, **kw)

    init, step, _ = zpp.zeropp_train_step_factory(
        lambda p, b: jnp.mean((b[0] @ p["w"] - b[1]) ** 2),
        optax.sgd(1e-2), mesh, dp_axis="dp")
    zpp.quantized_all_gather = spy
    try:
        rng = np.random.default_rng(0)
        state = init({"w": jnp.asarray(rng.normal(size=(16, 4)), jnp.float32)})
        x = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
        y = jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)
        state, loss = step(state, (x, y))
    finally:
        zpp.quantized_all_gather = orig
    assert captured.get("hit")  # legacy qwZ gather still the default
    assert np.isfinite(float(loss))


def test_config_string_shorthand_and_mode_validation():
    from deepspeed_tpu.runtime.config import load_config

    cfg = load_config({"comm_planner": "static"})
    assert cfg.comm_planner.mode == "static"
    cfg2 = load_config({"comm_planner": {"mode": "measure",
                                         "measure_reps": 2}})
    assert cfg2.comm_planner.measure_reps == 2
    with pytest.raises(ValueError, match="comm_planner mode"):
        CollectivePlanner("turbo")


def test_measure_mode_times_survivors():
    """measure mode: microbenchmarks run for the pruned candidate set and
    the winner is recorded with source 'measured' (or cost-model when only
    one survivor exists)."""
    set_topology(Topology(TopologySpec(ep=2)))
    p = CollectivePlanner("measure", use_cache=False, measure_reps=2,
                          measure_max_elems=1 << 12, margin=50.0)
    d = p.resolve(make_site(op="all_gather", shape=(4096,), dtype="float32",
                            axes=("dp_outer", "ep"), consumer="zeropp"))
    assert d.impl in IMPLEMENTATIONS
    assert d.source in ("measured", "cost-model")
    assert d.est_us is not None and d.est_us > 0


# ---------------------------------------------------------------------------
# r6: the embedding-gather site (ring-overlapped vocab-sharded embedding)
# ---------------------------------------------------------------------------


def test_embed_gather_site_cost_model_and_static_resolution():
    """embed_gather is a first-class op: the cost model ranks its menu
    (xla vs the table ring) and static mode resolves + records it in the
    plan table next to the PR 1 sites."""
    cm = CostModel(_tpu_fp())
    site = make_site(op="embed_gather", shape=(32000 // 4, 4096),
                     dtype=jnp.bfloat16, axes=("tp",), consumer="embed")
    cands = cm.candidates(site)
    assert set(cands) == {"xla", "ring", "bidir_ring"}
    # the ring's overlap credit beats the serial gather+take on a big table
    assert cm.estimate(site, "ring") < cm.estimate(site, "xla")
    assert np.isfinite(cm.estimate(site, "bidir_ring"))

    set_topology(Topology(TopologySpec(tp=4)))
    configure_planner("static", use_cache=False)
    d = resolve_site(op="embed_gather", shape=(32000 // 4, 4096),
                     dtype=jnp.bfloat16, axes=("tp",), consumer="embed")
    assert d.impl in ("xla", "ring", "bidir_ring")
    assert d.source == "cost-model"
    recs = dist.get_comms_logger().plan_records
    assert any(v["consumer"] == "embed" for v in recs.values())


def test_embed_gather_microbench_probe_runs():
    """measure mode's ground truth: the embed_gather probes build and run
    on the live mesh for every menu member."""
    from deepspeed_tpu.comm.planner import benchmark_site

    set_topology(Topology(TopologySpec(tp=4)))
    site = make_site(op="embed_gather", shape=(2048, 128), dtype="float32",
                     axes=("tp",), consumer="embed")
    for impl in ("xla", "ring", "bidir_ring"):
        t = benchmark_site(site, impl, reps=2, repeats=1, max_elems=1 << 14)
        assert t > 0.0


def test_model_embed_auto_defers_to_planner():
    """embed_overlap='auto' + an active planner: the model consults the
    embed site; with the planner off the declarative gather stays (the
    bit-identical default)."""
    import dataclasses

    from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                  TransformerLM, init_params)

    cfg = TransformerConfig(vocab_size=64, hidden_size=32,
                            intermediate_size=64, num_layers=1, num_heads=4,
                            max_seq_len=16, dtype=jnp.float32)
    set_topology(Topology(TopologySpec()))
    params = init_params(TransformerLM(cfg), seq=16)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 64, (4, 16)),
                       jnp.int32)
    ref = TransformerLM(cfg).apply({"params": params}, toks)

    set_topology(Topology(TopologySpec(tp=4)))
    configure_planner("static", use_cache=False)
    got = jax.jit(lambda t: TransformerLM(cfg).apply({"params": params}, t))(
        toks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)
    recs = dist.get_comms_logger().plan_records
    assert any(v["consumer"] == "embed" for v in recs.values())


# ---------------------------------------------------------------------------
# multi-phase program synthesis (ISSUE 8: DCN-aware hierarchical programs)
# ---------------------------------------------------------------------------


def _dcn_fp(ep=8, dcn=("dp_outer",)):
    return MeshFingerprint(platform="tpu", device_kind="TPU v5e",
                           n_devices=64, n_processes=8,
                           axis_sizes=(("pp", 1), ("dp_outer", 8), ("ep", ep),
                                       ("sp", 1), ("tp", 1)),
                           dcn_axes=tuple(dcn))


def _dp_site(n=1 << 22):
    return make_site(op="all_reduce", shape=(n,), dtype="float32",
                     axes=("dp_outer", "ep"), consumer="dp-grad")


def test_synthesize_programs_shapes_and_gating():
    from deepspeed_tpu.comm.planner import (PhaseStep, synthesize_programs)

    cm = CostModel(_dcn_fp())
    progs = synthesize_programs(_dp_site(), cm)
    assert len(progs) == 5
    for prog in progs:
        assert all(isinstance(s, PhaseStep) for s in prog)
        rs, ar, ag = prog
        # the canonical hierarchy: ICI rs/ag exact, the DCN hop in the middle
        assert rs.phase_op == "reduce_scatter" and rs.axes == ("ep",)
        assert rs.wire_dtype == "exact" and rs.link == "ici"
        assert ar.phase_op == "all_reduce" and ar.axes == ("dp_outer",)
        assert ar.link == "dcn"
        assert ag.phase_op == "all_gather" and ag.axes == ("ep",)
    # gradient consumer => error feedback on the quantized outer hop
    assert progs[0][1].wire_dtype == "int8_ef"
    assert progs[1][1].wire_dtype == "exact"
    assert progs[2][2].via == "bidir_ring"
    # the fused-hierarchical twins: ICI phases ride between the producing/
    # consuming matmul tiles, with role-correct compute bindings
    for prog in progs[3:]:
        rs, ar, ag = prog
        assert rs.via == "fused_matmul" and rs.compute.role == "producer"
        assert ag.via == "fused_matmul" and ag.compute.role == "consumer"
        assert rs.wire_dtype == "exact" and ag.wire_dtype == "exact"
    assert progs[3][1].wire_dtype == "int8_ef"
    assert progs[4][1].wire_dtype == "exact"
    # no inner level (ep=1): nothing to reduce-scatter over, no programs
    assert synthesize_programs(_dp_site(), CostModel(_dcn_fp(ep=1))) == []
    # activation consumer would get plain int8 (no dither, no feedback)
    act = make_site(op="all_reduce", shape=(1 << 20,), dtype="float32",
                    axes=("dp_outer", "ep"), consumer="ulysses")
    assert synthesize_programs(act, cm)[0][1].wire_dtype == "int8"
    # foreign-mesh and single-axis sites never synthesize
    single = make_site(op="all_reduce", shape=(1 << 20,), dtype="float32",
                       axes=("ep",), consumer="dp-grad")
    assert synthesize_programs(single, cm) == []


def test_program_cost_ordering_dcn_vs_all_ici():
    """The acceptance ordering: with a DCN axis in the dp span the
    hierarchical int8-outer program beats every flat impl (the DCN hop
    carries 1/p_inner the bytes at 1/4 the width); on an all-ICI mesh the
    extra full-width phases cost more than they save and flat wins."""
    from deepspeed_tpu.comm.planner import synthesize_programs

    site = _dp_site()
    cm_dcn = CostModel(_dcn_fp())
    progs = synthesize_programs(site, cm_dcn)
    best_prog = min(cm_dcn.estimate_program(site, p) for p in progs)
    assert best_prog < cm_dcn.estimate(site, "xla")
    assert best_prog < cm_dcn.estimate(site, "int8")
    assert best_prog < cm_dcn.estimate(site, "hierarchical")
    # the winning program quantizes the DCN hop (exact-outer loses there)
    ranked = sorted(progs, key=lambda p: cm_dcn.estimate_program(site, p))
    assert ranked[0][1].wire_dtype == "int8_ef"

    # all-ICI: the dp span crosses no DCN axis — synthesis declines (the
    # extra full-width phases cannot pay on uniform links), and the legacy
    # single-impl hierarchical estimate confirms the ordering: it loses to
    # flat int8 there
    cm_ici = CostModel(_dcn_fp(dcn=()))
    assert synthesize_programs(site, cm_ici) == []
    assert cm_ici.estimate(site, "hierarchical") > cm_ici.estimate(site,
                                                                   "int8")


def test_static_mode_resolves_program_on_dcn_mesh():
    set_topology(Topology(TopologySpec(ep=2)))
    p = CollectivePlanner("static", use_cache=False,
                          dcn_axes=["dp_outer"])
    assert "dp_outer" in p.fingerprint.dcn_axes  # forced into the print
    d = p.resolve(_dp_site())
    assert d.impl == "program" and d.source == "cost-model"
    rs, ar, ag = d.program
    assert (rs.phase_op, ar.wire_dtype, ag.phase_op) == \
        ("reduce_scatter", "int8_ef", "all_gather")
    # same mesh WITHOUT the override: single-process CPU mesh has no DCN
    # axis, programs lose, the site resolves to a flat impl
    q = CollectivePlanner("static", use_cache=False)
    assert q.resolve(_dp_site()).impl != "program"
    # forced fingerprints key a DIFFERENT plan-cache slot
    assert p.fingerprint.digest() != q.fingerprint.digest()


def test_program_decision_roundtrips_through_disk_cache(tmp_path):
    """Program-IR JSON round-trip through the cache file, plus byte-compat:
    a single-impl decision's serialized keys are exactly the pre-program
    set (old planners can keep reading mixed caches)."""
    set_topology(Topology(TopologySpec(ep=2)))
    site = _dp_site()
    a = CollectivePlanner("static", cache_dir=str(tmp_path),
                          dcn_axes=["dp_outer"])
    da = a.resolve(site)
    assert da.impl == "program"
    body = json.load(open(a.cache.path_for(a.fingerprint)))
    entry = body["sites"][site.signature()]
    assert isinstance(entry["program"], list) and len(entry["program"]) == 3
    assert entry["program"][1]["wire_dtype"] == "int8_ef"
    # fresh planner loads the SAME program from disk
    b = CollectivePlanner("static", cache_dir=str(tmp_path),
                          dcn_axes=["dp_outer"])
    db = b.resolve(site)
    assert db.source == "cache" and db.impl == "program"
    assert db.program == da.program
    # byte-compat: single-impl decisions serialize without a program key
    flat = PlanDecision(impl="int8", block=512, source="measured",
                        est_us=1.5)
    assert set(flat.to_dict()) == {"impl", "block", "source", "est_us"}
    assert PlanDecision.from_dict(flat.to_dict()) == flat


def test_program_decision_rank0_broadcast_spmd(monkeypatch):
    """Multi-host SPMD consistency: program decisions ride the same rank-0
    broadcast as single-impl ones — the payload must survive a strict JSON
    round-trip (what the wire does to it) with the program intact."""
    import deepspeed_tpu.comm.planner.planner as planner_mod

    set_topology(Topology(TopologySpec(ep=2)))
    p = CollectivePlanner("static", use_cache=False, dcn_axes=["dp_outer"])
    sent = {}

    def fake_agree(decision):
        wire = json.loads(json.dumps(decision.to_dict()))  # strict JSON
        sent["payload"] = wire
        return PlanDecision.from_dict(wire)

    monkeypatch.setattr(p, "_agree", fake_agree)
    d = p.resolve(_dp_site())
    assert d.impl == "program" and len(d.program) == 3
    assert d.program[1].wire_dtype == "int8_ef"
    assert sent["payload"]["program"][0]["axes"] == ["ep"]


def test_measure_mode_times_program_candidates():
    """measure mode executes synthesized programs through the microbench
    harness (probe caps keep it cheap); the winner is a real timing."""
    set_topology(Topology(TopologySpec(ep=2)))
    p = CollectivePlanner("measure", use_cache=False, measure_reps=2,
                          measure_max_elems=1 << 12, margin=50.0,
                          dcn_axes=["dp_outer"])
    d = p.resolve(make_site(op="all_reduce", shape=(1 << 12,),
                            dtype="float32", axes=("dp_outer", "ep"),
                            consumer="dp-grad"))
    assert d.source == "measured"
    # on the CPU mesh any winner is legitimate; the contract is that the
    # program candidates RAN (benchmark_site accepts them without error)
    from deepspeed_tpu.comm.planner import benchmark_site, synthesize_programs

    prog = synthesize_programs(_dp_site(1 << 12), p.cost)[0]
    t = benchmark_site(_dp_site(1 << 12), "program", program=prog,
                       reps=2, max_elems=1 << 12)
    assert t > 0


def _run_engine_dcn(extra_cfg, steps=4, seed=0):
    """Engine run on a (dp_outer=4, ep=2) mesh — ep is the slice-local dp
    axis (the zeropp split) — with a ~130k-param problem so the int8 DCN
    hop pays for its quantization in the cost model."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.parallel import Topology as Topo

    rng = np.random.default_rng(seed)
    params = {"w1": jnp.asarray(rng.normal(size=(256, 512)) * 0.05,
                                jnp.float32),
              "w2": jnp.asarray(rng.normal(size=(512, 32)) * 0.05,
                                jnp.float32)}

    def loss_fn(p, batch, rng=None):
        x, y = batch
        pred = jnp.tanh(x @ p["w1"]) @ p["w2"]
        return jnp.mean((pred - y) ** 2)

    def batch(i, n=16 * 8):
        r = np.random.default_rng(1000 + i)
        x = jnp.asarray(r.normal(size=(n, 256)), jnp.float32)
        return (x, jnp.asarray(x[:, :32] * 0.5, jnp.float32))

    cfg = {"train_micro_batch_size_per_gpu": 16,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 0}, "steps_per_print": 10**9}
    cfg.update(extra_cfg or {})
    eng, *_ = ds.initialize(model=loss_fn,
                            model_parameters=jax.tree.map(jnp.copy, params),
                            config=cfg,
                            topology=Topo(TopologySpec(ep=2)))
    return eng, [float(eng.train_batch(batch(i))) for i in range(steps)]


def test_engine_dp_grad_program_under_static_dcn():
    """The ISSUE 8 acceptance path: comm_planner static on a mesh with a
    DCN dp axis selects the multi-phase hierarchical program for the
    engine DP-grad site (ICI hop exact, DCN hop int8+feedback), the engine
    executes it, losses track the exact run within quantization tolerance,
    and the error-feedback residual is engine-owned state that actually
    carries across steps."""
    _, ref = _run_engine_dcn(None)
    eng, got = _run_engine_dcn({"comm_planner": {"mode": "static",
                                                 "use_cache": False,
                                                 "dcn_axes": ["dp_outer"]}})
    assert eng._compressed_dp is True
    mode_, _, prog = eng._dp_grad_impl
    assert mode_ == "program"
    assert [s.phase_op for s in prog] == ["reduce_scatter", "all_reduce",
                                          "all_gather"]
    assert prog[0].wire_dtype == "exact" and prog[1].wire_dtype == "int8_ef"
    # PR 14: static synthesis now fuses the ICI phases into the producing/
    # consuming matmul tiles, with the engine-bound real chunk size
    assert prog[0].via == "fused_matmul" and prog[2].via == "fused_matmul"
    assert prog[0].compute.role == "producer" and prog[0].compute.tile > 0
    # residual is engine state: initialized zero, NONZERO after stepping
    # (the reset-every-trace bug would leave it identically zero), and
    # stacked per-rank on the dp leading dim
    assert eng._dp_feedback is True
    fb = eng.state.comm_feedback
    assert fb.worker_error.shape[0] == 8  # dp world
    assert float(jnp.abs(fb.worker_error).max()) > 0
    # numerics: compressed DCN hop tracks the exact run (PR2 tolerance).
    # The first loss predates any reduction effect but the step compiles
    # as a different XLA program, so allow ulp-level fusion drift.
    assert abs(got[0] - ref[0]) < 1e-5 * abs(ref[0])
    for a, b in zip(ref, got):
        assert abs(a - b) < 0.05 * abs(a) + 1e-3, (ref, got)
    recs = dist.get_comms_logger().plan_records
    dp = [v for v in recs.values() if v["consumer"] == "dp-grad"]
    assert dp and dp[0]["impl"] == "program" and "program" in dp[0]


def test_engine_program_residual_carries_and_differs_per_step():
    """Regression for the satellite bugfix: two consecutive steps see a
    CARRIED residual (step-2 input residual == step-1 output residual, by
    construction of TrainState threading), not a fresh zero per trace."""
    eng, _ = _run_engine_dcn({"comm_planner": {"mode": "static",
                                               "use_cache": False,
                                               "dcn_axes": ["dp_outer"]}},
                             steps=1)
    fb1 = np.asarray(eng.state.comm_feedback.worker_error)
    assert np.abs(fb1).max() > 0  # step 1 left a residual behind

    def batch(i, n=16 * 8):
        r = np.random.default_rng(1000 + i)
        x = jnp.asarray(r.normal(size=(n, 256)), jnp.float32)
        return (x, jnp.asarray(x[:, :32] * 0.5, jnp.float32))

    eng.train_batch(batch(1))
    fb2 = np.asarray(eng.state.comm_feedback.worker_error)
    assert np.abs(fb2).max() > 0
    assert not np.array_equal(fb1, fb2)  # evolving carry, not a constant


def test_program_residual_rides_snapshots_and_rollback_restores_it(tmp_path):
    """Tentpole contract with the PR 4 resilience tier: the error-feedback
    residual is TrainState, so snapshots carry it, and a rollback restores
    the SNAPSHOT's residual — the one matching the restored params —
    instead of replaying the abandoned trajectory's carry into them."""
    eng, _ = _run_engine_dcn({"comm_planner": {"mode": "static",
                                               "use_cache": False,
                                               "dcn_axes": ["dp_outer"]},
                              "resilience": str(tmp_path)}, steps=2)
    assert eng.resilience is not None
    fb_snap = np.asarray(eng.state.comm_feedback.worker_error)
    assert np.abs(fb_snap).max() > 0
    eng.resilience.take_snapshot()

    def batch(i, n=16 * 8):
        r = np.random.default_rng(1000 + i)
        x = jnp.asarray(r.normal(size=(n, 256)), jnp.float32)
        return (x, jnp.asarray(x[:, :32] * 0.5, jnp.float32))

    eng.train_batch(batch(2))
    eng.train_batch(batch(3))
    fb_later = np.asarray(eng.state.comm_feedback.worker_error)
    assert not np.array_equal(fb_snap, fb_later)  # the carry moved on

    eng.resilience._rollback()
    fb_restored = np.asarray(eng.state.comm_feedback.worker_error)
    np.testing.assert_array_equal(fb_restored, fb_snap)


def test_engine_program_off_paths_unchanged():
    """Defaults-off bit-identity on the DCN-capable mesh: no planner, no
    knob => exact psum path, no feedback state in TrainState (zero extra
    pytree leaves), losses bitwise equal across runs."""
    eng1, run1 = _run_engine_dcn(None)
    eng2, run2 = _run_engine_dcn({"comm_planner": "off"})
    assert run1 == run2
    assert eng1._compressed_dp is False and eng1._dp_feedback is False
    assert eng1.state.comm_feedback == ()
    assert len(jax.tree.leaves(eng1.state.comm_feedback)) == 0
