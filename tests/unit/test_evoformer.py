"""Evoformer (DeepSpeed4Science) attention tests.

Reference: tests/unit/ops/deepspeed4science/test_DS4Sci_EvoformerAttention.py
— the reference checks the CUTLASS kernel against a naive torch attention
with both bias terms, forward and gradients. Here the ground truth is the
same naive formulation in numpy/jnp, and the chunked online-softmax path
must match the unchunked one exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.deepspeed4science import (DS4Sci_EvoformerAttention,
                                                 evoformer_attention)

B, N, S, H, D = 2, 3, 32, 4, 8


def _inputs(seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    q = rng.normal(size=(B, N, S, H, D)).astype(dtype)
    k = rng.normal(size=(B, N, S, H, D)).astype(dtype)
    v = rng.normal(size=(B, N, S, H, D)).astype(dtype)
    # bias1: mask-like per-row key bias; bias2: pair bias
    b1 = (rng.normal(size=(B, N, 1, 1, S)) * 2).astype(dtype)
    b2 = rng.normal(size=(B, 1, H, S, S)).astype(dtype)
    return map(jnp.asarray, (q, k, v, b1, b2))


def _naive(q, k, v, b1, b2):
    logits = np.einsum("bnqhd,bnkhd->bnhqk", np.asarray(q, np.float64),
                       np.asarray(k, np.float64)) / np.sqrt(q.shape[-1])
    if b1 is not None:
        logits = logits + np.asarray(b1, np.float64)
    if b2 is not None:
        logits = logits + np.asarray(b2, np.float64)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    return np.einsum("bnhqk,bnkhd->bnqhd", p, np.asarray(v, np.float64))


@pytest.mark.parametrize("with_biases", [True, False])
def test_matches_naive(with_biases):
    q, k, v, b1, b2 = _inputs()
    if not with_biases:
        b1 = b2 = None
    out = DS4Sci_EvoformerAttention(q, k, v, [b1, b2] if with_biases else [])
    ref = _naive(q, k, v, b1, b2)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_chunked_matches_unchunked():
    q, k, v, b1, b2 = _inputs(1)
    full = evoformer_attention(q, k, v, b1, b2)
    for chunk in (8, 16, 32):
        chunked = evoformer_attention(q, k, v, b1, b2, chunk_size=chunk)
        np.testing.assert_allclose(np.asarray(chunked), np.asarray(full),
                                   rtol=1e-5, atol=1e-5)


def test_bias_gradients_flow():
    """Both bias terms receive gradients (reference backward emits gB1/gB2)
    and the chunked path's gradients match the unchunked path's."""
    q, k, v, b1, b2 = _inputs(2)

    def loss(chunk):
        def f(args):
            qq, kk, vv, bb1, bb2 = args
            return jnp.sum(evoformer_attention(qq, kk, vv, bb1, bb2,
                                               chunk_size=chunk) ** 2)
        return f

    g_full = jax.grad(loss(None))((q, k, v, b1, b2))
    assert all(np.isfinite(np.asarray(g)).all() for g in g_full)
    assert float(jnp.abs(g_full[3]).sum()) > 0  # bias1 grad nonzero
    assert float(jnp.abs(g_full[4]).sum()) > 0  # bias2 grad nonzero
    g_chunk = jax.grad(loss(16))((q, k, v, b1, b2))
    for a, b in zip(g_full, g_chunk):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_shape_validation():
    q, k, v, b1, b2 = _inputs()
    with pytest.raises(AssertionError, match="bias1"):
        DS4Sci_EvoformerAttention(q, k, v, [jnp.zeros((B, N, 1, 1, S + 1)), None])
    with pytest.raises(AssertionError, match="bias2"):
        DS4Sci_EvoformerAttention(q, k, v, [b1, jnp.zeros((B, 1, H, S, S + 1))])
    with pytest.raises(ValueError, match="chunk_size"):
        evoformer_attention(q, k, v, chunk_size=5)


def test_triangle_attention_shapes():
    """The triangle-update usage pattern: starting-node attention where N is
    the pair-matrix row axis and bias2 carries the triangle bias."""
    rng = np.random.default_rng(3)
    b, n_res, h, d = 1, 16, 2, 8
    q = jnp.asarray(rng.normal(size=(b, n_res, n_res, h, d)), jnp.float32)
    b2 = jnp.asarray(rng.normal(size=(b, 1, h, n_res, n_res)), jnp.float32)
    out = DS4Sci_EvoformerAttention(q, q, q, [None, b2])
    assert out.shape == q.shape
    np.testing.assert_allclose(np.asarray(out), _naive(q, q, q, None, b2),
                               rtol=2e-5, atol=2e-5)


def test_chunked_handles_fully_masked_first_chunk():
    """-inf-style bias1 masking ALL of chunk 0 for some rows must not NaN the
    online-softmax rescale (reviewer repro): the chunked output still matches
    the unchunked one on those rows."""
    q, k, v, _, b2 = _inputs(4)
    b1 = np.zeros((B, N, 1, 1, S), np.float32)
    b1[:, 0, :, :, :16] = -np.inf  # row 0: first two chunks of 8 fully masked
    b1 = jnp.asarray(b1)
    full = evoformer_attention(q, k, v, b1, b2)
    chunked = evoformer_attention(q, k, v, b1, b2, chunk_size=8)
    assert np.isfinite(np.asarray(chunked)).all()
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(full),
                               rtol=1e-5, atol=1e-5)


def test_fully_masked_rows_zero_on_both_paths():
    """bias1 = -inf across ALL keys for one MSA row (the AlphaFold
    padding-row mask): both the unchunked and chunked paths must return 0
    for that row — plain softmax would NaN-poison it and every gradient."""
    q, k, v, _, b2 = _inputs(5)
    b1 = np.zeros((B, N, 1, 1, S), np.float32)
    b1[:, 1] = -np.inf  # MSA row 1 entirely padded out
    b1 = jnp.asarray(b1)
    for cs in (None, 8):
        out = np.asarray(evoformer_attention(q, k, v, b1, None, chunk_size=cs))
        assert np.isfinite(out).all(), f"chunk_size={cs} emitted non-finite"
        np.testing.assert_array_equal(out[:, 1], np.zeros_like(out[:, 1]))
    # gradients through the masked configuration stay finite
    g = jax.grad(lambda a: jnp.sum(
        evoformer_attention(a, k, v, b1, b2) ** 2))(q)
    assert np.isfinite(np.asarray(g)).all()
