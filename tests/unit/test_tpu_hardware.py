"""Real-TPU kernel spot-checks (VERDICT r3 item 10): run the Pallas kernels
COMPILED (not interpret-mode) on the actual chip at odd shapes — tile-fallback
boundaries (`_fit_blocks`), GQA 12/4, window edges — where bf16 MXU
accumulation and tiling bugs hide from CPU interpret mode.

Run: ``DSTPU_TPU_TESTS=1 JAX_PLATFORMS=axon python -m pytest tests/ -m tpu -q``
(skipped by default: ``pytest.ini`` addopts deselects the marker, and every
test here also skips when no TPU is attached).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.tpu


def _need_tpu():
    if jax.devices()[0].platform != "tpu":
        pytest.skip("no TPU attached")


def _dense_ref(q, k, v, causal=True, window=None):
    h, hk = q.shape[2], k.shape[2]
    if hk != h:
        k = jnp.repeat(k, h // hk, axis=2)
        v = jnp.repeat(v, h // hk, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / np.sqrt(q.shape[-1])
    sq, sk = q.shape[1], k.shape[1]
    pq = jnp.arange(sq)[:, None]
    pk = jnp.arange(sk)[None, :]
    mask = jnp.ones((sq, sk), bool)
    if causal:
        mask &= pq >= pk
    if window is not None:
        mask &= (pq - pk) < window
    logits = jnp.where(mask[None, None], logits, -jnp.inf)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v.astype(jnp.float32))


@pytest.mark.parametrize("seq,heads,kv_heads", [
    (640, 8, 8),    # odd seq: not a multiple of the 512 tile
    (640, 12, 4),   # GQA 12/4 at an odd seq
    (1024, 12, 4),  # GQA 12/4 aligned
    (384, 16, 1),   # MQA below one tile
])
def test_flash_compiled_parity_odd_shapes(seq, heads, kv_heads):
    _need_tpu()
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

    rng = np.random.default_rng(0)
    b, d = 2, 64
    q = jnp.asarray(rng.normal(size=(b, seq, heads, d)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=(b, seq, kv_heads, d)), jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=(b, seq, kv_heads, d)), jnp.bfloat16)
    out = jax.jit(lambda a, b_, c: flash_attention(a, b_, c, causal=True,
                                                   interpret=False))(q, k, v)
    ref = _dense_ref(q, k, v)
    # bf16 inputs, fp32 online softmax: tolerance covers MXU accumulation
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=0.05, atol=0.05)


def test_flash_backward_compiled_odd_seq():
    _need_tpu()
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

    rng = np.random.default_rng(1)
    b, seq, h, hk, d = 1, 640, 12, 4, 64
    q = jnp.asarray(rng.normal(size=(b, seq, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, seq, hk, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, seq, hk, d)), jnp.float32)

    def f(fn):
        return jax.jit(jax.grad(lambda a, b_, c: jnp.sum(
            fn(a, b_, c) ** 2), argnums=(0, 1, 2)))

    g_k = f(lambda a, b_, c: flash_attention(a, b_, c, interpret=False))(q, k, v)
    g_r = f(lambda a, b_, c: _dense_ref(a, b_, c))(q, k, v)
    for a, b_ in zip(g_k, g_r):
        # both sides hit the MXU at default (bf16-pass) precision; measured
        # worst case on v5e is 1 elt / 491520 at 0.029 abs — tolerance set
        # just above that so a real tiling bug (whole-tile garbage) still fails
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-2, atol=4e-2)


def test_paged_attention_compiled_window_edges():
    """Page-boundary cases: kv_len exactly at a page edge, one past it, and
    a chunk straddling pages."""
    _need_tpu()
    from deepspeed_tpu.ops.pallas.paged_attention import paged_attention

    rng = np.random.default_rng(2)
    S, Q, Hq, Hk, D, bs, N, B = 3, 8, 8, 4, 64, 128, 32, 8
    q = jnp.asarray(rng.normal(size=(S, Q, Hq, D)), jnp.bfloat16)
    kp = jnp.asarray(rng.normal(size=(N, Hk, bs, D)), jnp.bfloat16)
    vp = jnp.asarray(rng.normal(size=(N, Hk, bs, D)), jnp.bfloat16)
    bt = jnp.asarray(rng.permutation(N)[:S * B].reshape(S, B), jnp.int32)
    # kv_len: page-edge, page-edge+1, mid-page; chunk fills the rest
    kv_len = jnp.asarray([128, 129, 200], jnp.int32)
    start = kv_len - Q
    chunk = jnp.full((S,), Q, jnp.int32)
    out = jax.jit(lambda *a: paged_attention(*a, interpret=False))(
        q, kp, vp, bt, start, chunk, kv_len)
    assert out.shape == (S, Q, Hq, D)
    assert bool(jnp.all(jnp.isfinite(out.astype(jnp.float32))))

    # parity vs dense gather for sequence 0
    def gather(pool, s):
        pages = pool[bt[s]]                      # [B, Hk, bs, D]
        return jnp.swapaxes(pages, 1, 2).reshape(-1, Hk, D)[: int(kv_len[s])]

    s = 0
    ks, vs = gather(kp, s), gather(vp, s)
    ref = _dense_ref(q[s][None].astype(jnp.float32),
                     ks[None].astype(jnp.float32),
                     vs[None].astype(jnp.float32), causal=False)
    # causal-by-position: query i attends to <= start+i+1 keys
    refs = []
    for i in range(Q):
        n = int(start[s]) + i + 1
        r = _dense_ref(q[s, i][None, None].astype(jnp.float32),
                       ks[None, :n].astype(jnp.float32),
                       vs[None, :n].astype(jnp.float32), causal=False)
        refs.append(r[0, 0])
    ref = jnp.stack(refs)
    np.testing.assert_allclose(np.asarray(out[s], np.float32),
                               np.asarray(ref), rtol=0.05, atol=0.05)


def test_sparse_attention_compiled_layouts():
    _need_tpu()
    from deepspeed_tpu.ops.pallas.sparse_attention import (bigbird_layout,
                                                           sparse_attention)

    rng = np.random.default_rng(3)
    b, seq, h, d, block = 1, 512, 4, 64, 64
    q = jnp.asarray(rng.normal(size=(b, seq, h, d)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=(b, seq, h, d)), jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=(b, seq, h, d)), jnp.bfloat16)
    layout = np.ones((h, seq // block, seq // block), bool)  # dense layout
    del bigbird_layout  # imported to assert the builder vocabulary exists
    out = jax.jit(lambda a, b_, c: sparse_attention(
        a, b_, c, layout, causal=True, block=block, interpret=False))(q, k, v)
    ref = _dense_ref(q, k, v)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=0.05, atol=0.05)
