"""Telemetry spine tests (``deepspeed_tpu/telemetry/``).

Coverage: span nesting / buffer bounds / Chrome-trace export, flight-ring
semantics and the post-mortem dump (including a REAL watchdog exit-83 drill
in a subprocess and the sentinel-rollback path), registry exposition format
and the /metrics HTTP surface, default-off bitwise step identity, the
ladder gate (synthetic regression flagged, unchanged ladder passes), and
the satellite regressions (thread-safe JSONL monitor, cached timer sync
sentinel).
"""

import json
import os
import subprocess
import sys
import textwrap
import threading
import time
import urllib.request
from types import SimpleNamespace

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.telemetry import (FlightRecorder, MetricsRegistry,
                                     MetricsServer, SpanTracer, chrome_trace,
                                     configure_tracer, get_tracer)
from deepspeed_tpu.telemetry.spans import _NULL_SPAN, span

from .simple_model import make_simple_params, random_batches, simple_loss

HIDDEN = 48


@pytest.fixture(autouse=True)
def _reset_fleet_telemetry():
    """Every test leaves the fleet tracer off and the process-global
    registry fresh (TelemetryManager flips both)."""
    yield
    configure_tracer(enabled=False)
    get_tracer().clear()
    from deepspeed_tpu.telemetry import (configure_collective_recorder,
                                         get_collective_recorder,
                                         reset_registry)
    from deepspeed_tpu.telemetry import manager as _mgr

    configure_collective_recorder(enabled=False)
    get_collective_recorder().clear()
    reset_registry()
    _mgr._ACTIVE = False
    _mgr._OWNER = None


def _engine(cfg_extra, seed=42):
    cfg = {"train_micro_batch_size_per_gpu": 8,
           "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
           "steps_per_print": 1000, "seed": seed}
    cfg.update(cfg_extra)
    engine, *_ = ds.initialize(model=simple_loss,
                               model_parameters=make_simple_params(HIDDEN),
                               config=cfg)
    return engine


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


def test_span_nesting_depth_step_and_bounds():
    tr = SpanTracer(enabled=True, max_spans=4)
    tr.set_step(7)
    with tr.span("step"):
        with tr.span("inner", k="v"):
            pass
    recs = tr.drain()
    assert [r["name"] for r in recs] == ["inner", "step"]  # close order
    inner, outer = recs
    assert inner["depth"] == 1 and outer["depth"] == 0
    assert inner["step"] == 7 and inner["attrs"] == {"k": "v"}
    assert inner["dur_ns"] >= 0 and outer["dur_ns"] >= inner["dur_ns"]
    # the buffer is bounded: only the newest max_spans survive
    for i in range(10):
        with tr.span(f"s{i}"):
            pass
    assert [r["name"] for r in tr.drain()] == ["s6", "s7", "s8", "s9"]


def test_span_disabled_is_shared_noop():
    tr = SpanTracer(enabled=False)
    assert tr.span("x") is tr.span("y") is _NULL_SPAN
    with tr.span("x"):
        pass
    assert tr.drain() == [] and tr.open_spans() == []
    # the module-level fleet entry point too
    assert span("anything") is _NULL_SPAN


def test_open_spans_visible_from_other_thread():
    tr = SpanTracer(enabled=True)
    entered, release = threading.Event(), threading.Event()

    def worker():
        with tr.span("outer"):
            with tr.span("hung/phase"):
                entered.set()
                release.wait(5)

    t = threading.Thread(target=worker)
    t.start()
    assert entered.wait(5)
    open_spans = tr.open_spans()
    assert [s["name"] for s in open_spans] == ["outer", "hung/phase"]
    assert open_spans[1]["dur_ns"] is None and open_spans[1]["age_ns"] >= 0
    release.set()
    t.join()
    assert tr.open_spans() == []


def test_chrome_trace_export(tmp_path):
    tr = SpanTracer(enabled=True)
    with tr.span("step", step=3):
        with tr.span("compute/dispatch"):
            pass
    from deepspeed_tpu.telemetry import export_chrome

    path = export_chrome(str(tmp_path / "t.json"), tr.drain(),
                         tr.open_spans())
    doc = json.load(open(path))
    evs = doc["traceEvents"]
    assert {e["name"] for e in evs} == {"step", "compute/dispatch"}
    for e in evs:
        assert e["ph"] == "X" and e["dur"] >= 0 and "ts" in e
    # open spans export with their age and an open marker
    doc2 = chrome_trace([], [{"name": "hung", "t0_ns": 0, "age_ns": 5000,
                              "dur_ns": None, "depth": 0, "tid": 1,
                              "step": None}])
    (ev,) = doc2["traceEvents"]
    assert ev["dur"] == 5.0 and ev["args"]["open"] is True


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_ring_bounded_and_dump_schema(tmp_path):
    tr = SpanTracer(enabled=True)
    fl = FlightRecorder(tr, str(tmp_path), steps=3, rank=5)
    for step in range(6):
        with tr.span("compute/dispatch"):
            pass
        fl.record_step(step, step_time_s=0.01,
                       metrics={"loss": 1.5, "skip": "nonnumeric"})
    assert [e["step"] for e in fl.steps()] == [3, 4, 5]  # ring of 3
    assert fl.steps()[-1]["metrics"] == {"loss": 1.5}  # numeric only
    path = fl.dump("unit", {"extra_key": 1})
    assert path.endswith("flightdump-5.json")
    doc = json.load(open(path))
    assert doc["reason"] == "unit" and doc["rank"] == 5
    assert doc["extra_key"] == 1 and len(doc["steps"]) == 3
    assert doc["last_phase"] == "compute/dispatch"
    assert doc["open_spans"] == []


def test_flight_last_phase_names_the_open_span(tmp_path):
    tr = SpanTracer(enabled=True)
    fl = FlightRecorder(tr, str(tmp_path), steps=4)
    with tr.span("step"):
        with tr.span("grad/reduce"):
            doc = json.load(open(fl.dump("hang")))
    assert doc["last_phase"] == "grad/reduce"  # innermost OPEN span wins
    assert [s["name"] for s in doc["open_spans"]] == ["step", "grad/reduce"]


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def test_registry_counter_gauge_histogram_exposition():
    reg = MetricsRegistry()
    c = reg.counter("dstpu_test_total", "a counter")
    c.inc(2, op="all_reduce")
    c.inc(op="all_reduce")
    g = reg.gauge("dstpu_test_gauge")
    g.set(1.5)
    h = reg.histogram("dstpu_test_seconds", "a hist", buckets=(0.1, 1.0))
    h.observe(0.05, phase="fwd")
    h.observe(5.0, phase="fwd")
    text = reg.exposition()
    assert "# TYPE dstpu_test_total counter" in text
    assert 'dstpu_test_total{op="all_reduce"} 3' in text
    assert "dstpu_test_gauge 1.5" in text
    assert '# TYPE dstpu_test_seconds histogram' in text
    assert 'dstpu_test_seconds_bucket{le="0.1",phase="fwd"} 1' in text
    assert 'dstpu_test_seconds_bucket{le="+Inf",phase="fwd"} 2' in text
    assert 'dstpu_test_seconds_count{phase="fwd"} 2' in text
    # re-registration returns the same family; type clash fails loudly
    assert reg.counter("dstpu_test_total") is c
    with pytest.raises(ValueError):
        reg.gauge("dstpu_test_total")


def test_registry_collector_and_monitor_events():
    reg = MetricsRegistry()
    reg.counter("dstpu_x_total").inc(4)
    reg.register_collector("src", lambda: [
        ("dstpu_pull_gauge", "gauge", "", [("", {"k": "v"}, 9.0)])])
    text = reg.exposition()
    assert 'dstpu_pull_gauge{k="v"} 9' in text
    events = reg.monitor_events(step=12)
    names = {n for n, _v, _s in events}
    assert "Telemetry/dstpu_x_total" in names
    assert "Telemetry/dstpu_pull_gauge/k=v" in names
    assert all(s == 12 for _n, _v, s in events)
    # a replaced collector (same key) does not duplicate
    reg.register_collector("src", lambda: [])
    assert "dstpu_pull_gauge" not in reg.exposition()


def test_exposition_merges_same_family_across_collectors():
    """Two replicas' collectors emit the same family name; the text format
    requires ONE # TYPE block holding all samples (promtool rejects
    repeated family blocks)."""
    reg = MetricsRegistry()
    for rep in ("0", "1"):
        reg.register_collector(f"serving-{rep}", lambda rep=rep: [
            ("dstpu_serving_requests_total", "counter", "serving submitted",
             [("", {"replica": rep}, float(rep) + 1)])])
    text = reg.exposition()
    assert text.count("# TYPE dstpu_serving_requests_total counter") == 1
    assert 'dstpu_serving_requests_total{replica="0"} 1' in text
    assert 'dstpu_serving_requests_total{replica="1"} 2' in text


def test_comms_ledger_bridge_samples():
    from deepspeed_tpu.telemetry.manager import comms_ledger_samples
    from deepspeed_tpu.utils.comms_logging import CommsLogger

    ledger = CommsLogger(enabled=True)
    ledger.append("all_reduce", 1024, wire_bytes=256, hop_class="dcn")
    fams = {name: rows for name, _t, _h, rows in comms_ledger_samples(ledger)}
    assert fams["dstpu_comm_wire_bytes_total"][0] == ("", {"op": "all_reduce"},
                                                     256.0)
    assert fams["dstpu_comm_hop_bytes_total"][0] == ("", {"link": "dcn"},
                                                    256.0)


def test_metrics_server_scrape_and_healthz():
    reg = MetricsRegistry()
    reg.counter("dstpu_up_total").inc()
    verdicts = {"dead": [], "stragglers": []}
    srv = MetricsServer(reg, port=0, health_fn=lambda: verdicts)
    port = srv.start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert "dstpu_up_total 1" in body
        health = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5).read())
        assert health["status"] == "ok" and health["dead"] == []
        verdicts["dead"] = [3]          # a dead host flips the status code
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz",
                                   timeout=5)
        assert e.value.code == 503
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# engine integration
# ---------------------------------------------------------------------------


def test_engine_records_step_phases_and_flight_ring(tmp_path):
    e = _engine({"telemetry": {"enabled": True, "flight_steps": 8,
                               "flight_dir": str(tmp_path),
                               "drain_interval_steps": 2}})
    for b in random_batches(4, 8, HIDDEN):
        e.train_batch(b)
    tm = e.telemetry
    assert len(tm.flight.steps()) == 4
    # ring entry step numbers agree with the spans' stamps (and with what
    # the watchdog would report for the same step) — no off-by-one
    for entry in tm.flight.steps():
        stamped = {s["step"] for s in entry["spans"]}
        assert stamped == {entry["step"]}
    assert [entry["step"] for entry in tm.flight.steps()] == [0, 1, 2, 3]
    phases = {s["name"] for entry in tm.flight.steps()
              for s in entry["spans"]}
    assert {"step", "data/shape", "compute/dispatch",
            "metrics/report"} <= phases
    assert "compute/drain" in phases        # the once-per-window device drain
    assert tm.phase_hist.count(phase="step") == 4
    assert tm.step_counter.value() == 4
    text = tm.registry.exposition()
    assert 'dstpu_step_phase_seconds_count{phase="compute/dispatch"} 4' in text
    tm.close()


def test_telemetry_shorthand_and_default_off():
    from deepspeed_tpu.runtime.config import load_config

    cfg = load_config({"telemetry": True})
    assert cfg.telemetry.enabled and cfg.telemetry.flight_steps == 32
    cfg = load_config({"telemetry": "/tmp/fl"})
    assert cfg.telemetry.enabled and cfg.telemetry.flight_dir == "/tmp/fl"
    assert not load_config(None).telemetry.enabled


def test_telemetry_off_is_bitwise_identical():
    batches = random_batches(3, 8, HIDDEN)
    e_plain = _engine({})
    e_off = _engine({"telemetry": {"enabled": False}})
    e_on = _engine({"telemetry": {"enabled": True, "flight_steps": 4,
                                  "flight_dir": "/tmp"}})
    assert e_plain.telemetry is None and e_off.telemetry is None
    for b in batches:
        l0 = float(np.asarray(e_plain.train_batch(b)))
        l1 = float(np.asarray(e_off.train_batch(b)))
        l2 = float(np.asarray(e_on.train_batch(b)))
        assert l0 == l1 == l2  # bitwise, not allclose
    for p0, p2 in zip(np.asarray(e_plain.state.params["head"]["w"]).ravel(),
                      np.asarray(e_on.state.params["head"]["w"]).ravel()):
        assert p0 == p2
    e_on.telemetry.close()


def test_monitor_bridge_emits_registry_events(tmp_path):
    import types

    e = _engine({"steps_per_print": 1,
                 "telemetry": {"enabled": True, "flight_steps": 4,
                               "flight_dir": str(tmp_path),
                               "monitor_bridge": True}})
    events = []
    e.monitor = types.SimpleNamespace(
        write_events=lambda evs: events.extend(evs))
    for b in random_batches(2, 8, HIDDEN):
        e.train_batch(b)
    assert any(n.startswith("Telemetry/dstpu_steps_total")
               for n, _v, _s in events)
    e.telemetry.close()


def test_closing_superseded_manager_keeps_successor_live():
    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.telemetry import TelemetryManager, telemetry_active

    a = TelemetryManager(TelemetryConfig(enabled=True, flight_steps=0))
    b = TelemetryManager(TelemetryConfig(enabled=True, flight_steps=0))
    a.close()                       # superseded: must not mute b
    assert telemetry_active() and get_tracer().enabled
    b.close()                       # the owner: tears the globals down
    assert not telemetry_active() and not get_tracer().enabled


def test_trace_export_without_flight_ring_keeps_spans(tmp_path):
    """flight_steps=0 + trace_dir: drained step spans must survive into the
    Chrome-trace export via the side buffer, not vanish each step."""
    e = _engine({"telemetry": {"enabled": True, "flight_steps": 0,
                               "trace_dir": str(tmp_path)}})
    assert e.telemetry.flight is None
    for b in random_batches(3, 8, HIDDEN):
        e.train_batch(b)
    path = e.telemetry.export_trace()
    names = {ev["name"] for ev in json.load(open(path))["traceEvents"]}
    assert {"step", "compute/dispatch"} <= names
    assert sum(1 for ev in json.load(open(path))["traceEvents"]
               if ev["name"] == "step") == 3       # all three steps, not one
    e.telemetry.close()


def test_metrics_server_bind_failure_does_not_kill_engine():
    """One fixed prometheus_port across ranks: the second bind fails with
    EADDRINUSE — telemetry logs and disables /metrics instead of taking
    down engine bring-up."""
    import socket

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    try:
        e = _engine({"telemetry": {"enabled": True, "flight_steps": 4,
                                   "flight_dir": "/tmp",
                                   "prometheus_port": port}})
        assert e.telemetry.server is None          # bind failed, engine lives
        float(np.asarray(e.train_batch(random_batches(1, 8, HIDDEN)[0])))
        e.telemetry.close()
    finally:
        sock.close()


# ---------------------------------------------------------------------------
# flight dumps on the three post-mortem paths
# ---------------------------------------------------------------------------


def test_sentinel_rollback_dumps_flight_record(tmp_path):
    e = _engine({
        "telemetry": {"enabled": True, "flight_steps": 8},
        "resilience": {
            "enabled": True, "snapshot_dir": str(tmp_path),
            "snapshot_interval": 1,
            "sentinel": {"enabled": True, "nan_streak": 1, "policy": "rollback"},
            "faults": {"enabled": True, "nan_loss_at_steps": [2]}}})
    assert e.resilience._telemetry is e.telemetry
    for b in random_batches(5, 8, HIDDEN):
        e.train_batch(b)
    assert e.resilience.rollbacks == 1
    # default flight_dir falls back to the snapshot dir
    path = tmp_path / "flightdump-0.json"
    assert path.exists()
    doc = json.loads(path.read_text())
    assert doc["reason"] == "rollback" and doc["tripped_at"] >= 2
    assert doc["steps"] and doc["steps"][-1]["spans"]
    assert e.telemetry.res_counter.value(event="rollback") == 1
    assert e.telemetry.res_counter.value(event="snapshot") >= 1
    e.resilience.close()
    e.telemetry.close()


def test_preempt_drain_dumps_flight_record(tmp_path):
    e = _engine({
        "telemetry": {"enabled": True, "flight_steps": 8},
        "resilience": {
            "enabled": True, "snapshot_dir": str(tmp_path),
            "snapshot_interval": 0,
            "preemption": {"enabled": True, "install_signal_handler": False},
            "faults": {"enabled": True, "preempt_at_step": 2}}})
    for b in random_batches(3, 8, HIDDEN):
        e.train_batch(b)
        if e.should_stop():
            break
    assert e.resilience.drained
    doc = json.loads((tmp_path / "flightdump-0.json").read_text())
    assert doc["reason"] == "preempt_drain"
    e.resilience.close()
    e.telemetry.close()


def test_watchdog_expiry_dumps_flight_record_inprocess(tmp_path):
    """hang_at_step drill with an overridden on_expire: pre_dump (the flight
    recorder) must run FIRST and the dump's open spans must name the phase
    the step wedged in."""
    e = _engine({
        "telemetry": {"enabled": True, "flight_steps": 8},
        "resilience": {
            "enabled": True, "snapshot_dir": str(tmp_path),
            "snapshot_interval": 0,
            "watchdog": {"enabled": True, "floor_s": 0.15, "cap_s": 2.0,
                         "factor": 2.0},
            "faults": {"enabled": True, "hang_at_step": 2}}})
    rz = e.resilience
    assert rz.watchdog.pre_dump is not None   # telemetry attached it
    rz.watchdog.on_expire = lambda step: rz.release_hang()
    for b in random_batches(3, 8, HIDDEN):
        e.train_batch(b)
    assert rz.watchdog.fired
    doc = json.loads((tmp_path / "flightdump-0.json").read_text())
    assert doc["reason"] == "watchdog"
    open_names = [s["name"] for s in doc["open_spans"]]
    assert open_names[0] == "step"
    assert doc["last_phase"] == "resilience/post_step"  # where the hang lives
    rz.close()
    e.telemetry.close()


def test_watchdog_exit83_drill_writes_flightdump(tmp_path):
    """The REAL drill: a subprocess engine wedges (hang_at_step), the
    watchdog kills it with exit code 83, and the flightdump left behind
    names the hung phase — the acceptance path end to end."""
    from deepspeed_tpu.runtime.resilience import WATCHDOG_EXIT_CODE

    body = f"""\
        import os
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import sys
        sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))!r})
        import deepspeed_tpu as ds
        from tests.unit.simple_model import (make_simple_params,
                                             random_batches, simple_loss)
        engine, *_ = ds.initialize(
            model=simple_loss, model_parameters=make_simple_params({HIDDEN}),
            config={{
                "train_micro_batch_size_per_gpu": 8,
                "optimizer": {{"type": "adam", "params": {{"lr": 1e-2}}}},
                "steps_per_print": 1000,
                "telemetry": {{"enabled": True, "flight_steps": 8}},
                "resilience": {{
                    "enabled": True, "snapshot_dir": {str(tmp_path)!r},
                    "snapshot_interval": 0,
                    "watchdog": {{"enabled": True, "floor_s": 0.15,
                                  "cap_s": 2.0, "factor": 2.0}},
                    "faults": {{"enabled": True, "hang_at_step": 2}}}}}})
        for b in random_batches(4, 8, {HIDDEN}):
            engine.train_batch(b)
        raise SystemExit(99)  # unreachable: the watchdog must kill us first
        """
    script = tmp_path / "drill.py"
    script.write_text(textwrap.dedent(body))
    r = subprocess.run([sys.executable, str(script)], timeout=180,
                       capture_output=True, text=True)
    assert r.returncode == WATCHDOG_EXIT_CODE, r.stderr[-2000:]
    dump = tmp_path / "flightdump-0.json"
    assert dump.exists()
    doc = json.loads(dump.read_text())
    assert doc["reason"] == "watchdog"
    assert doc["last_phase"] == "resilience/post_step"
    assert any(s["name"] == "step" for s in doc["open_spans"])
    # the ring held every step COMPLETED before the hang (the hung step's
    # spans are in open_spans/inflight, not yet folded)
    assert len(doc["steps"]) >= 1
    assert doc["steps"][-1]["spans"]
    # the PR 5 hangdump rides beside it unchanged
    assert (tmp_path / "hangdump-0.txt").exists()


def test_crash_hook_dumps_flight_record(tmp_path):
    """Satellite: an unhandled train-loop exception leaves a
    reason="crash" flightdump (exception type + traceback summary) before
    re-raising — with or without the resilience tier armed. (Rides the
    same engine: on CPU memory_stats() is None, so no dstpu_mem_* series
    and no mem in ring entries — and no crash.)"""
    e = _engine({"telemetry": {"enabled": True, "flight_steps": 8,
                               "flight_dir": str(tmp_path)}})
    good = random_batches(1, 8, HIDDEN)[0]
    e.train_batch(good)
    assert all("mem" not in s for s in e.telemetry.flight.steps())
    assert "dstpu_mem_bytes_in_use" not in e.telemetry.registry.exposition()
    # feature dim off by one: the loss matmul fails at trace time — an
    # unhandled exception inside the step body
    bad = {"x": np.zeros((8, HIDDEN + 1), np.float32),
           "y": np.zeros((8, 1), np.float32)}
    with pytest.raises(Exception) as excinfo:
        e.train_batch(bad)
    doc = json.loads((tmp_path / "flightdump-0.json").read_text())
    assert doc["reason"] == "crash"
    assert doc["exception"] == type(excinfo.value).__name__
    assert doc["message"]
    assert "Traceback" in doc["traceback"]
    assert doc["steps"]                 # the completed step survived
    # the routine epoch-end StopIteration is NOT a crash: no fresh dump
    os.unlink(tmp_path / "flightdump-0.json")
    with pytest.raises(StopIteration):
        e.train_batch(data_iter=iter([]))
    assert not (tmp_path / "flightdump-0.json").exists()
    e.telemetry.close()


def test_chrome_trace_rank_pid_and_process_metadata():
    """Satellite: rank-stamped exports carry pid=rank plus process_name /
    process_sort_index metadata so multi-rank traces merge into one
    Perfetto timeline."""
    doc = chrome_trace([{"name": "step", "t0_ns": 0, "dur_ns": 1000,
                         "depth": 0, "tid": 1, "step": 0}], rank=3)
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert {m["name"] for m in metas} == {"process_name",
                                          "process_sort_index"}
    assert all(m["pid"] == 3 for m in metas)
    assert metas[0]["args"]["name"] == "rank 3"
    (span_ev,) = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert span_ev["pid"] == 3
    # rank-less exports keep the old behavior: os pid, no metadata
    doc2 = chrome_trace([{"name": "x", "t0_ns": 0, "dur_ns": 1, "depth": 0,
                          "tid": 1, "step": None}])
    assert all(e["ph"] != "M" for e in doc2["traceEvents"])
    assert doc2["traceEvents"][0]["pid"] == os.getpid()


def test_prometheus_port_zero_is_ephemeral_per_engine():
    """Satellite: prometheus_port: 0 binds an ephemeral port per manager —
    two engines on one host stop colliding — and the bound port is exposed
    via the prometheus_port attribute."""
    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.telemetry import TelemetryManager

    a = TelemetryManager(TelemetryConfig(enabled=True, flight_steps=0,
                                         prometheus_port=0))
    b = TelemetryManager(TelemetryConfig(enabled=True, flight_steps=0,
                                         prometheus_port=0))
    try:
        assert a.server is not None and b.server is not None
        assert a.prometheus_port > 0 and b.prometheus_port > 0
        assert a.prometheus_port != b.prometheus_port
        for tm in (a, b):
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{tm.prometheus_port}/metrics",
                timeout=5).read().decode()
            assert "dstpu_steps_total" in body
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# device-memory telemetry
# ---------------------------------------------------------------------------


def test_memory_sampler_folds_into_ring_and_gauges(tmp_path):
    """A fake memory_stats source: per-device gauges land in the registry,
    the fleet aggregate rides each flight-ring entry, and the sampler
    self-disables once the backend reports nothing."""
    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.telemetry import TelemetryManager

    tm = TelemetryManager(TelemetryConfig(enabled=True, flight_steps=4,
                                          flight_dir=str(tmp_path)))
    try:
        tm._mem_fn = lambda: [
            (0, {"bytes_in_use": 100, "peak_bytes_in_use": 150,
                 "bytes_limit": 1000}),
            (1, {"bytes_in_use": 200, "peak_bytes_in_use": 250,
                 "bytes_limit": 1000})]
        tm.on_step_end(0, step_time_s=0.01)
        entry = tm.flight.steps()[-1]
        assert entry["mem"] == {"bytes_in_use": 200,
                                "peak_bytes_in_use": 250,
                                "bytes_limit": 1000}
        text = tm.registry.exposition()
        assert 'dstpu_mem_bytes_in_use{device="0"} 100' in text
        assert 'dstpu_mem_bytes_in_use{device="1"} 200' in text
        assert 'dstpu_mem_peak_bytes_in_use{device="1"} 250' in text
        assert 'dstpu_mem_bytes_limit{device="0"} 1000' in text
        # the dump carries a fresh sample in its meta
        doc = json.load(open(tm.flight_dump("unit")))
        assert doc["mem"]["bytes_in_use"] == 200
        # a TRANSIENT read failure skips the step but keeps the sampler —
        # one flaky read must not end a multi-day job's HBM history
        def boom():
            raise RuntimeError("transient PJRT read failure")

        tm._mem_fn = boom
        tm.on_step_end(1)
        assert tm._mem_fn is boom
        assert "mem" not in tm.flight.steps()[-1]
        # backend SUCCESSFULLY reports nothing -> sampler disables itself
        tm._mem_fn = lambda: []
        tm.on_step_end(2)
        assert tm._mem_fn is None
        assert "mem" not in tm.flight.steps()[-1]
    finally:
        tm.close()


def test_watchdog_pre_dump_never_samples_device_memory(tmp_path):
    """The watchdog fires while the runtime is WEDGED: its flight dump
    must not read device.memory_stats() (a blocked client would stall the
    exit-83 kill). The ring's per-step mem history still rides the dump."""
    from types import SimpleNamespace

    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.telemetry import TelemetryManager

    tm = TelemetryManager(TelemetryConfig(enabled=True, flight_steps=4,
                                          flight_dir=str(tmp_path)))
    try:
        calls = {"n": 0}

        def sampler():
            calls["n"] += 1
            return [(0, {"bytes_in_use": 7, "peak_bytes_in_use": 9})]

        tm._mem_fn = sampler
        tm.on_step_end(0)                      # per-step sampling works
        assert calls["n"] == 1
        rz = SimpleNamespace(watchdog=SimpleNamespace(pre_dump=None,
                                                      fired_step=0),
                             health=None)
        tm.attach_resilience(rz)
        path = rz.watchdog.pre_dump()          # the wedged-path dump
        assert calls["n"] == 1                 # NOT sampled live
        doc = json.loads(open(path).read())
        assert "mem" not in doc                # no live sample in the meta
        assert doc["steps"][-1]["mem"]["bytes_in_use"] == 7  # history rides
        # the other dump reasons still take a live sample
        doc2 = json.loads(open(tm.flight_dump("rollback")).read())
        assert calls["n"] == 2 and doc2["mem"]["bytes_in_use"] == 7
    finally:
        tm.close()


def test_memory_analysis_recorded_and_bitwise_identical(tmp_path):
    """telemetry.memory_analysis AOT-measures each step variant: the
    breakdown lands in the comms ledger's plan table + registry, and the
    measured executable steps BITWISE identically to the plain jit path.
    (engine.compile() records the same breakdown with NO telemetry —
    checked on the plain engine.)"""
    from deepspeed_tpu.comm import get_comms_logger

    get_comms_logger().memory_records.clear()
    batches = random_batches(3, 8, HIDDEN)
    e_plain = _engine({})
    e_plain.compile(batches[0])  # AOT path: plan-table fact, telemetry-free
    assert "train_step" in get_comms_logger().memory_records
    get_comms_logger().memory_records.clear()
    e_mem = _engine({"telemetry": {"enabled": True, "flight_steps": 4,
                                   "flight_dir": str(tmp_path),
                                   "memory_analysis": True}})
    for b in batches:
        l0 = float(np.asarray(e_plain.train_batch(b)))
        l1 = float(np.asarray(e_mem.train_batch(b)))
        assert l0 == l1                     # bitwise, not allclose
    recs = get_comms_logger().memory_records
    assert "train_step" in recs
    info = recs["train_step"]
    assert info["argument_size_in_bytes"] > 0
    assert "temp_size_in_bytes" in info
    # one executable, measured once, reused across the steps
    assert len(e_mem._mem_execs) == 1
    text = e_mem.telemetry.registry.exposition()
    assert 'dstpu_mem_exec_bytes{exec="train_step",kind="argument"}' in text
    # the plan table surfaces the executable-memory rows
    lines = get_comms_logger().plan_table_lines()
    assert any("Executable memory" in ln for ln in lines)
    assert any("train_step" in ln for ln in lines)
    # and flight dumps carry the breakdown for the doctor
    doc = json.load(open(e_mem.telemetry.flight_dump("unit")))
    assert doc["exec_memory"]["train_step"] == info
    e_mem.telemetry.close()


# ---------------------------------------------------------------------------
# serving integration
# ---------------------------------------------------------------------------


def test_serving_spans_and_registry_bridge():
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig)
    from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                  TransformerLM)
    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.serving import LLMServer
    from deepspeed_tpu.telemetry import TelemetryManager, get_registry

    tm = TelemetryManager(TelemetryConfig(enabled=True, flight_steps=0))
    try:
        cfg = TransformerConfig(vocab_size=97, hidden_size=48,
                                intermediate_size=96, num_layers=2,
                                num_heads=4, num_kv_heads=2, max_seq_len=128,
                                dtype=jnp.float32, norm="rmsnorm",
                                activation="swiglu")
        model = TransformerLM(cfg)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32))["params"]
        engine = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
            token_budget=16, max_ragged_sequence_count=4, max_chunk_size=8,
            num_kv_blocks=64, kv_block_size=8, max_blocks_per_seq=8,
            dtype="float32"))
        server = LLMServer(engine, replica_id=3)
        out = server.generate([np.arange(1, 9, dtype=np.int32)],
                              max_new_tokens=4)
        assert len(out) == 1 and len(out[0]) >= 1
        text = get_registry().exposition()
        assert 'dstpu_serving_completed_total{replica="3"} 1' in text
        assert "dstpu_serving_ttft_p50_seconds" in text
        server.drain(timeout=30)
        names = {s["name"] for s in tm.tracer.snapshot()}
        assert "serve/admit" in names
        assert names & {"serve/prefill", "serve/decode", "serve/mixed"}
        # a stopped replica stops exporting: frozen series would read as a
        # live replica to every later scrape
        assert "dstpu_serving_completed_total" not in get_registry().exposition()
    finally:
        tm.close()


# ---------------------------------------------------------------------------
# ladder gate
# ---------------------------------------------------------------------------


def _bench():
    import importlib.util

    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    spec = importlib.util.spec_from_file_location(
        "dstpu_bench_gate", os.path.join(root, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def bench_mod():
    return _bench()


def test_gate_passes_unchanged_and_flags_regression(bench_mod):
    baseline = {"tok_per_s": {"metric": "tok_per_s", "value": 100.0},
                "arm_us": {"metric": "arm_us", "value": 10.0}}
    specs = {"arm_us": ("lower", 1.0)}
    ok = [{"metric": "tok_per_s", "value": 97.0},
          {"metric": "arm_us", "value": 12.0},
          {"metric": "brand_new", "value": 1.0}]      # no baseline: never gates
    assert bench_mod.gate_results(ok, baseline, specs) == []
    bad_lower = [{"metric": "tok_per_s", "value": 40.0}]   # < 100*(1-0.5)
    (f,) = bench_mod.gate_results(bad_lower, baseline, specs)
    assert f["metric"] == "tok_per_s" and "below" in f["why"]
    bad_higher = [{"metric": "arm_us", "value": 25.0}]     # > 10*(1+1.0)
    (f,) = bench_mod.gate_results(bad_higher, baseline, specs)
    assert f["metric"] == "arm_us" and "above" in f["why"]
    broken = [{"metric": "tok_per_s", "value": None, "error": "boom"}]
    (f,) = bench_mod.gate_results(broken, baseline, specs)
    assert f["value"] is None and f["why"] == "boom"
    # a CRASHED rung subprocess loses its metric name entirely — the error
    # row still gates via the baseline row's rung id
    rung_base = {"m": {"metric": "m", "value": 5.0, "rung": "ds"}}
    crashed = [{"metric": "rungds", "value": None, "rung": "ds",
                "error": "rc=-11"}]
    (f,) = bench_mod.gate_results(crashed, rung_base, specs)
    assert f["metric"] == "m" and f["value"] is None
    # but a SUCCESSFUL rung whose metric name differs (rung 3's TPU-vs-CPU
    # variants) is a different measurement — never gated by rung id
    variant = [{"metric": "m_cpu_smoke", "value": 0.1, "rung": "ds"}]
    assert bench_mod.gate_results(variant, rung_base, specs) == []


def test_vs_baseline_filled_from_ladder_row(bench_mod):
    baseline = {"m": {"metric": "m", "value": 50.0}}
    rec = bench_mod.fill_vs_baseline({"metric": "m", "value": 60.0,
                                      "vs_baseline": None}, baseline)
    assert rec["vs_baseline"] == 1.2
    # rows that computed a target-relative value keep it
    rec = bench_mod.fill_vs_baseline({"metric": "m", "value": 60.0,
                                      "vs_baseline": 0.9}, baseline)
    assert rec["vs_baseline"] == 0.9
    # the shipped LADDER.json parses and indexes by metric
    rows = bench_mod.load_ladder_baseline()
    assert "telemetry_span_overhead_ns" in rows


def test_gate_cli_exit_codes(tmp_path, bench_mod):
    """`bench.py --gate --results <file>` is the CI entry point: exit 0 on
    the unchanged ladder, nonzero once a rung degrades past tolerance."""
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    bench_py = os.path.join(root, "bench.py")
    rows = json.load(open(os.path.join(root, "LADDER.json")))
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps(rows))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, bench_py, "--gate",
                        "--results", str(ok)], env=env, timeout=180,
                       capture_output=True, text=True)
    assert r.returncode == 0 and "GATE PASS" in r.stdout
    for row in rows:
        if row["metric"] == "dcn_hierarchical":
            row["value"] = row["value"] * 0.5   # past the 5% byte gate
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(rows))
    r = subprocess.run([sys.executable, bench_py, "--gate",
                        "--results", str(bad)], env=env, timeout=180,
                       capture_output=True, text=True)
    assert r.returncode == 1 and "dcn_hierarchical" in r.stdout


# ---------------------------------------------------------------------------
# satellites: thread-safe JSONL monitor, cached timer sync sentinel
# ---------------------------------------------------------------------------


def test_jsonl_monitor_concurrent_writers(tmp_path):
    from deepspeed_tpu.monitor.monitor import JSONLMonitor

    cfg = SimpleNamespace(enabled=True, output_path=str(tmp_path),
                          job_name="job")
    mon = JSONLMonitor(cfg)
    n_threads, n_batches, batch = 8, 40, 5

    def writer(t):
        for i in range(n_batches):
            mon.write_events([(f"T{t}/m{j}", float(i), i)
                              for j in range(batch)])

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    lines = open(mon.path).read().splitlines()
    assert len(lines) == n_threads * n_batches * batch
    for line in lines:          # every line is a whole JSON event
        doc = json.loads(line)
        assert set(doc) == {"name", "value", "step"}


def test_timer_sync_reuses_one_device_sentinel(monkeypatch):
    import jax

    from deepspeed_tpu.profiling import timer

    monkeypatch.setattr(timer, "_SYNC_SENTINEL", None)
    calls = {"n": 0}
    real_put = jax.device_put

    def counting_put(x, *a, **kw):
        calls["n"] += 1
        return real_put(x, *a, **kw)

    monkeypatch.setattr(jax, "device_put", counting_put)
    for _ in range(5):
        timer._sync()
    assert calls["n"] == 1      # one transfer total, not one per stop()
    assert timer._SYNC_SENTINEL is not None


def test_timer_sync_rebuilds_after_invalid_sentinel(monkeypatch):
    from deepspeed_tpu.profiling import timer

    class Broken:
        def __add__(self, other):
            raise RuntimeError("deleted buffer")

    monkeypatch.setattr(timer, "_SYNC_SENTINEL", Broken())
    timer._sync()               # must not raise; rebuilds the sentinel
    assert not isinstance(timer._SYNC_SENTINEL, Broken)
    (timer._SYNC_SENTINEL + 0).block_until_ready()
