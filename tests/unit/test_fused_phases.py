"""Fused compute-collective phase programs (PR 14).

Coverage: the plan-IR ``via="fused_matmul"`` vocabulary (FusedCompute
bindings, validation, strict serialization), plan-cache format versioning
(the PR 8 stale-cache regression), the fused ring primitives and the
quantized-wire collective matmuls (``ops/collective_matmul.py``), the
``run_collective_program`` fused dispatch (fused-exact BITWISE equals
sequenced-exact; fused-int8_ef tracks flat int8_ef within quantization
tolerance), ledger hop-exposure accounting, per-hop flight-ring stamping,
the graph auditor's per-hop program expansion, and the engine end-to-end
on the simulated DCN mesh.
"""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

import deepspeed_tpu.comm as dist
from deepspeed_tpu.comm.compressed import (bind_fused_tiles,
                                           program_feedback_init,
                                           run_collective_program)
from deepspeed_tpu.comm.planner import (PLAN_FORMAT, CollectivePlanner,
                                        FusedCompute, PhaseStep, Plan,
                                        PlanCache, PlanDecision, make_phase,
                                        make_site, program_summary,
                                        reset_planner, synthesize_programs)
from deepspeed_tpu.ops.collective_matmul import (all_gather_matmul,
                                                 fused_ring_all_gather,
                                                 fused_ring_reduce_scatter,
                                                 matmul_reduce_scatter)
from deepspeed_tpu.parallel import Topology, TopologySpec
from deepspeed_tpu.parallel.topology import set_topology
from deepspeed_tpu.utils.shard_map_compat import shard_map_nocheck as _sm
from tests.conftest import require_devices


@pytest.fixture(autouse=True)
def _reset():
    logger = dist.get_comms_logger()
    logger.configure(enabled=True, prof_all=True)
    logger.reset()
    logger.plan_records.clear()
    reset_planner()
    yield
    logger.configure(enabled=False)
    logger.reset()
    logger.plan_records.clear()
    reset_planner()


def _mesh42():
    return Mesh(np.array(jax.devices()[:8]).reshape(4, 2),
                ("dp_outer", "ep"))


def _run_sharded(fn, x, mesh):
    return np.asarray(jax.jit(_sm(fn, mesh, in_specs=P(),
                                  out_specs=P()))(x))


# ---------------------------------------------------------------------------
# IR: fused vocabulary + validation + strict serialization
# ---------------------------------------------------------------------------


def test_fused_phase_validation_and_roundtrip():
    fc = FusedCompute(role="producer", site="dp-grad/bwd", tile=4096)
    ph = make_phase("reduce_scatter", ("ep",), via="fused_matmul",
                    link="ici", compute=fc)
    assert ph.fused and ph.compute.tag() == "dp-grad/bwd@producer"
    # round-trip preserves the binding
    assert PhaseStep.from_dict(ph.to_dict()) == ph
    # a fused phase REQUIRES a compute binding
    with pytest.raises(ValueError, match="FusedCompute"):
        make_phase("all_gather", ("ep",), via="fused_matmul")
    # and only gather/scatter phases fuse (all_reduce has no tile stream)
    with pytest.raises(ValueError, match="fused_matmul"):
        make_phase("all_reduce", ("ep",), via="fused_matmul", compute=fc)
    # int8_ef rides the all_reduce phase, never a fused hop
    with pytest.raises(ValueError, match="int8_ef"):
        make_phase("all_gather", ("ep",), via="fused_matmul",
                   wire_dtype="int8_ef",
                   compute=FusedCompute(role="consumer"))
    # a non-fused via must not carry a binding
    with pytest.raises(ValueError, match="must not carry"):
        make_phase("all_gather", ("ep",), compute=fc)
    with pytest.raises(ValueError, match="role"):
        FusedCompute(role="bystander")


def test_strict_from_dict_rejects_unknown_fields():
    """Version-skew hardening: unknown fields FAIL the load (the old
    silent-drop could strip the part of a phase that changes what it
    does)."""
    ph = make_phase("all_gather", ("ep",)).to_dict()
    ph["via2"] = "warp"
    with pytest.raises(ValueError, match="unknown PhaseStep"):
        PhaseStep.from_dict(ph)
    with pytest.raises(ValueError, match="unknown FusedCompute"):
        FusedCompute.from_dict({"role": "producer", "warp": 9})
    d = PlanDecision(impl="int8", block=512).to_dict()
    d["impl_v3"] = "x"
    with pytest.raises(ValueError, match="unknown PlanDecision"):
        PlanDecision.from_dict(d)


def test_plan_format_versioning_and_stale_cache(tmp_path):
    """The satellite bugfix: plan_<digest>.json format skew can never
    resolve into an executor that doesn't understand it. An unstamped
    PR 8 file migrates (its vocabulary is a strict subset); a file
    stamped with a NEWER format reads as a miss; a file whose phases
    carry unknown fields reads as a miss."""
    set_topology(Topology(TopologySpec(ep=2)))
    planner = CollectivePlanner("static", cache_dir=str(tmp_path),
                                dcn_axes=["dp_outer"])
    fp = planner.fingerprint
    cache = PlanCache(str(tmp_path))
    path = cache.path_for(fp)
    sig = "dp-grad:all_reduce:1024:float32@dp_outer,ep"
    v1_body = {  # hand-written PR 8 format: no "format" stamp
        "fingerprint": fp.digest(), "mesh": fp.to_dict(),
        "sites": {sig: {"impl": "program", "block": 2048,
                        "source": "measured", "est_us": 10.0,
                        "program": [
                            {"phase_op": "reduce_scatter", "axes": ["ep"],
                             "link": "ici"},
                            {"phase_op": "all_reduce", "axes": ["dp_outer"],
                             "wire_dtype": "int8_ef", "block": 2048,
                             "link": "dcn"},
                            {"phase_op": "all_gather", "axes": ["ep"],
                             "link": "ici"}]}}}
    os.makedirs(str(tmp_path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(v1_body, f)
    loaded = cache.load(fp)
    assert loaded is not None and sig in loaded.decisions  # migrated
    assert loaded.decisions[sig].program[1].wire_dtype == "int8_ef"
    # re-store stamps the current format
    cache.store(fp, loaded)
    assert json.load(open(path))["format"] == PLAN_FORMAT

    # a future-format file is rejected outright
    future = dict(v1_body)
    future["format"] = PLAN_FORMAT + 1
    with open(path, "w") as f:
        json.dump(future, f)
    assert cache.load(fp) is None
    with pytest.raises(ValueError, match="newer"):
        Plan.from_dict(future)

    # unknown phase fields (skewed vocabulary) read as a miss, and a
    # fresh planner quietly re-tunes instead of running a mystery plan
    skewed = dict(v1_body)
    skewed["sites"] = {sig: {"impl": "program", "program": [
        {"phase_op": "all_gather", "axes": ["ep"], "via": "fused_matmul",
         "compute": {"role": "consumer"}, "hyperdrive": True}]}}
    with open(path, "w") as f:
        json.dump(skewed, f)
    assert cache.load(fp) is None
    p2 = CollectivePlanner("static", cache_dir=str(tmp_path),
                           dcn_axes=["dp_outer"])
    d = p2.resolve(make_site(op="all_reduce", shape=(1 << 20,),
                             dtype="float32", axes=("dp_outer", "ep"),
                             consumer="dp-grad"))
    assert d.source == "cost-model"  # miss -> re-planned, not loaded


def test_fused_program_summary_and_cache_roundtrip(tmp_path):
    """A fused program decision survives the disk cache byte-faithfully,
    compute bindings included."""
    set_topology(Topology(TopologySpec(ep=2)))
    a = CollectivePlanner("static", cache_dir=str(tmp_path),
                          dcn_axes=["dp_outer"])
    site = make_site(op="all_reduce", shape=(1 << 22,), dtype="float32",
                     axes=("dp_outer", "ep"), consumer="dp-grad")
    da = a.resolve(site)
    assert da.impl == "program"
    assert [s.via for s in da.program] == ["fused_matmul", "xla",
                                           "fused_matmul"]
    assert "~fused_matmul" in program_summary(da.program)
    b = CollectivePlanner("static", cache_dir=str(tmp_path),
                          dcn_axes=["dp_outer"])
    db = b.resolve(site)
    assert db.source == "cache" and db.program == da.program
    assert db.program[0].compute == da.program[0].compute


# ---------------------------------------------------------------------------
# fused ring primitives + quantized-wire collective matmul
# ---------------------------------------------------------------------------


@require_devices(8)
def test_fused_ring_all_gather_exact_bitwise_and_int8_close():
    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))
    n = 8 * 640
    x = jnp.linspace(-2.0, 2.0, n, dtype=jnp.float32)

    def exact(v):
        local = lax.dynamic_slice_in_dim(
            v, lax.axis_index("dp") * (n // 8), n // 8)
        return fused_ring_all_gather(local, "dp")

    def ref(v):
        local = lax.dynamic_slice_in_dim(
            v, lax.axis_index("dp") * (n // 8), n // 8)
        return lax.all_gather(local, "dp", axis=0, tiled=True)

    got = _run_sharded(exact, x, mesh)
    want = _run_sharded(ref, x, mesh)
    np.testing.assert_array_equal(got, want)  # data movement: bitwise

    def quant(v):
        local = lax.dynamic_slice_in_dim(
            v, lax.axis_index("dp") * (n // 8), n // 8)
        return fused_ring_all_gather(local, "dp", wire_dtype="int8",
                                     block=128)

    got_q = _run_sharded(quant, x, mesh)
    assert np.abs(got_q - want).max() <= np.abs(want).max() / 127 + 1e-6


@require_devices(8)
def test_fused_ring_reduce_scatter_exact_and_int8():
    """Exact wire: same reduction tree as the sequenced ring (bitwise on a
    2-rank axis, where addition order is commutative-identical to ANY
    implementation); int8 wire: within per-hop quantization tolerance."""
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2),
                ("dp_outer", "ep"))
    n = 2 * 512
    x = jnp.linspace(-1.0, 1.0, n, dtype=jnp.float32)

    def fused(v):
        s = fused_ring_reduce_scatter(v, "ep")
        return lax.all_gather(s, "ep", axis=0, tiled=True)  # replicate back

    def ref(v):
        s = lax.psum_scatter(v, "ep", scatter_dimension=0, tiled=True)
        return lax.all_gather(s, "ep", axis=0, tiled=True)

    got = _run_sharded(fused, x, mesh)
    want = _run_sharded(ref, x, mesh)
    np.testing.assert_array_equal(got, want)

    def quant(v):
        s = fused_ring_reduce_scatter(v, "ep", wire_dtype="int8", block=128)
        return lax.all_gather(s, "ep", axis=0, tiled=True)

    got_q = _run_sharded(quant, x, mesh)
    assert np.abs(got_q - want).max() <= 2 * np.abs(want).max() / 127 + 1e-6


@require_devices(8)
def test_fused_ring_gather_ste_backward_is_exact_transpose():
    """The STE contract: d/dx of sum(fused_gather(x)) is the exact gather
    transpose (all-ones back through the sum reduce-scatter), whatever
    the wire dtype."""
    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))
    m = 256
    x = jnp.linspace(0.1, 1.0, 8 * m, dtype=jnp.float32)

    def grad_of(wire):
        def f(v):
            local = lax.dynamic_slice_in_dim(
                v, lax.axis_index("dp") * m, m)
            g = jax.grad(lambda l: jnp.sum(
                fused_ring_all_gather(l, "dp", wire_dtype=wire,
                                      block=128)))(local)
            return jnp.tile(g, 8)

        return _run_sharded(f, x, mesh)

    # every element of the gathered output consumes each shard element
    # exactly once per rank -> the summed cotangent is p (8) everywhere
    for wire in ("exact", "int8"):
        g = grad_of(wire)
        np.testing.assert_allclose(g, 8.0)


@require_devices(8)
def test_quantized_wire_collective_matmul_close_and_differentiable():
    """The generalized kernels: all_gather_matmul / matmul_reduce_scatter
    with an int8 wire track their exact twins within quantization
    tolerance, and the straight-through backward runs (exact dual)."""
    mesh = Mesh(np.array(jax.devices()[:8]), ("tp",))
    rng = np.random.default_rng(3)
    xs = jnp.asarray(rng.normal(size=(8 * 16, 32)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(32, 24)) * 0.2, jnp.float32)

    def agmm(wire):
        def f(v):
            local = lax.dynamic_slice_in_dim(
                v, lax.axis_index("tp") * 16, 16, axis=0)
            return all_gather_matmul(local, w, "tp", wire_dtype=wire,
                                     block=128)

        return _run_sharded(f, xs, mesh)

    exact, quant = agmm("exact"), agmm("int8")
    scale = np.abs(np.asarray(xs)).max() / 127
    assert np.abs(quant - exact).max() <= scale * np.abs(np.asarray(w)).sum(0).max() + 1e-5

    def mmrs(wire):
        def f(v):
            out = matmul_reduce_scatter(v, w, "tp", wire_dtype=wire,
                                        block=128)
            return lax.all_gather(out, "tp", axis=0, tiled=True)

        return _run_sharded(f, xs, mesh)

    exact_rs, quant_rs = mmrs("exact"), mmrs("int8")
    assert np.abs(quant_rs - exact_rs).max() <= \
        8 * np.abs(exact_rs).max() / 127 + 1e-4

    def grads(v):
        def loss(v_):
            local = lax.dynamic_slice_in_dim(
                v_, lax.axis_index("tp") * 16, 16, axis=0)
            y = all_gather_matmul(local, w, "tp", wire_dtype="int8",
                                  block=128)
            return jnp.sum(y ** 2)

        return lax.psum(jax.grad(loss)(v), "tp")

    g = _run_sharded(grads, xs, mesh)
    assert np.isfinite(g).all()


# ---------------------------------------------------------------------------
# executor: fused programs through run_collective_program
# ---------------------------------------------------------------------------


def _programs(block=512):
    seq = (make_phase("reduce_scatter", ("ep",), link="ici"),
           make_phase("all_reduce", ("dp_outer",), wire_dtype="int8_ef",
                      block=block, link="dcn"),
           make_phase("all_gather", ("ep",), link="ici"))
    fused = (make_phase("reduce_scatter", ("ep",), via="fused_matmul",
                        link="ici",
                        compute=FusedCompute(role="producer",
                                             site="dp-grad/bwd")),
             make_phase("all_reduce", ("dp_outer",), wire_dtype="int8_ef",
                        block=block, link="dcn"),
             make_phase("all_gather", ("ep",), via="fused_matmul",
                        link="ici",
                        compute=FusedCompute(role="consumer",
                                             site="dp-grad/apply")))
    return seq, fused


def _exact(prog):
    return tuple(dataclasses.replace(s, wire_dtype="exact", block=None)
                 for s in prog)


@require_devices(8)
def test_fused_exact_program_bitwise_equals_sequenced_exact():
    """THE parity acceptance criterion: on the t3 mesh (ep=2 inner) the
    fused-exact program is bit-identical to the sequenced exact program —
    the fused ring reshuffles only WHEN chunks move, never what is
    added to what."""
    mesh = _mesh42()
    seq, fused = _programs()
    n = 5000
    x = jnp.linspace(-1.0, 1.0, n, dtype=jnp.float32)

    def runner(prog):
        def f(v):
            out, _ = run_collective_program(v, prog)
            return out

        return _run_sharded(f, x, mesh)

    a = runner(_exact(seq))
    b = runner(_exact(fused))
    np.testing.assert_array_equal(a, b)
    # and both are the true mean (identical replicas -> identity)
    np.testing.assert_allclose(a, np.asarray(x), atol=1e-6)


@require_devices(8)
def test_fused_int8_ef_program_matches_flat_and_carries_residual():
    """Quantized parity: the fused program with the int8_ef DCN hop lands
    within quantization tolerance of the FLAT int8_ef all-reduce, and its
    error-feedback residual comes back non-zero (the carry exists) with
    the same layout the sequenced program allocates."""
    from deepspeed_tpu.comm.compressed import quantized_all_reduce

    mesh = _mesh42()
    seq, fused = _programs()
    n = 4096
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(n,)), jnp.float32)
    sizes = dict(mesh.shape)
    fb_seq = program_feedback_init(n, seq, sizes)
    fb_fused = program_feedback_init(n, fused, sizes)
    assert fb_seq is not None and fb_fused is not None
    assert fb_seq.worker_error.shape == fb_fused.worker_error.shape

    def run_prog(prog, fb):
        def f(v, w, s):
            out, nfb = run_collective_program(v, prog,
                                              feedback=type(fb)(w, s))
            # per-rank residuals differ (each ep shard quantizes its own
            # slice): reduce to a replicated magnitude for the assertion
            resid = lax.pmax(jnp.max(jnp.abs(nfb.worker_error)),
                             ("dp_outer", "ep"))
            return out, jnp.broadcast_to(resid, (1,))

        fn = _sm(f, mesh, in_specs=(P(), P(), P()), out_specs=(P(), P()))
        return jax.jit(fn)(x, fb.worker_error, fb.server_error)

    out_f, resid_f = run_prog(fused, fb_fused)
    out_s, resid_s = run_prog(seq, fb_seq)
    # both programs: exact ICI phases, identical DCN hop -> bitwise equal
    np.testing.assert_array_equal(np.asarray(out_f), np.asarray(out_s))
    assert float(resid_f[0]) > 0  # the residual carry exists

    def flat(v):
        return quantized_all_reduce(v, ("dp_outer", "ep"), block=512)

    out_flat = _run_sharded(flat, x, mesh)
    tol = 3 * np.abs(np.asarray(x)).max() / 127 + 1e-5
    assert np.abs(np.asarray(out_f) - out_flat).max() <= tol


@require_devices(8)
def test_bind_fused_tiles_stamps_real_chunk_sizes():
    mesh = _mesh42()
    _, fused = _programs()
    n = 5000
    bound = bind_fused_tiles(fused, n, dict(mesh.shape))
    # rs over ep=2: payload pads to the 2*128 quantum -> 5120, shard 2560
    assert bound[0].compute.tile == 2560
    # ag circulates its input shard (the post-rs width)
    assert bound[2].compute.tile == 2560
    assert bound[1] == fused[1]  # non-fused phases untouched
    # idempotent on a fused-free program
    seq, _ = _programs()
    assert bind_fused_tiles(seq, n, dict(mesh.shape)) == tuple(seq)


@require_devices(8)
def test_fused_phases_ledger_hidden_buckets_and_flight_stamps():
    """Fused phases: wire bytes land in the hop bucket AND the hidden
    bucket; the flight ring gets one impl="fused_matmul" record per hop
    with the compute tag + hop index in detail."""
    from deepspeed_tpu.telemetry import (configure_collective_recorder,
                                         get_collective_recorder)

    mesh = _mesh42()
    _, fused = _programs()
    fused = bind_fused_tiles(fused, 4096, dict(mesh.shape))
    configure_collective_recorder(enabled=True)
    get_collective_recorder().clear()
    try:
        x = jnp.linspace(-1, 1, 4096, dtype=jnp.float32)

        def f(v):
            return run_collective_program(v, fused)[0]

        jax.jit(_sm(f, mesh, in_specs=P(), out_specs=P())).lower(x)
        recs = get_collective_recorder().snapshot()
    finally:
        configure_collective_recorder(enabled=False)
        get_collective_recorder().clear()
    fused_recs = [r for r in recs if r.get("impl") == "fused_matmul"]
    # ep=2 -> 1 hop per fused phase, 2 fused phases
    assert len(fused_recs) == 2
    assert {r["op"] for r in fused_recs} == {"fused_ring_reduce_scatter",
                                             "fused_ring_all_gather"}
    assert all("hop1/1" in r["detail"] for r in fused_recs)
    assert any("dp-grad/bwd@producer" in r["detail"] for r in fused_recs)
    assert any("dp-grad/apply@consumer" in r["detail"] for r in fused_recs)

    expo = dist.get_comms_logger().hop_exposure()
    assert expo["ici"]["hidden"] == expo["ici"]["wire"] > 0
    assert expo["ici"]["exposed"] == 0
    assert expo["dcn"]["hidden"] == 0 and expo["dcn"]["exposed"] > 0


# ---------------------------------------------------------------------------
# graph auditor: per-hop reconciliation of fused plans
# ---------------------------------------------------------------------------


@require_devices(8)
def test_auditor_reconciles_fused_plan_per_hop():
    """Satellite contract: the interleaved ppermutes a fused PhaseStep
    emits reconcile against the plan table's EXPANDED program (per hop) —
    zero unplanned collectives, both with and without the jaxpr's help."""
    from deepspeed_tpu.analysis.auditor import (audit_compiled_text,
                                                audit_step,
                                                plan_expected_sites)
    from deepspeed_tpu.comm.planner import configure_planner

    set_topology(Topology(TopologySpec(ep=2)))
    logger = dist.get_comms_logger()
    planner = configure_planner("static", use_cache=False,
                                dcn_axes=["dp_outer"])
    n = 1 << 20
    d = planner.resolve(make_site(op="all_reduce", shape=(n,),
                                  dtype="float32",
                                  axes=("dp_outer", "ep"),
                                  consumer="dp-grad"))
    assert any(s.via == "fused_matmul" for s in d.program)
    rec = next(r for r in logger.plan_records.values()
               if r.get("consumer") == "dp-grad")
    assert rec.get("program_phases")  # the structured expansion rides along

    mesh = _mesh42()
    x = jnp.linspace(-1, 1, n, dtype=jnp.float32)

    def f(v):
        return run_collective_program(v, d.program)[0]

    fn = _sm(f, mesh, in_specs=P(), out_specs=P())
    rep = audit_step(fn, x, axis_sizes=dict(mesh.shape),
                     plan_records=logger.plan_records, ledger=logger)
    assert rep.context["unplanned_collectives"] == 0
    assert rep.context["matched_collectives"] == rep.context["hlo_collectives"] > 0

    # plan-table-only reconciliation (no jaxpr): the per-hop expansion is
    # what matches the interleaved collective-permutes
    text = jax.jit(fn).lower(x).compile().as_text()
    expected = plan_expected_sites(logger.plan_records, dict(mesh.shape))
    assert any(e.kind == "collective_permute" and "#hops=" in e.detail
               for e in expected)
    rep2 = audit_compiled_text(text, expected=expected,
                               axis_sizes=dict(mesh.shape))
    assert rep2.context["unplanned_collectives"] == 0


# ---------------------------------------------------------------------------
# planner: fused synthesis wins on the DCN mesh, cost ordering
# ---------------------------------------------------------------------------


def test_fused_program_wins_on_dcn_mesh_and_fused_zeropp_regime():
    from deepspeed_tpu.comm.planner import CostModel, MeshFingerprint

    fp = MeshFingerprint(platform="tpu", device_kind="TPU v4", n_devices=16,
                         n_processes=2,
                         axis_sizes=(("pp", 1), ("dp_outer", 8), ("ep", 2),
                                     ("sp", 1), ("tp", 1)),
                         dcn_axes=("dp_outer",))
    cm = CostModel(fp)
    site = make_site(op="all_reduce", shape=(1 << 22,), dtype="float32",
                     axes=("dp_outer", "ep"), consumer="dp-grad")
    progs = synthesize_programs(site, cm)
    assert len(progs) == 5
    ranked = sorted(progs, key=lambda p: cm.estimate_program(site, p))
    # the fused-hierarchical int8-outer program is the argmin: it keeps
    # the sequenced winner's wire bytes and hides the ICI hops
    assert ranked[0][0].via == "fused_matmul"
    assert ranked[0][1].wire_dtype == "int8_ef"
    seq_best = min(cm.estimate_program(site, p) for p in progs[:3])
    assert cm.estimate_program(site, ranked[0]) < seq_best

    # zeropp regime split on a cross-slice dp axis: fused wins the big
    # bandwidth-bound messages, exact transports keep the tiny ones
    zfp = MeshFingerprint(platform="tpu", device_kind="TPU v4", n_devices=8,
                          n_processes=2, axis_sizes=(("dp", 8),),
                          dcn_axes=("dp",))
    zcm = CostModel(zfp)
    big = make_site(op="all_gather", shape=(1 << 22,), dtype="float32",
                    axes=("dp",), consumer="zeropp", axis_size=8)
    tiny = make_site(op="all_gather", shape=(256,), dtype="float32",
                     axes=("dp",), consumer="zeropp", axis_size=8)
    assert zcm.decide(big).impl == "fused_matmul"
    assert zcm.decide(big).block is not None  # int8 wire needs a block
    assert zcm.decide(tiny).impl != "fused_matmul"


def test_dcn_axes_keeps_foreign_mesh_axes():
    """``comm_planner.dcn_axes`` naming an axis outside the fleet mesh is
    KEPT (with a warning), not dropped: it marks foreign-mesh sites — the
    zeropp factory's own ``dp`` axis — as cross-slice, which is how the
    qwZ/qgZ sites reach the fused/quantized regime on a dev box."""
    set_topology(Topology(TopologySpec()))
    p = CollectivePlanner("static", use_cache=False, dcn_axes=["dp"])
    assert "dp" in p.fingerprint.dcn_axes
    # the foreign axis re-keys the cache identity like any forced axis
    q = CollectivePlanner("static", use_cache=False)
    assert p.fingerprint.digest() != q.fingerprint.digest()
    # and a zeropp-style foreign-mesh site now prices its link as DCN:
    # flat exact transports lose to a quantized arm at bandwidth-bound
    # sizes (the ring family would win on an ICI-class link)
    big = make_site(op="all_gather", shape=(1 << 22,), dtype="float32",
                    axes=("dp",), consumer="zeropp", axis_size=8)
    assert p.cost.decide(big).impl == "fused_matmul"


@require_devices(8)
def test_zeropp_fused_gather_scatter_end_to_end(monkeypatch):
    """The qwZ/qgZ fused wiring: force the planner's zeropp resolution to
    fused_matmul and train — the factory maps it onto the fused rings,
    the step runs, the loss is finite and tracks the exact run."""
    import optax

    from deepspeed_tpu.comm.planner import configure_planner
    from deepspeed_tpu.runtime.zero.zeropp import zeropp_train_step_factory

    rng = np.random.default_rng(0)
    params = {"w1": jnp.asarray(rng.normal(size=(32, 16)) * 0.3,
                                jnp.float32),
              "w2": jnp.asarray(rng.normal(size=(16, 8)) * 0.3, jnp.float32)}

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((jnp.tanh(x @ p["w1"]) @ p["w2"] - y) ** 2)

    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))
    x = jnp.asarray(rng.normal(size=(8, 32)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(8, 8)), jnp.float32)

    # exact reference (no planner, explicit exact knobs)
    reset_planner()
    init_e, step_e, _ = zeropp_train_step_factory(
        loss_fn, optax.sgd(1e-2), mesh, dp_axis="dp",
        quantized_weights=False, quantized_gradients=False)
    st_e = init_e(jax.tree.map(jnp.copy, params))
    st_e, loss_e = step_e(st_e, (x, y))

    # planner resolving both zeropp sites to fused_matmul
    planner = configure_planner("static", use_cache=False)
    import deepspeed_tpu.comm.planner.planner as planner_mod

    real_resolve = planner.resolve

    def force_fused(site):
        if site.consumer == "zeropp":
            return PlanDecision(impl="fused_matmul", block=128,
                                source="measured", est_us=1.0)
        return real_resolve(site)

    monkeypatch.setattr(planner, "resolve", force_fused)
    init_f, step_f, _ = zeropp_train_step_factory(
        loss_fn, optax.sgd(1e-2), mesh, dp_axis="dp")
    st_f = init_f(jax.tree.map(jnp.copy, params))
    st_f, loss_f = step_f(st_f, (x, y))
    assert np.isfinite(float(loss_f))
    assert abs(float(loss_f) - float(loss_e)) < 0.05 * abs(float(loss_e)) + 1e-3
    # the fused rings actually ran: their ledger ops are present
    tot = dist.get_comms_logger().totals()
    assert "fused_ring_all_gather" in tot
    assert "fused_ring_reduce_scatter" in tot
