"""The shared jaxpr walker (analysis/jaxpr_walk.py) and the two retrofits.

Covers the sub-jaxpr shapes the three pre-unification walkers each handled
differently (and partially): scan with trip-count multipliers, remat
nested in pjit, custom_vjp bwd programs under grad, and jaxpr Literal
invars (the unhashable-constant case the old auto_tp noted inline).  Plus
regression proofs that the retrofitted FLOPs profiler and AutoTP
classifier produce the same numbers the pre-unification code did.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.analysis import jaxpr_walk as jw
from deepspeed_tpu.profiling.flops_profiler import count_flops

# ---------------------------------------------------------------------------
# subjaxprs enumeration
# ---------------------------------------------------------------------------


def test_pjit_subjaxpr_aligned():
    def inner(x):
        return x * 2.0

    def outer(x):
        return jax.jit(inner)(x) + 1.0

    closed = jax.make_jaxpr(outer)(jnp.ones((4,)))
    pjit_eqns = [e for e in closed.jaxpr.eqns if jw.subjaxprs(e)]
    assert pjit_eqns
    sub = jw.subjaxprs(pjit_eqns[0])[0]
    assert sub.invars is not None and sub.outvars is not None
    assert sub.mult == 1
    assert len(sub.invars) == len(sub.jaxpr.invars)


def test_scan_subjaxpr_mult_and_unaligned():
    def f(x):
        def body(c, _):
            return c * 1.5, c
        return jax.lax.scan(body, x, None, length=7)

    closed = jax.make_jaxpr(f)(jnp.ones((3,)))
    scan_eqn = next(e for e in closed.jaxpr.eqns
                    if e.primitive.name == "scan")
    (sub,) = jw.subjaxprs(scan_eqn)
    assert sub.mult == 7
    assert sub.tag == "scan"
    assert sub.invars is None  # consts/carries/slices: no 1:1 mapping


def test_cond_subjaxpr_branches():
    def f(x):
        return jax.lax.cond(x.sum() > 0, lambda v: v * 2, lambda v: v - 1, x)

    closed = jax.make_jaxpr(f)(jnp.ones((3,)))
    cond_eqn = next(e for e in closed.jaxpr.eqns
                    if e.primitive.name == "cond")
    subs = jw.subjaxprs(cond_eqn)
    assert len(subs) == 2 and all(s.tag == "cond" for s in subs)


def test_while_subjaxpr_includes_body_and_cond():
    def f(x):
        return jax.lax.while_loop(lambda c: c[0] < 5,
                                  lambda c: (c[0] + 1, c[1] * 2.0), (0, x))

    closed = jax.make_jaxpr(f)(jnp.ones((3,)))
    w = next(e for e in closed.jaxpr.eqns if e.primitive.name == "while")
    subs = jw.subjaxprs(w)
    assert len(subs) == 2  # body + predicate (the auditor wants both)


def test_leaf_primitive_has_no_subjaxprs():
    closed = jax.make_jaxpr(lambda x: x + 1.0)(jnp.ones((2,)))
    for eqn in closed.jaxpr.eqns:
        assert jw.subjaxprs(eqn) == []


# ---------------------------------------------------------------------------
# walk: scope + multiplier threading, HANDLED protocol
# ---------------------------------------------------------------------------


def test_walk_threads_scan_multiplier():
    def f(x):
        def body(c, _):
            return c @ jnp.ones((4, 4)), None
        out, _ = jax.lax.scan(body, x, None, length=5)
        return out

    closed = jax.make_jaxpr(f)(jnp.ones((2, 4)))
    mults = []
    jw.walk(closed.jaxpr,
            lambda e, c: mults.append(c.mult)
            if e.primitive.name == "dot_general" else None)
    assert mults == [5]


def test_walk_handled_stops_recursion():
    def f(x):
        def body(c, _):
            return c * 2.0, None
        out, _ = jax.lax.scan(body, x, None, length=3)
        return out

    closed = jax.make_jaxpr(f)(jnp.ones((2,)))
    seen = []

    def visit(eqn, ctx):
        seen.append(eqn.primitive.name)
        if eqn.primitive.name == "scan":
            return jw.HANDLED

    jw.walk(closed.jaxpr, visit)
    assert "scan" in seen and "mul" not in seen


def test_literal_invars_are_tag_free():
    # x + 1.0 carries a Literal invar: unhashable, must not be treated as
    # a Var (the case noted at the old auto_tp.py:165)
    closed = jax.make_jaxpr(lambda x: x + 1.0)(jnp.ones((2,)))
    add = closed.jaxpr.eqns[-1]
    kinds = [jw.is_var(v) for v in add.invars]
    assert False in kinds  # the literal
    assert jw.literal_value(add.invars[kinds.index(False)]) is not None
    # and consumers tracking skips literals without raising
    jw.collect_consumers(closed.jaxpr)


# ---------------------------------------------------------------------------
# FLOPs profiler on the shared walker: edge-case counts stay analytic
# ---------------------------------------------------------------------------


def test_flops_scan_trip_count_multiplies():
    m, k, n, length = 8, 16, 4, 6

    def f(x, w):
        def body(c, _):
            return jnp.tanh(c @ w), None
        out, _ = jax.lax.scan(body, x, None, length=length)
        return out

    total, scopes = count_flops(f, jnp.ones((m, k)), jnp.ones((k, k)))
    dot = 2 * m * k * k
    tanh = m * k
    assert total == length * (dot + tanh)
    assert any(s.endswith("scan") or "scan" in s for s in scopes)


def test_flops_remat_in_pjit():
    m, k, n = 4, 8, 2

    def inner(x, w):
        return jax.checkpoint(lambda a: jnp.tanh(a @ w))(x)

    def f(x, w):
        return jax.jit(inner)(x, w).sum()

    total, _ = count_flops(f, jnp.ones((m, k)), jnp.ones((k, n)))
    # remat body counted once under the pjit: dot + tanh + final reduce
    assert total == 2 * m * k * n + m * n + m * n


def test_flops_custom_vjp_bwd_jaxpr():
    k = 16

    @jax.custom_vjp
    def f(x, w):
        return x @ w

    def fwd(x, w):
        return x @ w, (x, w)

    def bwd(res, g):
        x, w = res
        return g @ w.T, x.T @ g

    f.defvjp(fwd, bwd)

    x, w = jnp.ones((4, k)), jnp.ones((k, 8))
    fwd_only, _ = count_flops(lambda a, b: f(a, b).sum(), x, w)
    with_grad, _ = count_flops(
        lambda a, b: jax.grad(lambda p, q: f(p, q).sum())(a, b).sum(), x, w)
    # the bwd program holds two more matmuls — the walker must descend
    # into the custom_vjp bwd jaxpr to see them
    assert with_grad > fwd_only + 2 * 2 * 4 * k * 8 - 1


def test_flops_cond_counts_max_branch_only():
    m, k, n = 8, 32, 8

    def f(x, w):
        return jax.lax.cond(x.sum() > 0,
                            lambda: (x @ w).sum(),   # expensive branch
                            lambda: x.sum())

    total, _ = count_flops(f, jnp.ones((m, k)), jnp.ones((k, n)))
    dot = 2 * m * k * n
    assert total >= dot          # the matmul branch is in
    assert total < dot + 3 * m * k  # not both branches double-counted


def test_flops_while_counts_one_iteration():
    def f(x):
        return jax.lax.while_loop(
            lambda c: c[0] < 10,
            lambda c: (c[0] + 1, jnp.tanh(c[1] @ jnp.eye(4))), (0, x))

    total, _ = count_flops(f, jnp.ones((4, 4)))
    dot = 2 * 4 * 4 * 4
    # one body iteration, not ten; predicate never counted
    assert dot <= total <= dot + 64


# ---------------------------------------------------------------------------
# AutoTP on the shared walker: classification regression
# ---------------------------------------------------------------------------


def test_auto_tp_classification_unchanged():
    from deepspeed_tpu.module_inject.auto_tp import infer_tp_roles

    params = {"up": jnp.ones((16, 64)), "down": jnp.ones((64, 16))}

    def apply_fn(p, x):
        h = jnp.maximum(x @ p["up"], 0.0)
        return h @ p["down"]

    roles = infer_tp_roles(apply_fn, params, jnp.ones((4, 16)))
    assert roles["up"] == ("col", 1)
    assert roles["down"] == ("row", 0)


def test_auto_tp_through_jit_boundary():
    # tags must cross an aligned pjit boundary (the shared _sub path)
    from deepspeed_tpu.module_inject.auto_tp import infer_tp_roles

    params = {"up": jnp.ones((16, 64)), "down": jnp.ones((64, 16))}

    def apply_fn(p, x):
        h = jax.jit(lambda a: jnp.maximum(a @ p["up"], 0.0))(x)
        return h @ p["down"]

    roles = infer_tp_roles(apply_fn, params, jnp.ones((4, 16)))
    assert roles.get("up") == ("col", 1)
    assert roles.get("down") == ("row", 0)


def test_auto_tp_literal_operands_ride_along():
    # Literal invars (inline Python constants) between the paired matmuls
    # must neither crash the walk nor break the tag flow
    from deepspeed_tpu.module_inject.auto_tp import infer_tp_roles

    params = {"up": jnp.ones((8, 32)), "down": jnp.ones((32, 8))}

    def apply_fn(p, x):
        h = (x @ p["up"]) * 0.125 + 1.0
        return h @ p["down"]

    roles = infer_tp_roles(apply_fn, params, jnp.ones((2, 8)))
    assert roles.get("up") == ("col", 1)
    assert roles.get("down") == ("row", 0)
