import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.lr_schedules import (build_lr_schedule, one_cycle, warmup_cosine_lr,
                                                warmup_decay_lr, warmup_lr)
from deepspeed_tpu.runtime.loss_scaler import (LossScaleState, has_overflow,
                                               make_loss_scale_state, update_loss_scale)


def steps(n):
    return jnp.arange(1, n + 1)


def test_warmup_lr_linear():
    s = warmup_lr(warmup_min_lr=0.0, warmup_max_lr=1.0, warmup_num_steps=10, warmup_type="linear")
    lrs = np.asarray(s(steps(20)))
    np.testing.assert_allclose(lrs[4], 0.5, atol=1e-6)
    np.testing.assert_allclose(lrs[10:], 1.0)
    assert np.all(np.diff(lrs[:10]) >= 0)


def test_warmup_decay_hits_zero():
    s = warmup_decay_lr(total_num_steps=100, warmup_max_lr=1.0, warmup_num_steps=10,
                        warmup_type="linear")
    lrs = np.asarray(s(steps(100)))
    assert lrs.max() <= 1.0 + 1e-6
    np.testing.assert_allclose(lrs[-1], 0.0, atol=2e-2)


def test_warmup_cosine():
    s = warmup_cosine_lr(total_num_steps=100, warmup_num_steps=10, base_lr=2.0)
    lrs = np.asarray(s(steps(100)))
    assert lrs[9] <= 2.0 + 1e-5
    assert lrs[-1] < 0.01


def test_one_cycle_shape():
    s = one_cycle(cycle_min_lr=0.1, cycle_max_lr=1.0, cycle_first_step_size=10)
    lrs = np.asarray(s(steps(30)))
    peak = np.argmax(lrs)
    assert 8 <= peak <= 11
    np.testing.assert_allclose(lrs.max(), 1.0, atol=0.05)


def test_build_unknown_raises():
    with pytest.raises(ValueError):
        build_lr_schedule("Bogus", {})


def test_loss_scaler_overflow_backoff():
    st = make_loss_scale_state(initial_scale_power=4, hysteresis=1)
    assert float(st.scale) == 16.0
    st = update_loss_scale(st, jnp.asarray(True), min_scale=1.0, max_hysteresis=1)
    assert float(st.scale) == 8.0


def test_loss_scaler_hysteresis():
    st = make_loss_scale_state(initial_scale_power=4, hysteresis=2)
    st = update_loss_scale(st, jnp.asarray(True), max_hysteresis=2)
    assert float(st.scale) == 16.0 and int(st.hysteresis) == 1  # tolerated
    st = update_loss_scale(st, jnp.asarray(True), max_hysteresis=2)
    assert float(st.scale) == 8.0  # now backed off


def test_loss_scaler_growth():
    st = make_loss_scale_state(initial_scale_power=2, hysteresis=1)
    for _ in range(4):
        st = update_loss_scale(st, jnp.asarray(False), scale_window=2, max_hysteresis=1)
    assert float(st.scale) == 16.0  # grew twice: 4 -> 8 -> 16


def test_has_overflow():
    good = {"a": jnp.ones((3,)), "b": jnp.zeros((2,))}
    bad = {"a": jnp.asarray([1.0, jnp.inf]), "b": jnp.zeros((2,))}
    assert not bool(has_overflow(good))
    assert bool(has_overflow(bad))
