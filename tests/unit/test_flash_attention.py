"""Pallas flash attention parity vs jnp reference (interpret mode on CPU)
— the analogue of reference tests/unit/ops golden tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.transformer import attention_core
from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

B, S, H, D = 2, 256, 4, 64


def _rand(shape, seed):
    return jnp.asarray(np.random.default_rng(seed).normal(size=shape), jnp.float32)


@pytest.mark.parametrize("causal", [True, False])
def test_forward_parity(causal):
    q, k, v = _rand((B, S, H, D), 0), _rand((B, S, H, D), 1), _rand((B, S, H, D), 2)
    ref = attention_core(q, k, v, causal=causal, impl="xla")
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_forward_multi_block():
    q, k, v = _rand((1, 512, 2, 32), 3), _rand((1, 512, 2, 32), 4), _rand((1, 512, 2, 32), 5)
    ref = attention_core(q, k, v, causal=True, impl="xla")
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_gqa_forward():
    q = _rand((B, S, 8, 32), 6)
    k, v = _rand((B, S, 2, 32), 7), _rand((B, S, 2, 32), 8)
    ref = attention_core(q, k, v, causal=True, impl="xla")
    out = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_backward_parity(causal):
    q, k, v = _rand((1, 128, 2, 32), 9), _rand((1, 128, 2, 32), 10), _rand((1, 128, 2, 32), 11)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, block_q=64, block_k=64) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_core(q, k, v, causal=causal, impl="xla") ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), rtol=5e-4, atol=5e-4,
                                   err_msg=f"grad mismatch for {name}")


def test_bf16_forward():
    q, k, v = (x.astype(jnp.bfloat16) for x in
               (_rand((1, 128, 2, 64), 12), _rand((1, 128, 2, 64), 13), _rand((1, 128, 2, 64), 14)))
    ref = attention_core(q, k, v, causal=True, impl="xla")
    out = flash_attention(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref, np.float32),
                               rtol=3e-2, atol=3e-2)


def test_indivisible_seq_raises():
    q = k = v = _rand((1, 100, 2, 32), 15)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=64, block_k=64)


def test_model_attn_impl_flash():
    """TransformerLM with attn_impl='flash' runs and matches xla impl."""
    from deepspeed_tpu.models.transformer import TransformerConfig, TransformerLM, init_params

    kw = dict(vocab_size=64, hidden_size=64, intermediate_size=96, num_layers=1,
              num_heads=4, max_seq_len=128, dtype=jnp.float32)
    m_x = TransformerLM(TransformerConfig(attn_impl="xla", **kw))
    m_f = TransformerLM(TransformerConfig(attn_impl="flash", **kw))
    params = init_params(m_x, seq=128)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 128)), jnp.int32)
    lx = m_x.apply({"params": params}, toks)
    lf = m_f.apply({"params": params}, toks)
    np.testing.assert_allclose(np.asarray(lx), np.asarray(lf), rtol=2e-3, atol=2e-3)


# ---------------------------------------------------------------------------
# r3 hardening: the TPU-compiled bench configuration (512x512 bf16 blocks)
# and in-kernel GQA (fwd + bwd, no kv repeat) get interpret-mode coverage
# ---------------------------------------------------------------------------


def test_block512_bf16_parity():
    """The exact bench kernel shape: 512-token blocks, bf16 inputs (r2's MFU
    path had no test at its production block size/dtype)."""
    q, k, v = (x.astype(jnp.bfloat16) for x in
               (_rand((1, 512, 2, 64), 16), _rand((1, 512, 2, 64), 17),
                _rand((1, 512, 2, 64), 18)))
    ref = attention_core(q, k, v, causal=True, impl="xla")
    out = flash_attention(q, k, v, causal=True, block_q=512, block_k=512)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), rtol=3e-2, atol=3e-2)


def test_block512_fp32_parity():
    q, k, v = _rand((1, 512, 2, 64), 19), _rand((1, 512, 2, 64), 20), _rand((1, 512, 2, 64), 21)
    ref = attention_core(q, k, v, causal=True, impl="xla")
    out = flash_attention(q, k, v, causal=True, block_q=512, block_k=512)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# r6 hardening: sm_scale pass-through, non-512-divisible sequences, and the
# no-repeat GQA XLA path + explicit flash-ineligible fallback
# ---------------------------------------------------------------------------


def _repeat_ref(q, k, v, **kw):
    """The pre-r6 XLA reference: kv heads repeat-materialized to H."""
    rep = q.shape[2] // k.shape[2]
    return attention_core(q, jnp.repeat(k, rep, axis=2),
                          jnp.repeat(v, rep, axis=2), impl="xla", **kw)


def test_seq640_gqa_smscale_fwd_bwd():
    """The ISSUE-named shape: seq 640 (divides 128, not the 512 default
    block), GQA 4/2, explicit sm_scale — fwd + bwd vs the XLA reference."""
    q = _rand((1, 640, 4, 32), 25)
    k, v = _rand((1, 640, 2, 32), 26), _rand((1, 640, 2, 32), 27)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, sm_scale=0.2) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_core(q, k, v, causal=True, impl="xla",
                                      scale=0.2) ** 2)

    out = flash_attention(q, k, v, causal=True, sm_scale=0.2)
    ref = attention_core(q, k, v, causal=True, impl="xla", scale=0.2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-4)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), rtol=1e-3,
                                   atol=1e-3, err_msg=f"grad mismatch for {name}")


def test_attention_core_flash_takes_scale():
    """attention_core(impl='flash', scale=...) must reach the kernel (the
    r2-r5 behavior silently bailed to XLA whenever scale was set)."""
    q, k, v = _rand((1, 128, 2, 32), 28), _rand((1, 128, 2, 32), 29), _rand((1, 128, 2, 32), 30)
    got = attention_core(q, k, v, causal=True, impl="flash", scale=1.0)
    ref = attention_core(q, k, v, causal=True, impl="xla", scale=1.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4,
                               atol=2e-4)


def test_gqa_xla_no_repeat_matches_repeat():
    """The grouped-einsum XLA GQA path == the old repeat-materialized path,
    incl. alibi (pre- and post-scale), windows and explicit scale."""
    from deepspeed_tpu.models.transformer import alibi_slopes

    q = _rand((2, 32, 8, 16), 31)
    k, v = _rand((2, 32, 2, 16), 32), _rand((2, 32, 2, 16), 33)
    al = alibi_slopes(8)
    for kw in ({}, {"scale": 0.3}, {"window": 8},
               {"alibi": al}, {"alibi": al, "alibi_post_scale": True},
               {"alibi": al, "window": 16, "scale": 0.5}):
        got = attention_core(q, k, v, causal=True, impl="xla", **kw)
        ref = _repeat_ref(q, k, v, causal=True, **kw)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5, err_msg=str(kw))


def test_flash_fallback_warns_once(caplog):
    """attn_impl=flash + window/alibi degrades to XLA with a one-time
    warning naming the reason — never silently."""
    import logging

    from deepspeed_tpu.models.transformer import (_FLASH_FALLBACK_WARNED,
                                                  alibi_slopes)

    _FLASH_FALLBACK_WARNED.clear()
    q = k = v = _rand((1, 64, 2, 16), 34)
    dlog = logging.getLogger("deepspeed_tpu")  # propagate=False: attach
    dlog.addHandler(caplog.handler)
    try:
        got = attention_core(q, k, v, causal=True, impl="flash", window=8)
        attention_core(q, k, v, causal=True, impl="flash", window=8)
        attention_core(q, k, v, causal=True, impl="flash",
                       alibi=alibi_slopes(2))
    finally:
        dlog.removeHandler(caplog.handler)
    ref = attention_core(q, k, v, causal=True, impl="xla", window=8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-6)
    msgs = [r.message for r in caplog.records if "attn_impl=flash" in r.message]
    assert len(msgs) == 2, msgs  # one per reason, not per call
    assert any("window" in m for m in msgs) and any("ALiBi" in m for m in msgs)


def test_model_attn_impl_fleet_knob():
    """TransformerLM(attn_impl='auto') defers to the training_fastpath
    fleet knob: forcing 'flash' engages the kernel on CPU (interpret) and
    matches the xla reference."""
    from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                  TransformerLM, init_params)
    from deepspeed_tpu.ops.fastpath import configure_fastpath, reset_fastpath

    kw = dict(vocab_size=64, hidden_size=64, intermediate_size=96,
              num_layers=1, num_heads=4, num_kv_heads=2, max_seq_len=128,
              dtype=jnp.float32)
    model = TransformerLM(TransformerConfig(**kw))
    params = init_params(model, seq=128)
    toks = jnp.asarray(np.random.default_rng(35).integers(0, 64, (2, 128)),
                       jnp.int32)
    ref = model.apply({"params": params}, toks)
    try:
        configure_fastpath(attn_impl="flash")
        got = model.apply({"params": params}, toks)
    finally:
        reset_fastpath()
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-3,
                               atol=2e-3)


def test_gqa_backward_parity():
    """GQA grads (dk/dv group-summed in the kernel wrapper) match the
    repeat-expanded XLA reference."""
    q = _rand((1, 128, 8, 32), 22)
    k, v = _rand((1, 128, 2, 32), 23), _rand((1, 128, 2, 32), 24)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=64,
                                       block_k=64) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_core(q, k, v, causal=True, impl="xla") ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    assert g_flash[1].shape == (1, 128, 2, 32)  # kv grads stay unexpanded
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), rtol=5e-4,
                                   atol=5e-4, err_msg=f"grad mismatch for {name}")
