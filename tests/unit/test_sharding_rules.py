"""Declarative sharding rules (deepspeed_tpu/sharding/): the regex-path ->
PartitionSpec engine — precedence, overlap/ambiguity detection, mesh-axis
validation, versioned JSON round-trips — plus the two bitwise acceptance
predicates: ``derive_rules`` reproduces ``tp_parser`` and the built-in packs
reproduce the hand-written ``param_specs`` ladder."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.sharding import (RULES_FORMAT, AmbiguousRuleError, Rule,
                                    RuleSet, RulesFormatError,
                                    ShardingRuleError, UnknownAxisError,
                                    UnmatchedParamError, derive_rules,
                                    derived_matches_parser, get_pack,
                                    pack_for_config)


def toy_params():
    return {
        "layers_0": {
            "attn": {
                "q_proj": {"kernel": jnp.zeros((8, 8)),
                           "bias": jnp.zeros((8,))},
                "o_proj": {"kernel": jnp.zeros((8, 8)),
                           "bias": jnp.zeros((8,))},
            },
            "mlp": {
                "dense_h_to_4h": {"kernel": jnp.zeros((8, 32))},
                "dense_4h_to_h": {"kernel": jnp.zeros((32, 8))},
            },
            "input_layernorm": {"scale": jnp.zeros((8,))},
        },
        "embed_tokens": {"embedding": jnp.zeros((64, 8))},
    }


# ---------------------------------------------------------------------------
# precedence
# ---------------------------------------------------------------------------


class TestPrecedence:
    def test_higher_priority_wins(self):
        rs = RuleSet([Rule(r"kernel", (None, "tp"), priority=1),
                      Rule(r"q_proj/kernel", ("tp", None), priority=5)])
        assert rs.match_path("attn/q_proj/kernel", 2).spec == ("tp", None)
        assert rs.match_path("mlp/up/kernel", 2).spec == (None, "tp")

    def test_ndim_specific_beats_generic(self):
        rs = RuleSet([Rule(r"proj", (None, "tp"), ndim=2),
                      Rule(r"proj", ("tp",), ndim=1),
                      Rule(r"proj", (None,))])
        assert rs.match_path("q_proj", 2).spec == (None, "tp")
        assert rs.match_path("q_proj", 1).spec == ("tp",)
        # no ndim-conditioned candidate at rank 3: the generic rule wins
        assert rs.match_path("q_proj", 3).spec == (None,)

    def test_equal_priority_same_spec_is_fine(self):
        rs = RuleSet([Rule(r"q_proj", (None, "tp")),
                      Rule(r"proj", (None, "tp"))])
        assert rs.match_path("q_proj/kernel", 2).spec == (None, "tp")

    def test_ambiguity_raises(self):
        rs = RuleSet([Rule(r"q_proj", (None, "tp")),
                      Rule(r"proj", ("tp", None))])
        with pytest.raises(AmbiguousRuleError, match="q_proj"):
            rs.match_path("attn/q_proj/kernel", 2)

    def test_overlap_report_lists_survivors(self):
        rs = RuleSet([Rule(r"kernel", (None, "tp")),
                      Rule(r"q_proj/kernel", ("tp", None), priority=5)])
        report = rs.overlap_report(toy_params())
        paths = [row["path"] for row in report]
        assert "layers_0/attn/q_proj/kernel" in paths
        row = report[paths.index("layers_0/attn/q_proj/kernel")]
        assert len(row["rules"]) == 2

    def test_bad_regex_refused(self):
        with pytest.raises(ShardingRuleError, match="regex"):
            Rule(r"q_proj(", (None, "tp"))


# ---------------------------------------------------------------------------
# axis validation + divisibility
# ---------------------------------------------------------------------------


class TestValidation:
    def test_unknown_axis_rejected(self):
        rs = RuleSet([Rule(r"kernel", (None, "model"))])
        with pytest.raises(UnknownAxisError, match="model"):
            rs.validate(("dp_outer", "tp", "ep"))

    def test_declared_axes_checked_at_construction(self):
        with pytest.raises(UnknownAxisError):
            RuleSet([Rule(r"kernel", (None, "model"))], axes=("tp",))

    def test_match_validates_against_axis_sizes(self):
        rs = RuleSet([Rule(r"kernel", (None, "model"))])
        with pytest.raises(UnknownAxisError):
            rs.match(toy_params(), axis_sizes={"tp": 2})

    def test_indivisible_dim_downgrades_to_replicated(self):
        rs = RuleSet([Rule(r"kernel", (None, "tp"))])
        params = {"a": {"kernel": jnp.zeros((8, 30))},
                  "b": {"kernel": jnp.zeros((8, 32))}}
        specs = rs.match(params, axis_sizes={"tp": 4})
        assert specs["a"]["kernel"] == P(None, None)
        assert specs["b"]["kernel"] == P(None, "tp")

    def test_unmatched_replicates_at_leaf_rank(self):
        rs = RuleSet([Rule(r"nothing_matches_this", ("tp",))])
        specs = rs.match({"w": jnp.zeros((4, 4)), "b": jnp.zeros((4,))})
        assert specs["w"] == P(None, None)
        assert specs["b"] == P(None)

    def test_strict_raises_on_unmatched(self):
        rs = RuleSet([Rule(r"kernel", (None, "tp"))], name="toy")
        with pytest.raises(UnmatchedParamError, match="bias"):
            rs.match({"bias": jnp.zeros((4,))}, strict=True)

    def test_renamed_rewrites_axes(self):
        rs = RuleSet([Rule(r"kernel", (None, "tp"))], axes=("tp",))
        out = rs.renamed({"tp": "model"})
        assert out.rules[0].spec == (None, "model")
        assert out.axes == frozenset({"model"})


# ---------------------------------------------------------------------------
# serialization
# ---------------------------------------------------------------------------


class TestSerialization:
    def test_json_round_trip(self):
        rs = get_pack("llama")
        back = RuleSet.from_json(rs.to_json())
        assert back == rs
        assert back.format_version == RULES_FORMAT

    def test_round_trip_preserves_match(self):
        params = toy_params()
        rs = get_pack("generic")
        back = RuleSet.from_json(rs.to_json())
        a = jax.tree_util.tree_leaves(
            rs.match(params), is_leaf=lambda x: isinstance(x, P))
        b = jax.tree_util.tree_leaves(
            back.match(params), is_leaf=lambda x: isinstance(x, P))
        assert a == b

    def test_future_format_refused(self):
        d = get_pack("llama").to_dict()
        d["format"] = RULES_FORMAT + 1
        with pytest.raises(RulesFormatError, match="understands"):
            RuleSet.from_dict(d)

    def test_future_format_refused_at_construction(self):
        with pytest.raises(RulesFormatError):
            RuleSet([], format_version=RULES_FORMAT + 1)

    def test_tuple_entries_survive_json(self):
        rs = RuleSet([Rule(r"w", (("dp_outer", "ep"), None))])
        back = RuleSet.from_json(rs.to_json())
        assert back.rules[0].spec == (("dp_outer", "ep"), None)


# ---------------------------------------------------------------------------
# packs
# ---------------------------------------------------------------------------


class TestPacks:
    def test_unknown_pack_name(self):
        with pytest.raises(KeyError, match="unknown"):
            get_pack("nope")

    def test_generic_pack_matches_canonical_vocabulary(self):
        # the vocabulary params_from_hf normalizes every family into
        params = {
            "layers_0": {
                "attn": {
                    "q_proj": {"kernel": jnp.zeros((8, 8)),
                               "bias": jnp.zeros((8,))},
                    "o_proj": {"kernel": jnp.zeros((8, 8)),
                               "bias": jnp.zeros((8,))},
                },
                "mlp": {
                    "up_proj": {"kernel": jnp.zeros((8, 32))},
                    "down_proj": {"kernel": jnp.zeros((32, 8))},
                },
                "input_layernorm": {"scale": jnp.zeros((8,))},
            },
            "embed_tokens": {"embedding": jnp.zeros((64, 8))},
        }
        specs = get_pack("generic").match(params)
        l0 = specs["layers_0"]
        assert l0["attn"]["q_proj"]["kernel"] == P(None, "tp")
        assert l0["attn"]["q_proj"]["bias"] == P("tp")
        assert l0["attn"]["o_proj"]["kernel"] == P("tp", None)
        assert l0["attn"]["o_proj"]["bias"] == P(None)
        assert l0["mlp"]["up_proj"]["kernel"] == P(None, "tp")
        assert l0["mlp"]["down_proj"]["kernel"] == P("tp", None)
        assert l0["input_layernorm"]["scale"] == P(None)
        assert specs["embed_tokens"]["embedding"] == P(None, "tp")

    def test_pack_matches_param_specs_bitwise(self):
        """The generic pack IS the hand-written param_specs ladder."""
        from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                      TransformerLM,
                                                      param_specs)
        cfg = TransformerConfig(vocab_size=64, hidden_size=32,
                                intermediate_size=64, num_layers=2,
                                num_heads=4, num_kv_heads=2, max_seq_len=32)
        params = TransformerLM(cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
        want = param_specs(params)
        got = get_pack("generic").match(params)
        eq = jax.tree_util.tree_map(lambda a, b: a == b, got, want,
                                    is_leaf=lambda x: isinstance(x, P))
        assert all(jax.tree_util.tree_leaves(eq))

    def test_pack_for_config_structural(self):
        class Cfg:
            num_experts = 0
            position = "rope"
            norm = "rmsnorm"
            tie_embeddings = False
            num_heads = 8
            num_kv_heads = 8

        cfg = Cfg()
        assert pack_for_config(cfg).name == get_pack("llama").name
        cfg.num_kv_heads = 2
        assert pack_for_config(cfg).name == get_pack("mistral").name
        cfg.num_experts = 4
        assert pack_for_config(cfg).name == get_pack("mixtral").name


# ---------------------------------------------------------------------------
# derive: AutoTP inference -> explicit rules
# ---------------------------------------------------------------------------


class TestDerive:
    def test_derive_matches_tp_parser_bitwise(self):
        from deepspeed_tpu.module_inject import tp_parser
        params = toy_params()
        rs = derive_rules(params)
        assert derived_matches_parser(params, rs, tp_parser(params))

    def test_derive_matches_parser_on_toy_transformer(self):
        from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                      TransformerLM)
        from deepspeed_tpu.module_inject import tp_parser
        cfg = TransformerConfig(vocab_size=64, hidden_size=32,
                                intermediate_size=64, num_layers=2,
                                num_heads=4, num_kv_heads=4, max_seq_len=32)
        params = TransformerLM(cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
        rs = derive_rules(params)
        assert derived_matches_parser(params, rs, tp_parser(params))

    def test_derived_rules_serialize(self):
        params = toy_params()
        rs = derive_rules(params)
        back = RuleSet.from_json(rs.to_json())
        assert derived_matches_parser(
            params, back,
            __import__("deepspeed_tpu").module_inject.tp_parser(params))

    def test_derive_generalizes_layer_indices(self):
        """Numbered layers collapse to one pattern, so the rule set stays
        depth-independent."""
        params = {f"layers_{i}": {"q_proj": {"kernel": jnp.zeros((8, 8))}}
                  for i in range(4)}
        rs = derive_rules(params)
        assert len(rs) < 4
