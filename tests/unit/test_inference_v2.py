"""Inference v2 (ragged continuous batching) tests.

Reference: tests/unit/inference/v2/ (ragged components + kernels). The
anchor test is exact greedy parity between the v2 paged/ragged path and the
v1 dense-cache path on the same weights.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import InferenceEngineV2, RaggedInferenceEngineConfig
from deepspeed_tpu.inference.v2.ragged import (BlockedAllocator, BlockedKVCache,
                                               DSStateManager, RaggedBatchWrapper)
from deepspeed_tpu.inference.v2.ragged.sequence_descriptor import DSSequenceDescriptor
from deepspeed_tpu.models.transformer import TransformerConfig, TransformerLM


# ---------------------------------------------------------------------------
# allocator
# ---------------------------------------------------------------------------


def test_allocator_basics():
    a = BlockedAllocator(8)
    assert a.free_blocks == 7  # block 0 is the trash block
    blocks = a.allocate(3)
    assert len(blocks) == 3 and 0 not in blocks
    a.free(blocks)
    assert a.free_blocks == 7
    with pytest.raises(RuntimeError):
        a.allocate(100)
    with pytest.raises(ValueError):
        a.free([0])  # trash block
    b = a.allocate(1)
    a.free(b)
    with pytest.raises(ValueError):
        a.free(b)  # double free


def test_sequence_descriptor_chunking():
    seq = DSSequenceDescriptor(uid=1, prompt_tokens=np.arange(10, dtype=np.int32))
    assert seq.in_prefill and seq.prompt_remaining == 10
    np.testing.assert_array_equal(seq.next_tokens(4), np.arange(4))
    seq.seen_tokens = 4
    np.testing.assert_array_equal(seq.next_tokens(100), np.arange(4, 10))
    seq.seen_tokens = 10
    assert not seq.in_prefill
    assert seq.blocks_needed(1, block_size=4) == 3  # ceil(11/4)


def test_wrapper_packing():
    w = RaggedBatchWrapper(token_budget=16, max_seqs=4, max_chunk=8,
                           max_blocks_per_seq=4)
    s1 = DSSequenceDescriptor(uid=7, prompt_tokens=np.arange(5, dtype=np.int32))
    s1.blocks = [1, 2]
    s2 = DSSequenceDescriptor(uid=9, prompt_tokens=np.arange(100, 103, dtype=np.int32))
    s2.blocks = [3]
    s2.seen_tokens = 3
    s2.generated = [55]
    batch = w.pack([(s1, np.arange(5, dtype=np.int32)),
                    (s2, np.array([55], np.int32))], block_size=4)
    assert batch.num_tokens == 6
    np.testing.assert_array_equal(batch.tokens[:6], [0, 1, 2, 3, 4, 55])
    np.testing.assert_array_equal(batch.positions[:6], [0, 1, 2, 3, 4, 3])
    assert batch.kv_len[0] == 5 and batch.kv_len[1] == 4
    assert batch.logits_idx[0] == 4 and batch.logits_idx[1] == 5
    assert batch.sample_slots == [0, 1]
    # padding marks
    assert (batch.gather_idx[0, 5:] == 16).all()
    assert (batch.gather_idx[2:] == 16).all()


# ---------------------------------------------------------------------------
# end-to-end: v2 == v1 greedy parity
# ---------------------------------------------------------------------------


def _tiny_model(position="rope", tie=False):
    cfg = TransformerConfig(vocab_size=97, hidden_size=48, intermediate_size=96,
                            num_layers=2, num_heads=4, num_kv_heads=2,
                            max_seq_len=128, dtype=jnp.float32,
                            position=position,
                            norm="rmsnorm" if position == "rope" else "layernorm",
                            activation="swiglu" if position == "rope" else "gelu",
                            tie_embeddings=tie)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


@pytest.mark.parametrize("position", ["rope", "learned"])
def test_v2_matches_v1_greedy(position):
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig

    model, params = _tiny_model(position)
    prompts = [np.array([5, 6, 7, 8, 9], np.int32),
               np.array([40, 41, 42], np.int32),
               np.array([3, 1, 4, 1, 5, 9, 2, 6], np.int32)]
    max_new = 8

    # v1 dense path (right-padded batch)
    v1 = InferenceEngine(model, params,
                         DeepSpeedInferenceConfig.from_dict(
                             {"dtype": "float32", "max_out_tokens": 64}))
    smax = max(len(p) for p in prompts)
    toks = np.zeros((len(prompts), smax), np.int32)
    for i, p in enumerate(prompts):
        toks[i, :len(p)] = p
    lens = np.array([len(p) for p in prompts], np.int32)
    ref = v1.generate(toks, prompt_lengths=lens, max_new_tokens=max_new)

    # v2 ragged path (several batch mixes: small budget forces chunking)
    v2 = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
        token_budget=8, max_ragged_sequence_count=4, max_chunk_size=4,
        num_kv_blocks=32, kv_block_size=8, max_blocks_per_seq=8,
        dtype="float32"))
    outs = v2.generate(prompts, max_new_tokens=max_new)
    for i, o in enumerate(outs):
        np.testing.assert_array_equal(o, ref[i], err_msg=f"seq {i} ({position})")


def test_v2_tied_embeddings():
    model, params = _tiny_model(tie=True)
    v2 = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
        num_kv_blocks=16, kv_block_size=16, dtype="float32"))
    outs = v2.generate([np.array([1, 2, 3], np.int32)], max_new_tokens=4)
    assert outs[0].shape == (4,)


def test_v2_continuous_admission():
    """New sequences join mid-flight (the continuous-batching property)."""
    model, params = _tiny_model()
    v2 = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
        token_budget=16, max_ragged_sequence_count=4, max_chunk_size=8,
        num_kv_blocks=64, kv_block_size=8, dtype="float32"))
    v2.put([100], [np.array([5, 6, 7], np.int32)], max_new_tokens=6)
    v2.step()  # prompt of 100 fully scheduled; first token sampled
    v2.put([200], [np.array([9, 9, 9, 9], np.int32)], max_new_tokens=6)
    while not (v2.query(100)[0] and v2.query(200)[0]):
        v2.step()
    done1, gen1 = v2.query(100)
    done2, gen2 = v2.query(200)
    assert done1 and done2 and len(gen1) == 6 and len(gen2) == 6

    # single-sequence reference (independent engine, fresh cache)
    v2b = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
        num_kv_blocks=64, kv_block_size=8, dtype="float32"))
    ref2 = v2b.generate([np.array([9, 9, 9, 9], np.int32)], max_new_tokens=6)
    np.testing.assert_array_equal(gen2, ref2[0])  # isolation between seqs
    v2.flush(100); v2.flush(200)
    assert v2.kv.free_blocks == v2b.kv.free_blocks


def test_v2_eos_and_capacity():
    model, params = _tiny_model()
    v2 = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
        num_kv_blocks=8, kv_block_size=4, max_blocks_per_seq=4, dtype="float32"))
    ok, why = v2.can_schedule(prompt_len=100, max_new_tokens=100)
    assert not ok and "max_seq_len" in why
    ok, why = v2.can_schedule(prompt_len=50, max_new_tokens=50)
    assert not ok and "blocks" in why  # fits max_seq_len but not the pool
    with pytest.raises(RuntimeError, match="cannot schedule"):
        v2.put([1], [np.arange(50, dtype=np.int32)], max_new_tokens=50)
    # over-commit guard: admitted seqs may not jointly exceed the pool
    v2.put([2], [np.array([1, 2], np.int32)], max_new_tokens=10)  # commits 3
    v2.put([3], [np.array([1, 2], np.int32)], max_new_tokens=10)  # commits 3 more
    ok, why = v2.can_schedule(prompt_len=2, max_new_tokens=6)     # needs 2, 1 left
    assert not ok and "uncommitted" in why
    v2.flush(2)
    v2.flush(3)  # releasing commitments frees admission capacity
    # max_new_tokens bounds generation (2 + 10 tokens fits 3 of 4 blocks)
    outs = v2.generate([np.array([1, 2], np.int32)], max_new_tokens=10,
                       eos_token_id=None)
    assert len(outs[0]) == 10


def test_v2_block_reuse_after_flush():
    model, params = _tiny_model()
    cfgv2 = RaggedInferenceEngineConfig(num_kv_blocks=16, kv_block_size=8,
                                        dtype="float32")
    v2 = InferenceEngineV2(model, params, cfgv2)
    free0 = v2.kv.free_blocks
    v2.generate([np.arange(10, dtype=np.int32)], max_new_tokens=4)
    assert v2.kv.free_blocks == free0  # generate() flushes
    v2.put([5], [np.arange(10, dtype=np.int32)], max_new_tokens=4)
    v2.step()
    assert v2.kv.free_blocks < free0
    v2.flush(5)
    assert v2.kv.free_blocks == free0


def test_v2_long_prompt_chunked_generate():
    """A single prompt spanning multiple SplitFuse chunks must generate fully
    (regression: chunk-only steps return no tokens and used to end generate)."""
    model, params = _tiny_model()
    v2 = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
        token_budget=4, max_ragged_sequence_count=2, max_chunk_size=4,
        num_kv_blocks=32, kv_block_size=8, dtype="float32"))
    prompt = np.arange(1, 15, dtype=np.int32)  # 14 tokens -> 4 chunk steps
    outs = v2.generate([prompt], max_new_tokens=5)
    assert outs[0].shape == (5,)


def test_decode_stream_windowed_matches_single_fused():
    """decode_stream with a small max_fused_window (multiple fused dispatches,
    each over a fresh frozen pool) must produce the same greedy tokens as one
    big window and as the per-step step() loop."""
    model, params = _tiny_model("rope")
    prompts = [np.array([5, 6, 7, 8, 9], np.int32),
               np.array([40, 41, 42], np.int32)]

    def run(window):
        eng = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
            token_budget=16, max_ragged_sequence_count=2, max_chunk_size=8,
            num_kv_blocks=32, kv_block_size=8, max_blocks_per_seq=8,
            dtype="float32", max_fused_window=window))
        eng.put([0, 1], prompts, max_new_tokens=13)
        while any(s.in_prefill for s in eng.state_manager.all()):
            eng.step()
        eng.decode_stream(12)  # 1 token came from prefill
        return [eng.query(uid)[1] for uid in (0, 1)]

    big = run(512)     # one fused dispatch
    small = run(4)     # 3 chunked dispatches of <= 4
    for a, b in zip(big, small):
        np.testing.assert_array_equal(a, b)

    # reference: per-token step() loop
    eng = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
        token_budget=16, max_ragged_sequence_count=2, max_chunk_size=8,
        num_kv_blocks=32, kv_block_size=8, max_blocks_per_seq=8,
        dtype="float32"))
    eng.put([0, 1], prompts, max_new_tokens=13)
    while eng.has_work():
        eng.step()
    for uid, want in zip((0, 1), big):
        np.testing.assert_array_equal(eng.query(uid)[1], want)


@pytest.mark.parametrize("shared", [False, True])
def test_v2_moe_matches_v1_greedy(shared):
    """v2 ragged serving of MoE models (reference FastGen mixtral /
    qwen2_moe implementations): dropless routing in the packed forward and
    the fused decode must match the v1 dense path exactly."""
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig

    cfg = TransformerConfig(vocab_size=97, hidden_size=48, intermediate_size=96,
                            num_layers=2, num_heads=4, num_kv_heads=2,
                            max_seq_len=128, dtype=jnp.float32,
                            num_experts=4, moe_top_k=2, moe_dropless=True,
                            moe_intermediate_size=64 if shared else None,
                            moe_shared_expert_size=80 if shared else 0,
                            moe_norm_topk=not shared)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]

    prompts = [np.array([5, 6, 7, 8, 9], np.int32),
               np.array([40, 41, 42], np.int32)]
    v1 = InferenceEngine(model, params,
                         DeepSpeedInferenceConfig.from_dict(
                             {"dtype": "float32", "max_out_tokens": 64}))
    smax = max(len(p) for p in prompts)
    toks = np.zeros((len(prompts), smax), np.int32)
    for i, p in enumerate(prompts):
        toks[i, :len(p)] = p
    lens = np.array([len(p) for p in prompts], np.int32)
    ref = v1.generate(toks, prompt_lengths=lens, max_new_tokens=8)

    v2 = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
        token_budget=8, max_ragged_sequence_count=4, max_chunk_size=4,
        num_kv_blocks=32, kv_block_size=8, max_blocks_per_seq=8,
        dtype="float32"))
    outs = v2.generate(prompts, max_new_tokens=8)
    for i, o in enumerate(outs):
        np.testing.assert_array_equal(o, ref[i], err_msg=f"seq {i}")


def test_decode_with_oversized_block_table():
    """An oversized max_blocks_per_seq (sized for max_seq_len) must not
    change decode results — the engine slices the table to the pages the
    window can touch (and gathers only those)."""
    model, params = _tiny_model("rope")
    prompts = [np.array([5, 6, 7, 8, 9], np.int32),
               np.array([40, 41, 42], np.int32)]

    def run(mbps):
        eng = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
            token_budget=16, max_ragged_sequence_count=2, max_chunk_size=8,
            num_kv_blocks=64, kv_block_size=8, max_blocks_per_seq=mbps,
            dtype="float32"))
        eng.put([0, 1], prompts, max_new_tokens=13)
        while any(s.in_prefill for s in eng.state_manager.all()):
            eng.step()
        eng.decode_stream(12)
        return [eng.query(uid)[1] for uid in (0, 1)]

    small = run(4)
    big = run(16)   # 4x oversized table, sliced per dispatch
    for a, b in zip(small, big):
        np.testing.assert_array_equal(a, b)

    # decode_batch shares the slicing helper — cover it too
    eng = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
        token_budget=16, max_ragged_sequence_count=2, max_chunk_size=8,
        num_kv_blocks=64, kv_block_size=8, max_blocks_per_seq=16,
        dtype="float32", decode_chunk=4))
    eng.put([0, 1], prompts, max_new_tokens=13)
    while any(s.in_prefill for s in eng.state_manager.all()):
        eng.step()
    while eng.has_work():
        if not eng.decode_batch():
            break
    for uid, want in zip((0, 1), small):
        np.testing.assert_array_equal(eng.query(uid)[1], want)


@pytest.mark.parametrize("family", ["falcon7b", "gptj", "phi"])
def test_v2_parallel_residual_families_match_v1(family):
    """v2 ragged serving of parallel-residual families (reference FastGen
    falcon/phi implementations; gptj adds interleaved rotary + biased
    lm_head) must match the v1 dense path exactly."""
    transformers = pytest.importorskip("transformers")
    torch = pytest.importorskip("torch")

    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.hf import params_from_hf

    torch.manual_seed(31)
    if family == "falcon7b":
        hf = transformers.FalconForCausalLM(transformers.FalconConfig(
            vocab_size=96, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=4, multi_query=True, parallel_attn=True,
            new_decoder_architecture=False, bias=False, alibi=False,
            max_position_embeddings=64, hidden_dropout=0.0,
            attention_dropout=0.0)).eval()
    elif family == "gptj":
        hf = transformers.GPTJForCausalLM(transformers.GPTJConfig(
            vocab_size=96, n_embd=64, n_layer=2, n_head=4, n_positions=64,
            rotary_dim=8, resid_pdrop=0.0, embd_pdrop=0.0,
            attn_pdrop=0.0)).eval()
    else:
        hf = transformers.PhiForCausalLM(transformers.PhiConfig(
            vocab_size=96, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            partial_rotary_factor=0.5, max_position_embeddings=64,
            resid_pdrop=0.0, embd_pdrop=0.0, attention_dropout=0.0)).eval()
    cfg, params = params_from_hf(hf)
    model = TransformerLM(type(cfg)(**{**cfg.__dict__, "dtype": jnp.float32}))

    prompts = [np.array([5, 6, 7, 8, 9], np.int32),
               np.array([40, 41, 42], np.int32)]
    v1 = InferenceEngine(model, params,
                         DeepSpeedInferenceConfig.from_dict(
                             {"dtype": "float32", "max_out_tokens": 64}))
    toks = np.zeros((2, 5), np.int32)
    for i, p in enumerate(prompts):
        toks[i, :len(p)] = p
    lens = np.array([5, 3], np.int32)
    ref = v1.generate(jnp.asarray(toks), prompt_lengths=jnp.asarray(lens),
                      max_new_tokens=8)

    v2 = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
        token_budget=8, max_ragged_sequence_count=4, max_chunk_size=4,
        num_kv_blocks=32, kv_block_size=8, max_blocks_per_seq=8,
        dtype="float32"))
    outs = v2.generate(prompts, max_new_tokens=8)
    for i, o in enumerate(outs):
        np.testing.assert_array_equal(o, np.asarray(ref)[i],
                                      err_msg=f"{family} seq {i}")


def test_fp8_kv_cache():
    """kv_cache_dtype='float8_e4m3fn' halves KV storage (reference
    FP-quantizer KV use case): the engine runs end-to-end with fp8 pools and
    its logits stay close to the full-precision path."""
    model, params = _tiny_model("rope")
    prompts = [np.array([5, 6, 7, 8, 9], np.int32),
               np.array([40, 41, 42], np.int32)]

    def build(kv_dtype):
        return InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
            token_budget=16, max_ragged_sequence_count=2, max_chunk_size=8,
            num_kv_blocks=32, kv_block_size=8, max_blocks_per_seq=8,
            dtype="float32", kv_cache_dtype=kv_dtype))

    full = build(None)
    fp8 = build("float8_e4m3fn")
    assert fp8.kv.k.dtype == jnp.float8_e4m3fn
    assert full.kv.k.dtype == jnp.float32

    # engine runs end-to-end on fp8 pools
    fp8.put([0, 1], prompts, max_new_tokens=6)
    while fp8.has_work():
        fp8.step()
    for uid in (0, 1):
        done, gen = fp8.query(uid)
        assert done and len(gen) == 6

    # single-step logits agreement: run one prefill chunk on both engines
    # and compare the sampled-token logits closeness via the first token
    full.put([0, 1], prompts, max_new_tokens=6)
    while full.has_work():
        full.step()
    agree = sum(int(np.array_equal(full.query(u)[1][:2], fp8.query(u)[1][:2]))
                for u in (0, 1))
    assert agree >= 1, "fp8 KV diverged from full precision immediately"


def test_int8_kv_cache_parity():
    """kv_cache_dtype='int8' stores quantized rows + per-row scales
    (ops/pallas/quant.py quantize_rows: ~2x smaller than bf16, ~4x vs fp32);
    greedy decode must track the full-precision and bf16 paths closely
    (int8 row-wise error ~0.4% is below bf16's own rounding)."""
    model, params = _tiny_model("rope")
    prompts = [np.array([5, 6, 7, 8, 9], np.int32),
               np.array([40, 41, 42], np.int32),
               np.array([3, 1, 4, 1, 5, 9, 2, 6], np.int32)]

    def run(compute, kv_dtype):
        eng = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
            token_budget=16, max_ragged_sequence_count=4, max_chunk_size=8,
            num_kv_blocks=32, kv_block_size=8, max_blocks_per_seq=8,
            dtype=compute, kv_cache_dtype=kv_dtype))
        eng.put([0, 1, 2], prompts, max_new_tokens=8)
        while eng.has_work():
            eng.step()
        return eng, [eng.query(u)[1] for u in (0, 1, 2)]

    q_eng, q = run("float32", "int8")
    assert q_eng.kv.quantized
    assert q_eng.kv.k.dtype == jnp.int8 and q_eng.kv.v.dtype == jnp.int8
    assert q_eng.kv.k_scale.shape == q_eng.kv.k.shape[:-1]
    assert q_eng.kv.k_scale.dtype == jnp.float32
    _, full = run("float32", None)
    # int8 KV vs full precision: every first token matches, and most
    # sequences agree over the first half of the run
    for i in range(3):
        assert q[i][0] == full[i][0], f"seq {i} first token diverged"
    agree = sum(int(np.array_equal(q[i][:4], full[i][:4])) for i in range(3))
    assert agree >= 2, f"int8 KV diverged from fp32 immediately: {q} vs {full}"

    # the named satellite: parity vs the bf16 pool at bf16 compute
    _, bf = run("bfloat16", None)
    _, qbf = run("bfloat16", "int8")
    agree = sum(int(np.array_equal(qbf[i][:4], bf[i][:4])) for i in range(3))
    assert agree >= 2, f"int8 KV diverged from bf16: {qbf} vs {bf}"


def test_int8_kv_pallas_backend_fused_decode():
    """attn_backend='pallas' + int8 KV no longer raises: the prompt chunks
    fall back (warn-once) to the einsum gather — the legacy prefill kernel
    takes fp pools — while the fused decode keeps the pallas kernel with
    the (values, scales) pools fed directly (dequant fused in-kernel), and
    the generated tokens track the all-einsum int8 engine."""
    model, params = _tiny_model("rope")
    prompts = [np.array([5, 6, 7, 8, 9], np.int32),
               np.array([40, 41, 42], np.int32)]

    def run(backend):
        eng = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
            token_budget=16, max_ragged_sequence_count=4, max_chunk_size=8,
            num_kv_blocks=32, kv_block_size=8, max_blocks_per_seq=8,
            dtype="float32", kv_cache_dtype="int8", attn_backend=backend))
        return eng, eng.generate(prompts, max_new_tokens=6)

    eng_p, out_p = run("pallas")
    assert eng_p.attn_impl == "einsum"          # prefill kernel: fp pools
    assert eng_p.decode_attn_impl == "pallas"   # fused-dequant decode kernel
    eng_e, out_e = run("einsum")
    assert eng_e.decode_attn_impl == "einsum"
    agree = sum(int(np.array_equal(a, b)) for a, b in zip(out_p, out_e))
    assert agree >= 1, f"int8 fused decode diverged: {out_p} vs {out_e}"


def test_decode_attn_resolution_order():
    """model field > engine/serving config > heuristic, with a warned
    structural fallback instead of the old silent einsum pin."""
    from dataclasses import replace

    from deepspeed_tpu.models.transformer import TransformerLM

    model, params = _tiny_model("rope")

    def build(model_, **kw):
        return InferenceEngineV2(model_, params, RaggedInferenceEngineConfig(
            token_budget=8, num_kv_blocks=16, kv_block_size=8,
            max_blocks_per_seq=4, dtype="float32", **kw))

    # heuristic on CPU: einsum
    eng = build(model)
    assert (eng.decode_attn_impl, eng.decode_attn_source) == ("einsum",
                                                              "heuristic")
    # engine config decode_attn_backend wins over the shared attn_backend
    eng = build(model, attn_backend="einsum", decode_attn_backend="pallas")
    assert (eng.decode_attn_impl, eng.decode_attn_source) == ("pallas",
                                                              "config")
    # the model field wins over everything
    pinned = TransformerLM(replace(model.cfg, decode_attn_impl="einsum"))
    eng = build(pinned, decode_attn_backend="pallas")
    assert (eng.decode_attn_impl, eng.decode_attn_source) == ("einsum",
                                                              "model")
    # structural fallback: an alibi family demotes a pallas pick, loudly
    alibi_model, alibi_params = _tiny_model("alibi")
    eng = InferenceEngineV2(alibi_model, alibi_params,
                            RaggedInferenceEngineConfig(
                                token_budget=8, num_kv_blocks=16,
                                kv_block_size=8, max_blocks_per_seq=4,
                                dtype="float32",
                                decode_attn_backend="pallas"))
    assert (eng.decode_attn_impl, eng.decode_attn_source) == ("einsum",
                                                              "fallback")
    # invalid knob names are rejected, not silently einsum-pinned — at
    # every precedence level, including the model field
    with pytest.raises(ValueError, match="auto|pallas|einsum"):
        build(model, decode_attn_backend="cuda")
    with pytest.raises(ValueError, match="auto|pallas|einsum"):
        build(TransformerLM(replace(model.cfg, decode_attn_impl="palas")))


def test_decode_attn_plan_table_row():
    """Every engine records its resolved decode_attn decision in the plan
    table (CommsLogger.record_plan), whatever the resolution source — the
    sv/pd ladder rows and the static auditor read it from there."""
    from deepspeed_tpu.comm import get_comms_logger

    model, params = _tiny_model("rope")
    eng = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
        token_budget=8, num_kv_blocks=16, kv_block_size=8,
        max_blocks_per_seq=4, dtype="float32", kv_cache_dtype="int8"))
    sig = eng._decode_attn_site(jnp.dtype(jnp.int8)).signature()
    rec = get_comms_logger().plan_records.get(sig)
    assert rec is not None
    assert rec["op"] == "decode_attn" and rec["consumer"] == "decode"
    assert rec["impl"] == eng.decode_attn_impl
    assert rec["source"] == eng.decode_attn_source


def test_einsum_backend_bitwise_default_contract():
    """attn_backend='einsum' is the default-off contract on CPU: explicit
    einsum and auto resolution produce bitwise-identical generations."""
    model, params = _tiny_model("rope")
    prompts = [np.array([5, 6, 7, 8, 9], np.int32),
               np.array([40, 41, 42], np.int32)]

    def run(**kw):
        eng = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
            token_budget=16, max_ragged_sequence_count=4, max_chunk_size=8,
            num_kv_blocks=32, kv_block_size=8, max_blocks_per_seq=8,
            dtype="float32", **kw))
        return eng.generate(prompts, max_new_tokens=6)

    for a, b in zip(run(), run(attn_backend="einsum",
                               decode_attn_backend="einsum")):
        np.testing.assert_array_equal(a, b)


def test_flush_step_interleaving_block_consistency():
    """Regression (serving cancellation paths): blocks freed by flush are
    re-allocatable and _outstanding_blocks stays consistent after mixed
    flush/step interleavings — flush mid-prefill, mid-decode, and while
    other sequences keep stepping."""
    model, params = _tiny_model()
    v2 = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
        token_budget=8, max_ragged_sequence_count=4, max_chunk_size=4,
        num_kv_blocks=16, kv_block_size=8, max_blocks_per_seq=4,
        dtype="float32"))
    free0 = v2.kv.free_blocks

    def slack():
        s = v2.kv.free_blocks - v2._outstanding_blocks()
        assert s >= 0, "pool over-committed"
        return s

    v2.put([1], [np.arange(1, 13, dtype=np.int32)], max_new_tokens=6)
    v2.put([2], [np.arange(20, 26, dtype=np.int32)], max_new_tokens=6)
    v2.step()                       # both advance (seq 1 still in prefill)
    assert v2.state_manager.get(1).in_prefill
    v2.flush(1)                     # cancel mid-prefill
    assert v2.state_manager.get(1) is None
    slack()
    v2.step()                       # survivor keeps generating
    assert len(v2.state_manager.get(2).generated) >= 1
    v2.put([3], [np.arange(1, 9, dtype=np.int32)], max_new_tokens=6)
    slack()
    for _ in range(3):
        v2.step()
    assert not v2.state_manager.get(2).done
    v2.flush(2)                     # cancel mid-decode
    slack()
    while not v2.query(3)[0]:
        v2.step()
    assert len(v2.query(3)[1]) == 6  # unaffected by the interleaved flushes
    v2.flush(3)
    assert v2.kv.free_blocks == free0
    assert v2._outstanding_blocks() == 0
    # the whole pool is re-allocatable after the churn
    ok, why = v2.can_schedule(prompt_len=12, max_new_tokens=12)
    assert ok, why


# ---------------------------------------------------------------------------
# family breadth: ALiBi / OPT / windowed / embed-norm under ragged serving
# (VERDICT r4 item 5; reference serves these under FastGen — e.g.
#  inference/v2/model_implementations/opt/model.py)
# ---------------------------------------------------------------------------


def _family_cfg(family):
    base = dict(vocab_size=97, hidden_size=48, intermediate_size=96,
                num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
                dtype=jnp.float32, norm="layernorm")
    if family == "bloom":      # ALiBi + word_embeddings_layernorm
        return TransformerConfig(**base, position="alibi", embed_norm=True,
                                 activation="gelu")
    if family == "mpt":        # post-scale ALiBi, bias-free LayerNorm
        return TransformerConfig(**base, position="alibi",
                                 alibi_post_scale=True, norm_bias=False,
                                 activation="gelu_exact")
    if family == "opt":        # learned positions offset 2, ReLU MLP
        return TransformerConfig(**base, position="learned", pos_offset=2,
                                 activation="relu")
    if family == "gpt_neo":    # unscaled attention + alternating local window
        return TransformerConfig(**base, position="learned", attn_scale=1.0,
                                 layer_windows=(None, 4), activation="gelu")
    raise ValueError(family)


@pytest.mark.parametrize("family", ["bloom", "mpt", "opt", "gpt_neo"])
def test_v2_family_breadth_matches_v1(family):
    """Exact greedy parity v2 (ragged paged, chunked prefill + fused decode)
    vs v1 (dense) for the families previously rejected by engine_v2."""
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.inference.engine import InferenceEngine

    cfg = _family_cfg(family)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]

    prompts = [np.array([5, 6, 7, 8, 9], np.int32),
               np.array([40, 41, 42], np.int32),
               np.array([3, 1, 4, 1, 5, 9, 2, 6], np.int32)]
    max_new = 8

    v1 = InferenceEngine(model, params,
                         DeepSpeedInferenceConfig.from_dict(
                             {"dtype": "float32", "max_out_tokens": 64}))
    smax = max(len(p) for p in prompts)
    toks = np.zeros((len(prompts), smax), np.int32)
    for i, p in enumerate(prompts):
        toks[i, :len(p)] = p
    lens = np.array([len(p) for p in prompts], np.int32)
    ref = v1.generate(toks, prompt_lengths=lens, max_new_tokens=max_new)

    v2 = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
        token_budget=8, max_ragged_sequence_count=4, max_chunk_size=4,
        num_kv_blocks=32, kv_block_size=8, max_blocks_per_seq=8,
        dtype="float32"))
    assert v2.attn_impl == "einsum"
    outs = v2.generate(prompts, max_new_tokens=max_new)
    for i, o in enumerate(outs):
        np.testing.assert_array_equal(o, np.asarray(ref)[i],
                                      err_msg=f"{family} seq {i}")


def test_v2_pallas_backend_rejects_special_attention():
    cfg = _family_cfg("bloom")
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    with pytest.raises(ValueError, match="einsum path"):
        InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
            token_budget=8, num_kv_blocks=16, kv_block_size=8,
            attn_backend="pallas", dtype="float32"))


@pytest.mark.parametrize("family", ["bloom", "opt", "gpt_neo"])
def test_v2_hf_family_breadth_matches_v1(family):
    """Same parity but with REAL transformers checkpoints ingested via
    params_from_hf — pins the HF layout conventions (fused bloom qkv,
    OPT offset-2 positions, gpt_neo local attention) through the ragged
    engine, not just our own synthetic configs."""
    transformers = pytest.importorskip("transformers")
    torch = pytest.importorskip("torch")

    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.hf import params_from_hf

    torch.manual_seed(17)
    if family == "bloom":
        hf = transformers.BloomForCausalLM(transformers.BloomConfig(
            vocab_size=96, hidden_size=64, n_layer=2, n_head=4,
            hidden_dropout=0.0, attention_dropout=0.0)).eval()
    elif family == "opt":
        hf = transformers.OPTForCausalLM(transformers.OPTConfig(
            vocab_size=96, hidden_size=64, ffn_dim=128, num_hidden_layers=2,
            num_attention_heads=4, max_position_embeddings=64,
            do_layer_norm_before=True, dropout=0.0)).eval()
    else:
        hf = transformers.GPTNeoForCausalLM(transformers.GPTNeoConfig(
            vocab_size=96, hidden_size=64, num_layers=2, num_heads=4,
            attention_types=[[["global", "local"], 1]], window_size=4,
            max_position_embeddings=64, resid_dropout=0.0,
            embed_dropout=0.0, attention_dropout=0.0)).eval()
    cfg, params = params_from_hf(hf)
    model = TransformerLM(type(cfg)(**{**cfg.__dict__, "dtype": jnp.float32}))

    prompts = [np.array([5, 6, 7, 8, 9], np.int32),
               np.array([40, 41, 42], np.int32)]
    v1 = InferenceEngine(model, params,
                         DeepSpeedInferenceConfig.from_dict(
                             {"dtype": "float32", "max_out_tokens": 64}))
    toks = np.zeros((2, 5), np.int32)
    for i, p in enumerate(prompts):
        toks[i, :len(p)] = p
    lens = np.array([5, 3], np.int32)
    ref = v1.generate(jnp.asarray(toks), prompt_lengths=jnp.asarray(lens),
                      max_new_tokens=8)

    v2 = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
        token_budget=8, max_ragged_sequence_count=4, max_chunk_size=4,
        num_kv_blocks=32, kv_block_size=8, max_blocks_per_seq=8,
        dtype="float32"))
    outs = v2.generate(prompts, max_new_tokens=8)
    for i, o in enumerate(outs):
        np.testing.assert_array_equal(o, np.asarray(ref)[i],
                                      err_msg=f"{family} seq {i}")


def test_reference_surface_properties():
    """Reference engine_v2 vocabulary: free_blocks, model,
    get_remaining_block_capacity; v1 exposes .module."""
    import jax.numpy as jnp

    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig)
    from deepspeed_tpu.models.transformer import (TransformerLM, init_params,
                                                  llama_config)

    cfg = llama_config("7b", num_layers=1, hidden_size=64,
                       intermediate_size=128, num_heads=4, num_kv_heads=2,
                       vocab_size=128, max_seq_len=64, dtype=jnp.float32)
    model = TransformerLM(cfg)
    params = init_params(model, batch=1, seq=16)
    eng = InferenceEngineV2(model, params, RaggedInferenceEngineConfig(
        token_budget=16, max_ragged_sequence_count=2, max_chunk_size=16,
        num_kv_blocks=8, kv_block_size=16, max_blocks_per_seq=4,
        dtype="float32"))
    assert eng.model is model
    total = eng.free_blocks
    assert total > 0
    eng.put([7], [np.arange(10, dtype=np.int32)], max_new_tokens=4)
    while any(s.in_prefill for s in eng.state_manager.all()):
        eng.step()
    # 10 tokens cached in 16-token pages: 6 slots left in the open page
    assert eng.get_remaining_block_capacity(7) == 6
    assert eng.get_remaining_block_capacity(999) == 0  # unknown uid
    assert eng.free_blocks < total  # pages actually allocated

    v1 = InferenceEngine(model, params,
                         DeepSpeedInferenceConfig(dtype="float32",
                                                  max_out_tokens=32))
    assert v1.module is v1.model


# ---------------------------------------------------------------------------
# TP-sharded decode projections (model.py tp_decode_*): the decode-TP
# collective-matmul wiring — sequence rows sharded over tp, weights
# column-sharded, the row gather hidden behind the projection matmul
# ---------------------------------------------------------------------------


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 devices")
@pytest.mark.parametrize("impl", ["xla", "fused_matmul"])
def test_tp_decode_projections_match_dense(impl):
    from jax.sharding import Mesh, PartitionSpec as P

    from deepspeed_tpu.inference.v2.model import (tp_decode_logits,
                                                  tp_decode_matmul,
                                                  tp_decode_out_proj,
                                                  tp_greedy_token)
    from deepspeed_tpu.utils.shard_map_compat import shard_map_nocheck

    mesh = Mesh(np.array(jax.devices()[:4]), ("tp",))
    rng = np.random.default_rng(11)
    S, H, NL, V = 8, 32, 16, 64   # 4*NL total out cols, V/4 vocab shards
    x = jnp.asarray(rng.normal(size=(S, H)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(H, 4 * NL)), jnp.float32)
    wo = jnp.asarray(rng.normal(size=(4 * NL, H)), jnp.float32)
    wv = jnp.asarray(rng.normal(size=(H, V)), jnp.float32)
    attn = jnp.asarray(rng.normal(size=(S, 4 * NL)), jnp.float32)

    # column-parallel projection: [S/p, H] rows x [H, n/p] shard -> [S, n/p]
    fn = jax.jit(shard_map_nocheck(
        lambda xl, wl: tp_decode_matmul(xl, wl, "tp", impl=impl),
        mesh, in_specs=(P("tp", None), P(None, "tp")),
        out_specs=P(None, "tp")))
    np.testing.assert_allclose(np.asarray(fn(x, w)), np.asarray(x @ w),
                               rtol=1e-5, atol=1e-5)

    # row-parallel output projection: psum + row scatter back to [S/p, H]
    fn_o = jax.jit(shard_map_nocheck(
        lambda al, wol: tp_decode_out_proj(al, wol, "tp", impl=impl),
        mesh, in_specs=(P(None, "tp"), P("tp", None)),
        out_specs=P("tp", None)))
    np.testing.assert_allclose(np.asarray(fn_o(attn, wo)),
                               np.asarray(attn @ wo), rtol=1e-4, atol=1e-4)

    # vocab-parallel LM head + global greedy sample without [S, V] gathers:
    # tokens must match the dense argmax exactly (tie-break included)
    fn_l = jax.jit(shard_map_nocheck(
        lambda hl, wvl: tp_greedy_token(
            tp_decode_logits(hl, wvl, "tp", impl=impl), "tp"),
        mesh, in_specs=(P("tp", None), P(None, "tp")), out_specs=P()))
    np.testing.assert_array_equal(
        np.asarray(fn_l(x, wv)),
        np.asarray(jnp.argmax(x @ wv, axis=-1).astype(jnp.int32)))


# ---------------------------------------------------------------------------
# content-addressed prefix KV reuse + n-gram speculative decode
# ---------------------------------------------------------------------------


def _cache_engine(**over):
    model, params = _tiny_model()
    kw = dict(token_budget=16, max_ragged_sequence_count=4, max_chunk_size=8,
              num_kv_blocks=32, kv_block_size=8, max_blocks_per_seq=8,
              dtype="float32")
    kw.update(over)
    return InferenceEngineV2(model, params, RaggedInferenceEngineConfig(**kw))


def test_prefix_index_chain_lookup_and_eviction():
    from deepspeed_tpu.inference.v2.ragged import (ROOT_HASH, PrefixIndex,
                                                   chain_hashes, hash_block)

    toks = np.arange(20, dtype=np.int32)
    hashes = chain_hashes(toks, 8)
    assert len(hashes) == 2                      # full blocks only (20 // 8)
    # deterministic and chained: same tokens -> same digests, first digest
    # keyed off the sentinel root, second off the first
    assert hashes == chain_hashes(toks, 8)
    assert hashes[0] == hash_block(ROOT_HASH, toks[:8])
    assert hashes[1] == hash_block(hashes[0], toks[8:16])
    # a different PARENT changes the digest even for identical block tokens
    assert hash_block("other", toks[:8]) != hashes[0]

    idx = PrefixIndex()
    assert idx.register(hashes[0], 3)
    assert not idx.register(hashes[0], 4)        # first writer wins
    assert idx.register(hashes[1], 5)
    assert idx.lookup(hashes) == [3, 5]
    # a chain whose FIRST block misses matches nothing, even if a later
    # digest were somehow known (prefix means prefix)
    assert idx.lookup([hash_block(ROOT_HASH, toks[1:9])] + hashes[1:]) == []
    # eviction respects refcounts (page 3 pinned) and LRU among the rest
    assert idx.evict(2, refs={3: 1}) == [5]
    assert idx.lookup(hashes) == [3]


def test_v2_prefix_cache_warm_put_parity_and_cow():
    """Warm-cache admission must (a) reproduce cold greedy output bitwise,
    (b) skip the cached prefill, (c) COW-fork exactly once when the prompt
    is fully block-aligned-covered, and (d) conserve the pool."""
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, 97, 24).astype(np.int32)   # 24 % 8 == 0
    ref = _cache_engine().generate([prompt], max_new_tokens=12)[0]

    eng = _cache_engine(enable_prefix_cache=True)
    cold = eng.generate([prompt], max_new_tokens=12)[0]
    warm = eng.generate([prompt], max_new_tokens=12)[0]
    np.testing.assert_array_equal(cold, ref)
    np.testing.assert_array_equal(warm, ref)
    r = eng.reuse
    assert r.prefix_lookups == 2 and r.prefix_hits == 1
    assert r.prefix_tokens_reused == 23          # plen - 1: COW rewind
    assert r.cow_forks == 1
    eng.kv.assert_conservation(
        [s.blocks for s in eng.state_manager.all()])
    # all flushed: every page is free or reclaimable cache, none leaked
    assert eng.kv.free_blocks == eng.config.num_kv_blocks - 1

    # unaligned prompt (no COW case): tail prefill starts in a fresh page
    p2 = rng.integers(0, 97, 21).astype(np.int32)
    ref2 = _cache_engine().generate([p2], max_new_tokens=6)[0]
    eng2 = _cache_engine(enable_prefix_cache=True)
    eng2.generate([p2], max_new_tokens=6)
    warm2 = eng2.generate([p2], max_new_tokens=6)[0]
    np.testing.assert_array_equal(warm2, ref2)
    assert eng2.reuse.cow_forks == 0
    assert eng2.reuse.prefix_tokens_reused == 16  # 2 full blocks of 21


def test_v2_prefix_cache_shared_pages_and_partial_reuse():
    """Two live sequences with a common 2-block prefix share pages
    (refcount 2), and a LONGER prompt re-admitted over a cached shorter
    one reuses exactly the common full blocks."""
    rng = np.random.default_rng(1)
    head = rng.integers(0, 97, 16).astype(np.int32)
    a = np.concatenate([head, rng.integers(0, 97, 5).astype(np.int32)])
    b = np.concatenate([head, rng.integers(0, 97, 7).astype(np.int32)])
    ref_a = _cache_engine().generate([a], max_new_tokens=6)[0]
    ref_b = _cache_engine().generate([b], max_new_tokens=6)[0]

    eng = _cache_engine(enable_prefix_cache=True)
    eng.put([1], [a], max_new_tokens=6)
    while any(s.in_prefill for s in eng.state_manager.all()):
        eng.step()
    eng.put([2], [b], max_new_tokens=6)
    seq_a, seq_b = eng.state_manager.get(1), eng.state_manager.get(2)
    assert seq_b.prefix_reused_tokens == 16      # the two shared head blocks
    assert seq_b.blocks[:2] == seq_a.blocks[:2]
    assert all(eng.kv.refs[p] == 2 for p in seq_b.blocks[:2])
    eng.kv.assert_conservation([seq_a.blocks, seq_b.blocks])
    while eng.has_work():
        if not eng.step() and eng.last_num_scheduled == 0:
            break
    np.testing.assert_array_equal(eng.query(1)[1], ref_a)
    np.testing.assert_array_equal(eng.query(2)[1], ref_b)
    eng.flush(1)
    # flushing ONE owner must not free the shared pages under the other
    assert all(eng.kv.refs[p] == 1 for p in seq_b.blocks[:2])
    eng.kv.assert_conservation([seq_b.blocks])
    eng.flush(2)
    eng.kv.assert_conservation([])
    assert eng.kv.free_blocks == eng.config.num_kv_blocks - 1


def test_v2_prefix_cache_eviction_under_pressure():
    """Filling the pool with distinct prompts must evict reclaimable cache
    LRU-first instead of failing allocation, and conservation holds
    throughout."""
    rng = np.random.default_rng(2)
    eng = _cache_engine(enable_prefix_cache=True, num_kv_blocks=16)
    for i in range(12):
        p = rng.integers(0, 97, 16).astype(np.int32)
        out = eng.generate([p], max_new_tokens=4)[0]
        assert len(out) == 4
        eng.kv.assert_conservation(
            [s.blocks for s in eng.state_manager.all()])
    assert eng.kv.index.evictions > 0            # pressure actually evicted
    assert eng.kv.free_blocks == eng.config.num_kv_blocks - 1


def test_v2_spec_decode_greedy_parity_and_acceptance():
    """The correctness contract: greedy output with speculation on is
    bitwise identical to the plain path, and a repetitive prompt yields
    nonzero draft acceptance (the speedup exists)."""
    p = np.array([5, 6, 7, 8] * 6, np.int32)
    ref = _cache_engine().generate([p], max_new_tokens=16)[0]

    eng = _cache_engine(spec_decode_k=4, spec_ngram=2)
    eng.put([1], [p], max_new_tokens=16)
    while any(s.in_prefill for s in eng.state_manager.all()):
        eng.step()
    got = list(eng.query(1)[1])
    steps = 0
    while not eng.query(1)[0]:
        r = eng.spec_decode_batch()
        if not r:
            break
        got.extend(r[1])
        steps += 1
    np.testing.assert_array_equal(np.asarray(got, np.int32), ref)
    assert eng.reuse.spec_accepted > 0
    assert steps < 15              # accepted drafts beat 1 token/step
    eng.flush(1)

    # eos mid-draft: committed tokens still truncate exactly at eos
    eos = int(ref[5])
    ref_eos = _cache_engine().generate([p], max_new_tokens=16,
                                       eos_token_id=eos)[0]
    eng2 = _cache_engine(spec_decode_k=4, spec_ngram=2)
    eng2.put([1], [p], max_new_tokens=16, eos_token_id=eos)
    while any(s.in_prefill for s in eng2.state_manager.all()):
        eng2.step()
    got2 = list(eng2.query(1)[1])
    while not eng2.query(1)[0]:
        r = eng2.spec_decode_batch()
        if not r:
            break
        got2.extend(r[1])
    np.testing.assert_array_equal(np.asarray(got2, np.int32), ref_eos)


def test_v2_spec_decode_requires_greedy():
    with pytest.raises(ValueError, match="greedy"):
        _cache_engine(spec_decode_k=4, greedy=False)
