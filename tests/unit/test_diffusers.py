"""Diffusion serving path (reference ``model_implementations/diffusers/``:
DSUNet/DSVAE CUDA-graph wrappers — here the denoise loop is one XLA program)."""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.diffusers import (DiffusionEngine, UNet2DCondition,
                                               UNetConfig, VAEConfig,
                                               VAEDecoder, VAEEncoder)


def _unet_cfg():
    return UNetConfig(block_channels=(16, 32), context_dim=16, num_heads=2,
                      time_embed_dim=32, groups=4)


def test_unet_shapes_and_jit():
    cfg = _unet_cfg()
    model = UNet2DCondition(cfg)
    lat = jnp.zeros((2, 16, 16, 4), jnp.float32)
    t = jnp.asarray([10, 500], jnp.int32)
    ctx = jnp.zeros((2, 8, 16), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), lat, t, ctx)["params"]
    out = jax.jit(lambda p, a, b, c: model.apply({"params": p}, a, b, c))(
        params, lat, t, ctx)
    assert out.shape == (2, 16, 16, 4)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_unet_conditioning_matters():
    cfg = _unet_cfg()
    model = UNet2DCondition(cfg)
    rng = np.random.default_rng(0)
    lat = jnp.asarray(rng.normal(size=(1, 8, 8, 4)), jnp.float32)
    t = jnp.asarray([100], jnp.int32)
    c1 = jnp.asarray(rng.normal(size=(1, 4, 16)), jnp.float32)
    c2 = jnp.asarray(rng.normal(size=(1, 4, 16)), jnp.float32)
    params = model.init(jax.random.PRNGKey(1), lat, t, c1)["params"]
    o1 = model.apply({"params": params}, lat, t, c1)
    o2 = model.apply({"params": params}, lat, t, c2)
    assert not np.allclose(np.asarray(o1), np.asarray(o2))
    # timestep conditioning too
    o3 = model.apply({"params": params}, lat, jnp.asarray([900], jnp.int32), c1)
    assert not np.allclose(np.asarray(o1), np.asarray(o3))


def test_vae_roundtrip_shapes():
    cfg = VAEConfig(block_channels=(8, 16), groups=4)
    enc, dec = VAEEncoder(cfg), VAEDecoder(cfg)
    img = jnp.zeros((1, 32, 32, 3), jnp.float32)
    ep = enc.init(jax.random.PRNGKey(0), img)["params"]
    z = enc.apply({"params": ep}, img)
    assert z.shape == (1, 8, 8, 4)  # 2 levels -> /4
    dp = dec.init(jax.random.PRNGKey(1), z)["params"]
    out = dec.apply({"params": dp}, z)
    assert out.shape == (1, 32, 32, 3)
    assert float(jnp.max(jnp.abs(out))) <= 1.0  # tanh range


def test_engine_generates_deterministic_images():
    ucfg = _unet_cfg()
    model = UNet2DCondition(ucfg)
    lat = jnp.zeros((1, 8, 8, 4), jnp.float32)
    ctx = jnp.zeros((1, 4, 16), jnp.float32)
    uparams = model.init(jax.random.PRNGKey(2), lat,
                         jnp.asarray([0], jnp.int32), ctx)["params"]
    vcfg = VAEConfig(block_channels=(8, 16), groups=4)
    z = jnp.zeros((1, 8, 8, 4), jnp.float32)
    vparams = VAEDecoder(vcfg).init(jax.random.PRNGKey(3), z)["params"]

    eng = DiffusionEngine(ucfg, uparams, vcfg, vparams, num_steps=4)
    rng = np.random.default_rng(1)
    context = jnp.asarray(rng.normal(size=(1, 4, 16)), jnp.float32)
    img1 = eng.generate(context, height=8, width=8, seed=7)
    img2 = eng.generate(context, height=8, width=8, seed=7)
    assert img1.shape == (1, 32, 32, 3)
    np.testing.assert_array_equal(np.asarray(img1), np.asarray(img2))
    assert bool(jnp.all(jnp.isfinite(img1)))
    # guidance: different context -> different image
    ctx_b = jnp.asarray(rng.normal(size=(1, 4, 16)), jnp.float32)
    img3 = eng.generate(ctx_b, height=8, width=8, seed=7)
    assert not np.allclose(np.asarray(img1), np.asarray(img3))
