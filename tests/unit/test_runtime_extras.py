"""Tests for TiledLinear, Domino, PLD, eigenvalue, MoQ, sparse grads
(reference: tests/unit/runtime/{test_pld,...}, ops tiling tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.runtime.domino import DominoTransformerLayer, domino_chunked
from deepspeed_tpu.runtime.eigenvalue import Eigenvalue, hvp
from deepspeed_tpu.runtime.progressive_layer_drop import (ProgressiveLayerDrop,
                                                          pld_apply)
from deepspeed_tpu.runtime.quantize import MoQQuantizer, WeightQuantization
from deepspeed_tpu.runtime.sparse_tensor import (SparseTensor, from_dense,
                                                 sparse_all_reduce)
from deepspeed_tpu.runtime.zero.tiling import TiledLinear, tiled_matmul


# ---------------------------------------------------------------------------
# TiledLinear
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("in_splits,out_splits,remat",
                         [(1, 4, False), (2, 2, True), (4, 1, False)])
def test_tiled_matmul_matches_dense(in_splits, out_splits, remat):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(3, 16)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))
    y = tiled_matmul(x, w, out_splits, in_splits, remat)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x @ w), rtol=1e-5)
    # gradients flow through tiles
    g = jax.grad(lambda w: jnp.sum(tiled_matmul(x, w, out_splits, in_splits,
                                                remat)))(w)
    np.testing.assert_allclose(np.asarray(g),
                               np.asarray(jax.grad(lambda w: jnp.sum(x @ w))(w)),
                               rtol=1e-5)


def test_tiled_linear_module():
    m = TiledLinear(in_features=8, out_features=12, in_splits=2, out_splits=3)
    x = jnp.ones((2, 8))
    params = m.init(jax.random.PRNGKey(0), x)["params"]
    y = m.apply({"params": params}, x)
    ref = x @ params["kernel"] + params["bias"]
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5)
    with pytest.raises(ValueError, match="not divisible"):
        tiled_matmul(x, params["kernel"], out_splits=5)


# ---------------------------------------------------------------------------
# Domino
# ---------------------------------------------------------------------------


def test_domino_chunked_equivalence():
    w = jnp.asarray(np.random.default_rng(1).normal(size=(8, 8)).astype(np.float32))
    fn = lambda x: jnp.tanh(x @ w)
    x = jnp.asarray(np.random.default_rng(2).normal(size=(6, 8)).astype(np.float32))
    np.testing.assert_allclose(np.asarray(domino_chunked(fn, x, 2)),
                               np.asarray(fn(x)), rtol=1e-6)
    # indivisible batch falls back to unchunked
    x5 = x[:5]
    np.testing.assert_allclose(np.asarray(domino_chunked(fn, x5, 2)),
                               np.asarray(fn(x5)), rtol=1e-6)
    layer = DominoTransformerLayer(lambda x, s: x * s, num_chunks=2)
    np.testing.assert_allclose(np.asarray(layer(x, 2.0)), np.asarray(x * 2.0))


# ---------------------------------------------------------------------------
# progressive layer drop
# ---------------------------------------------------------------------------


def test_pld_theta_schedule():
    pld = ProgressiveLayerDrop(theta=0.5, gamma=0.01)
    assert pld.get_theta(0) == pytest.approx(1.0)
    assert pld.get_theta(10**6) == pytest.approx(0.5)
    mid = pld.get_theta(100)
    assert 0.5 < mid < 1.0
    pld.update_state(100)
    assert pld.get_state()["pld_theta"] == pytest.approx(mid)
    # deeper layers drop more
    assert pld.keep_prob(1, 12) > pld.keep_prob(11, 12)


def test_pld_apply_semantics():
    layer = lambda x: x + 1.0  # residual contribution = 1
    x = jnp.zeros((4, 4))
    # deterministic: always applied
    out = pld_apply(layer, x, jax.random.PRNGKey(0), keep_prob=0.3,
                    deterministic=True)
    np.testing.assert_allclose(np.asarray(out), 1.0)
    # stochastic: either skipped (0) or scaled (1/keep_prob)
    outs = {float(np.asarray(pld_apply(layer, x, jax.random.PRNGKey(s), 0.5))[0, 0])
            for s in range(20)}
    assert outs <= {0.0, 2.0} and len(outs) == 2


# ---------------------------------------------------------------------------
# eigenvalue
# ---------------------------------------------------------------------------


def test_eigenvalue_quadratic():
    """For loss = 0.5 x^T A x the Hessian is A; power iteration finds the
    dominant eigenvalue."""
    a = jnp.diag(jnp.asarray([5.0, 2.0, 1.0]))

    def loss(params, batch):
        x = params["x"]
        return 0.5 * x @ a @ x

    params = {"x": jnp.asarray([1.0, 1.0, 1.0])}
    hv = hvp(loss, params, None, {"x": jnp.asarray([1.0, 0.0, 0.0])})
    np.testing.assert_allclose(np.asarray(hv["x"]), [5.0, 0.0, 0.0], atol=1e-5)
    eig = Eigenvalue(max_iter=50, tol=1e-4).compute_eigenvalue(loss, params, None)
    assert eig == pytest.approx(5.0, rel=1e-2)


# ---------------------------------------------------------------------------
# MoQ
# ---------------------------------------------------------------------------


def test_moq_schedule_and_eigen_modulation():
    q = MoQQuantizer(start_bits=16, target_bits=4, quantize_period=10,
                     eigenvalue_scale={"sharp": 2.0})
    assert q.bits_at(0) == 16
    assert q.bits_at(10) == 8
    assert q.bits_at(20) == 4
    assert q.bits_at(1000) == 4
    # sharp layer quantizes later (doubled period)
    assert q.bits_at(10, key="sharp") == 16
    assert q.bits_at(20, key="sharp") == 8
    assert issubclass(WeightQuantization, MoQQuantizer)


def test_moq_quantize_params():
    q = MoQQuantizer(start_bits=8, target_bits=8, quantize_period=0)
    params = {"w": jnp.asarray(np.random.default_rng(0)
                               .normal(size=(8, 8)).astype(np.float32)),
              "b": jnp.zeros((8,))}
    out = q.quantize(params, step=100)
    assert not np.array_equal(np.asarray(out["w"]), np.asarray(params["w"]))
    np.testing.assert_allclose(np.asarray(out["w"]), np.asarray(params["w"]),
                               atol=0.05)
    np.testing.assert_array_equal(np.asarray(out["b"]), 0)  # 1-D untouched


# ---------------------------------------------------------------------------
# sparse gradients
# ---------------------------------------------------------------------------


def test_sparse_tensor_roundtrip():
    dense = jnp.zeros((10, 4)).at[jnp.asarray([2, 7])].set(
        jnp.asarray([[1.0, 2, 3, 4], [5, 6, 7, 8]]))
    st = from_dense(dense, max_rows=3)
    assert st.sparse_size == 12  # vs dense 40
    np.testing.assert_allclose(np.asarray(st.to_dense()), np.asarray(dense))


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 devices")
def test_sparse_all_reduce_matches_dense():
    ndev = 4
    mesh = Mesh(np.array(jax.devices()[:ndev]), ("dp",))
    rng = np.random.default_rng(0)
    # per-rank embedding grads touching few rows
    dense = np.zeros((ndev, 16, 4), np.float32)
    for r in range(ndev):
        rows = rng.choice(16, size=2, replace=False)
        dense[r, rows] = rng.normal(size=(2, 4))
    expected = dense.mean(axis=0)

    def body(g):
        st = from_dense(g[0], max_rows=4)
        return sparse_all_reduce(st, "dp")[None]

    from deepspeed_tpu.utils.shard_map_compat import shard_map_nocheck

    out = jax.jit(shard_map_nocheck(body, mesh, in_specs=P("dp"),
                                    out_specs=P("dp")))(jnp.asarray(dense))
    np.testing.assert_allclose(np.asarray(out)[0], expected, rtol=1e-5)


# ---------------------------------------------------------------------------
# activation checkpointing API + mu optimizers
# ---------------------------------------------------------------------------


def test_activation_checkpointing_api():
    """Reference deepspeed.checkpointing: configure + checkpoint wrap; on TPU
    checkpoint == jax.checkpoint (gradients must match the unwrapped fn)."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.runtime import activation_checkpointing as ac

    ac.configure(deepspeed_config={"activation_checkpointing": {
        "partition_activations": True, "cpu_checkpointing": False}},
        policy="nothing_saveable")
    assert ac.get_config()["partition_activations"]

    def f(x, w):
        return jnp.sum(jnp.tanh(x @ w) ** 2)

    x = jnp.ones((4, 8))
    w = jnp.full((8, 8), 0.1)
    g_plain = jax.grad(f, argnums=1)(x, w)
    g_ckpt = jax.grad(lambda x_, w_: ac.checkpoint(f, x_, w_),
                      argnums=1)(x, w)
    import numpy as np
    np.testing.assert_allclose(np.asarray(g_plain), np.asarray(g_ckpt),
                               rtol=1e-6)


def test_mu_optimizers():
    """muAdam scales matrix-param lr by base_width/fan_in; muSGD scales
    vector params by fan_out/base_width (reference test_mup_optimizers)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.ops.optimizers import build_optimizer

    params = {"w": jnp.zeros((64, 4)), "b": jnp.zeros((4,)),
              "o_proj": {"kernel": jnp.zeros((8, 8, 4))},   # row: fan_in 64
              "embed_tokens": {"embedding": jnp.zeros((1000, 4))},
              "moe": {"expert_up_proj": jnp.zeros((2, 64, 8))}}  # E batch dim
    grads = jax.tree.map(jnp.ones_like, params)

    tx = build_optimizer("MuAdam", {"lr": 1e-2, "base_width": 16})
    state = tx.init(params)
    upd, _ = tx.update(grads, state, params)
    # adam step magnitude is ~lr per element; matrix gets * 16/64 = 0.25
    ratio = float(jnp.abs(upd["w"]).mean() / jnp.abs(upd["b"]).mean())
    np.testing.assert_allclose(ratio, 0.25, rtol=1e-3)
    # 3-D row-parallel kernel contracts all but the last dim: 16/(8*8)
    r3 = float(jnp.abs(upd["o_proj"]["kernel"]).mean()
               / jnp.abs(upd["b"]).mean())
    np.testing.assert_allclose(r3, 0.25, rtol=1e-3)
    # input embedding tables are NOT width-scaled (vocab is finite)
    re_ = float(jnp.abs(upd["embed_tokens"]["embedding"]).mean()
                / jnp.abs(upd["b"]).mean())
    np.testing.assert_allclose(re_, 1.0, rtol=1e-3)
    # stacked expert kernels [E, d, f]: the expert dim is NOT a width;
    # fan_in = d -> 16/64
    rex = float(jnp.abs(upd["moe"]["expert_up_proj"]).mean()
                / jnp.abs(upd["b"]).mean())
    np.testing.assert_allclose(rex, 0.25, rtol=1e-3)

    tx = build_optimizer("MuSGD", {"lr": 1e-2, "base_width": 2})
    state = tx.init(params)
    upd, _ = tx.update(grads, state, params)
    # sgd: matrix unscaled, vector scaled by 4/2 = 2
    ratio = float(jnp.abs(upd["b"]).mean() / jnp.abs(upd["w"]).mean())
    np.testing.assert_allclose(ratio, 2.0, rtol=1e-6)


def test_cpu_checkpointing_offloads_and_matches():
    """checkpoint_in_cpu=True engages the pinned-host offload remat policy
    (reference checkpointing.py CPU-checkpointing tier) without changing
    values or gradients."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.runtime import activation_checkpointing as ckpt

    prev = ckpt.get_config()
    try:
        ckpt.configure(checkpoint_in_cpu=True)
        assert ckpt.get_config()["cpu_checkpointing"] is True
        w = jnp.asarray(np.random.default_rng(0).normal(size=(32, 32)), jnp.float32)
        x = jnp.asarray(np.random.default_rng(1).normal(size=(4, 32)), jnp.float32)

        def f(w_, x_):
            return jnp.sum(ckpt.checkpoint(lambda a: jnp.tanh(a @ w_) @ w_, x_) ** 2)

        g_off = jax.jit(jax.grad(f))(w, x)
        ckpt.configure(checkpoint_in_cpu=False)
        g_plain = jax.jit(jax.grad(f))(w, x)
        np.testing.assert_allclose(np.asarray(g_off), np.asarray(g_plain),
                                   rtol=1e-5)
    finally:
        ckpt._config.update(prev)


def test_moq_quantize_training_wired_into_engine():
    """A quantize_training config section drives fake-quantized training
    end-to-end: full precision through schedule_offset, annealed bit-widths
    after, one compiled program per width (reference MoQ runtime)."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.parallel.topology import Topology, TopologySpec, set_topology

    from .simple_model import make_simple_params, random_batches, simple_loss

    set_topology(Topology(TopologySpec()))
    engine, *_ = ds.initialize(
        model=simple_loss, model_parameters=make_simple_params(hidden=64, seed=0),
        config={"train_micro_batch_size_per_gpu": 8,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
                "quantize_training": {
                    "quantize_bits": {"start_bits": 8, "target_bits": 4},
                    "quantize_schedule": {"quantize_period": 2,
                                          "schedule_offset": 2},
                    "quantize_groups": 4},
                "steps_per_print": 10**9})
    assert engine.moq is not None
    batches = random_batches(8, 8, hidden=64, seed=0)
    for b in batches[:2]:
        engine.train_batch(b)          # steps 0-1: warmup, unquantized
    assert set(engine._train_steps) == {(None, None)}
    for b in batches[2:4]:
        engine.train_batch(b)          # steps 2-3: 8-bit program
    assert (None, 8) in engine._train_steps
    for b in batches[4:6]:
        engine.train_batch(b)          # steps 4-5: 4-bit program
    assert (None, 4) in engine._train_steps
    losses = [float(engine.train_batch(b)) for b in batches[6:]]
    assert all(np.isfinite(losses))
    # target reached: no further programs appear
    n = len(engine._train_steps)
    engine.train_batch(batches[0])
    assert len(engine._train_steps) == n
