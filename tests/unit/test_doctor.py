"""Fleet post-mortem doctor tests (``deepspeed_tpu/doctor`` + the
collective flight recorder, ``telemetry/collective.py``).

Coverage: recorder ring/seq/phase semantics and the comm-wrapper hooks,
collective rings riding flight dumps, stream-divergence analysis (mismatch,
extra-tail, ring truncation), doctor verdicts on synthetic dump sets
(clean/hang, missing rank, desync, straggler, dead host, plan mismatch),
trace merging, the CLI (report file + desync exit code 2), the supervisor's
exit-83 doctor wiring — and the REAL drill: three engine processes, rank 1
issues an extra collective, the watchdogs fire exit-83, and the doctor
names rank 1 and the first divergent seq from the artifacts alone.
"""

import json
import os
import subprocess
import sys
import textwrap
import threading

import numpy as np
import pytest

from deepspeed_tpu import doctor
from deepspeed_tpu.telemetry import (CollectiveRecorder,
                                     configure_collective_recorder,
                                     get_collective_recorder)
from deepspeed_tpu.telemetry.spans import configure_tracer, get_tracer

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
HIDDEN = 48


@pytest.fixture(autouse=True)
def _reset_recorder():
    yield
    configure_collective_recorder(enabled=False)
    get_collective_recorder().clear()
    configure_tracer(enabled=False)
    get_tracer().clear()
    from deepspeed_tpu.telemetry import reset_registry
    from deepspeed_tpu.telemetry import manager as _mgr

    reset_registry()
    _mgr._ACTIVE = False
    _mgr._OWNER = None


# ---------------------------------------------------------------------------
# collective recorder
# ---------------------------------------------------------------------------


def test_recorder_ring_seq_and_disabled_noop():
    rec = CollectiveRecorder(enabled=True, max_records=4)
    for i in range(6):
        rec.record("all_reduce", shape=(8,), dtype="float32", axes=("dp",))
    snap = rec.snapshot()
    assert [r["seq"] for r in snap] == [2, 3, 4, 5]  # bounded, seqs survive
    assert rec.last_seq() == 5
    assert snap[0]["op"] == "all_reduce" and snap[0]["axes"] == ["dp"]
    off = CollectiveRecorder(enabled=False)
    assert off.record("x") is None
    assert off.snapshot() == [] and off.last_seq() == -1


def test_recorder_stamps_phase_and_step_from_tracer():
    tr = configure_tracer(enabled=True)
    tr.set_step(9)
    rec = CollectiveRecorder(enabled=True)
    with tr.span("compute/dispatch"):
        rec.record("all_gather", shape=(4,), axes=("tp",))
    rec.record("barrier", eager=True, detail="step-end")
    a, b = rec.snapshot()
    assert a["phase"] == "compute/dispatch" and a["step"] == 9
    assert "phase" not in b and b["detail"] == "step-end" and b["eager"]


def test_comm_wrappers_record_launches():
    """The real hook: tracing a shard_map program through the comm wrappers
    records op/shape/dtype/axes at trace time; eager barriers record with
    their name; disabled records nothing."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.utils.shard_map_compat import shard_map

    mesh = Mesh(np.array(jax.devices()[:1]), ("dp",))

    def f(x):
        return dist.all_reduce(x, "dp") + dist.all_gather(x, "dp").sum()

    fn = jax.jit(shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")))
    fn(jnp.ones((4,), jnp.float32))  # recorder off: nothing recorded
    assert get_collective_recorder().snapshot() == []

    configure_collective_recorder(enabled=True, max_records=64)

    def g(x):
        return dist.all_reduce(x * 2, "dp")

    jax.jit(shard_map(g, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")))(
        jnp.ones((8,), jnp.float32))
    dist.barrier("unit-barrier")
    recs = get_collective_recorder().snapshot()
    assert [r["op"] for r in recs] == ["all_reduce", "barrier"]
    assert recs[0]["shape"] == [8] and recs[0]["axes"] == ["dp"]
    assert recs[0]["dtype"] == "float32"
    assert recs[1]["detail"] == "unit-barrier" and recs[1]["eager"]


def test_flight_dump_carries_collective_ring(tmp_path):
    from deepspeed_tpu.telemetry import FlightRecorder, SpanTracer

    rec = CollectiveRecorder(enabled=True)
    tr = SpanTracer(enabled=True)
    fl = FlightRecorder(tr, str(tmp_path), steps=4, rank=2, collectives=rec)
    rec.record("all_reduce", shape=(8,), axes=("dp",))
    fl.record_step(0)
    rec.record("all_gather", shape=(8,), axes=("dp",))
    entry = fl.record_step(1)
    assert entry["collective_seq"] == 1
    doc = json.load(open(fl.dump("unit")))
    assert [c["op"] for c in doc["collectives"]] == ["all_reduce",
                                                     "all_gather"]
    assert [s["collective_seq"] for s in doc["steps"]] == [0, 1]


# ---------------------------------------------------------------------------
# stream divergence analysis
# ---------------------------------------------------------------------------


def _C(seq, op, shape=(64,), axes=("dp",), dtype="float32", detail=None,
       impl=None):
    r = {"seq": seq, "op": op, "shape": list(shape), "dtype": dtype,
         "axes": list(axes), "t_ns": seq}
    if detail is not None:
        r["detail"] = detail
    if impl is not None:
        r["impl"] = impl
    return r


def test_divergence_mismatch_names_minority_rank():
    base = [_C(0, "all_reduce"), _C(1, "all_gather"),
            _C(2, "barrier", shape=(), axes=(), detail="step-end")]
    div = base[:2] + [_C(2, "barrier", shape=(), axes=(),
                         detail="injected")]
    d = doctor.analyze_collective_streams({0: base, 1: div, 2: base})
    assert d["kind"] == "mismatch" and d["first_divergent_seq"] == 2
    assert d["divergent_ranks"] == [1]
    assert "injected" in d["per_rank"]["1"]["signature"]
    assert "step-end" in d["majority"]


def test_divergence_shape_mismatch_and_none_when_identical():
    a = [_C(0, "all_reduce", shape=(128,))]
    b = [_C(0, "all_reduce", shape=(256,))]
    d = doctor.analyze_collective_streams({0: a, 1: b, 2: a})
    assert d["kind"] == "mismatch" and d["first_divergent_seq"] == 0
    assert d["divergent_ranks"] == [1]
    assert doctor.analyze_collective_streams({0: a, 1: list(a)}) is None
    assert doctor.analyze_collective_streams({0: a}) is None  # 1 rank


def test_divergence_extra_tail_gated_on_stopped():
    base = [_C(0, "all_reduce"), _C(1, "all_gather")]
    extra = base + [_C(2, "all_reduce")]
    d = doctor.analyze_collective_streams({0: base, 1: extra, 2: base})
    assert d["kind"] == "extra" and d["first_divergent_seq"] == 2
    assert d["divergent_ranks"] == [1]
    # dump-time skew (rollback/drain sets): the tail is NOT evidence
    assert doctor.analyze_collective_streams(
        {0: base, 1: extra, 2: base}, tail_is_evidence=False) is None


def test_divergence_far_apart_windows_is_cheap():
    """Seq counters are process-lifetime: a stale dump can sit millions of
    seqs from a fresh one. The walk must be bounded by recorded seqs, not
    range(min, max)."""
    import time as _time

    near = [_C(i, "all_reduce") for i in range(3)]
    far = [_C(10_000_000 + i, "all_reduce") for i in range(3)]
    t0 = _time.perf_counter()
    d = doctor.analyze_collective_streams({0: near, 1: far})
    assert _time.perf_counter() - t0 < 1.0
    assert d["kind"] == "extra" and d["divergent_ranks"] == [1]


def test_divergence_tolerates_seq_hole_in_window():
    """Two recording threads can interleave seq assignment and append, so
    eviction may leave a hole inside a rank's window — absent evidence,
    not a KeyError."""
    full = [_C(i, "all_reduce") for i in range(4)]
    holed = [_C(0, "all_reduce"), _C(2, "all_reduce"),
             _C(3, "all_reduce")]                 # seq 1 evicted out of order
    assert doctor.analyze_collective_streams({0: full, 1: holed}) is None
    bad = holed[:-1] + [_C(3, "all_gather")]
    d = doctor.analyze_collective_streams({0: full, 1: bad})
    assert d["kind"] == "mismatch" and d["first_divergent_seq"] == 3


def test_divergence_fused_vs_sequenced_fallback_names_rank():
    """PR 14 fused phases stamp ONE launch per hop (impl="fused_matmul",
    per-hop detail); a rank that degraded to the sequenced program records
    a single program_reduce_scatter launch instead. The seq streams
    diverge at the FIRST fused hop and the doctor names the sequenced
    rank against the fused majority."""
    def fused_stream():
        hops = [_C(h, "fused_ring_reduce_scatter", shape=(2560,),
                   axes=("ep",), impl="fused_matmul",
                   detail=f"dp-grad/bwd@producer:exact:hop{h + 1}/3")
                for h in range(3)]
        return hops + [_C(3, "quantized_all_reduce", shape=(10240,),
                          axes=("dp_outer",), impl="int8_ef")]

    sequenced = [_C(0, "program_reduce_scatter", shape=(10240,),
                    axes=("ep",), impl="exact"),
                 _C(1, "quantized_all_reduce", shape=(10240,),
                    axes=("dp_outer",), impl="int8_ef")]
    d = doctor.analyze_collective_streams(
        {0: fused_stream(), 1: fused_stream(), 2: fused_stream(),
         3: sequenced})
    assert d["kind"] == "mismatch" and d["first_divergent_seq"] == 0
    assert d["divergent_ranks"] == [3]
    assert "fused_matmul" in d["majority"] and "hop1/3" in d["majority"]
    assert "program_reduce_scatter" in d["per_rank"]["3"]["signature"]


def test_divergence_respects_ring_truncation():
    """A rank whose bounded ring evicted old seqs is only compared where
    its window overlaps — eviction is not divergence."""
    full = [_C(i, "all_reduce") for i in range(6)]
    trunc = [_C(i, "all_reduce") for i in range(3, 6)]  # ring of 3
    assert doctor.analyze_collective_streams({0: full, 1: trunc}) is None
    bad = trunc[:-1] + [_C(5, "all_gather")]
    d = doctor.analyze_collective_streams({0: full, 1: bad})
    assert d["kind"] == "mismatch" and d["first_divergent_seq"] == 5


# ---------------------------------------------------------------------------
# doctor on synthetic dump sets
# ---------------------------------------------------------------------------


def _write_dump(d, rank, colls, reason="watchdog",
                phase="compute/dispatch", extra=None):
    doc = {"reason": reason, "rank": rank, "pid": 100 + rank, "sequence": 1,
           "wall_time": 1000.0, "last_phase": phase,
           "open_spans": ([{"name": "step"}, {"name": phase}]
                          if reason == "watchdog" else []),
           "inflight_spans": [],
           "steps": [{"step": 3, "wall_time": 999.0, "spans": []}],
           "collectives": colls}
    doc.update(extra or {})
    path = os.path.join(d, f"flightdump-{rank}.json")
    json.dump(doc, open(path, "w"))
    return path


def _write_beacon(d, rank, wall, step_time=0.1, step=3):
    json.dump({"rank": rank, "step": step, "step_time_s": step_time,
               "wall_time": wall},
              open(os.path.join(d, f"hb-{rank}.json"), "w"))


_BASE = [_C(0, "all_reduce"), _C(1, "all_gather"),
         _C(2, "barrier", shape=(), axes=(), detail="step-end")]


def test_doctor_hang_verdict_on_consistent_streams(tmp_path):
    d = str(tmp_path)
    for r in range(3):
        _write_dump(d, r, list(_BASE))
        _write_beacon(d, r, 1000.0 + 0.1 * r)
    rep = doctor.diagnose(d)
    assert rep["verdict"] == "hang"
    assert rep["desync"] is None and rep["missing_ranks"] == []
    assert rep["phases"] == {"compute/dispatch": [0, 1, 2]}
    assert any("genuine hang" in e for e in rep["evidence"])
    text = doctor.render_report(rep)
    assert "HANG" in text and "compute/dispatch" in text


def test_doctor_desync_verdict_and_report(tmp_path):
    d = str(tmp_path)
    div = _BASE[:2] + [_C(2, "barrier", shape=(), axes=(),
                          detail="injected"),
                       _C(3, "barrier", shape=(), axes=(),
                          detail="step-end")]
    _write_dump(d, 0, list(_BASE))
    _write_dump(d, 1, div)
    _write_dump(d, 2, list(_BASE))
    rep = doctor.diagnose(d)
    assert rep["verdict"] == "desync"
    ds = rep["desync"]
    assert ds["first_divergent_seq"] == 2 and ds["divergent_ranks"] == [1]
    path = doctor.write_report(rep, os.path.join(d, doctor.REPORT_NAME))
    assert json.load(open(path))["verdict"] == "desync"


def test_doctor_missing_rank_is_dead_host(tmp_path):
    d = str(tmp_path)
    for r in (0, 1, 3):
        _write_dump(d, r, list(_BASE))
    rep = doctor.diagnose(d)   # world inferred from the highest rank seen
    assert rep["missing_ranks"] == [2]
    assert rep["verdict"] == "dead_host"
    rep5 = doctor.diagnose(d, world=5)
    assert rep5["missing_ranks"] == [2, 4]


def test_doctor_dead_beacon_and_straggler(tmp_path):
    d = str(tmp_path)
    # rank 2's beacon froze 120s before the newest; no desync evidence
    for r in range(3):
        _write_dump(d, r, list(_BASE), reason="preempt_drain", phase=None)
    _write_beacon(d, 0, 1000.0)
    _write_beacon(d, 1, 1000.5)
    _write_beacon(d, 2, 880.0)
    rep = doctor.diagnose(d, dead_after_s=60.0)
    assert rep["health"]["dead"] == [2]
    assert rep["verdict"] == "dead_host"
    # straggler set: all alive, rank 1 steps 10x slower than its peers
    d2 = str(tmp_path / "s")
    os.makedirs(d2)
    for r in range(3):
        _write_dump(d2, r, list(_BASE), reason="preempt_drain", phase=None)
        _write_beacon(d2, r, 1000.0, step_time=1.0 if r == 1 else 0.1)
    rep2 = doctor.diagnose(d2)
    assert rep2["health"]["stragglers"] == [1]
    assert rep2["verdict"] == "straggler"
    assert rep2["health"]["rows"]["1"]["ratio"] == 10.0


def test_doctor_plan_mismatch_is_desync(tmp_path):
    d = str(tmp_path)
    plan_a = {"site": {"impl": "ring"}}
    plan_b = {"site": {"impl": "xla"}}
    _write_dump(d, 0, [], extra={"plan": plan_a})
    _write_dump(d, 1, [], extra={"plan": plan_b})
    _write_dump(d, 2, [], extra={"plan": plan_a})
    rep = doctor.diagnose(d)
    assert rep["verdict"] == "desync"
    assert rep["plan_mismatch"]["ranks"] == [1]


def test_doctor_plan_rank_local_fields_not_a_mismatch(tmp_path):
    """est_us (live microbench timing) and source (cache warmth) are
    rank-local: fake-fleet measure-mode runs differ there on every healthy
    rank and must NOT read as a desync."""
    d = str(tmp_path)
    for r in range(3):
        _write_dump(d, r, list(_BASE), extra={"plan": {
            "site": {"impl": "ring", "block": 2048,
                     "est_us": 10.0 + r,                   # rank-local
                     "source": "measured" if r else "cache"}}})
    rep = doctor.diagnose(d)
    assert rep["plan_mismatch"] is None
    assert rep["verdict"] == "hang"


def test_doctor_crash_verdict_with_exception_meta(tmp_path):
    d = str(tmp_path)
    _write_dump(d, 0, [], reason="crash",
                extra={"exception": "ValueError",
                       "message": "batch dim 7 not divisible"})
    rep = doctor.diagnose(d)
    assert rep["verdict"] == "crash"
    assert rep["ranks"]["0"]["exception"] == "ValueError"
    assert any("ValueError" in e for e in rep["evidence"])


def test_doctor_hangdump_meta_parsed(tmp_path):
    d = str(tmp_path)
    (tmp_path / "hangdump-0.txt").write_text(
        "==== watchdog hangdump rank=0 pid=77 step=5 deadline_s=2.0 "
        "wall=1234.500 ====\nThread 0x1 (most recent call first):\n...\n"
        "==== watchdog hangdump rank=0 pid=78 step=9 deadline_s=1.5 "
        "wall=1300.250 ====\nstacks\n")
    rep = doctor.diagnose(d)
    hd = rep["ranks"]["0"]["hangdump"]
    assert hd["dumps"] == 2 and hd["last_step"] == 9
    assert hd["deadline_s"] == 1.5 and hd["wall_time"] == 1300.25
    # telemetry was off (no flightdumps) but the watchdog clearly fired:
    # that is a HANG verdict, not "clean"
    assert rep["verdict"] == "hang"
    assert any("hangdump" in e for e in rep["evidence"])


def test_doctor_merge_trace(tmp_path):
    d = str(tmp_path)
    for r in range(2):
        json.dump({"traceEvents": [
            {"name": "process_name", "ph": "M", "pid": r,
             "args": {"name": f"rank {r}"}},
            {"name": "step", "ph": "X", "pid": r, "tid": 1,
             "ts": 0, "dur": 5}]},
            open(os.path.join(d, f"spans-{r}.trace.json"), "w"))
    out = doctor.merge_traces(d)
    evs = json.load(open(out))["traceEvents"]
    assert len(evs) == 4
    assert {e["pid"] for e in evs} == {0, 1}
    assert doctor.merge_traces(str(tmp_path / "empty" )) is None


def test_doctor_cli_exit_codes_and_report(tmp_path, capsys):
    """In-process CLI (the drill exercises the real subprocess form): exit
    2 + report file on desync, exit 0 on a clean set."""
    from deepspeed_tpu.doctor.__main__ import main as doctor_main

    d = str(tmp_path)
    div = _BASE[:2] + [_C(2, "all_reduce", shape=(999,))]
    _write_dump(d, 0, list(_BASE))
    _write_dump(d, 1, div)
    _write_dump(d, 2, list(_BASE))
    rc = doctor_main([d])
    assert rc == doctor.EXIT_DESYNC
    assert "DESYNC" in capsys.readouterr().out
    rep = json.load(open(os.path.join(d, doctor.REPORT_NAME)))
    assert rep["desync"]["divergent_ranks"] == [1]
    # a clean set exits 0
    d2 = str(tmp_path / "clean")
    os.makedirs(d2)
    for rk in range(2):
        _write_dump(d2, rk, list(_BASE), reason="preempt_drain", phase=None)
    rc = doctor_main([d2, "--json"])
    assert rc == 0
    assert json.loads(capsys.readouterr().out)["verdict"] == "preempt"
    # not-a-directory is a usage error, not a crash
    assert doctor_main([str(tmp_path / "nope")]) == 1


def test_supervise_hang_runs_doctor(tmp_path):
    """The launcher wiring: a watchdog-hang child exit makes _supervise
    write doctor-report.json next to the dumps before relaunching."""
    from deepspeed_tpu.launcher.launch import (EXIT_WATCHDOG_HANG,
                                               RestartPolicy, _supervise)

    dump_dir = tmp_path / "dumps"
    dump_dir.mkdir()
    div = _BASE[:2] + [_C(2, "barrier", shape=(), axes=(),
                          detail="injected")]
    _write_dump(str(dump_dir), 0, list(_BASE))
    _write_dump(str(dump_dir), 1, div)
    _write_dump(str(dump_dir), 2, list(_BASE))
    marker = tmp_path / "marker"
    child = tmp_path / "child.py"
    child.write_text(textwrap.dedent(f"""\
        import os, sys
        m = {str(marker)!r}
        if not os.path.exists(m):
            open(m, 'w').close()
            sys.exit({EXIT_WATCHDOG_HANG})
        sys.exit(0)
        """))
    pol = RestartPolicy(backoff_base_s=0.0, jitter_frac=0.0)
    env = dict(os.environ, DSTPU_DUMP_DIR=str(dump_dir))
    rc = _supervise([sys.executable, str(child)], env, policy=pol,
                    sleep=lambda s: None)
    assert rc == 0
    rep = json.load(open(dump_dir / doctor.REPORT_NAME))
    assert rep["verdict"] == "desync"
    assert rep["desync"]["divergent_ranks"] == [1]
    # the TERMINAL hang (budget exhausted -> rc propagates) must also get
    # its post-mortem: that last hang is the one the operator reads
    os.unlink(dump_dir / doctor.REPORT_NAME)
    always_hang = tmp_path / "always.py"
    always_hang.write_text(f"import sys; sys.exit({EXIT_WATCHDOG_HANG})\n")
    pol2 = RestartPolicy(backoff_base_s=0.0, jitter_frac=0.0,
                         crash_loop_budget=1, min_uptime_s=60.0)
    rc = _supervise([sys.executable, str(always_hang)], env, policy=pol2,
                    sleep=lambda s: None)
    assert rc == EXIT_WATCHDOG_HANG
    assert (dump_dir / doctor.REPORT_NAME).exists()


def test_run_doctor_forwards_known_world_size(tmp_path):
    """The supervisor knows DSTPU_NUM_PROCESSES: a dead highest-rank host
    (no artifacts at all) must read as missing, not shrink the world."""
    from deepspeed_tpu.launcher.launch import _run_doctor

    d = tmp_path / "dumps"
    d.mkdir()
    for r in (0, 1):
        _write_dump(str(d), r, list(_BASE))
    _run_doctor(str(d), {"DSTPU_DUMP_DIR": str(d),
                         "DSTPU_NUM_PROCESSES": "3"})
    rep = json.load(open(d / doctor.REPORT_NAME))
    assert rep["world"] == 3
    assert rep["missing_ranks"] == [2]
    assert rep["verdict"] == "dead_host"


# ---------------------------------------------------------------------------
# THE DRILL: a real multi-process desync
# ---------------------------------------------------------------------------


_DRILL_BODY = """\
    import os, sys
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    rank = int(sys.argv[1]); dump_dir = sys.argv[2]
    os.environ["DSTPU_PROCESS_ID"] = str(rank)
    sys.path.insert(0, {root!r})
    import deepspeed_tpu as ds
    import deepspeed_tpu.comm as dist
    from tests.unit.simple_model import (make_simple_params, random_batches,
                                         simple_loss)
    engine, *_ = ds.initialize(
        model=simple_loss, model_parameters=make_simple_params({hidden}),
        config={{
            "train_micro_batch_size_per_gpu": 8,
            "optimizer": {{"type": "adam", "params": {{"lr": 1e-2}}}},
            "steps_per_print": 1000,
            "telemetry": {{"enabled": True, "flight_steps": 8}},
            "resilience": {{
                "enabled": True, "snapshot_dir": dump_dir,
                "snapshot_interval": 0,
                "watchdog": {{"enabled": True, "floor_s": 0.15,
                              "cap_s": 4.0, "factor": 2.0}},
                "faults": {{"enabled": True, "hang_at_step": 3}}}}}})
    for i, b in enumerate(random_batches(5, 8, {hidden})):
        if i == 2 and rank == 1:
            # THE FAULT: rank 1 enters a collective no other rank entered
            dist.barrier("injected-desync")
        dist.barrier("step-end")   # the fleet's routine per-step sync point
        engine.train_batch(b)
    raise SystemExit(99)  # unreachable: the watchdog must kill us first
    """


def test_multiprocess_desync_drill_end_to_end(tmp_path):
    """The acceptance drill: three REAL engine processes share a dump dir;
    rank 1 issues an extra collective at step 2; every rank wedges at step
    3 (the desync's downstream hang) and the watchdog kills each with exit
    83. The doctor — from the artifacts alone — must name rank 1, the
    first mismatched collective (seq + op), and the hung phase, and exit
    nonzero."""
    from deepspeed_tpu.runtime.resilience import WATCHDOG_EXIT_CODE

    dump_dir = tmp_path / "dumps"
    dump_dir.mkdir()
    script = tmp_path / "drill.py"
    script.write_text(textwrap.dedent(
        _DRILL_BODY.format(root=REPO_ROOT, hidden=HIDDEN)))
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(rank), str(dump_dir)],
        cwd=REPO_ROOT, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True) for rank in range(3)]
    rcs = {}
    for rank, p in enumerate(procs):
        try:
            _out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            _out, err = p.communicate()
        rcs[rank] = (p.returncode, err[-1500:])
    for rank, (rc, err) in rcs.items():
        assert rc == WATCHDOG_EXIT_CODE, f"rank {rank}: rc={rc}\n{err}"

    # every rank left a flightdump with its collective stream + a hangdump
    for rank in range(3):
        assert (dump_dir / f"flightdump-{rank}.json").exists()
        assert (dump_dir / f"hangdump-{rank}.txt").exists()

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-m", "deepspeed_tpu.doctor",
                        str(dump_dir), "--world", "3"],
                       env=env, cwd=REPO_ROOT, timeout=180,
                       capture_output=True, text=True)
    assert r.returncode == doctor.EXIT_DESYNC, (r.stdout, r.stderr[-1500:])
    rep = json.load(open(dump_dir / doctor.REPORT_NAME))
    assert rep["verdict"] == "desync"
    ds = rep["desync"]
    # rank 1 is named, and the first divergent launch is its injected
    # barrier — op + seq + per-rank signatures all in the report
    assert ds["divergent_ranks"] == [1]
    assert "injected-desync" in ds["per_rank"]["1"]["signature"]
    assert "step-end" in (ds["majority"] or "")
    assert isinstance(ds["first_divergent_seq"], int)
    assert rep["missing_ranks"] == []
    # the hung phase is named for every rank (the fault wedges post_step)
    assert rep["phases"].get("resilience/post_step") == [0, 1, 2]


def test_engine_flightdump_carries_stream_and_rank_override(tmp_path,
                                                           monkeypatch):
    """In-process half of the drill: DSTPU_PROCESS_ID stamps the artifact
    rank of a single-process engine, and the engine's flight dump carries
    the comm-wrapper stream (the eager barrier issued mid-loop)."""
    import deepspeed_tpu as ds
    import deepspeed_tpu.comm as dist

    from .simple_model import make_simple_params, random_batches, simple_loss

    monkeypatch.setenv("DSTPU_PROCESS_ID", "2")
    e, *_ = ds.initialize(
        model=simple_loss, model_parameters=make_simple_params(HIDDEN),
        config={"train_micro_batch_size_per_gpu": 8,
                "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
                "steps_per_print": 1000,
                "telemetry": {"enabled": True, "flight_steps": 8,
                              "flight_dir": str(tmp_path)}})
    assert e.artifact_rank == 2
    for b in random_batches(2, 8, HIDDEN):
        dist.barrier("step-end")
        e.train_batch(b)
    path = e.telemetry.flight_dump("unit")
    assert path.endswith("flightdump-2.json")
    doc = json.load(open(path))
    barriers = [c for c in doc["collectives"] if c["op"] == "barrier"]
    assert len(barriers) == 2
    assert all(c["detail"] == "step-end" and c.get("eager")
               for c in barriers)
    e.telemetry.close()


# ---------------------------------------------------------------------------
# chaos drills (ISSUE 15 satellite): the doctor must name every injected
# fault from chaos-generated dump sets — verdict AND evidence line per class
# ---------------------------------------------------------------------------


def _chaos_dump_set(d, kind):
    """Build the artifact set a real drill of ``kind`` leaves behind, plus
    the chaos manifest, and return the expected (verdict, evidence
    substring) the doctor must produce."""
    from deepspeed_tpu.runtime.resilience.chaos import (ChaosEvent,
                                                        ChaosSchedule)

    sites = {"transport_put_error": "heartbeat.put",
             "transport_get_error": "heartbeat.get",
             "torn_beacon": "heartbeat.put",
             "plan_cache_error": "plan_cache.load",
             "snapshot_io_error": "snapshot.commit",
             "replica_kill": "replica0",
             "kv_exhaustion": "scheduler.admit",
             "slow_prefill": "replica0",
             "drop_token": "replica0",
             "replica_spawn_fail": "replica2",
             "replica_slow_warm": "replica2",
             "stale_health": "health.read",
             "flap_straggler": "health.read",
             "sdc_bitflip_transient": "training",
             "sdc_bitflip_sticky": "training"}
    site = sites[kind]
    schedule = ChaosSchedule([ChaosEvent(kind=kind, site=site, at=1)])
    assert schedule.fire(kind, site) is False and schedule.fire(kind, site)
    schedule.dump(d)
    # corroborating artifacts per layer: a dead replica 0 for the kill, a
    # flapping straggler for the control classes, retry logs for transport
    if kind == "replica_kill":
        for r in range(2):
            _write_dump(d, r, list(_BASE), reason="preempt_drain", phase=None)
        _write_beacon(d, 0, 800.0)            # killed replica: stale beacon
        _write_beacon(d, 1, 1000.0)
        return "dead_host", f"chaos drill injected {kind}"
    if kind == "flap_straggler":
        for r in range(3):
            _write_dump(d, r, list(_BASE), reason="preempt_drain", phase=None)
            _write_beacon(d, r, 1000.0, step_time=1.0 if r == 0 else 0.1)
        return "straggler", f"chaos drill injected {kind}"
    if kind in ("sdc_bitflip_transient", "sdc_bitflip_sticky"):
        # integrity-monitor snapshots riding the dumps: rank 1 is the
        # fingerprint minority at step 8, classified by shadow replay
        verdict = "transient" if kind.endswith("transient") else "sticky"
        quarantined = [1] if verdict == "sticky" else []
        for r in range(3):
            integ = {"enabled": True, "rank": r, "world": 3,
                     "interval_steps": 2, "checks": 4,
                     "replays": int(r == 1),
                     "last_fp": ("bb" if r == 1 else "aa") * 8,
                     "last_fp_step": 8, "last_clean_step": 6,
                     "tainted_since": 8, "quarantined": quarantined,
                     "divergences": [{"step": 8,
                                      "sigs": {"0": "aa" * 8, "1": "bb" * 8,
                                               "2": "aa" * 8},
                                      "minority": [1], "verdict": verdict}]}
            _write_dump(d, r, list(_BASE), reason="rollback", phase=None,
                        extra={"integrity": integ})
            _write_beacon(d, r, 1000.0)
        return "sdc", f"chaos drill injected {kind}"
    if kind in ("transport_put_error", "transport_get_error",
                "plan_cache_error", "snapshot_io_error"):
        retries = [{"site": site, "attempt": a, "error": "OSError('x')",
                    "final": False, "wall_time": 999.0 + a}
                   for a in (1, 2)]
        _write_dump(d, 0, list(_BASE), reason="preempt_drain", phase=None,
                    extra={"retries": retries})
        _write_dump(d, 1, list(_BASE), reason="preempt_drain", phase=None)
        return "preempt", f"rank 0 retried {site} 2x"
    for r in range(2):
        _write_dump(d, r, list(_BASE), reason="preempt_drain", phase=None)
        _write_beacon(d, r, 1000.0)
    return "preempt", f"chaos drill injected {kind}"


from deepspeed_tpu.runtime.resilience.chaos import FAULT_CLASSES


@pytest.mark.parametrize("kind", sorted(FAULT_CLASSES))
def test_doctor_names_every_injected_fault_class(tmp_path, kind):
    d = str(tmp_path)
    verdict, needle = _chaos_dump_set(d, kind)
    rep = doctor.diagnose(d)
    assert rep["verdict"] == verdict
    assert rep["chaos"] is not None
    assert [e["kind"] for e in rep["chaos"]["fired"]] == [kind]
    assert any(needle in ev for ev in rep["evidence"]), rep["evidence"]
    # every fired fault class is named somewhere in the evidence
    assert any(f"chaos drill injected {kind}" in ev
               for ev in rep["evidence"])
    text = doctor.render_report(rep)
    assert "chaos schedule" in text and kind in text


def test_doctor_cli_renders_chaos_and_retries(tmp_path, capsys):
    """The CLI form of the drill: `python -m deepspeed_tpu.doctor` over a
    chaos dump set prints the chaos summary and the retry trail."""
    from deepspeed_tpu.doctor.__main__ import main as doctor_main

    d = str(tmp_path)
    _chaos_dump_set(d, "transport_put_error")
    rc = doctor_main([d, "--no-report"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "chaos schedule" in out and "transport_put_error" in out
    assert "retried heartbeat.put" in out


def test_doctor_retry_storm_evidence_rides_dead_verdict(tmp_path):
    """'host X retried the bucket 14x before the dead verdict' — the retry
    trail must surface WITH the dead-host classification, pointing the
    post-mortem at the store rather than the host."""
    d = str(tmp_path)
    retries = [{"site": "heartbeat.put", "attempt": a,
                "error": "ChaosInjectedError('chaos[transport_put_error]')",
                "final": a == 14, "wall_time": 900.0 + a}
               for a in range(1, 15)]
    _write_dump(d, 0, list(_BASE), reason="preempt_drain", phase=None,
                extra={"retries": retries})
    _write_dump(d, 1, list(_BASE), reason="preempt_drain", phase=None)
    _write_beacon(d, 0, 800.0)                 # rank 0 then went dead
    _write_beacon(d, 1, 1000.0)
    rep = doctor.diagnose(d)
    assert rep["verdict"] == "dead_host"
    assert rep["ranks"]["0"]["retries"]["heartbeat.put"]["count"] == 14
    assert rep["ranks"]["0"]["retries"]["heartbeat.put"]["gave_up"] == 1
    assert any("rank 0 retried heartbeat.put 14x" in e
               for e in rep["evidence"])
