"""Model-level convergence tier (reference ``tests/model/Megatron_GPT2/``):
train a small GPT-2 on deterministic synthetic data for hundreds of steps and
assert the loss curve against golden values checked into the repo.

The reference runs Megatron-GPT2 under several DeepSpeed configs and diffs the
curves against a known-good baseline (``tests/model/Megatron_GPT2/run_func_test.py``).
Here: one golden curve (ZeRO-0 fp32, ``GOLDEN_LOSSES``) + three variants that
must track it — ZeRO-3 (same math, different sharding: tight tolerance), bf16
mixed precision, and fp16 with dynamic loss scaling (loose tolerance, but the
end-of-training loss must land in the same basin).

Regenerate goldens after an intentional math change:
    python -m tests.model.test_convergence
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.transformer import (TransformerConfig, TransformerLM,
                                              init_params, make_loss_fn)
from deepspeed_tpu.parallel import Topology, TopologySpec, set_topology

STEPS = 300
RECORD_EVERY = 10
GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "golden_gpt2_losses.json")

# Deterministic task: next-token prediction on modular arithmetic walks —
# learnable to near-zero loss, no data files needed, identical on every run.
VOCAB, SEQ, BATCH = 64, 32, 16


def _batch(step: int):
    rng = np.random.default_rng(10_000 + step)
    start = rng.integers(0, VOCAB, size=(BATCH, 1))
    stride = rng.integers(1, 4, size=(BATCH, 1))
    toks = (start + stride * np.arange(SEQ)) % VOCAB
    return {"tokens": jnp.asarray(toks, jnp.int32)}


def _gpt2_tiny(dtype):
    return TransformerConfig(vocab_size=VOCAB, hidden_size=64,
                             intermediate_size=256, num_layers=2, num_heads=4,
                             max_seq_len=SEQ, norm="layernorm",
                             activation="gelu", position="learned",
                             tie_embeddings=True, dtype=dtype)


def _train(config_extra, dtype=jnp.float32, steps=STEPS):
    set_topology(Topology(TopologySpec()))
    cfg = _gpt2_tiny(dtype)
    model = TransformerLM(cfg)
    params = init_params(model, seq=SEQ, seed=7)
    config = {"train_micro_batch_size_per_gpu": BATCH,
              "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
              "scheduler": {"type": "WarmupLR",
                            "params": {"warmup_num_steps": 20,
                                       "warmup_min_lr": 0.0,
                                       "warmup_max_lr": 1e-3}},
              "gradient_clipping": 1.0, "steps_per_print": 10**9}
    config.update(config_extra)
    engine, *_ = ds.initialize(model=make_loss_fn(model),
                               model_parameters=params, config=config)
    losses = []
    for s in range(steps):
        loss = engine.train_batch(_batch(s))
        if s % RECORD_EVERY == 0:
            losses.append(float(loss))
    return losses


def _golden():
    with open(GOLDEN_PATH) as f:
        return json.load(f)["losses"]


def test_zero0_fp32_matches_golden():
    """The baseline itself must reproduce bit-for-bit-deterministic XLA math
    within float tolerance across machines."""
    losses = _train({"zero_optimization": {"stage": 0}})
    np.testing.assert_allclose(losses, _golden(), rtol=2e-3,
                               err_msg="ZeRO-0 fp32 diverged from golden curve")
    assert losses[-1] < 0.15, losses[-1]


def test_zero3_fp32_matches_golden():
    """ZeRO-3 is a sharding layout, not a math change: same curve, tight."""
    losses = _train({"zero_optimization": {"stage": 3}})
    np.testing.assert_allclose(losses, _golden(), rtol=2e-3,
                               err_msg="ZeRO-3 fp32 diverged from golden curve")


def test_bf16_tracks_golden():
    losses = _train({"zero_optimization": {"stage": 3}, "bf16": {"enabled": True}},
                    dtype=jnp.bfloat16)
    golden = np.asarray(_golden())
    got = np.asarray(losses)
    # early curve within 10%, convergence basin shared
    np.testing.assert_allclose(got[:5], golden[:5], rtol=0.10,
                               err_msg="bf16 early curve diverged")
    assert got[-1] < max(4 * golden[-1], 0.5), (got[-1], golden[-1])


def test_fp16_dynamic_tracks_golden():
    losses = _train({"zero_optimization": {"stage": 3},
                     "fp16": {"enabled": True, "initial_scale_power": 12,
                              "loss_scale_window": 100}},
                    dtype=jnp.float16)
    golden = np.asarray(_golden())
    got = np.asarray(losses)
    np.testing.assert_allclose(got[:5], golden[:5], rtol=0.10,
                               err_msg="fp16 early curve diverged")
    assert got[-1] < max(4 * golden[-1], 0.5), (got[-1], golden[-1])


def test_variants_agree_with_each_other():
    """Cross-config agreement on a shorter horizon (the reference asserts
    configs agree with the baseline run, not only with a stored file)."""
    short = 60
    z0 = _train({"zero_optimization": {"stage": 0}}, steps=short)
    z3 = _train({"zero_optimization": {"stage": 3}}, steps=short)
    np.testing.assert_allclose(z0, z3, rtol=1e-3)


def test_pipeline_agrees_with_dense():
    """The pipeline split is a layout, not a math change: the same untied
    GPT-2-tiny trained pp=4 (gpipe) for 60 steps must track
    the dense run step-for-step (reference run_func_test pipeline configs)."""
    import dataclasses

    from deepspeed_tpu.models.transformer import (stack_transformer_params,
                                                  transformer_pipeline_fns)
    from deepspeed_tpu.runtime.pipe.pipeline import (make_pipeline_loss_fn,
                                                     pipeline_param_specs)

    short = 60
    cfg = dataclasses.replace(_gpt2_tiny(jnp.float32), tie_embeddings=False,
                              num_layers=4)
    model = TransformerLM(cfg)
    base = init_params(model, seq=SEQ, seed=7)

    # dense run
    set_topology(Topology(TopologySpec()))
    engine_d, *_ = ds.initialize(
        model=make_loss_fn(model), model_parameters=base,
        config={"train_micro_batch_size_per_gpu": BATCH,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "gradient_clipping": 1.0, "steps_per_print": 10**9})
    dense = [float(engine_d.train_batch(_batch(s))) for s in range(short)]

    # pipeline run: same weights, pp=4, microbatches = 4
    try:
        topo = Topology(TopologySpec(pp=4))
        set_topology(topo)
        pparams = stack_transformer_params(base, cfg)
        e_fn, b_fn, h_fn = transformer_pipeline_fns(cfg)
        loss_fn = make_pipeline_loss_fn(e_fn, b_fn, h_fn, num_layers=4,
                                        num_stages=4, num_microbatches=4)
        engine_p, *_ = ds.initialize(
            model=loss_fn, model_parameters=pparams,
            config={"train_micro_batch_size_per_gpu": BATCH,
                    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                    "pipeline": {"stages": 4}, "gradient_clipping": 1.0,
                    "steps_per_print": 10**9},
            topology=topo, param_specs=pipeline_param_specs(pparams))
        piped = [float(engine_p.train_batch(_batch(s))) for s in range(short)]
    finally:
        set_topology(Topology(TopologySpec()))
    np.testing.assert_allclose(piped, dense, rtol=2e-3,
                               err_msg="pipeline curve diverged from dense")


def test_moe_capacity_and_dropless_converge():
    """MoE convergence tier (reference Megatron MoE curve analogue): a tiny
    top-2/4-expert model on the same task must LEARN (final loss well under
    the dense golden's start) on BOTH gating paths, and the two paths must
    agree at the end — capacity dropping and dropless grouped-GEMM are the
    same math when capacity suffices."""
    from deepspeed_tpu.models.transformer import mixtral_config

    def run(dropless):
        topo = Topology(TopologySpec(ep=4))
        set_topology(topo)
        try:
            cfg = mixtral_config(
                "tiny", vocab_size=VOCAB, hidden_size=64,
                intermediate_size=128, num_layers=2, num_heads=4,
                num_kv_heads=4, max_seq_len=SEQ, num_experts=4, moe_top_k=2,
                moe_dropless=dropless, dtype=jnp.float32)
            model = TransformerLM(cfg)
            params = init_params(model, seq=SEQ, seed=7)
            engine, *_ = ds.initialize(
                model=make_loss_fn(model), model_parameters=params,
                config={"train_micro_batch_size_per_gpu": BATCH,
                        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                        "moe": {"enabled": True, "ep_size": 4,
                                "num_experts": 4},
                        "gradient_clipping": 1.0, "steps_per_print": 10**9},
                topology=topo)
            return [float(engine.train_batch(_batch(s))) for s in range(STEPS)]
        finally:
            set_topology(Topology(TopologySpec()))

    cap = run(dropless=False)
    drop = run(dropless=True)
    for name, curve in (("capacity", cap), ("dropless", drop)):
        assert np.isfinite(curve).all(), f"{name} produced non-finite loss"
        assert curve[-1] < 0.5, f"{name} did not learn: final {curve[-1]:.3f}"
    # both paths end in the same basin (distinct step-by-step trajectories
    # are expected: token dropping perturbs early steps)
    assert abs(cap[-1] - drop[-1]) < 0.25, (cap[-1], drop[-1])


if __name__ == "__main__":
    # standalone regeneration: pin the CPU mesh the way conftest does (the
    # env var alone is too late — the axon sitecustomize registers its PJRT
    # plugin at interpreter start and first backend use would hang on a
    # wedged tunnel)
    jax.config.update("jax_platforms", "cpu")
    losses = _train({"zero_optimization": {"stage": 0}})
    with open(GOLDEN_PATH, "w") as f:
        json.dump({"losses": losses, "steps": STEPS,
                   "record_every": RECORD_EVERY,
                   "task": "modular arithmetic walks",
                   "config": "gpt2-tiny 2L/64h fp32 adamw lr1e-3 warmup20 clip1.0",
                   "seed_params": 7}, f, indent=2)
    print(f"wrote {GOLDEN_PATH}: final loss {losses[-1]:.4f}")
