"""Test harness: run all tests on a virtual 8-device CPU mesh.

TPU analogue of the reference's distributed-in-one-box harness
(``tests/unit/common.py:129`` ``DistributedExec``): instead of spawning N
processes over NCCL/gloo, we give XLA 8 virtual CPU devices and express
"world_size=N" tests as meshes/submeshes over them.
"""

import os

# Must run before any XLA backend is initialized. Note: the environment may
# import jax at interpreter start (sitecustomize), so the env-var route for
# JAX_PLATFORMS is too late — use jax.config.update as well.
_TPU_LANE = os.environ.get("DSTPU_TPU_TESTS") == "1"  # `pytest -m tpu` runs
if not _TPU_LANE:
    if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=8")
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

if not _TPU_LANE:
    jax.config.update("jax_platforms", "cpu")
import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


def require_devices(n):
    """Skip a test when fewer than n XLA devices are available."""
    return pytest.mark.skipif(len(jax.devices()) < n, reason=f"needs {n} devices")
