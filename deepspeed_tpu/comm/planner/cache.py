"""On-disk plan cache: tuning runs once per topology.

One JSON file per mesh fingerprint digest (``plan_<digest>.json``), holding
the fingerprint (human-readable provenance) and the site->decision map. The
default location is ``~/.cache/deepspeed_tpu/comm_plans`` overridable via
``DSTPU_PLAN_CACHE`` or the ``comm_planner.cache_dir`` config knob. Writes
are atomic (tmp + rename) and merge with what is already on disk, so
concurrent jobs on the same topology only add sites, never lose them.
"""

import json
import os
import tempfile
from typing import Optional

from ...runtime.resilience.chaos import get_chaos
from ...utils.retry import RetryError, RetryPolicy, retry_call
from .ir import Plan
from .topo import MeshFingerprint

_ENV_VAR = "DSTPU_PLAN_CACHE"

# cache reads sit on the engine-build path: short backoffs, tight deadline —
# a shared-FS hiccup should not cost a re-tune, but a dead mount must
# degrade to a miss quickly (the planner just re-tunes)
_READ_RETRY = RetryPolicy(max_attempts=4, base_s=0.02, cap_s=0.5,
                          deadline_s=5.0)


def default_cache_dir() -> str:
    env = os.environ.get(_ENV_VAR)
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "deepspeed_tpu",
                        "comm_plans")


class PlanCache:
    def __init__(self, cache_dir: Optional[str] = None,
                 space_version: Optional[int] = None):
        self.cache_dir = cache_dir or default_cache_dir()
        # search-space version (``compiler.SEARCH_SPACE``): part of the
        # cache identity. A winner is only the argmin OVER THE SPACE IT WAS
        # SEARCHED IN — widening the program grammar must read as a clean
        # miss (re-tune), never replay a stale narrower-space winner. None
        # keeps the legacy unversioned filename (pre-compiler callers).
        self.space_version = (None if space_version is None
                              else int(space_version))

    def path_for(self, fp: MeshFingerprint) -> str:
        tag = ("" if self.space_version is None
               else f"_s{self.space_version}")
        return os.path.join(self.cache_dir, f"plan_{fp.digest()}{tag}.json")

    def load(self, fp: MeshFingerprint) -> Optional[Plan]:
        """The cached plan for this fingerprint, or None. A corrupt or
        foreign-format file reads as a miss, never an error — the planner
        just re-tunes and overwrites it. Transient read errors (shared-FS
        hiccups) retry under the shared backoff first (``dstpu_retry_total
        {site=plan_cache.load}``); an absent file is an immediate miss.

        A version-carrying cache also falls back to the LEGACY unversioned
        filename: a pre-compiler plan file has no search-space identity and
        migrates on read (same precedent as the unstamped-format
        migration), while a file stamped with a DIFFERENT version — the
        case the versioning exists for — stays a miss."""
        plan = self._load_path(self.path_for(fp), fp)
        if plan is None and self.space_version is not None:
            legacy = os.path.join(self.cache_dir,
                                  f"plan_{fp.digest()}.json")
            plan = self._load_path(legacy, fp)
        return plan

    def _load_path(self, path: str, fp: MeshFingerprint) -> Optional[Plan]:
        chaos = get_chaos()

        def _read():
            if chaos is not None:
                chaos.maybe_raise("plan_cache_error", "plan_cache.load")
            with open(path) as f:
                return f.read()

        try:
            body = retry_call(_read, site="plan_cache.load",
                              policy=_READ_RETRY)
            plan = Plan.from_dict(json.loads(body))
        except (RetryError, OSError, ValueError, KeyError, TypeError):
            return None
        if self.space_version is not None:
            # belt + braces beside the filename tag: a copied/renamed file
            # from another search-space version still reads as a miss (an
            # UNSTAMPED body is legacy and migrates)
            try:
                stamped = json.loads(body).get("search_space")
            except ValueError:
                return None
            if stamped is not None and int(stamped) != self.space_version:
                return None
        return plan if plan.fingerprint == fp.digest() else None

    def store(self, fp: MeshFingerprint, plan: Plan) -> str:
        """Merge ``plan`` into the on-disk plan for ``fp`` (new decisions
        win) and write atomically. An exclusive flock serializes the whole
        read-merge-write against concurrent writers (two jobs on a shared
        home dir) so neither can drop the other's decisions; tmp+rename
        additionally keeps readers from ever seeing a torn file. Returns
        the file path."""
        os.makedirs(self.cache_dir, exist_ok=True)
        path = self.path_for(fp)
        lock = open(path + ".lock", "w")
        try:
            try:
                import fcntl

                fcntl.flock(lock, fcntl.LOCK_EX)
            except (ImportError, OSError):
                pass  # no flock (non-POSIX / odd FS): best-effort merge
            merged = self.load(fp) or Plan(fingerprint=fp.digest())
            merged.decisions.update(plan.decisions)
            body = {"fingerprint": fp.digest(), "mesh": fp.to_dict(),
                    **merged.to_dict()}
            if self.space_version is not None:
                body["search_space"] = self.space_version
            fd, tmp = tempfile.mkstemp(dir=self.cache_dir, suffix=".tmp")
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(body, f, indent=1, sort_keys=True)
                os.replace(tmp, path)
            except OSError:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        finally:
            lock.close()
        return path
