"""The collective planner: per-site implementation selection, cached.

Resolution order for ``resolve(site)``:

1. **knob** — a raw config knob the user explicitly set always wins
   (``compressed_collectives.mode != none``, ``overlap_collective_matmul``);
   the planner never overrides an explicit choice.
2. **memo / cache** — a decision already made this run, or loaded from the
   on-disk plan for this mesh fingerprint (``planner/cache.py``).
3. **off** — today's defaults, bit-identical to the pre-planner tree (the
   wiring short-circuits before even calling resolve in this mode; resolve
   still answers for direct callers).
4. **static** — the alpha-beta cost model's argmin (``planner/topo.py``).
5. **measure** — cost-model pruning, then microbenchmarks pick the winner
   (``planner/microbench.py``); written through to the disk cache.

Every resolution is recorded once in the comms ledger
(``CommsLogger.record_plan``) so ``comm.log_summary()`` prints the plan
table next to the traffic table.
"""

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .cache import PlanCache
from .compiler import (DEFAULT_BEAM_WIDTH, SEARCH_SPACE, compile_programs,
                       legacy_menu_programs, program_capable)
from .ir import (GRADIENT_CONSUMERS, CollectiveSite, FusedCompute, PhaseStep,
                 Plan, PlanDecision, make_phase, make_site, program_summary)
from .microbench import benchmark_site
from .topo import CostModel, MeshFingerprint

MODES = ("off", "static", "measure")


def synthesize_programs(site: CollectiveSite, cost: CostModel,
                        block: int = 2048) -> List[Tuple[PhaseStep, ...]]:
    """Compat shim: PR 8's five hand-written hierarchical candidates,
    exactly as before. Real synthesis moved to ``planner/compiler.py`` —
    :func:`compile_programs` searches the full program space (axis
    groupings x algorithm shapes x wire dtypes x chunking) and the
    planner's ``_candidates`` uses that beam; this function remains for
    callers and tests that want the legacy fixed menu."""
    return legacy_menu_programs(site, cost, block=block)


class CollectivePlanner:
    def __init__(self, mode: str = "off", *,
                 knobs: Optional[Dict[str, Any]] = None,
                 cache_dir: Optional[str] = None,
                 use_cache: bool = True,
                 margin: float = 3.0,
                 measure_reps: int = 4,
                 measure_max_elems: int = 1 << 16,
                 block: int = 2048,
                 dcn_axes: Optional[Sequence[str]] = None,
                 beam_width: Optional[int] = None,
                 overlap_credit: Optional[float] = None,
                 topology=None):
        if mode not in MODES:
            raise ValueError(f"comm_planner mode must be one of {MODES}, "
                             f"got {mode!r}")
        self.mode = mode
        self.knobs = dict(knobs or {})
        self.margin = float(margin)
        self.measure_reps = int(measure_reps)
        self.measure_max_elems = int(measure_max_elems)
        self.block = int(block)
        self.beam_width = int(beam_width) if beam_width else DEFAULT_BEAM_WIDTH
        self.overlap_credit = (None if overlap_credit is None
                               else float(overlap_credit))
        self.fingerprint = MeshFingerprint.capture(topology)
        forced = ()
        if dcn_axes:
            # operator-forced DCN axes (``comm_planner.dcn_axes``): rehearse
            # a multi-slice plan on a single-slice (or CPU) dev box. The
            # override is part of the fingerprint, so forced plans never
            # collide with this mesh's organic plan cache entry. Axes that
            # name no fleet mesh axis are KEPT (they mark foreign-mesh
            # sites — the zeropp factory's own ``dp`` axis resolves with an
            # explicit ``axis_size`` and its link class comes from exactly
            # this membership test) but called out, since a typo here
            # switches costing to fleet (accelerator) rates
            known = {n for n, s in self.fingerprint.axis_sizes if s > 1}
            forced = tuple(dict.fromkeys(str(a) for a in dcn_axes))
            foreign = [a for a in forced if a not in known]
            if foreign:
                from ...utils.logging import logger

                logger.warning(
                    f"comm_planner.dcn_axes: {foreign} match no multi-rank "
                    f"fleet mesh axis (known: {sorted(known)}) — kept as "
                    f"foreign-mesh DCN axes (zeropp-style sites with their "
                    f"own mesh); no cross-slice PROGRAM will be "
                    f"synthesized for them, and a typo here prices plans "
                    f"at fleet rates")
            if forced:
                self.fingerprint = dataclasses.replace(
                    self.fingerprint,
                    dcn_axes=tuple(sorted(set(self.fingerprint.dcn_axes)
                                          | set(forced))))
        # fleet costing only when an override actually took: a typo'd
        # dcn_axes must not silently switch quantization to TPU rates
        self._assume_fleet = bool(forced)
        self.cost = CostModel(self.fingerprint, block=self.block,
                              assume_fleet=self._assume_fleet,
                              overlap_credit=self.overlap_credit)
        # the winner cache is keyed by (fingerprint, SEARCH_SPACE): widening
        # the compiler's grammar in a later version is a clean cache miss —
        # a winner searched over a narrower space must not be replayed
        self.cache = (PlanCache(cache_dir, space_version=SEARCH_SPACE)
                      if use_cache else None)
        self._search_notes: Dict[str, str] = {}
        self.plan = Plan(fingerprint=self.fingerprint.digest())
        self._from_cache = set()
        if self.cache is not None and mode != "off":
            cached = self.cache.load(self.fingerprint)
            if cached is not None:
                self.plan.decisions.update(cached.decisions)
                self._from_cache = set(cached.decisions)
        self._recorded = set()
        self._agreed = set()  # sigs already broadcast-synced across hosts

    # ------------------------------------------------------------------
    def resolve(self, site: CollectiveSite) -> PlanDecision:
        sig = site.signature()
        knob = self._knob_decision(site)
        if knob is not None:
            # an explicit raw knob is answered directly and NEVER stored:
            # a knob choice is the user's, not a tuned plan — it must not
            # leak into the cache a later knob-less run would load
            self._record(site, knob)
            return knob
        decision = self.plan.decisions.get(sig)
        if decision is not None and sig in self._from_cache:
            decision = dataclasses.replace(decision, source="cache")
        if decision is None:
            if self.mode == "off":
                decision = self._default_decision(site)
            elif self.mode == "static":
                decision = self._static_decision(site)
            else:
                decision = self._measure(site)
        if sig not in self._agreed:
            # multi-host: every process MUST run the same implementation or
            # the SPMD programs issue mismatched collectives and deadlock —
            # measured timings (and per-host caches) can disagree, so rank
            # 0's decision is broadcast. Every host resolves the same sites
            # in the same order (same program construction), and knob
            # decisions come from the shared config, so the broadcasts
            # align; memoized re-resolutions never re-broadcast.
            decision = self._agree(decision)
            self._agreed.add(sig)
        self.plan.decisions[sig] = decision
        if self.cache is not None and self.mode != "off" \
                and sig not in self._from_cache:
            # write-through: one file per mesh fingerprint, merge-on-store
            try:
                self.cache.store(self.fingerprint, self.plan)
            except OSError:
                pass  # read-only FS: plan still lives in memory
        self._record(site, decision)
        return decision

    def replan_around(self, slow_axes: Sequence[str], *,
                      penalty: float = 4.0,
                      consumers: Sequence[str] = GRADIENT_CONSUMERS) -> bool:
        """Control-plane re-plan: demote the named mesh axes to DCN-class
        links (a straggler's link IS a slow cross-host link, whatever the
        nominal topology says), penalize them by the observed slowdown,
        and forget every decision for ``consumers`` so the next resolve
        re-synthesizes against the demoted fingerprint — hierarchical
        programs whose full-width phases EXCLUDE the slow axes become
        eligible (and, with the penalty, win).

        The fingerprint mutation re-keys the plan/cache identity exactly
        like the ``comm_planner.dcn_axes`` override does, so a replanned
        decision can never pollute this mesh's organic cache entry — and a
        restart that performs the same demotion resolves the same cached
        replanned plan. Returns False (no state touched) when none of the
        axes name a multi-rank mesh axis or the planner is off.

        ``consumers`` defaults to every gradient consumer (dp-grad AND
        zeropp) so a ZeRO++ factory rebuilt after the demotion re-resolves
        against the demoted links too — keeping only one consumer would
        re-persist the other's stale fast-link decisions under the new
        fingerprint."""
        if self.mode == "off":
            return False
        known = {n for n, s in self.fingerprint.axis_sizes if s > 1}
        slow = tuple(a for a in slow_axes if a in known)
        if not slow:
            return False
        self.fingerprint = dataclasses.replace(
            self.fingerprint,
            dcn_axes=tuple(sorted(set(self.fingerprint.dcn_axes)
                                  | set(slow))))
        penalties = dict(self.cost.link_penalties)
        for a in slow:
            penalties[a] = max(penalties.get(a, 1.0), float(penalty))
        # fleet costing: the demoted link is priced as the slow cross-host
        # hop it behaves as; quant at accelerator rates, as with dcn_axes
        self._assume_fleet = True
        self.cost = CostModel(self.fingerprint, block=self.block,
                              assume_fleet=True, link_penalties=penalties,
                              overlap_credit=self.overlap_credit)
        drop = {sig for sig in self.plan.decisions
                if sig.split(":", 1)[0] in set(consumers)}
        self.plan = Plan(
            fingerprint=self.fingerprint.digest(),
            decisions={sig: d for sig, d in self.plan.decisions.items()
                       if sig not in drop})
        self._from_cache -= drop
        self._agreed -= drop
        self._recorded -= drop
        if self.cache is not None:
            # a PREVIOUS run already measured under this demoted identity:
            # load its decisions (current in-memory ones win) so a restart
            # that repeats the demotion reuses them instead of re-running
            # microbenchmarks mid-training
            cached = self.cache.load(self.fingerprint)
            if cached is not None:
                for sig, d in cached.decisions.items():
                    if sig not in self.plan.decisions:
                        self.plan.decisions[sig] = d
                        self._from_cache.add(sig)
        return True

    def _agree(self, decision: PlanDecision) -> PlanDecision:
        """Rank 0's decision, on every process (no-op single-process)."""
        import jax

        if jax.process_count() <= 1:
            return decision
        from ..comm import broadcast_host_data

        return PlanDecision.from_dict(broadcast_host_data(decision.to_dict(),
                                                          src=0))

    # ------------------------------------------------------------------
    def _knob_decision(self, site: CollectiveSite) -> Optional[PlanDecision]:
        """Explicitly-set raw knobs win over any planning."""
        if site.op == "decode_attn":
            # the serving decode kernel choice: no raw training knob maps
            # to it (the engine's own attn_backend pins are applied BEFORE
            # the planner is consulted), and the compression knob must not
            # hijack it into an "xla" decision that isn't on its menu
            return None
        if site.op == "gather_matmul":
            if self.knobs.get("overlap"):
                return PlanDecision(impl="fused_matmul", source="knob")
            return None
        comp = self.knobs.get("compression")
        if comp is None:
            return None
        site_key = {"dp-grad": "dp_gradients", "ulysses": "ulysses",
                    "moe-a2a": "moe"}.get(site.consumer)
        if site.consumer == "zeropp":
            site_key = ("zero_gradients" if site.op == "reduce_scatter"
                        else "zero_weights")
        if site_key is None or not comp.get("sites", {}).get(site_key, True):
            return PlanDecision(impl="xla", source="knob")
        mode = comp["mode"]
        if site.consumer not in GRADIENT_CONSUMERS:
            mode = "int8"  # activation exchanges never dither
        if site.consumer == "dp-grad" and comp.get("hierarchical"):
            # same gate as the engine wiring: both split levels must be real
            p_in, p_out = self.cost._split_axes(site)
            if p_in > 1 and p_out > 1:
                return PlanDecision(impl="hierarchical",
                                    block=comp.get("block"), source="knob")
        return PlanDecision(impl=mode, block=comp.get("block"), source="knob")

    def _default_decision(self, site: CollectiveSite) -> PlanDecision:
        """Planner off, no knob: what the tree does today."""
        if site.op == "decode_attn":
            return PlanDecision(impl="einsum", source="default")
        if site.consumer == "zeropp":
            # zeropp_train_step_factory's legacy default is quantized ON
            return PlanDecision(impl="int8", block=self.block,
                                source="default")
        return PlanDecision(impl="xla", source="default")

    def _candidates(self, site: CollectiveSite):
        """Cost-ranked, margin-pruned ``(impl, est_s, program)`` candidates:
        the single-impl menu (``CostModel.prune``) PLUS the compiled program
        beam (``compiler.compile_programs`` — groupings x shapes x wires x
        chunking, slot-pruned), priced on the same alpha-beta scale. Stable
        sort keeps emission order on ties, with singles listed first so a
        program that merely MATCHES a flat impl can never displace it.

        Program candidates only survive at sites whose wiring can execute
        a program decision (``compiler.PROGRAM_CAPABLE`` — today the
        engine's dp-grad reduction). Elsewhere the beam is still compiled
        and the outcome recorded (``program_search`` in the plan table),
        but handing "program" to a wiring that dispatches on impl flags
        would silently run the exact path under a quantized-plan label —
        the planner keeps the best executable impl instead."""
        cands = [(impl, est, None)
                 for impl, est in self.cost.prune(site, margin=self.margin)]
        beam = compile_programs(site, self.cost, block=self.block,
                                beam_width=self.beam_width)
        note = None
        if beam and program_capable(site):
            cands.extend(("program", est, prog) for prog, est in beam)
            note = f"beam:{len(beam)}"
        elif beam:
            note = ("skipped:foreign-axis" if site.axis_size is not None
                    else "skipped:wiring")
        elif site.axis_size is not None:
            note = "skipped:foreign-axis"
        if note is not None:
            self._search_notes[site.signature()] = note
        cands.sort(key=lambda t: t[1])
        best = cands[0][1]
        cut = best * self.margin if best > 0 else float("inf")
        return [c for c in cands if c[1] <= cut] or cands[:1]

    def _static_decision(self, site: CollectiveSite) -> PlanDecision:
        """Static-mode decision: argmin over single impls AND programs."""
        impl, est, prog = self._candidates(site)[0]
        return self._finish(site, impl, est_s=est, source="cost-model",
                            program=prog)

    def _measure(self, site: CollectiveSite) -> PlanDecision:
        survivors = self._candidates(site)
        if len(survivors) == 1:
            impl, est, prog = survivors[0]
            return self._finish(site, impl, est_s=est, source="cost-model",
                                program=prog)
        timed, errs = [], []
        for impl, _, prog in survivors:
            try:
                t = benchmark_site(site, impl, block=self.block,
                                   program=prog,
                                   reps=self.measure_reps,
                                   max_elems=self.measure_max_elems)
            except Exception as e:  # a candidate that fails to build loses
                name = impl if prog is None else program_summary(prog)
                errs.append(f"{name}: {type(e).__name__}: {e}")
                continue
            timed.append((impl, t, prog))
        if not timed:
            # degrade loudly, not silently: the user asked for measurement
            from ...utils.logging import logger

            logger.warning(
                f"comm_planner: no candidate probe ran for "
                f"{site.signature()} — falling back to the cost model "
                f"({'; '.join(errs)[:300]})")
            impl, est, prog = survivors[0]
            return self._finish(site, impl, est_s=est, source="cost-model",
                                program=prog)
        impl, t, prog = min(timed, key=lambda kv: kv[1])
        return self._finish(site, impl, est_s=t, source="measured",
                            program=prog)

    def calibrate_overlap_credit(self, site: CollectiveSite, *,
                                 reps: Optional[int] = None
                                 ) -> Optional[float]:
        """Measure the fused-matmul overlap credit instead of trusting the
        0.55 default: time a fused-hierarchical program against its
        sequenced twin (same phases, ``via="xla"``, no compute binding)
        through the real executor, set ``CostModel.overlap_credit`` to the
        observed hidden fraction ``(t_seq - t_fused) / t_seq`` (clamped to
        [0.05, 0.95] — no transfer hides completely, and a noisy negative
        sample must not zero the credit), and return it. Returns None —
        cost model untouched — when the site admits no fused program or a
        probe fails; subsequent ``resolve`` calls price candidates with the
        calibrated credit."""
        fused = next((p for p in legacy_menu_programs(site, self.cost,
                                                      block=self.block)
                      if any(s.via == "fused_matmul" for s in p)), None)
        if fused is None:
            return None
        seq = tuple(dataclasses.replace(s, via="xla", compute=None)
                    if s.via == "fused_matmul" else s for s in fused)
        reps = int(reps or self.measure_reps)
        try:
            t_fused = benchmark_site(site, "program", block=self.block,
                                     program=fused, reps=reps,
                                     max_elems=self.measure_max_elems)
            t_seq = benchmark_site(site, "program", block=self.block,
                                   program=seq, reps=reps,
                                   max_elems=self.measure_max_elems)
        except Exception:
            return None
        if not (t_seq > 0.0 and t_fused > 0.0):
            return None
        credit = min(0.95, max(0.05, (t_seq - t_fused) / t_seq))
        self.overlap_credit = credit
        self.cost = CostModel(self.fingerprint, block=self.block,
                              assume_fleet=self._assume_fleet,
                              link_penalties=self.cost.link_penalties,
                              overlap_credit=credit)
        return credit

    def _finish(self, site: CollectiveSite, impl: str, *, est_s: float,
                source: str, program=None) -> PlanDecision:
        block = self.block if impl in ("int8", "int8_sr", "hierarchical",
                                       "program") else None
        if impl == "fused_matmul" and site.op in ("all_gather",
                                                  "reduce_scatter"):
            # the fused gather/scatter rings carry an int8 wire (the TP
            # gather_matmul fused impl stays exact and blockless)
            block = self.block
        return PlanDecision(impl=impl, block=block, source=source,
                            est_us=round(est_s * 1e6, 3),
                            program=program)

    def _record(self, site: CollectiveSite, decision: PlanDecision) -> None:
        sig = site.signature()
        if sig in self._recorded:
            return
        self._recorded.add(sig)
        from ..comm import get_comms_logger

        info = {
            "consumer": site.consumer, "op": site.op,
            "shape": "x".join(str(d) for d in site.shape) or "scalar",
            "axes": ",".join(site.axes), "impl": decision.impl,
            "block": decision.block, "source": decision.source,
            "est_us": decision.est_us, "mode": self.mode,
        }
        note = self._search_notes.get(sig)
        if note is not None:
            # what the program compiler did here: "beam:N" (N candidates
            # competed) or an explicit skip — "skipped:foreign-axis" /
            # "skipped:wiring" (programs compiled but the site's wiring
            # can't execute a program decision; silent degradation is the
            # one thing this column exists to rule out)
            info["program_search"] = note
        if decision.program is not None:
            info["program"] = program_summary(decision.program)
            # the structured per-phase dicts ride beside the summary so
            # the graph auditor expands a program decision per hop (a
            # fused/ring phase emits p-1 collective-permutes, not the
            # phase's nominal fused collective) without re-parsing the
            # summary string
            info["program_phases"] = [s.to_dict()
                                      for s in decision.program]
        get_comms_logger().record_plan(sig, info)


# ---------------------------------------------------------------------------
# Fleet-wide planner instance (the configure_compression pattern):
# initialize() maps config.comm_planner onto this; the wiring reads it.
# ---------------------------------------------------------------------------

_PLANNER: Optional[CollectivePlanner] = None


def configure_planner(mode: str = "off", **kwargs) -> CollectivePlanner:
    global _PLANNER
    _PLANNER = CollectivePlanner(mode, **kwargs)
    return _PLANNER


def reset_planner() -> None:
    global _PLANNER
    _PLANNER = None


def get_planner() -> CollectivePlanner:
    global _PLANNER
    if _PLANNER is None:
        _PLANNER = CollectivePlanner("off")
    return _PLANNER


def planner_active() -> bool:
    """True when a planner with mode static|measure is configured — the
    wiring's gate: inactive means every site keeps today's exact behavior
    (``comm_planner: off`` is bit-identical to the pre-planner tree)."""
    return _PLANNER is not None and _PLANNER.mode != "off"


def resolve_site(**kwargs) -> PlanDecision:
    """Build a site from keyword parts and resolve it against the fleet
    planner — the one-liner the five wirings call."""
    return get_planner().resolve(make_site(**kwargs))


def configure_from_config(config, topology=None) -> CollectivePlanner:
    """Map the runtime config onto the fleet planner: the ``comm_planner``
    block picks the mode/cache knobs, and the explicitly-set raw fast-path
    knobs (``compressed_collectives``, ``overlap_collective_matmul``) are
    snapshotted so they keep winning at their sites."""
    pl = config.comm_planner
    knobs: Dict[str, Any] = {}
    cc = config.compressed_collectives
    if cc.mode != "none":
        knobs["compression"] = {"mode": cc.mode, "block": cc.block,
                                "hierarchical": cc.hierarchical,
                                "sites": cc.site_map()}
    if config.tensor_parallel.overlap_collective_matmul:
        knobs["overlap"] = True
    return configure_planner(pl.mode, knobs=knobs, cache_dir=pl.cache_dir,
                             use_cache=pl.use_cache, margin=pl.margin,
                             measure_reps=pl.measure_reps,
                             measure_max_elems=pl.measure_max_elems,
                             block=cc.block, dcn_axes=pl.dcn_axes,
                             beam_width=pl.beam_width,
                             overlap_credit=pl.overlap_credit,
                             topology=topology)
