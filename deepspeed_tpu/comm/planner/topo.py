"""Topology model: mesh fingerprint + alpha-beta cost model.

The fingerprint identifies WHAT we are planning for — axis sizes, device
kind, host span, which mesh axes cross hosts (DCN) vs stay on-chip
interconnect (ICI) — and keys the on-disk plan cache. The cost model is a
classical alpha-beta (latency + inverse-bandwidth) estimate per (site,
implementation) pair, the Big-Send-off observation made executable: it is
deliberately coarse — its job is to PRUNE obviously-dominated candidates
(and rank the survivors in ``static`` mode), not to replace measurement.
``measure`` mode times the survivors for real (``planner/microbench.py``).
"""

import dataclasses
import hashlib
import json
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from .ir import (GRADIENT_CONSUMERS, OP_MENU, CollectiveSite, PhaseStep,
                 PlanDecision)

# default quantization block (elements per scale) — matches ops/pallas/quant
_DEFAULT_BLOCK = 2048


@dataclass(frozen=True)
class LinkParams:
    alpha: float  # per-hop latency, seconds
    beta: float   # seconds per byte (inverse bandwidth)


# Link classes by locality. Numbers are order-of-magnitude public figures
# (TPU ICI ~100 GB/s/link-direction, DCN ~12.5 GB/s, virtual CPU mesh =
# memcpy); the model only needs the RATIOS to rank candidates sanely.
LINK_TABLE: Dict[str, LinkParams] = {
    "ici": LinkParams(alpha=1e-6, beta=1.0 / 9e10),
    "dcn": LinkParams(alpha=25e-6, beta=1.0 / 12.5e9),
    "host": LinkParams(alpha=5e-6, beta=1.0 / 2e10),
}

# int8 quantize+dequantize compute, seconds per (logical) byte processed —
# the term that makes exact transport win for small messages. Per platform:
# the TPU VPU streams the block quant at memory speed; the virtual CPU mesh
# pays real vectorized-numpy rates
QUANT_COST_PER_BYTE = {"tpu": 1.0 / 2e11, "cpu": 1.0 / 1e10}
_QUANT_DEFAULT = 1.0 / 5e10
# fixed per-quantization-stage overhead (kernel launch, scale lanes): the
# term that keeps tiny alpha-dominated messages on the exact path
QUANT_FIXED = 5e-6
# fraction of the wire time a ring-chunked transfer hides behind compute
# (T3-style overlap); the credit the fused/chunked impls get over xla.
# This module constant is the DEFAULT — CostModel carries it as a field so
# the ``comm_planner.overlap_credit`` knob (or a measured fused-vs-sequenced
# probe pair, ``planner.calibrate_overlap_credit``) can track the real mesh
OVERLAP_CREDIT = 0.55
# extra per-chunk scheduling overhead of an explicit ppermute ring vs the
# fused XLA collective
RING_HOP_PENALTY = 1.5
# per-round scheduling overhead of a recursive-doubling/halving butterfly
# round vs one fused-collective alpha: each of the log2(p) rounds is a
# full-vector ppermute exchange that XLA schedules as an exposed step, so a
# round costs noticeably more than a pipelined ring hop. Calibrated so the
# tree wins the alpha-dominated DCN regime (log2(p) rounds beat 2(p-1) ring
# hops once p >= 4) without stealing the bandwidth-bound regime from the
# quantized xla path
TREE_ROUND_PENALTY = 2.8

# --- decode-shape regime (serving decode_attn) -----------------------------
# HBM streaming rates, bytes/s: decode attention moves no link traffic —
# the candidates differ only in pool bytes touched per step (order-of-
# magnitude public figures; ratios are what rank the impls)
HBM_BW = {"tpu": 8e11, "cpu": 2e10}
# fraction of the (power-of-two-sliced) block table's pages that are live
# mid-generation — what the pallas kernel's clamped index map actually DMAs
DECODE_LIVE_FRACTION = 0.75


@dataclass(frozen=True)
class MeshFingerprint:
    """What the planner keys plans on: if two jobs land on meshes with the
    same fingerprint, the same plan applies."""
    platform: str
    device_kind: str
    n_devices: int
    n_processes: int
    axis_sizes: Tuple[Tuple[str, int], ...]
    dcn_axes: Tuple[str, ...]

    @classmethod
    def capture(cls, topology=None) -> "MeshFingerprint":
        """Fingerprint the live mesh (``jax.devices()`` + the resolved
        ``parallel.topology``). An axis is DCN when stepping along it
        changes the owning host process."""
        import jax

        from ...parallel.topology import get_topology

        topo = topology or get_topology()
        devs = jax.devices()
        d0 = devs[0]
        mesh = topo.mesh
        arr = np.asarray(mesh.devices)
        names = tuple(mesh.axis_names)
        dcn = []
        for i, name in enumerate(names):
            if arr.shape[i] <= 1:
                continue
            step = np.moveaxis(arr, i, 0)
            procs0 = np.vectorize(lambda d: d.process_index)(step[0])
            procs1 = np.vectorize(lambda d: d.process_index)(step[1])
            if (procs0 != procs1).any():
                dcn.append(name)
        return cls(platform=str(d0.platform),
                   device_kind=str(getattr(d0, "device_kind", d0.platform)),
                   n_devices=len(devs),
                   n_processes=int(jax.process_count()),
                   axis_sizes=tuple((n, int(mesh.shape[n])) for n in names),
                   dcn_axes=tuple(dcn))

    def axis_size(self, axes: Tuple[str, ...]) -> int:
        table = dict(self.axis_sizes)
        p = 1
        for a in axes:
            p *= int(table.get(a, 1))
        return p

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)

    def digest(self) -> str:
        """Short stable hash — the plan-cache file key."""
        blob = json.dumps(self.to_dict(), sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()[:16]


class CostModel:
    """Alpha-beta estimates per (site, implementation).

    ``assume_fleet`` plans AS the target fleet rather than as this host:
    quantization is costed at the accelerator's streaming rate even when
    the live platform is the virtual CPU mesh. Set when the operator
    force-marked DCN axes (``comm_planner.dcn_axes`` — rehearsing a
    multi-slice plan on a dev box); without it the CPU's vectorized-numpy
    quant rate would veto every compressed candidate the real fleet wants.
    """

    def __init__(self, fingerprint: MeshFingerprint,
                 block: int = _DEFAULT_BLOCK, assume_fleet: bool = False,
                 link_penalties: Optional[Dict[str, float]] = None,
                 overlap_credit: Optional[float] = None):
        self.fp = fingerprint
        self.block = block
        platform = "tpu" if assume_fleet else fingerprint.platform
        self.platform = platform
        self.quant_cost = QUANT_COST_PER_BYTE.get(platform, _QUANT_DEFAULT)
        self.quant_fixed = QUANT_FIXED
        # the fused/chunked overlap credit: config- or measurement-settable
        # (clamped away from 1.0 — no transfer hides completely)
        if overlap_credit is None:
            overlap_credit = OVERLAP_CREDIT
        self.overlap_credit = min(0.95, max(0.0, float(overlap_credit)))
        # per-axis cost multipliers (alpha AND beta): the control plane's
        # straggler re-plan marks the slow host's link here so every
        # candidate that touches it is priced at its OBSERVED slowness,
        # not the link class's nominal figure
        self.link_penalties: Dict[str, float] = dict(link_penalties or {})

    def _penalized(self, lp: LinkParams,
                   axes: Tuple[str, ...]) -> LinkParams:
        f = 1.0
        for a in axes:
            f = max(f, float(self.link_penalties.get(a, 1.0)))
        if f == 1.0:
            return lp
        return LinkParams(alpha=lp.alpha * f, beta=lp.beta * f)

    def link(self, axes: Tuple[str, ...]) -> LinkParams:
        if any(a in self.fp.dcn_axes for a in axes):
            return self._penalized(LINK_TABLE["dcn"], axes)
        if self.fp.platform == "tpu" or self.fp.dcn_axes:
            # a mesh that DISTINGUISHES DCN axes makes every other axis
            # slice-local interconnect by definition
            return self._penalized(LINK_TABLE["ici"], axes)
        return self._penalized(LINK_TABLE["host"], axes)

    def link_params(self, link: Optional[str],
                    axes: Tuple[str, ...]) -> LinkParams:
        """A phase's link params: the stamped link class when the program
        carries one (penalties still apply — a demoted slow axis stays
        expensive whatever class synthesis stamped), else by axes."""
        if link:
            return self._penalized(LINK_TABLE[link], axes)
        return self.link(axes)

    def dcn_split(self, site: CollectiveSite) -> Tuple[Tuple[str, ...],
                                                       Tuple[str, ...]]:
        """Partition ``site.axes`` into (inner slice-local axes, outer
        cross-slice axes) for hierarchical program synthesis. Programs only
        make sense when the span actually CROSSES ``fp.dcn_axes`` — on an
        all-ICI mesh the extra full-width phases buy nothing (the legacy
        single-impl ``hierarchical`` estimate already prices that shape and
        loses there), so either side empty means: no split, no program."""
        axes = site.axes
        if site.axis_size is not None or len(axes) < 2:
            return ((), ())
        outer = tuple(a for a in axes if a in self.fp.dcn_axes)
        inner = tuple(a for a in axes if a not in self.fp.dcn_axes)
        if not outer or not inner:
            return ((), ())
        return inner, outer

    def axis_size_of(self, site: CollectiveSite) -> int:
        """The collective's rank count: the site's explicit override (a
        foreign-mesh site, e.g. zeropp's own dp axis) or the fingerprint."""
        if site.axis_size is not None:
            return int(site.axis_size)
        return self.fp.axis_size(site.axes)

    # -- wire-byte model ---------------------------------------------------
    def _wire_ratio(self, dtype: str) -> float:
        """on-wire bytes / logical bytes for an int8 payload + one fp32
        scale lane per block (comm/compressed.py accounting)."""
        item = max(1, int(np.dtype(dtype).itemsize))
        return (1.0 + 4.0 / self.block) / item

    def _estimate_decode_attn(self, site: CollectiveSite, impl: str) -> float:
        """Decode-shape regime: ``site.shape`` is the gathered pool view one
        decode step touches ([S, B*bs, Hk, D] in the STORAGE dtype, one
        pool); K and V double it. The einsum path materializes a
        compute-dtype copy (read the pool, write the copy, read it back in
        the attention einsum — plus the dequant stream for int8 storage);
        the pallas kernel streams the live pages once, in place. No link
        term: decode_attn is a kernel choice, not a collective."""
        bw = HBM_BW.get(self.platform, HBM_BW["cpu"])
        n = 2.0 * float(site.nbytes)          # K and V pools
        item = max(1, int(np.dtype(site.dtype).itemsize))
        if impl == "einsum":
            # the gathered copy lands in the COMPUTE dtype: same width as
            # fp/bf16 storage, widened for int8 pools (bf16 is the serving
            # compute dtype on TPU, so assume 2 bytes there)
            copy = n * (max(2.0, float(item)) / item)
            t = n / bw + 2.0 * copy / bw
            if site.dtype == "int8":
                t += n * self.quant_cost
            return t
        if impl == "pallas":
            if self.platform != "tpu":
                # interpret mode off-TPU: a reference path, never a win
                return float("inf")
            return n * DECODE_LIVE_FRACTION / bw
        return float("inf")

    # -- per-impl estimate -------------------------------------------------
    def estimate(self, site: CollectiveSite, impl: str) -> float:
        """Predicted seconds for one execution of ``site`` via ``impl``."""
        if site.op == "decode_attn":
            return self._estimate_decode_attn(site, impl)
        p = self.axis_size_of(site)
        if p <= 1:
            return 0.0
        lp = self.link(site.axes)
        n = float(site.nbytes)
        q = self._wire_ratio(site.dtype)
        hops = p - 1

        if site.op == "all_reduce":
            exact = 2 * hops * lp.alpha + 2 * n * hops / p * lp.beta
            if impl == "xla":
                return exact
            if impl in ("int8", "int8_sr"):
                t = 2 * hops * lp.alpha + 2 * n * q * hops / p * lp.beta \
                    + 2 * n * self.quant_cost + 2 * self.quant_fixed
                return t * (1.02 if impl == "int8_sr" else 1.0)
            if impl == "hierarchical":
                # inner axis exact (cheap links), outer hops quantized
                p_in, p_out = self._split_axes(site)
                if p_in <= 1 or p_out <= 1:
                    return float("inf")
                inner = self.link(site.axes[-1:])
                t = 2 * (p_in - 1) * inner.alpha \
                    + 2 * n * (p_in - 1) / p_in * inner.beta
                outer = self.link(site.axes[:1])
                t += 2 * (p_out - 1) * outer.alpha \
                    + 2 * n * q * (p_out - 1) / p_out * outer.beta \
                    + 2 * n * self.quant_cost + 2 * self.quant_fixed
                return t
        elif site.op in ("all_gather", "embed_gather"):
            # site.shape is the local shard; (p-1)*n bytes ride per rank.
            # embed_gather (the vocab-sharded table ring) has the same wire
            # profile — its menu simply has no int8 arm, and ring means the
            # chunk hops hide behind the resident chunk's row lookups
            # (ops/collective_matmul.py ring_embedding_gather)
            if impl == "xla":
                return hops * lp.alpha + hops * n * lp.beta
            if impl == "ring":
                return (hops * lp.alpha * RING_HOP_PENALTY
                        + hops * n * lp.beta * (1 - self.overlap_credit))
            if impl == "bidir_ring":
                return (-(-hops // 2) * lp.alpha * RING_HOP_PENALTY
                        + hops * n * lp.beta * (1 - self.overlap_credit))
            if impl == "int8":
                return (hops * lp.alpha + hops * n * q * lp.beta
                        + n * self.quant_cost * p + self.quant_fixed)
            if impl == "fused_matmul":
                # the compute-bound quantized chunk ring (fused_ring_all_
                # gather): int8 wire AND the overlap credit at once — wins
                # the big-message regime where both terms matter, loses
                # tiny alpha-dominated sites to exact xla (ring penalty +
                # quant_fixed)
                return (hops * lp.alpha * RING_HOP_PENALTY
                        + hops * n * q * lp.beta * (1 - self.overlap_credit)
                        + n * self.quant_cost * p + self.quant_fixed)
        elif site.op == "reduce_scatter":
            # site.shape is the full local input; (p-1)/p*n bytes per rank
            frac = n * hops / p
            if impl == "xla":
                return hops * lp.alpha + frac * lp.beta
            if impl == "ring":
                return (hops * lp.alpha * RING_HOP_PENALTY
                        + frac * lp.beta * (1 - self.overlap_credit))
            if impl in ("int8", "int8_sr"):
                t = hops * lp.alpha + frac * q * lp.beta \
                    + n * self.quant_cost + self.quant_fixed
                return t * (1.02 if impl == "int8_sr" else 1.0)
            if impl == "fused_matmul":
                # quantized ring reduction bound to the producing matmul:
                # one re-quantization round per hop (the shard-sized
                # accumulator), hops hidden behind the tiles
                return (hops * lp.alpha * RING_HOP_PENALTY
                        + frac * q * lp.beta * (1 - self.overlap_credit)
                        + n * self.quant_cost + hops * self.quant_fixed)
        elif site.op == "all_to_all":
            frac = n * hops / p
            if impl == "xla":
                return hops * lp.alpha + frac * lp.beta
            if impl == "int8":
                return (hops * lp.alpha + frac * q * lp.beta
                        + 2 * n * self.quant_cost + 2 * self.quant_fixed)
        elif site.op == "gather_matmul":
            # the collective half of a TP/Ulysses linear: gather n bytes of
            # activations; fused_matmul hides the ring behind the matmul
            if impl == "xla":
                return hops * lp.alpha + hops * n * lp.beta
            if impl == "fused_matmul":
                return (hops * lp.alpha * RING_HOP_PENALTY
                        + hops * n * lp.beta * (1 - self.overlap_credit))
        return float("inf")

    def phase_span(self, site: CollectiveSite, st: PhaseStep) -> Optional[int]:
        """Rank count of one phase of a program at ``site``. A foreign-mesh
        site (explicit ``axis_size``) is one flat axis the fingerprint knows
        nothing about: only phases spanning exactly the site's own axes are
        estimable there (span = the override); any other phase axes make
        the program un-costable (None -> inf)."""
        if site.axis_size is not None:
            if tuple(st.axes) == tuple(site.axes):
                return int(site.axis_size)
            return None
        return self.fp.axis_size(st.axes)

    def estimate_phase(self, site: CollectiveSite, st: PhaseStep,
                       n: float) -> Tuple[float, float]:
        """(seconds, per-rank payload bytes AFTER the phase) for one phase
        of a program at ``site``, entered with ``n`` payload bytes.

        Via arms: ``xla`` pays one fused-collective alpha per hop; ``ring``
        / ``bidir_ring`` pay :data:`RING_HOP_PENALTY` per hop (bidir halves
        the hop count); ``fused_matmul`` additionally earns the overlap
        credit on bandwidth (hops hidden behind the bound matmul's tiles);
        ``tree`` is the recursive-doubling/halving butterfly — ceil(log2 p)
        rounds at :data:`TREE_ROUND_PENALTY` each instead of O(p) hops, the
        alpha-dominated DCN shape, at ring-equivalent bandwidth for
        reduce_scatter/all_gather but log2(p)/2x the ring's bandwidth for
        all_reduce (every round moves the full vector). ``chunks`` = K > 1
        pipelines an xla phase: K alphas, but the next phase starts on
        chunk 1 while this one streams chunk 2 — the bandwidth term earns
        ``overlap_credit x (K-1)/K`` (only the first chunk is exposed)."""
        p = self.phase_span(site, st)
        if p is None:
            return float("inf"), n
        if p <= 1:
            return 0.0, n
        lp = self.link_params(st.link, st.axes)
        hops = p - 1
        rounds = max(1, int(np.ceil(np.log2(p))))
        k = max(1, int(st.chunks))
        q = self._wire_ratio(site.dtype) if st.quantized else 1.0
        overlap = 1.0
        if st.via in ("ring", "fused_matmul"):
            alpha_t = hops * RING_HOP_PENALTY * lp.alpha
            if st.via == "fused_matmul":
                overlap = 1 - self.overlap_credit
        elif st.via == "bidir_ring":
            alpha_t = -(-hops // 2) * RING_HOP_PENALTY * lp.alpha
        elif st.via == "tree":
            alpha_t = rounds * TREE_ROUND_PENALTY * lp.alpha
        else:
            alpha_t = hops * lp.alpha * k
            if k > 1:
                overlap = 1 - self.overlap_credit * (k - 1) / k
        t = 0.0
        if st.phase_op == "reduce_scatter":
            # recursive halving moves the same n(p-1)/p bytes as the ring
            t += alpha_t + n * hops / p * q * lp.beta * overlap
            if st.quantized:
                t += n * self.quant_cost + k * self.quant_fixed
            n = n / p
        elif st.phase_op == "all_reduce":
            if st.via == "tree":
                # recursive doubling: every round exchanges the FULL vector
                t += alpha_t + rounds * n * q * lp.beta
            else:
                t += 2 * alpha_t + 2 * n * q * hops / p * lp.beta * overlap
            if st.quantized:
                t += 2 * n * self.quant_cost + 2 * k * self.quant_fixed
        elif st.phase_op == "all_gather":
            t += alpha_t + hops * n * q * lp.beta * overlap
            if st.quantized:
                t += n * p * self.quant_cost + k * self.quant_fixed
            n = n * p
        elif st.phase_op == "all_to_all":
            t += alpha_t + n * hops / p * q * lp.beta * overlap
            if st.quantized:
                t += 2 * n * self.quant_cost + 2 * k * self.quant_fixed
        if st.via == "tree" and st.quantized:
            # each butterfly round re-quantizes its sent piece
            t += (rounds - 1) * self.quant_fixed
        return t, n

    def estimate_program(self, site: CollectiveSite,
                         program: Tuple[PhaseStep, ...]) -> float:
        """Predicted seconds for one execution of a multi-phase program at
        ``site``. Each phase is costed with ITS OWN link params (the
        distinct DCN alpha/beta in :data:`LINK_TABLE` — the term that makes
        'exact on ICI, int8 on DCN' beat both flat variants the moment a
        slice boundary enters the span) and the per-rank payload tracks
        the phase algebra: a reduce-scatter shrinks it by the axis span, an
        all-gather grows it back. Fused phases (``via="fused_matmul"``)
        take the ring alpha penalty but earn the overlap credit on the
        bandwidth term — their hops ride behind the bound matmul's tiles,
        the term that lets a fused-hierarchical program beat its sequenced
        twin on the same cost scale. Foreign-mesh sites (explicit
        ``axis_size``) are estimable only for programs whose every phase
        spans exactly the site's axes (the compiler's single-phase
        tree/chunked shapes); anything else prices to inf."""
        n = float(site.nbytes)
        t = 0.0
        for st in program:
            dt, n = self.estimate_phase(site, st, n)
            t += dt
            if not np.isfinite(t):
                return float("inf")
        return t

    def _split_axes(self, site: CollectiveSite) -> Tuple[int, int]:
        """(inner, outer) sizes for the hierarchical split: last axis is the
        inner (ICI-local) hop, the rest the outer — the zeropp
        hierarchical_all_gather convention. A foreign-mesh site (explicit
        axis_size) is one flat axis: no split."""
        axes = site.axes
        if len(axes) < 2 or site.axis_size is not None:
            return (1, self.axis_size_of(site))
        return (self.fp.axis_size(axes[-1:]), self.fp.axis_size(axes[:-1]))

    # -- candidate enumeration + pruning -----------------------------------
    def candidates(self, site: CollectiveSite) -> List[str]:
        """Structurally-valid implementations for ``site``."""
        out = []
        for impl in OP_MENU[site.op]:
            if impl == "hierarchical":
                p_in, p_out = self._split_axes(site)
                if p_in <= 1 or p_out <= 1:
                    continue
            if impl == "int8_sr" and site.consumer not in GRADIENT_CONSUMERS:
                continue  # activations round to nearest, never dithered
            out.append(impl)
        return out

    def prune(self, site: CollectiveSite,
              margin: float = 3.0) -> List[Tuple[str, float]]:
        """Rank candidates by estimated cost; drop any whose estimate
        exceeds ``margin`` x the best (dominated — not worth measuring).
        Ties keep menu order (xla first), so ranking is deterministic."""
        ests = [(impl, self.estimate(site, impl))
                for impl in self.candidates(site)]
        ests.sort(key=lambda kv: kv[1])
        if not ests:
            raise ValueError(f"no candidate implementation for {site}")
        best = ests[0][1]
        cut = best * margin if best > 0 else float("inf")
        survivors = [(i, e) for i, e in ests if e <= cut]
        return survivors or ests[:1]

    def decide(self, site: CollectiveSite,
               margin: float = 3.0) -> PlanDecision:
        """Static-mode decision: the cost model's argmin."""
        impl, est = self.prune(site, margin=margin)[0]
        quantized = impl in ("int8", "int8_sr", "hierarchical") or (
            # the fused gather/scatter rings carry an int8 wire; the TP
            # gather_matmul fused impl is exact and needs no block
            impl == "fused_matmul"
            and site.op in ("all_gather", "reduce_scatter"))
        return PlanDecision(impl=impl,
                            block=self.block if quantized else None,
                            source="cost-model",
                            est_us=round(est * 1e6, 3))
