"""Collective planner: topology-aware plan IR + microbenchmark autotuner.

PR 1 (ring-overlapped collective matmul) and PR 2 (quantized collectives)
added the fast-path *menu*; this subsystem is the *selector* that turns the
menu into an automatic, measured, cached per-site decision (GC3, arxiv
2201.11840; The Big Send-off, arxiv 2504.18658). See
``docs/comm_planner.md`` for the IR, cache format, and tuning workflow.
"""

from .cache import PlanCache, default_cache_dir
from .compiler import (DEFAULT_BEAM_WIDTH, PROGRAM_CAPABLE, SEARCH_SPACE,
                       compile_programs, legacy_menu_programs,
                       program_capable)
from .ir import (CONSUMERS, FUSED_PHASE_OPS, FUSED_ROLES, IMPLEMENTATIONS,
                 LINK_CLASSES, OP_MENU, PHASE_OPS, PHASE_VIAS, PLAN_FORMAT,
                 WIRE_DTYPES, CollectiveSite, FusedCompute, PhaseStep, Plan,
                 PlanDecision, make_phase, make_site, program_summary)
from .microbench import benchmark_site, probe_stats, reset_probe_memo
from .planner import (MODES, CollectivePlanner, configure_from_config,
                      configure_planner, get_planner, planner_active,
                      reset_planner, resolve_site, synthesize_programs)
from .topo import CostModel, LinkParams, MeshFingerprint

__all__ = [
    "CONSUMERS", "IMPLEMENTATIONS", "OP_MENU", "MODES",
    "PHASE_OPS", "PHASE_VIAS", "WIRE_DTYPES", "LINK_CLASSES",
    "FUSED_PHASE_OPS", "FUSED_ROLES", "PLAN_FORMAT",
    "CollectiveSite", "Plan", "PlanDecision", "PhaseStep", "FusedCompute",
    "make_site", "make_phase", "program_summary", "synthesize_programs",
    "SEARCH_SPACE", "DEFAULT_BEAM_WIDTH", "PROGRAM_CAPABLE",
    "compile_programs", "legacy_menu_programs", "program_capable",
    "MeshFingerprint", "CostModel", "LinkParams",
    "PlanCache", "default_cache_dir", "benchmark_site", "probe_stats",
    "reset_probe_memo",
    "CollectivePlanner", "configure_planner", "configure_from_config",
    "get_planner", "planner_active", "reset_planner", "resolve_site",
]
