"""Plan IR: collective sites and per-site implementation decisions.

GC3 (arxiv 2201.11840) compiles collectives from a small IR; The Big
Send-off (arxiv 2504.18658) shows the *choice* of algorithm per topology and
message size is itself the optimization. This module is the vocabulary that
choice is expressed in: a :class:`CollectiveSite` names one collective call
site in the training program (op kind, shape/dtype, mesh axes, consumer
tag), a :class:`PlanDecision` names one concrete implementation drawn from
the menu PR 1/PR 2 built (XLA native, ppermute rings, hierarchical, int8
block-quantized, fused collective-matmul), and a :class:`Plan` maps sites to
decisions for one mesh fingerprint. Everything serializes to JSON so plans
cache on disk and survive relaunches (``planner/cache.py``).
"""

import dataclasses
import json
from dataclasses import dataclass
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

# The implementation menu (what the existing fast paths can actually run):
#   xla          — the fused XLA-native collective (psum / all_gather /
#                  psum_scatter / all_to_all); today's default everywhere
#   ring         — p-1 ppermute chunk hops (ops/collective_matmul.py
#                  ring_all_gather / ring_reduce_scatter), exact
#   bidir_ring   — both ICI directions busy, half the ring steps, exact
#   hierarchical — two-level all-reduce: inner (ICI) axis exact, outer
#                  (DCN) hops int8 (comm/compressed.py)
#   int8         — block-quantized payload, nearest rounding
#   int8_sr      — block-quantized + stochastic rounding (gradient paths)
#   fused_matmul — collective matmul: the gather/reduction ring hidden
#                  behind the partial matmuls (all_gather_matmul /
#                  matmul_reduce_scatter)
#   program      — not a fixed impl at all: the decision carries an ordered
#                  multi-phase PROGRAM of PhaseStep entries (GC3-style
#                  synthesis) executed by comm.compressed.
#                  run_collective_program — e.g. exact reduce-scatter over
#                  the ICI axes, int8+error-feedback all-reduce over the
#                  DCN axis, all-gather back over ICI
#   einsum       — serving decode_attn only: the gathered-page dense
#                  reference path (inference/v2/model.paged_attention)
#   pallas       — serving decode_attn only: the resident-pool paged
#                  flash-decode kernel (ops/pallas/paged_attention.
#                  paged_flash_decode, int8 dequant fused in-kernel)
IMPLEMENTATIONS = ("xla", "ring", "bidir_ring", "hierarchical", "int8",
                   "int8_sr", "fused_matmul", "program", "einsum", "pallas")

# the phase vocabulary a program decision is built from; each phase lowers
# to one collective primitive over its own axes with its own wire dtype
PHASE_OPS = ("reduce_scatter", "all_reduce", "all_gather")
# exact     — native-dtype payload, bit-faithful transport
# int8      — block-quantized payload + one-lane scales, nearest rounding
# int8_sr   — block-quantized + stochastic rounding (unbiased per element)
# int8_ef   — block-quantized + ErrorFeedbackState residual carry (the DCN
#             gradient hop: quantization error re-injected next step)
WIRE_DTYPES = ("exact", "int8", "int8_sr", "int8_ef")
# how a phase lowers: the fused XLA collective or a ppermute chunk ring
PHASE_VIAS = ("xla", "ring", "bidir_ring")
# link classes a phase's traffic is accounted under in the comms ledger
LINK_CLASSES = ("ici", "dcn", "host")

# op kind -> implementations that can realize it
OP_MENU: Dict[str, Tuple[str, ...]] = {
    "all_reduce": ("xla", "int8", "int8_sr", "hierarchical"),
    "all_gather": ("xla", "ring", "bidir_ring", "int8"),
    "reduce_scatter": ("xla", "ring", "int8", "int8_sr"),
    "all_to_all": ("xla", "int8"),
    "gather_matmul": ("xla", "fused_matmul"),
    # the vocab-sharded embedding table gather (shape = the per-rank table
    # shard): xla is all_gather(table) + take, ring/bidir_ring hide the
    # chunk hops behind the resident chunk's row lookups
    # (ops/collective_matmul.py ring_embedding_gather / ring_tied_lm_head)
    "embed_gather": ("xla", "ring", "bidir_ring"),
    # serving fused-decode attention (inference/v2): not a collective at
    # all but a kernel choice with a decode-shape cost regime — the site
    # shape is the gathered pool view one decode step touches
    # ([S, B*bs, Hk, D] in the storage dtype), axes are empty. einsum
    # materializes a compute-dtype copy of it per step; pallas streams the
    # live pages of the resident pool in place (topo._estimate_decode_attn)
    "decode_attn": ("einsum", "pallas"),
}

# the wired consumers (PR 3's five + the PR 6 embedding site + the
# serving decode tier: decode_attn and the decode-TP projections'
# gather_matmul both resolve under "decode")
CONSUMERS = ("tp-linear", "ulysses", "moe-a2a", "dp-grad", "zeropp", "embed",
             "decode")

# consumers whose payload is a gradient: stochastic rounding is admissible
# (unbiased compression matters there); activation exchanges keep nearest
GRADIENT_CONSUMERS = ("dp-grad", "zeropp")


@dataclass(frozen=True)
class CollectiveSite:
    """One collective call site: what moves, over which axes, for whom.

    ``shape`` is the per-rank tensor the call site passes (the ledger's
    "logical" convention), ``axes`` the mesh axis names the collective runs
    over, ``consumer`` one of :data:`CONSUMERS`. ``axis_size`` overrides the
    mesh fingerprint's axis-size lookup — for sites living on a mesh other
    than the fleet topology (the zeropp factory takes its own ``mesh`` and
    ``dp_axis``); when set it is part of the site identity.
    """
    op: str
    shape: Tuple[int, ...]
    dtype: str
    axes: Tuple[str, ...]
    consumer: str
    axis_size: Optional[int] = None

    def __post_init__(self):
        if self.op not in OP_MENU:
            raise ValueError(f"unknown collective op {self.op!r}; "
                             f"known: {sorted(OP_MENU)}")
        if self.consumer not in CONSUMERS:
            raise ValueError(f"unknown consumer tag {self.consumer!r}; "
                             f"known: {CONSUMERS}")

    @property
    def nbytes(self) -> int:
        n = int(np.prod(self.shape)) if self.shape else 1
        return n * int(np.dtype(self.dtype).itemsize)

    def signature(self) -> str:
        """Canonical site key — the cache/ledger identity of this site."""
        dims = "x".join(str(d) for d in self.shape) or "scalar"
        axes = ",".join(self.axes)
        if self.axis_size is not None:
            axes += f"*{self.axis_size}"
        return f"{self.consumer}:{self.op}:{dims}:{self.dtype}@{axes}"


def make_site(*, op: str, shape: Sequence[int], dtype: Any,
              axes: Sequence[str], consumer: str,
              axis_size: Optional[int] = None) -> CollectiveSite:
    """Normalizing constructor: any shape sequence / dtype-like goes in,
    a canonical (hashable, JSON-stable) :class:`CollectiveSite` comes out."""
    return CollectiveSite(op=str(op),
                          shape=tuple(int(d) for d in shape),
                          dtype=np.dtype(dtype).name,
                          axes=tuple(str(a) for a in axes),
                          consumer=str(consumer),
                          axis_size=None if axis_size is None
                          else int(axis_size))


@dataclass(frozen=True)
class PhaseStep:
    """One phase of a multi-phase collective program.

    ``phase_op`` is the collective primitive, ``axes`` the mesh axes THIS
    phase runs over (each phase gets its own axes — the whole point:
    different hops ride different links), ``wire_dtype`` what rides those
    links, ``via`` whether the phase lowers to the fused XLA collective or
    a ppermute chunk ring, and ``link`` the ledger hop class the phase's
    wire bytes are accounted under (``ici``/``dcn``/``host``; synthesis
    stamps it from the mesh fingerprint so the ledger can report DCN-class
    bytes without re-deriving topology at trace time).
    """
    phase_op: str
    axes: Tuple[str, ...]
    wire_dtype: str = "exact"
    block: Optional[int] = None
    via: str = "xla"
    link: Optional[str] = None

    def __post_init__(self):
        if self.phase_op not in PHASE_OPS:
            raise ValueError(f"unknown phase op {self.phase_op!r}; "
                             f"menu: {PHASE_OPS}")
        if self.wire_dtype not in WIRE_DTYPES:
            raise ValueError(f"unknown wire dtype {self.wire_dtype!r}; "
                             f"menu: {WIRE_DTYPES}")
        if self.via not in PHASE_VIAS:
            raise ValueError(f"unknown phase via {self.via!r}; "
                             f"menu: {PHASE_VIAS}")
        if self.link is not None and self.link not in LINK_CLASSES:
            raise ValueError(f"unknown link class {self.link!r}; "
                             f"menu: {LINK_CLASSES}")
        if not self.axes:
            raise ValueError("a PhaseStep needs at least one mesh axis")

    @property
    def quantized(self) -> bool:
        return self.wire_dtype != "exact"

    def to_dict(self) -> Dict[str, Any]:
        d = {"phase_op": self.phase_op, "axes": list(self.axes)}
        if self.wire_dtype != "exact":
            d["wire_dtype"] = self.wire_dtype
        if self.block is not None:
            d["block"] = self.block
        if self.via != "xla":
            d["via"] = self.via
        if self.link is not None:
            d["link"] = self.link
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PhaseStep":
        known = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in d.items() if k in known}
        kw["axes"] = tuple(str(a) for a in kw.get("axes", ()))
        return cls(**kw)


def make_phase(phase_op: str, axes: Sequence[str], *,
               wire_dtype: str = "exact", block: Optional[int] = None,
               via: str = "xla", link: Optional[str] = None) -> PhaseStep:
    """Normalizing :class:`PhaseStep` constructor (the ``make_site`` twin)."""
    return PhaseStep(phase_op=str(phase_op),
                     axes=tuple(str(a) for a in axes),
                     wire_dtype=str(wire_dtype),
                     block=None if block is None else int(block),
                     via=str(via), link=link)


def program_summary(program: Sequence[PhaseStep]) -> str:
    """Compact one-line program rendering for logs and the plan table:
    ``rs(ep)>ar.int8_ef(dp_outer)>ag(ep)``."""
    short = {"reduce_scatter": "rs", "all_reduce": "ar", "all_gather": "ag"}
    parts = []
    for s in program:
        tag = short[s.phase_op]
        if s.wire_dtype != "exact":
            tag += f".{s.wire_dtype}"
        if s.via != "xla":
            tag += f"~{s.via}"
        parts.append(f"{tag}({','.join(s.axes)})")
    return ">".join(parts)


@dataclass(frozen=True)
class PlanDecision:
    """One site's resolved implementation.

    ``source`` records WHO decided: ``knob`` (an explicitly-set raw config
    knob — always wins), ``cache`` (loaded from the on-disk plan),
    ``cost-model`` (static alpha-beta ranking), ``measured`` (microbenchmark
    winner), or ``default`` (planner off — today's behavior).
    ``est_us`` is the model's (or measurement's) cost estimate.

    ``impl == "program"`` decisions carry the synthesized multi-phase
    ``program`` (a tuple of :class:`PhaseStep`) instead of naming a fixed
    implementation; every other impl keeps ``program is None``, so
    single-impl decisions serialize byte-identically to the pre-program
    plan-cache format.
    """
    impl: str
    block: Optional[int] = None
    source: str = "default"
    est_us: Optional[float] = None
    program: Optional[Tuple[PhaseStep, ...]] = None

    def __post_init__(self):
        if self.impl not in IMPLEMENTATIONS:
            raise ValueError(f"unknown implementation {self.impl!r}; "
                             f"menu: {IMPLEMENTATIONS}")
        if self.impl == "program":
            if not self.program:
                raise ValueError("impl='program' needs a non-empty program")
            object.__setattr__(self, "program", tuple(self.program))
        elif self.program is not None:
            raise ValueError(f"impl={self.impl!r} must not carry a program")

    @property
    def quantized(self) -> bool:
        if self.impl == "program":
            return any(s.quantized for s in self.program)
        return self.impl in ("int8", "int8_sr", "hierarchical")

    def to_dict(self) -> Dict[str, Any]:
        d = {k: v for k, v in dataclasses.asdict(self).items()
             if v is not None and k != "program"}
        if self.program is not None:
            d["program"] = [s.to_dict() for s in self.program]
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PlanDecision":
        known = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in d.items() if k in known}
        prog = kw.get("program")
        if prog is not None:
            kw["program"] = tuple(
                s if isinstance(s, PhaseStep) else PhaseStep.from_dict(s)
                for s in prog)
        return cls(**kw)


class Plan:
    """Site signature -> :class:`PlanDecision` for one mesh fingerprint."""

    def __init__(self, fingerprint: str = "",
                 decisions: Optional[Dict[str, PlanDecision]] = None):
        self.fingerprint = fingerprint
        self.decisions: Dict[str, PlanDecision] = dict(decisions or {})

    def get(self, site: CollectiveSite) -> Optional[PlanDecision]:
        return self.decisions.get(site.signature())

    def set(self, site: CollectiveSite, decision: PlanDecision) -> None:
        self.decisions[site.signature()] = decision

    def __len__(self) -> int:
        return len(self.decisions)

    def __eq__(self, other) -> bool:
        return (isinstance(other, Plan)
                and self.fingerprint == other.fingerprint
                and self.decisions == other.decisions)

    def to_dict(self) -> Dict[str, Any]:
        return {"fingerprint": self.fingerprint,
                "sites": {sig: d.to_dict()
                          for sig, d in sorted(self.decisions.items())}}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Plan":
        return cls(fingerprint=d.get("fingerprint", ""),
                   decisions={sig: PlanDecision.from_dict(dd)
                              for sig, dd in d.get("sites", {}).items()})

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=1, sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "Plan":
        return cls.from_dict(json.loads(s))
