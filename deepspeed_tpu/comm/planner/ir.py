"""Plan IR: collective sites and per-site implementation decisions.

GC3 (arxiv 2201.11840) compiles collectives from a small IR; The Big
Send-off (arxiv 2504.18658) shows the *choice* of algorithm per topology and
message size is itself the optimization. This module is the vocabulary that
choice is expressed in: a :class:`CollectiveSite` names one collective call
site in the training program (op kind, shape/dtype, mesh axes, consumer
tag), a :class:`PlanDecision` names one concrete implementation drawn from
the menu PR 1/PR 2 built (XLA native, ppermute rings, hierarchical, int8
block-quantized, fused collective-matmul), and a :class:`Plan` maps sites to
decisions for one mesh fingerprint. Everything serializes to JSON so plans
cache on disk and survive relaunches (``planner/cache.py``).
"""

import dataclasses
import json
from dataclasses import dataclass
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

# The implementation menu (what the existing fast paths can actually run):
#   xla          — the fused XLA-native collective (psum / all_gather /
#                  psum_scatter / all_to_all); today's default everywhere
#   ring         — p-1 ppermute chunk hops (ops/collective_matmul.py
#                  ring_all_gather / ring_reduce_scatter), exact
#   bidir_ring   — both ICI directions busy, half the ring steps, exact
#   hierarchical — two-level all-reduce: inner (ICI) axis exact, outer
#                  (DCN) hops int8 (comm/compressed.py)
#   int8         — block-quantized payload, nearest rounding
#   int8_sr      — block-quantized + stochastic rounding (gradient paths)
#   fused_matmul — collective matmul: the gather/reduction ring hidden
#                  behind the partial matmuls (all_gather_matmul /
#                  matmul_reduce_scatter)
#   program      — not a fixed impl at all: the decision carries an ordered
#                  multi-phase PROGRAM of PhaseStep entries (GC3-style
#                  synthesis) executed by comm.compressed.
#                  run_collective_program — e.g. exact reduce-scatter over
#                  the ICI axes, int8+error-feedback all-reduce over the
#                  DCN axis, all-gather back over ICI
#   einsum       — serving decode_attn only: the gathered-page dense
#                  reference path (inference/v2/model.paged_attention)
#   pallas       — serving decode_attn only: the resident-pool paged
#                  flash-decode kernel (ops/pallas/paged_attention.
#                  paged_flash_decode, int8 dequant fused in-kernel)
IMPLEMENTATIONS = ("xla", "ring", "bidir_ring", "hierarchical", "int8",
                   "int8_sr", "fused_matmul", "program", "einsum", "pallas")

# the phase vocabulary a program decision is built from; each phase lowers
# to one collective primitive over its own axes with its own wire dtype
# (all_to_all phases exist for the compiler's single-phase a2a-site
# programs — chunked/quantized variants of the flat exchange)
PHASE_OPS = ("reduce_scatter", "all_reduce", "all_gather", "all_to_all")
# exact     — native-dtype payload, bit-faithful transport
# int8      — block-quantized payload + one-lane scales, nearest rounding
# int8_sr   — block-quantized + stochastic rounding (unbiased per element)
# int8_ef   — block-quantized + ErrorFeedbackState residual carry (the DCN
#             gradient hop: quantization error re-injected next step)
WIRE_DTYPES = ("exact", "int8", "int8_sr", "int8_ef")
# how a phase lowers: the fused XLA collective, a ppermute chunk ring, a
# ppermute chunk ring BOUND to the matmul that produces/consumes the payload
# (T3-style: the hops ride between the compute site's tile steps and hide
# behind them — such phases must carry a FusedCompute descriptor), or a
# recursive-doubling/halving butterfly ("tree": log2(p) ppermute rounds
# instead of p-1 ring hops — the alpha-dominated regime's shape; the span
# must be a power of two, enforced at synthesis where the span is known)
PHASE_VIAS = ("xla", "ring", "bidir_ring", "fused_matmul", "tree")
# phase ops a fused_matmul via can realize: the all-gather side (consumer
# matmul eats the arriving chunks) and the reduce-scatter side (producer
# matmul feeds the departing chunks); a one-shot all_reduce has no tile
# stream to interleave with
FUSED_PHASE_OPS = ("all_gather", "reduce_scatter")
# which side of the matmul the fused phase binds to
FUSED_ROLES = ("producer", "consumer")
# link classes a phase's traffic is accounted under in the comms ledger
LINK_CLASSES = ("ici", "dcn", "host")

# op kind -> implementations that can realize it.
# all_gather/reduce_scatter "fused_matmul": the compute-bound quantized
# chunk ring (ops/collective_matmul.py fused_ring_all_gather /
# fused_ring_reduce_scatter) — int8 payload per hop AND the hops hidden
# behind the consuming/producing matmul tiles (the ZeRO-3 qwZ gather
# fusing into its projection, the qgZ scatter into the backward matmuls)
OP_MENU: Dict[str, Tuple[str, ...]] = {
    "all_reduce": ("xla", "int8", "int8_sr", "hierarchical"),
    "all_gather": ("xla", "ring", "bidir_ring", "int8", "fused_matmul"),
    "reduce_scatter": ("xla", "ring", "int8", "int8_sr", "fused_matmul"),
    "all_to_all": ("xla", "int8"),
    "gather_matmul": ("xla", "fused_matmul"),
    # the vocab-sharded embedding table gather (shape = the per-rank table
    # shard): xla is all_gather(table) + take, ring/bidir_ring hide the
    # chunk hops behind the resident chunk's row lookups
    # (ops/collective_matmul.py ring_embedding_gather / ring_tied_lm_head)
    "embed_gather": ("xla", "ring", "bidir_ring"),
    # serving fused-decode attention (inference/v2): not a collective at
    # all but a kernel choice with a decode-shape cost regime — the site
    # shape is the gathered pool view one decode step touches
    # ([S, B*bs, Hk, D] in the storage dtype), axes are empty. einsum
    # materializes a compute-dtype copy of it per step; pallas streams the
    # live pages of the resident pool in place (topo._estimate_decode_attn)
    "decode_attn": ("einsum", "pallas"),
}

# the wired consumers (PR 3's five + the PR 6 embedding site + the
# serving decode tier: decode_attn and the decode-TP projections'
# gather_matmul both resolve under "decode"; "autotp" is the sharding
# subsystem's load-time registration of the gather-class collectives a
# rule-sharded foreign param tree implies — sharding/autotp.py)
CONSUMERS = ("tp-linear", "ulysses", "moe-a2a", "dp-grad", "zeropp", "embed",
             "decode", "autotp")

# consumers whose payload is a gradient: stochastic rounding is admissible
# (unbiased compression matters there); activation exchanges keep nearest
GRADIENT_CONSUMERS = ("dp-grad", "zeropp")


@dataclass(frozen=True)
class CollectiveSite:
    """One collective call site: what moves, over which axes, for whom.

    ``shape`` is the per-rank tensor the call site passes (the ledger's
    "logical" convention), ``axes`` the mesh axis names the collective runs
    over, ``consumer`` one of :data:`CONSUMERS`. ``axis_size`` overrides the
    mesh fingerprint's axis-size lookup — for sites living on a mesh other
    than the fleet topology (the zeropp factory takes its own ``mesh`` and
    ``dp_axis``); when set it is part of the site identity.
    """
    op: str
    shape: Tuple[int, ...]
    dtype: str
    axes: Tuple[str, ...]
    consumer: str
    axis_size: Optional[int] = None

    def __post_init__(self):
        if self.op not in OP_MENU:
            raise ValueError(f"unknown collective op {self.op!r}; "
                             f"known: {sorted(OP_MENU)}")
        if self.consumer not in CONSUMERS:
            raise ValueError(f"unknown consumer tag {self.consumer!r}; "
                             f"known: {CONSUMERS}")

    @property
    def nbytes(self) -> int:
        n = int(np.prod(self.shape)) if self.shape else 1
        return n * int(np.dtype(self.dtype).itemsize)

    def signature(self) -> str:
        """Canonical site key — the cache/ledger identity of this site."""
        dims = "x".join(str(d) for d in self.shape) or "scalar"
        axes = ",".join(self.axes)
        if self.axis_size is not None:
            axes += f"*{self.axis_size}"
        return f"{self.consumer}:{self.op}:{dims}:{self.dtype}@{axes}"


def make_site(*, op: str, shape: Sequence[int], dtype: Any,
              axes: Sequence[str], consumer: str,
              axis_size: Optional[int] = None) -> CollectiveSite:
    """Normalizing constructor: any shape sequence / dtype-like goes in,
    a canonical (hashable, JSON-stable) :class:`CollectiveSite` comes out."""
    return CollectiveSite(op=str(op),
                          shape=tuple(int(d) for d in shape),
                          dtype=np.dtype(dtype).name,
                          axes=tuple(str(a) for a in axes),
                          consumer=str(consumer),
                          axis_size=None if axis_size is None
                          else int(axis_size))


@dataclass(frozen=True)
class FusedCompute:
    """The compute-site binding of a ``via="fused_matmul"`` phase.

    ``role`` says which side of the matmul the hops interleave with:
    ``"consumer"`` — the matmul consumes the gathered operand (the
    all-gather side: each arriving chunk's partial product runs while the
    next chunk's permute is in flight); ``"producer"`` — the matmul
    produces the payload the reduction consumes (the reduce-scatter side:
    each departing partial sum's hop overlaps the next tile's matmul).
    ``site`` is a free-form tag naming the bound matmul site (shows up in
    flight-ring ``detail`` and the doctor's divergence report); ``tile``
    the per-hop chunk element count (0 = unbound: the executor's per-rank
    shard — the engine re-binds it to the real chunk size at compile).
    """
    role: str
    site: str = ""
    tile: int = 0

    def __post_init__(self):
        if self.role not in FUSED_ROLES:
            raise ValueError(f"unknown fused-compute role {self.role!r}; "
                             f"menu: {FUSED_ROLES}")

    def tag(self) -> str:
        """The flight-ring/doctor label: ``site@role`` (or just the role)."""
        return f"{self.site}@{self.role}" if self.site else self.role

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"role": self.role}
        if self.site:
            d["site"] = self.site
        if self.tile:
            d["tile"] = int(self.tile)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FusedCompute":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            # strict: a compute descriptor from a newer build must fail the
            # load (cache miss), never silently shed fields
            raise ValueError(f"unknown FusedCompute fields {sorted(unknown)}")
        return cls(**d)


@dataclass(frozen=True)
class PhaseStep:
    """One phase of a multi-phase collective program.

    ``phase_op`` is the collective primitive, ``axes`` the mesh axes THIS
    phase runs over (each phase gets its own axes — the whole point:
    different hops ride different links), ``wire_dtype`` what rides those
    links, ``via`` whether the phase lowers to the fused XLA collective, a
    ppermute chunk ring, or a compute-bound fused ring
    (``"fused_matmul"`` — requires ``compute``), and ``link`` the ledger
    hop class the phase's wire bytes are accounted under
    (``ici``/``dcn``/``host``; synthesis stamps it from the mesh
    fingerprint so the ledger can report DCN-class bytes without
    re-deriving topology at trace time).

    ``chunks`` > 1 column-splits the payload into K pipelined pieces so
    the next phase can start on chunk 1 while this phase streams chunk 2
    (priced alpha x K vs overlapped beta in ``topo.estimate_program``).
    The column layout keeps reduce_scatter/all_gather rank-placement
    identical to the flat collective, so a chunked exact phase stays
    bitwise-equal to its unchunked twin. Only the ``xla`` via chunks (the
    ring/tree/fused lowerings already stream per-hop pieces), and
    ``int8_ef`` never chunks (the residual is one full-tensor carry).
    """
    phase_op: str
    axes: Tuple[str, ...]
    wire_dtype: str = "exact"
    block: Optional[int] = None
    via: str = "xla"
    link: Optional[str] = None
    compute: Optional[FusedCompute] = None
    chunks: int = 1

    def __post_init__(self):
        if self.phase_op not in PHASE_OPS:
            raise ValueError(f"unknown phase op {self.phase_op!r}; "
                             f"menu: {PHASE_OPS}")
        if self.wire_dtype not in WIRE_DTYPES:
            raise ValueError(f"unknown wire dtype {self.wire_dtype!r}; "
                             f"menu: {WIRE_DTYPES}")
        if self.via not in PHASE_VIAS:
            raise ValueError(f"unknown phase via {self.via!r}; "
                             f"menu: {PHASE_VIAS}")
        if self.link is not None and self.link not in LINK_CLASSES:
            raise ValueError(f"unknown link class {self.link!r}; "
                             f"menu: {LINK_CLASSES}")
        if not self.axes:
            raise ValueError("a PhaseStep needs at least one mesh axis")
        if int(self.chunks) < 1:
            raise ValueError(f"chunks must be >= 1, got {self.chunks}")
        if self.chunks > 1 and self.via != "xla":
            raise ValueError(
                f"chunked pipelining only lowers via 'xla' (the "
                f"{self.via!r} via already streams per-hop pieces)")
        if self.wire_dtype == "int8_ef" and self.chunks > 1:
            raise ValueError("int8_ef never chunks (the error-feedback "
                             "residual is one full-tensor carry)")
        if self.phase_op == "all_to_all" and self.via != "xla":
            raise ValueError("all_to_all phases lower via 'xla' only")
        if self.via in ("ring", "bidir_ring", "tree"):
            if self.wire_dtype == "int8_ef":
                raise ValueError(
                    "int8_ef rides xla all_reduce phases (the two-stage "
                    "server layout); hop-structured vias take "
                    "exact|int8|int8_sr")
        if self.via == "fused_matmul":
            if self.phase_op not in FUSED_PHASE_OPS:
                raise ValueError(
                    f"via='fused_matmul' only fuses {FUSED_PHASE_OPS} "
                    f"(a one-shot {self.phase_op} has no tile stream to "
                    f"interleave with)")
            if self.compute is None:
                raise ValueError("via='fused_matmul' needs a FusedCompute "
                                 "binding (which matmul hides the hops)")
            if self.wire_dtype == "int8_ef":
                raise ValueError(
                    "int8_ef rides the all_reduce phase (the residual is a "
                    "full-tensor carry); fused hops take exact|int8|int8_sr")
        elif self.compute is not None:
            raise ValueError(f"via={self.via!r} must not carry a "
                             "FusedCompute binding")

    @property
    def quantized(self) -> bool:
        return self.wire_dtype != "exact"

    @property
    def fused(self) -> bool:
        return self.via == "fused_matmul"

    def to_dict(self) -> Dict[str, Any]:
        d = {"phase_op": self.phase_op, "axes": list(self.axes)}
        if self.wire_dtype != "exact":
            d["wire_dtype"] = self.wire_dtype
        if self.block is not None:
            d["block"] = self.block
        if self.via != "xla":
            d["via"] = self.via
        if self.link is not None:
            d["link"] = self.link
        if self.compute is not None:
            d["compute"] = self.compute.to_dict()
        if self.chunks != 1:
            d["chunks"] = int(self.chunks)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PhaseStep":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            # strict: a phase from a newer plan format must fail the load
            # (the cache treats ValueError as a miss and re-tunes) — the
            # old behavior of silently dropping unknown fields could strip
            # the part of a phase that changes its semantics
            raise ValueError(f"unknown PhaseStep fields {sorted(unknown)}")
        kw = dict(d)
        kw["axes"] = tuple(str(a) for a in kw.get("axes", ()))
        comp = kw.get("compute")
        if comp is not None and not isinstance(comp, FusedCompute):
            kw["compute"] = FusedCompute.from_dict(comp)
        return cls(**kw)


def make_phase(phase_op: str, axes: Sequence[str], *,
               wire_dtype: str = "exact", block: Optional[int] = None,
               via: str = "xla", link: Optional[str] = None,
               compute: Optional[FusedCompute] = None,
               chunks: int = 1) -> PhaseStep:
    """Normalizing :class:`PhaseStep` constructor (the ``make_site`` twin)."""
    return PhaseStep(phase_op=str(phase_op),
                     axes=tuple(str(a) for a in axes),
                     wire_dtype=str(wire_dtype),
                     block=None if block is None else int(block),
                     via=str(via), link=link, compute=compute,
                     chunks=int(chunks))


def program_summary(program: Sequence[PhaseStep]) -> str:
    """Compact one-line program rendering for logs and the plan table:
    ``rs(ep)>ar.int8_ef(dp_outer)>ag(ep)`` (chunked phases carry ``xK``:
    ``ar.int8(dp_outer)x4``)."""
    short = {"reduce_scatter": "rs", "all_reduce": "ar", "all_gather": "ag",
             "all_to_all": "a2a"}
    parts = []
    for s in program:
        tag = short[s.phase_op]
        if s.wire_dtype != "exact":
            tag += f".{s.wire_dtype}"
        if s.via != "xla":
            tag += f"~{s.via}"
        tag += f"({','.join(s.axes)})"
        if s.chunks != 1:
            tag += f"x{s.chunks}"
        parts.append(tag)
    return ">".join(parts)


@dataclass(frozen=True)
class PlanDecision:
    """One site's resolved implementation.

    ``source`` records WHO decided: ``knob`` (an explicitly-set raw config
    knob — always wins), ``cache`` (loaded from the on-disk plan),
    ``cost-model`` (static alpha-beta ranking), ``measured`` (microbenchmark
    winner), or ``default`` (planner off — today's behavior).
    ``est_us`` is the model's (or measurement's) cost estimate.

    ``impl == "program"`` decisions carry the synthesized multi-phase
    ``program`` (a tuple of :class:`PhaseStep`) instead of naming a fixed
    implementation; every other impl keeps ``program is None``, so
    single-impl decisions serialize byte-identically to the pre-program
    plan-cache format.
    """
    impl: str
    block: Optional[int] = None
    source: str = "default"
    est_us: Optional[float] = None
    program: Optional[Tuple[PhaseStep, ...]] = None

    def __post_init__(self):
        if self.impl not in IMPLEMENTATIONS:
            raise ValueError(f"unknown implementation {self.impl!r}; "
                             f"menu: {IMPLEMENTATIONS}")
        if self.impl == "program":
            if not self.program:
                raise ValueError("impl='program' needs a non-empty program")
            object.__setattr__(self, "program", tuple(self.program))
        elif self.program is not None:
            raise ValueError(f"impl={self.impl!r} must not carry a program")

    @property
    def quantized(self) -> bool:
        if self.impl == "program":
            return any(s.quantized for s in self.program)
        return self.impl in ("int8", "int8_sr", "hierarchical")

    def to_dict(self) -> Dict[str, Any]:
        d = {k: v for k, v in dataclasses.asdict(self).items()
             if v is not None and k != "program"}
        if self.program is not None:
            d["program"] = [s.to_dict() for s in self.program]
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PlanDecision":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            # strict (the PhaseStep.from_dict contract): version skew must
            # surface as a failed load, never a silently-narrowed decision
            raise ValueError(f"unknown PlanDecision fields {sorted(unknown)}")
        kw = dict(d)
        prog = kw.get("program")
        if prog is not None:
            kw["program"] = tuple(
                s if isinstance(s, PhaseStep) else PhaseStep.from_dict(s)
                for s in prog)
        return cls(**kw)


# On-disk plan format. 1 = the PR 8 shape (no version stamp, phase vias
# xla|ring|bidir_ring); 2 adds the fused_matmul via + FusedCompute compute
# bindings and stamps ``format`` into the serialized plan; 3 adds the
# compiler vocabulary — ``chunks`` pipelining, the ``tree`` via, and
# ``all_to_all`` phases. Loading:
#   - no stamp (a stale PR 8 ``plan_<digest>.json``): version-skew-migrated —
#     every decision re-parses under the STRICT from_dict vocabulary, so a
#     file whose content doesn't actually match the v1 vocabulary fails the
#     load (cache miss -> re-tune) instead of resolving into an executor
#     that doesn't understand it;
#   - stamp > PLAN_FORMAT (a plan written by a newer build): rejected
#     outright — its decisions may carry semantics this executor can't run.
PLAN_FORMAT = 3


class Plan:
    """Site signature -> :class:`PlanDecision` for one mesh fingerprint."""

    def __init__(self, fingerprint: str = "",
                 decisions: Optional[Dict[str, PlanDecision]] = None):
        self.fingerprint = fingerprint
        self.decisions: Dict[str, PlanDecision] = dict(decisions or {})

    def get(self, site: CollectiveSite) -> Optional[PlanDecision]:
        return self.decisions.get(site.signature())

    def set(self, site: CollectiveSite, decision: PlanDecision) -> None:
        self.decisions[site.signature()] = decision

    def __len__(self) -> int:
        return len(self.decisions)

    def __eq__(self, other) -> bool:
        return (isinstance(other, Plan)
                and self.fingerprint == other.fingerprint
                and self.decisions == other.decisions)

    def to_dict(self) -> Dict[str, Any]:
        return {"format": PLAN_FORMAT,
                "fingerprint": self.fingerprint,
                "sites": {sig: d.to_dict()
                          for sig, d in sorted(self.decisions.items())}}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Plan":
        fmt = int(d.get("format", 1))  # unstamped = the PR 8 v1 shape
        if fmt > PLAN_FORMAT:
            raise ValueError(
                f"plan format {fmt} is newer than this build's "
                f"{PLAN_FORMAT}; refusing to load (its decisions may name "
                f"implementations this executor doesn't understand)")
        return cls(fingerprint=d.get("fingerprint", ""),
                   decisions={sig: PlanDecision.from_dict(dd)
                              for sig, dd in d.get("sites", {}).items()})

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=1, sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "Plan":
        return cls.from_dict(json.loads(s))
