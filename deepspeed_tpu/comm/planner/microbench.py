"""Microbenchmark harness: time one (site, implementation) pair for real.

``measure`` mode's ground truth. Each candidate becomes a tiny shard_map
program over the live mesh exercising the SAME primitive the wiring would
run (``lax`` native / ``ops.collective_matmul`` rings / ``comm.compressed``
int8 paths), on a probe tensor shaped from the site but capped at
``max_elems`` so tuning stays cheap. Chained through a ``lax.scan`` carry so
XLA cannot CSE the collective away, timed as min-over-reps after a compile
warmup (the ``bench.py`` convention).
"""

import time
from typing import Optional

import numpy as np

from .ir import CollectiveSite


def _probe_elems(site: CollectiveSite, p: int, max_elems: int) -> int:
    n = int(np.prod(site.shape)) if site.shape else 1
    n = min(n, int(max_elems))
    # the quantized paths pad to the 128-lane quantum per rank; the a2a /
    # scatter paths need divisibility by p — round up to a shared quantum
    quantum = 128 * p
    return max(quantum, -(-n // quantum) * quantum)


def _decode_attn_probe(site: CollectiveSite, impl: str, *, reps: int,
                       max_elems: int):
    """Single-device probe for the serving ``decode_attn`` site (a kernel
    choice, not a collective — no mesh axis, no shard_map): one fused-decode
    attention step at a capped version of the site's pool shape, the
    gathered-page einsum reference vs the Pallas paged flash-decode kernel
    (interpret mode off-TPU, so measure mode stays honest about what THIS
    host would actually run). ``site.dtype == int8`` probes the quantized
    (values, scales) pool form through both paths."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ...inference.v2.model import paged_attention as einsum_paged
    from ...ops.pallas.paged_attention import paged_flash_decode
    from ...ops.pallas.quant import quantize_rows

    S, slots, Hk, D = (tuple(site.shape) + (4, 64, 2, 32))[:4]
    S = max(1, min(int(S), 4))
    bs = 8 if slots < 128 else 128
    # cap the pool at max_elems total values
    slots = max(bs, min(int(slots), max(bs, int(max_elems) // (Hk * D))))
    B = -(-slots // bs)
    N = S * B + 1
    key = jax.random.PRNGKey(0)
    kp = jax.random.normal(key, (1, N, Hk, bs, D), jnp.float32)
    vp = jax.random.normal(jax.random.fold_in(key, 1),
                           (1, N, Hk, bs, D), jnp.float32)
    if site.dtype == "int8":
        kp, vp = quantize_rows(kp), quantize_rows(vp)
    bt = (1 + jnp.arange(S * B, dtype=jnp.int32)).reshape(S, B)
    kvl = jnp.full((S,), B * bs - bs // 2, jnp.int32)  # partial last page
    pos = kvl  # the decode query sits one past the pool
    q = jax.random.normal(jax.random.fold_in(key, 2), (S, Hk, D), jnp.float32)

    def one(qv):
        if impl == "pallas":
            return paged_flash_decode(qv, kp, vp, bt, pos, kvl)
        out = einsum_paged(qv[:, None], _layer(kp), _layer(vp), bt,
                           pos[:, None], jnp.ones((S, 1), bool), kvl)
        return out[:, 0]

    def _layer(pool):
        return (pool[0][0], pool[1][0]) if isinstance(pool, tuple) else pool[0]

    def loop(qv):
        def body(c, _):
            return one(c) * jnp.float32(0.5) + qv * jnp.float32(0.5), ()

        c, _ = lax.scan(body, qv, None, length=reps)
        return c.reshape(-1)[0]

    return jax.jit(loop), q


def build_probe(site: CollectiveSite, impl: str, *, mesh=None,
                block: Optional[int] = None, reps: int = 4,
                max_elems: int = 1 << 16, program=None):
    """(jitted_fn, probe_array): a compiled program running ``reps`` chained
    executions of ``impl`` for ``site`` on ``mesh``. The probe is fp32 and
    replicated (each rank holds the same flat vector — per-shard calling
    convention, like every ``comm.comm`` collective).

    ``impl == "program"`` probes a synthesized multi-phase plan-IR program
    (``program`` = tuple of ``ir.PhaseStep``) through the same executor the
    engine wiring runs (``comm.compressed.run_collective_program``), so
    measured mode validates synthesis against reality, not against the
    cost model's own assumptions. Error-feedback phases probe stateless
    (feedback=None → plain int8): the timing is identical and the probe
    carries no cross-step residual."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ...parallel.topology import get_topology
    from ...utils.shard_map_compat import shard_map_nocheck

    if site.op == "decode_attn":
        return _decode_attn_probe(site, impl, reps=reps, max_elems=max_elems)

    topo = get_topology()
    mesh = mesh or topo.mesh
    names = tuple(site.axes)
    if any(a not in mesh.shape for a in names):
        # foreign-mesh site (zeropp's own dp axis): probe on a fresh mesh
        # of the site's declared size over the leading devices
        from jax.sharding import Mesh

        p_want = site.axis_size
        devs = np.array(jax.devices())
        if len(names) != 1 or not p_want or p_want > devs.size:
            raise ValueError(
                f"cannot build a probe mesh for axes {names} "
                f"(axis_size={site.axis_size}, {devs.size} devices)")
        mesh = Mesh(devs[:p_want], (names[0],))
    axes = names if len(names) > 1 else names[0]
    p = 1
    for a in names:
        p *= int(mesh.shape[a])
    n = _probe_elems(site, p, max_elems)
    blk = min(block or 2048, max(128, n // p))
    blk = max(128, blk - blk % 128)
    x = jnp.linspace(-1.0, 1.0, n, dtype=jnp.float32)

    def one(v):
        if impl == "program":
            if not program:
                raise ValueError("impl='program' probe needs a program")
            from ..compressed import run_collective_program

            out, _ = run_collective_program(v, program)
            # close the carry shape: gather/scatter/a2a programs change the
            # payload width (all_reduce ones keep it) — fold back to n
            out = out.reshape(-1)
            if out.size == v.size:
                return out
            if out.size > v.size:
                return out[:v.size]
            return jnp.tile(out, -(-v.size // out.size))[:v.size]
        if site.op == "all_reduce":
            if impl == "xla":
                return lax.pmean(v, axes)
            if impl in ("int8", "int8_sr"):
                from ..compressed import quantized_all_reduce

                sr = impl == "int8_sr"
                return quantized_all_reduce(
                    v, axes, block=blk, stochastic=sr,
                    key=jax.random.PRNGKey(0) if sr else None)
            if impl == "hierarchical":
                from ..compressed import hierarchical_quantized_all_reduce

                return hierarchical_quantized_all_reduce(
                    v, names[-1], names[:-1], block=blk)
        elif site.op == "all_gather":
            if impl == "xla":
                full = lax.all_gather(v, axes, axis=0, tiled=True)
            elif impl in ("ring", "bidir_ring"):
                from ...ops.collective_matmul import ring_all_gather

                # chain one ring per axis so a multi-axis site moves the
                # SAME total bytes as the fused gather it competes against
                full = v
                for a in names:
                    full = ring_all_gather(full, a,
                                           bidirectional=impl == "bidir_ring")
            elif impl == "fused_matmul":
                # the compute-bound int8 chunk ring — the SAME primitive
                # the zeropp wiring runs when this impl wins
                from ...ops.collective_matmul import fused_ring_all_gather

                full = v
                for a in names:
                    full = fused_ring_all_gather(full, a, wire_dtype="int8",
                                                 block=blk, tag="probe")
            elif impl == "int8":
                from ..compressed import quantized_all_gather

                full = quantized_all_gather(v, axes, block=blk).reshape(-1)
            else:
                raise ValueError(impl)
            return full[:n]  # keep the carry shape closed
        elif site.op == "reduce_scatter":
            if impl == "xla":
                shard = lax.psum_scatter(v, axes, scatter_dimension=0,
                                         tiled=True)
            elif impl == "ring":
                from ...ops.collective_matmul import ring_reduce_scatter

                shard = v  # per-axis chain: same bytes as the fused scatter
                for a in names:
                    shard = ring_reduce_scatter(shard, a)
            elif impl == "fused_matmul":
                from ...ops.collective_matmul import fused_ring_reduce_scatter

                shard = v
                for a in names:
                    shard = fused_ring_reduce_scatter(shard, a,
                                                      wire_dtype="int8",
                                                      block=blk, tag="probe")
            elif impl in ("int8", "int8_sr"):
                from ..compressed import quantized_reduce_scatter

                sr = impl == "int8_sr"
                shard = quantized_reduce_scatter(
                    v, axes, block=blk, stochastic=sr,
                    key=jax.random.PRNGKey(0) if sr else None)
            else:
                raise ValueError(impl)
            return jnp.tile(shard, p)[:n]
        elif site.op == "all_to_all":
            vv = v.reshape(p, n // p)
            if impl == "xla":
                out = lax.all_to_all(vv, names[0], split_axis=0,
                                     concat_axis=0, tiled=True)
            elif impl == "int8":
                from ..compressed import quantized_all_to_all

                out = quantized_all_to_all(vv, names[0], split_dim=0,
                                           concat_dim=0, block=blk)
            else:
                raise ValueError(impl)
            return out.reshape(-1)
        elif site.op == "embed_gather":
            # the vocab-sharded embedding site: per-rank table shard of
            # n/(128*p) rows x 128 lanes, a fixed probe token set
            e = 128
            rows = max(8, n // (e * p))
            tab = v[:rows * e].reshape(rows, e)
            tok = (lax.iota(jnp.int32, 128) * 131) % (rows * p)
            if impl == "xla":
                full = lax.all_gather(tab, names[0], axis=0, tiled=True)
                out = jnp.take(full, tok, axis=0)
            elif impl in ("ring", "bidir_ring"):
                from ...ops.collective_matmul import ring_embedding_gather

                out = ring_embedding_gather(tok, tab, names[0],
                                            bidirectional=impl == "bidir_ring")
            else:
                raise ValueError(impl)
            return jnp.tile(out.reshape(-1), -(-n // out.size))[:n]
        elif site.op == "gather_matmul":
            # activation gather + projection, the TP-linear shape: the probe
            # matmul is deliberately small so the collective dominates on
            # xla and the overlap credit is what the fused path must earn
            k = 128
            m = max(1, n // (k * p))  # per-rank row chunk; m*k*p <= n
            xm = v[:m * k].reshape(m, k)
            w = jnp.eye(k, dtype=jnp.float32)
            if impl == "xla":
                full = lax.all_gather(xm, axes, axis=0, tiled=True)
                out = jnp.einsum("mk,kn->mn", full, w)
            elif impl == "fused_matmul":
                from ...ops.collective_matmul import all_gather_matmul

                out = all_gather_matmul(xm, w, names[0])
            else:
                raise ValueError(impl)
            return jnp.tile(out.reshape(-1), -(-n // out.size))[:n]
        raise ValueError(f"unsupported probe {site.op}/{impl}")

    def loop(v):
        def body(c, _):
            return one(c) * jnp.float32(0.5) + v * jnp.float32(0.5), ()

        c, _ = lax.scan(body, v, None, length=reps)
        return c[0]

    fn = jax.jit(shard_map_nocheck(loop, mesh, in_specs=P(), out_specs=P()))  # spec-ok: microbench probe: replicated shard_map wiring
    return fn, x


# --------------------------------------------------------------------------
# process-level probe memo: measure-mode tuning and the autotune sweeps
# resolve overlapping candidate sets (several planner instances in one
# process, the autotuner's program sweep, the bench rungs) — each distinct
# probe SIGNATURE compiles and times exactly once per process. Keyed by
# everything that changes the compiled probe or its timing; the live mesh
# rides in as its axis-size map so a set_topology() switch is a different
# signature, never a stale hit.
# --------------------------------------------------------------------------

_PROBE_MEMO: dict = {}
_PROBE_STATS = {"calls": 0, "built": 0, "hits": 0}


def _memo_key(site: CollectiveSite, impl: str, mesh, block, reps, repeats,
              max_elems, program):
    if mesh is None:
        try:
            from ...parallel.topology import get_topology

            mesh = get_topology().mesh
        except Exception:
            mesh = None
    mesh_key = (tuple(sorted(mesh.shape.items())) if mesh is not None
                else ())
    return (site.signature(), impl, mesh_key, block, int(reps), int(repeats),
            int(max_elems), tuple(program) if program else None)


def probe_stats() -> dict:
    """Counters for the process-level probe memo: ``calls`` (benchmark_site
    invocations), ``built`` (probes actually compiled+timed), ``hits``
    (answered from the memo). ``built`` is the cost that must shrink."""
    return dict(_PROBE_STATS)


def reset_probe_memo() -> None:
    _PROBE_MEMO.clear()
    _PROBE_STATS.update(calls=0, built=0, hits=0)


def benchmark_site(site: CollectiveSite, impl: str, *, mesh=None,
                   block: Optional[int] = None, reps: int = 4,
                   repeats: int = 3, max_elems: int = 1 << 16,
                   program=None, memo: bool = True) -> float:
    """Min-of-``repeats`` wall-clock seconds per single execution of
    ``impl`` at (a capped version of) ``site``. Compile excluded.

    ``memo=False`` bypasses the process-level memo both ways (no read, no
    write) — for callers that want a fresh wall-clock sample, e.g. drift
    re-checks."""
    _PROBE_STATS["calls"] += 1
    key = _memo_key(site, impl, mesh, block, reps, repeats, max_elems,
                    program) if memo else None
    if key is not None and key in _PROBE_MEMO:
        _PROBE_STATS["hits"] += 1
        return _PROBE_MEMO[key]
    fn, x = build_probe(site, impl, mesh=mesh, block=block, reps=reps,
                        max_elems=max_elems, program=program)
    float(fn(x))  # compile + drain
    best = float("inf")
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        float(fn(x))
        best = min(best, (time.perf_counter() - t0) / reps)
    _PROBE_STATS["built"] += 1
    if key is not None:
        _PROBE_MEMO[key] = best
    return best
