"""Collective-program compiler: generative synthesis + pruned beam search.

PR 8 synthesized exactly five hand-written program shapes (hierarchical
twins + fused variants, all_reduce sites only). This module is the GC3
move done properly (arxiv 2201.11840): programs are *compiled* from a
grammar — axis orderings and groupings, per-phase algorithm shape
(xla | ring | bidir_ring | tree recursive-halving | fused_matmul),
per-phase wire dtype (exact | int8 | int8_ef under the existing
gradient-consumer rule), per-phase chunked pipelining — and ranked by
``topo.CostModel.estimate_phase`` on one alpha-beta scale, with "The Big
Send-off"'s topology-aware shapes (arxiv 2504.18658) as the option pool.

The search is slot-wise pruned: for each program *structure* (an ordered
grouping of the site's axes into shell/core phases) every slot keeps its
top-k options by per-phase estimate, the capped cross-product is priced
whole, and the global top ``beam_width`` programs survive. Static mode
takes the argmin; measure mode times the beam through the real executor
(``microbench.benchmark_site``). Everything is deterministic: stable
enumeration order + stable sorts, so two fresh planners on the same
fingerprint compile the identical beam.

``SEARCH_SPACE`` versions the generator. It is folded into the on-disk
winner-cache identity (``cache.PlanCache``), so widening the grammar in a
later PR invalidates persisted winners (clean miss -> re-tune) instead of
silently replaying a plan searched over a narrower space.

Tree and chunked options are only generated for DCN-class phase links:
the tree's log2(p) rounds buy alpha on high-latency cross-slice hops
(the regime the ISSUE's 3-axis mesh exposes), and chunk pipelining hides
wire time that is only *exposed* at a slice boundary. ICI/host phases
keep the PR 8/14 option set, so all-ICI meshes resolve exactly as before.
"""

import itertools
from typing import List, Optional, Tuple

from .ir import (GRADIENT_CONSUMERS, CollectiveSite, FusedCompute, PhaseStep,
                 make_phase)
from .topo import CostModel

# Version of the generator grammar. Bump when the program space WIDENS
# (new vias, new wire dtypes, new structures): a cached winner searched
# over an older space may no longer be the argmin, so the plan cache keys
# files by this version and treats a mismatch as a miss.
SEARCH_SPACE = 1

# beam width the planner uses when the config leaves the default
DEFAULT_BEAM_WIDTH = 8
# per-slot option survivors before the cross-product (the prune that keeps
# the search linear-ish in structure count)
TOP_PER_SLOT = 2
# chunk-count options offered per xla phase (K=1 is the unchunked slot)
CHUNK_OPTIONS = (2, 4)
# don't chunk phases whose payload is too small to amortize K alphas
MIN_CHUNK_BYTES = 1 << 16

# (consumer, op) pairs whose wiring can EXECUTE a program decision
# (runtime/engine.py binds fused tiles + threads the feedback carry for
# the DP gradient reduction). Everything else still gets its programs
# compiled, priced and probed — but ``CollectivePlanner.resolve`` keeps
# the best single impl and records the search outcome, because handing
# a "program" decision to a wiring that dispatches on impl flags would
# silently degrade to the exact path.
PROGRAM_CAPABLE = (("dp-grad", "all_reduce"),)


def _is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def _link_for(fp, axes) -> str:
    if any(a in fp.dcn_axes for a in axes):
        return "dcn"
    if fp.platform == "tpu" or fp.dcn_axes:
        return "ici"
    return "host"


def _span(site: CollectiveSite, cost: CostModel, group) -> int:
    if site.axis_size is not None:
        return int(site.axis_size)
    return cost.fp.axis_size(tuple(group))


def _tree_ok(site: CollectiveSite, cost: CostModel, group) -> bool:
    """Tree phases need a power-of-two span on EVERY axis of the group
    (the butterfly partner is rank XOR 2^r, per axis)."""
    if site.axis_size is not None:
        return _is_pow2(int(site.axis_size))
    return all(_is_pow2(cost.fp.axis_size((a,)))
               for a in group)


def _ordered_set_partitions(axes: Tuple[str, ...], max_groups: int = 3):
    """All ordered partitions of ``axes`` into <= max_groups non-empty
    groups (group members keep the site's relative axis order). Order
    matters between groups — which axes scatter first is part of the
    program — and the enumeration order is deterministic."""
    axes = tuple(axes)
    if not axes:
        yield ()
        return
    n = len(axes)
    # choose a non-empty subset (as a bitmask, ascending) for the first
    # group, recurse on the remainder
    for mask in range(1, 1 << n):
        first = tuple(a for i, a in enumerate(axes) if mask >> i & 1)
        rest = tuple(a for i, a in enumerate(axes) if not mask >> i & 1)
        if not rest:
            yield (first,)
            continue
        if max_groups <= 1:
            continue
        for tail in _ordered_set_partitions(rest, max_groups - 1):
            yield (first,) + tail


def _compositions(axes: Tuple[str, ...], max_groups: int = 3):
    """Ordered partitions of ``axes`` into CONTIGUOUS segments, order
    preserved — the only groupings whose per-group collective chain
    reproduces the flat tiled placement for gather/scatter/exchange
    sites (an all_reduce's replicated result is placement-free, so it
    gets the full reordering space instead)."""
    axes = tuple(axes)
    n = len(axes)
    if n == 0:
        yield ()
        return
    for k in range(1, min(n, max_groups) + 1):
        for cuts in itertools.combinations(range(1, n), k - 1):
            bounds = (0,) + cuts + (n,)
            yield tuple(axes[bounds[i]:bounds[i + 1]] for i in range(k))


class _Options:
    """Per-slot option list builder (deterministic emission order)."""

    def __init__(self, site: CollectiveSite, cost: CostModel, block: int):
        self.site = site
        self.cost = cost
        self.block = block
        self.fp = cost.fp
        self.gradient = site.consumer in GRADIENT_CONSUMERS

    def _chunk_ks(self, n_in: float):
        return [k for k in CHUNK_OPTIONS if n_in >= MIN_CHUNK_BYTES * k]

    def _fast_link(self, group) -> Tuple[str, bool]:
        link = _link_for(self.fp, group)
        return link, link == "dcn"

    def rs_shell(self, group, n_in: float, fused_ok: bool) -> List[PhaseStep]:
        link, dcn = self._fast_link(group)
        opts = [make_phase("reduce_scatter", group, link=link)]
        if dcn:
            for k in self._chunk_ks(n_in):
                opts.append(make_phase("reduce_scatter", group, link=link,
                                       chunks=k))
            if _tree_ok(self.site, self.cost, group):
                opts.append(make_phase("reduce_scatter", group, via="tree",
                                       link=link))
        if fused_ok:
            opts.append(make_phase(
                "reduce_scatter", group, via="fused_matmul", link=link,
                compute=FusedCompute(role="producer",
                                     site=f"{self.site.consumer}/bwd")))
        if not self.gradient:
            opts.append(make_phase("reduce_scatter", group,
                                   wire_dtype="int8", block=self.block,
                                   link=link))
        return opts

    def ag_shell(self, group, n_in: float, fused_ok: bool) -> List[PhaseStep]:
        link, dcn = self._fast_link(group)
        opts = [make_phase("all_gather", group, link=link),
                make_phase("all_gather", group, via="bidir_ring", link=link)]
        if dcn:
            for k in self._chunk_ks(n_in):
                opts.append(make_phase("all_gather", group, link=link,
                                       chunks=k))
            if _tree_ok(self.site, self.cost, group):
                opts.append(make_phase("all_gather", group, via="tree",
                                       link=link))
        if fused_ok:
            opts.append(make_phase(
                "all_gather", group, via="fused_matmul", link=link,
                compute=FusedCompute(role="consumer",
                                     site=f"{self.site.consumer}/apply")))
        if not self.gradient:
            opts.append(make_phase("all_gather", group, wire_dtype="int8",
                                   block=self.block, link=link))
        return opts

    def ar_core(self, group, n_in: float) -> List[PhaseStep]:
        link, dcn = self._fast_link(group)
        opts = [make_phase("all_reduce", group, link=link)]
        if self.gradient:
            # the existing gradient-consumer rule: the quantized core hop
            # carries the error-feedback residual (full-tensor carry, so
            # xla-only and never chunked — IR validation)
            opts.append(make_phase("all_reduce", group,
                                   wire_dtype="int8_ef", block=self.block,
                                   link=link))
        else:
            opts.append(make_phase("all_reduce", group, wire_dtype="int8",
                                   block=self.block, link=link))
        if dcn:
            for k in self._chunk_ks(n_in):
                opts.append(make_phase("all_reduce", group, link=link,
                                       chunks=k))
                if not self.gradient:
                    opts.append(make_phase("all_reduce", group,
                                           wire_dtype="int8",
                                           block=self.block, link=link,
                                           chunks=k))
            if _tree_ok(self.site, self.cost, group):
                opts.append(make_phase("all_reduce", group, via="tree",
                                       link=link))
                if not self.gradient:
                    opts.append(make_phase("all_reduce", group, via="tree",
                                           wire_dtype="int8",
                                           block=self.block, link=link))
        return opts

    def gather(self, group, n_in: float) -> List[PhaseStep]:
        link, dcn = self._fast_link(group)
        opts = [make_phase("all_gather", group, link=link),
                make_phase("all_gather", group, via="ring", link=link),
                make_phase("all_gather", group, via="bidir_ring", link=link),
                make_phase("all_gather", group, wire_dtype="int8",
                           block=self.block, link=link)]
        if dcn:
            for k in self._chunk_ks(n_in):
                opts.append(make_phase("all_gather", group, link=link,
                                       chunks=k))
            if _tree_ok(self.site, self.cost, group):
                opts.append(make_phase("all_gather", group, via="tree",
                                       link=link))
                opts.append(make_phase("all_gather", group, via="tree",
                                       wire_dtype="int8", block=self.block,
                                       link=link))
        return opts

    def scatter(self, group, n_in: float) -> List[PhaseStep]:
        link, dcn = self._fast_link(group)
        wire = "int8_sr" if self.gradient else "int8"
        opts = [make_phase("reduce_scatter", group, link=link),
                make_phase("reduce_scatter", group, wire_dtype=wire,
                           block=self.block, link=link)]
        if dcn:
            for k in self._chunk_ks(n_in):
                opts.append(make_phase("reduce_scatter", group, link=link,
                                       chunks=k))
            if _tree_ok(self.site, self.cost, group):
                opts.append(make_phase("reduce_scatter", group, via="tree",
                                       link=link))
        return opts

    def exchange(self, group, n_in: float) -> List[PhaseStep]:
        link, dcn = self._fast_link(group)
        opts = [make_phase("all_to_all", group, link=link),
                make_phase("all_to_all", group, wire_dtype="int8",
                           block=self.block, link=link)]
        if dcn:
            for k in self._chunk_ks(n_in):
                opts.append(make_phase("all_to_all", group, link=link,
                                       chunks=k))
                opts.append(make_phase("all_to_all", group,
                                       wire_dtype="int8", block=self.block,
                                       link=link, chunks=k))
        return opts


def _structures(site: CollectiveSite):
    """The ordered-grouping skeletons for ``site``: a list of
    ``(kind, group)`` slot sequences (kinds: rs/ar/ag/a2a). A foreign-mesh
    site (explicit ``axis_size``) is one flat axis the fingerprint can't
    decompose — single-group structures only."""
    axes = tuple(site.axes)
    if site.axis_size is not None:
        parts_iter = [(axes,)] if axes else []
    elif site.op == "all_reduce":
        parts_iter = list(_ordered_set_partitions(axes))
    else:
        parts_iter = list(_compositions(axes))
    out = []
    if site.op == "all_reduce":
        for parts in parts_iter:
            shells, core = parts[:-1], parts[-1]
            slots = [("rs", g) for g in shells]
            slots.append(("ar", core))
            slots.extend(("ag", g) for g in reversed(shells))
            out.append(tuple(slots))
    elif site.op == "all_gather":
        for parts in parts_iter:
            # execution order: LAST placement group first (the per-group
            # chain that reproduces the flat tuple collective's tiled
            # placement — see run_collective_program's reversed chains)
            out.append(tuple(("ag", g) for g in reversed(parts)))
    elif site.op == "reduce_scatter":
        for parts in parts_iter:
            out.append(tuple(("rs", g) for g in parts))
    elif site.op == "all_to_all":
        # a2a placement does not decompose into per-group exchanges;
        # the program space is the single-phase option pool
        out.append((("a2a", axes),))
    return out


def _slot_options(kind: str, group, n_in: float, opts: "_Options",
                  fused_ok: bool, site_op: str) -> List[PhaseStep]:
    if site_op == "all_reduce":
        if kind == "rs":
            return opts.rs_shell(group, n_in, fused_ok)
        if kind == "ar":
            return opts.ar_core(group, n_in)
        return opts.ag_shell(group, n_in, fused_ok)
    if kind == "ag":
        return opts.gather(group, n_in)
    if kind == "rs":
        return opts.scatter(group, n_in)
    return opts.exchange(group, n_in)


def _is_flat_twin(program: Tuple[PhaseStep, ...]) -> bool:
    """A single-phase xla/unchunked program IS the flat single-impl menu
    entry — emitting it as a program would duplicate (and on ties shadow)
    the single-impl candidate the planner already prices."""
    if len(program) != 1:
        return False
    st = program[0]
    return st.via == "xla" and st.chunks == 1


def compile_programs(site: CollectiveSite, cost: CostModel, *,
                     block: int = 2048,
                     beam_width: int = DEFAULT_BEAM_WIDTH
                     ) -> List[Tuple[Tuple[PhaseStep, ...], float]]:
    """The searched program beam for ``site``: up to ``beam_width``
    ``(program, est_seconds)`` pairs, cost-ascending, deterministic.

    Covers any site op (all_reduce | all_gather | reduce_scatter |
    all_to_all), multi-axis AND foreign-axis (explicit ``axis_size``)
    spans. Slot-wise pruning: per structure, each slot keeps its
    ``TOP_PER_SLOT`` cheapest options by :meth:`CostModel.estimate_phase`;
    the cross-product is priced whole by ``estimate_program`` and the
    global top-``beam_width`` survives. The all-exact sequenced variant of
    each structure is always priced too (the parity/safety anchor), and
    PR 8's five legacy shapes are merged in verbatim so the old menu's
    winners can never be lost to slot pruning."""
    if site.op not in ("all_reduce", "all_gather", "reduce_scatter",
                      "all_to_all"):
        return []
    p_total = cost.axis_size_of(site)
    if p_total <= 1:
        return []
    if not any(a in cost.fp.dcn_axes for a in site.axes):
        # homogeneous links: a flat XLA collective is already
        # bandwidth-optimal and the decomposed phases only add launches —
        # same decline as the legacy menu's dcn_split gate. A foreign-mesh
        # site (zeropp's own ``dp``) qualifies when the operator marked its
        # axis via comm_planner.dcn_axes (that membership IS its link class)
        return []
    if site.op == "all_to_all" and site.axis_size is None:
        n_elems = 1
        for d in site.shape:
            n_elems *= int(d)
        if n_elems % p_total:
            return []  # uneven exchange: the wiring's xla fallback owns it
    fused_ok = (site.op == "all_reduce" and site.axis_size is None)
    opts = _Options(site, cost, block)
    seen = {}
    order = itertools.count()
    for slots in _structures(site):
        # payload walk (depends on structure only, never on options)
        n = float(site.nbytes)
        slot_opts: List[List[PhaseStep]] = []
        anchor: List[PhaseStep] = []
        ok = True
        for kind, group in slots:
            span = _span(site, cost, group)
            if span <= 1 and len(slots) > 1:
                ok = False  # degenerate group: same program exists without it
                break
            cands = _slot_options(kind, group, n, opts, fused_ok, site.op)
            ranked = sorted(
                ((cost.estimate_phase(site, st, n)[0], i, st)
                 for i, st in enumerate(cands)),
                key=lambda t: (t[0], t[1]))
            keep = [st for _, _, st in ranked[:TOP_PER_SLOT]]
            slot_opts.append(keep)
            anchor.append(cands[0])  # emission position 0 = exact xla
            if kind == "rs":
                n = n / span
            elif kind == "ag":
                n = n * span
        if not ok or not slot_opts:
            continue
        combos = [tuple(c) for c in itertools.product(*slot_opts)]
        combos.append(tuple(anchor))
        for prog in combos:
            if _is_flat_twin(prog) or prog in seen:
                continue
            est = cost.estimate_program(site, prog)
            if est != est or est == float("inf"):
                continue
            seen[prog] = (est, next(order))
    for prog in legacy_menu_programs(site, cost, block=block):
        prog = tuple(prog)
        if prog not in seen:
            est = cost.estimate_program(site, prog)
            if est != float("inf"):
                seen[prog] = (est, next(order))
    beam = sorted(seen.items(), key=lambda kv: (kv[1][0], kv[1][1]))
    return [(prog, est) for prog, (est, _) in beam[:max(1, int(beam_width))]]


def legacy_menu_programs(site: CollectiveSite, cost: CostModel,
                         block: int = 2048
                         ) -> List[Tuple[PhaseStep, ...]]:
    """PR 8/14's five hand-synthesized candidates, verbatim — kept both as
    the ``synthesize_programs`` compat shim's body and as a merge-in floor
    for :func:`compile_programs` (slot pruning can never lose the old
    menu's winners)."""
    if site.op != "all_reduce" or site.axis_size is not None:
        return []
    inner, outer = cost.dcn_split(site)
    if not inner or not outer:
        return []
    fp = cost.fp
    if fp.axis_size(inner) <= 1 or fp.axis_size(outer) <= 1:
        return []
    in_link = "ici" if (fp.platform == "tpu" or fp.dcn_axes) else "host"
    out_link = ("dcn" if any(a in fp.dcn_axes for a in outer) else in_link)
    wire = "int8_ef" if site.consumer in GRADIENT_CONSUMERS else "int8"
    rs = make_phase("reduce_scatter", inner, link=in_link)
    ag = make_phase("all_gather", inner, link=in_link)
    ag_bidir = make_phase("all_gather", inner, via="bidir_ring", link=in_link)
    ar_exact = make_phase("all_reduce", outer, link=out_link)
    ar_int8 = make_phase("all_reduce", outer, wire_dtype=wire, block=block,
                         link=out_link)
    rs_f = make_phase("reduce_scatter", inner, via="fused_matmul",
                      link=in_link,
                      compute=FusedCompute(role="producer",
                                           site=f"{site.consumer}/bwd"))
    ag_f = make_phase("all_gather", inner, via="fused_matmul", link=in_link,
                      compute=FusedCompute(role="consumer",
                                           site=f"{site.consumer}/apply"))
    return [
        (rs, ar_int8, ag),          # hierarchical-int8-outer (the DCN shape)
        (rs, ar_exact, ag),         # hierarchical-exact
        (rs, ar_int8, ag_bidir),    # bidir-ring gather variant
        (rs_f, ar_int8, ag_f),      # fused-hierarchical (the t3 shape)
        (rs_f, ar_exact, ag_f),     # fused-hierarchical, exact outer
    ]


def program_capable(site: CollectiveSite) -> bool:
    """Whether a wiring exists that can EXECUTE a program decision at this
    site (see :data:`PROGRAM_CAPABLE`)."""
    return (site.consumer, site.op) in PROGRAM_CAPABLE
