"""Compressed collectives: EQuARX-style quantized all-reduce / all-to-all.

PR 1 hid collective *latency* behind compute (``ops/collective_matmul.py``);
this module attacks the remaining cost — *volume*. EQuARX (arxiv 2506.17615)
shows XLA-native block-quantized all-reduce recovers most of the wire
bandwidth with negligible quality loss; "The Big Send-off" (arxiv 2504.18658)
argues the hops should be topology-aware. Built on the Pallas int8 block
quant kernels (``ops/pallas/quant.py``), the library provides:

* :func:`quantized_all_reduce` — two-stage mean all-reduce:
  reduce-scatter (int8 all-to-all + one-lane scales, dequant-accumulate)
  then requantize + int8 all-gather. ~``4/(1+1/W)``× fewer wire bytes than
  the fp32 psum it replaces. Optional error feedback at BOTH stages
  (compose with ``compression.onebit.ErrorFeedbackState``) carries the
  quantization residual into the next step.
* :func:`hierarchical_quantized_all_reduce` — two-level variant reusing the
  ``zeropp.hierarchical_all_gather`` axis split: the inner (ICI-local) mesh
  axis reduces EXACT, only the outer hops (DCN / cross-slice) quantize.
* :func:`quantized_all_to_all` — int8 payload + one-lane scales for even
  splits (the MoE EP dispatch/combine and Ulysses head exchanges);
  ``custom_vjp`` straight-through: backward is the EXACT transposed
  all-to-all, so training gradients stay unbiased.
* :func:`quantized_all_gather` / :func:`quantized_reduce_scatter` — the
  ZeRO++ qwZ/qgZ one-shots, unified here with on-wire ledger accounting.

Every call records ONE comms-ledger entry (``comm.log_compressed``) with the
LOGICAL payload (what the exact collective would have moved) and the on-wire
bytes (int8 payload + fp32 scale lanes), so ``comm.log_summary()`` shows the
compression ratio. Collectives lower through ``lax`` directly — no inner
``dist.*`` entries, no double counting.

Rounding: ``"int8"`` rounds to nearest; ``"int8_sr"`` adds stochastic
rounding (unbiased per element) on the GRADIENT paths — activation
exchanges (MoE/Ulysses) always round to nearest, where a per-call rng would
cost more than the bias it removes. All functions are called INSIDE
``shard_map`` on per-shard values, the ``comm.comm`` calling convention.
"""

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.pallas.quant import (BLOCK, dequantize_int8, quantize_int8,
                                shard_layout as _shard_layout)

Axis = Union[str, Sequence[str]]

__all__ = [
    "quantized_all_reduce", "hierarchical_quantized_all_reduce",
    "quantized_all_to_all", "quantized_all_gather", "quantized_reduce_scatter",
    "configure_compression", "compression_mode", "compression_block",
    "compression_hierarchical", "allreduce_feedback_init",
    "run_collective_program", "program_feedback_layout",
    "program_feedback_init", "bind_fused_tiles", "feedback_state",
    "store_feedback", "clear_feedback",
]

# ---------------------------------------------------------------------------
# Fleet-wide knob state (the set_overlap_enabled pattern): initialize() maps
# config.compressed_collectives onto this; model/runtime wiring reads it.
# ---------------------------------------------------------------------------

_SITES = ("dp_gradients", "zero_weights", "zero_gradients", "moe", "ulysses")
_STATE = {
    "mode": "none",              # none | int8 | int8_sr
    "block": BLOCK,
    "hierarchical": False,
    "sites": {s: True for s in _SITES},
}


def configure_compression(mode: str = "none", *, block: Optional[int] = None,
                          hierarchical: Optional[bool] = None,
                          sites: Optional[dict] = None) -> None:
    """Set the fleet-wide compression state (called by ``initialize()`` from
    ``config.compressed_collectives``). Declarative: each call specifies the
    WHOLE state — omitted fields return to their defaults (block 2048, flat,
    all sites on), so a previous call's toggles never leak forward."""
    if mode not in ("none", "int8", "int8_sr"):
        raise ValueError(f"compressed_collectives mode must be none|int8|"
                         f"int8_sr, got {mode!r}")
    _STATE["mode"] = mode
    _STATE["block"] = BLOCK if block is None else int(block)
    _STATE["hierarchical"] = bool(hierarchical) if hierarchical is not None else False
    _STATE["sites"] = {s: True for s in _SITES}
    if sites:
        for k, v in sites.items():
            if k not in _STATE["sites"]:
                raise ValueError(f"unknown compressed-collective site {k!r}; "
                                 f"known: {_SITES}")
            _STATE["sites"][k] = bool(v)


def compression_mode(site: Optional[str] = None) -> str:
    """The active mode, or ``"none"`` when ``site`` is toggled off."""
    mode = _STATE["mode"]
    if mode == "none" or site is None:
        return mode
    return mode if _STATE["sites"].get(site, False) else "none"


def compression_block() -> int:
    return _STATE["block"]


def compression_hierarchical() -> bool:
    return _STATE["hierarchical"]


# ---------------------------------------------------------------------------
# internals
# ---------------------------------------------------------------------------


def _axis_size(axis: Axis) -> int:
    from .comm import _axis_tuple, get_axis_size

    return get_axis_size(_axis_tuple(axis))


def _nbytes(x) -> int:
    from .comm import _nbytes as nbytes

    return nbytes(x)


def _log(op: str, logical: int, wire: int,
         link: Optional[str] = None, axes=None,
         impl: Optional[str] = None) -> None:
    from .comm import _axis_tuple, log_compressed

    log_compressed(op, logical, wire, link=link,
                   axes=_axis_tuple(axes) if axes is not None else None,
                   impl=impl)


def _quantize_parts(parts, block, stochastic, key):
    """[world, shard_p] -> int8 [world, nb_per, block] + scales
    [world, nb_per, 1] (one lane on the wire)."""
    world, shard_p = parts.shape
    q, s, _ = quantize_int8(parts, block, stochastic=stochastic, key=key)
    nb_per = q.shape[0] // world
    return q.reshape(world, nb_per, block), s[:, :1].reshape(world, nb_per, 1)


def _dequantize_parts(q, s1):
    """Inverse of :func:`_quantize_parts`: -> fp32 [world, shard_p]."""
    world, nb_per, block = q.shape
    deq = dequantize_int8(q.reshape(world * nb_per, block),
                          s1.reshape(world * nb_per, 1),
                          (world * nb_per * block,))
    return deq.reshape(world, nb_per * block)


# ---------------------------------------------------------------------------
# quantized all-reduce (two-stage RS + AG, EQuARX pattern)
# ---------------------------------------------------------------------------


def allreduce_feedback_init(shape, world: int):
    """Zero ``ErrorFeedbackState`` for :func:`quantized_all_reduce` over a
    leaf of ``shape`` on a ``world``-rank axis: ``worker_error`` matches the
    input, ``server_error`` is this rank's stage-2 shard."""
    from ..compression.onebit import ErrorFeedbackState

    n = int(np.prod(shape)) if shape else 1
    shard = -(-n // world)
    return ErrorFeedbackState(worker_error=jnp.zeros(shape, jnp.float32),
                              server_error=jnp.zeros((shard,), jnp.float32))


def quantized_all_reduce(x, axis: Axis, *, block: Optional[int] = None,
                         stochastic: bool = False, key=None,
                         feedback=None, link: Optional[str] = None):
    """Mean all-reduce over ``axis`` with int8 payloads on every hop.

    Two stages (the EQuARX decomposition):

    1. *reduce-scatter*: each rank block-quantizes its full tensor, the int8
       shards + one-lane scales ride an all-to-all, each rank dequantizes
       and averages its shard (the accumulate stays fp32 — only transport
       quantizes).
    2. *all-gather*: the fp32 mean shard REQUANTIZES and the int8 shards +
       scales all-gather back to the full tensor.

    ``stochastic=True`` (needs ``key``) dithers both quantizations so the
    compression is unbiased per element. ``feedback`` (an
    ``onebit.ErrorFeedbackState`` from :func:`allreduce_feedback_init`)
    carries the residual of BOTH stages into the next call — pass it to get
    ``(out, new_feedback)`` instead of ``out``. Returns fp32 in ``x``'s
    shape; works for any size (tails pad to the 128-lane quantum and pad
    lanes quantize to exact zeros).
    """
    block = compression_block() if block is None else block
    world = _axis_size(axis)
    shape = x.shape
    n = int(np.prod(shape)) if shape else 1
    if world == 1:
        out = x.astype(jnp.float32)
        return (out, feedback) if feedback is not None else out
    shard, shard_p, b1 = _shard_layout(n, world, block)
    k1 = k2 = None
    if stochastic:
        if key is None:
            raise ValueError("stochastic quantized_all_reduce needs a key")
        # decorrelate the dither streams across ranks: a shared key would
        # give every rank the same rounding thresholds, so per-element
        # errors would add coherently instead of averaging ~1/W away
        names = (axis,) if isinstance(axis, str) else tuple(axis)
        key = jax.random.fold_in(key, lax.axis_index(names))
        k1, k2 = jax.random.split(key)

    comp = x.astype(jnp.float32).reshape(-1)
    if feedback is not None:
        comp = comp + feedback.worker_error.reshape(-1)
    parts = jnp.pad(comp, (0, world * shard - n))
    parts = jnp.pad(parts.reshape(world, shard), ((0, 0), (0, shard_p - shard)))

    # stage 1: quantize once, exchange shards, dequant + mean
    q, s1 = _quantize_parts(parts, b1, stochastic, k1)
    new_worker = None
    if feedback is not None:
        # residual vs what the receivers decode of THIS rank's contribution
        decoded = _dequantize_parts(q, s1)[:, :shard].reshape(-1)[:n]
        new_worker = (comp[:n] - decoded).reshape(shape)
    qt = lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=False)
    st = lax.all_to_all(s1, axis, split_axis=0, concat_axis=0, tiled=False)
    shard_mean = jnp.mean(_dequantize_parts(qt, st)[:, :shard], axis=0)

    # stage 2: requantize the mean shard, gather it back
    s_comp = shard_mean
    if feedback is not None:
        s_comp = s_comp + feedback.server_error
    # _shard_layout guarantees shard_p % b1 == 0, so stage 2 reuses b1
    q2, s2, _ = quantize_int8(jnp.pad(s_comp, (0, shard_p - shard)), b1,
                              stochastic=stochastic, key=k2)
    new_server = None
    if feedback is not None:
        dec2 = dequantize_int8(q2, s2, (shard_p,))[:shard]
        new_server = s_comp - dec2
    qg = lax.all_gather(q2, axis, axis=0, tiled=False)        # [W, nb2, b1]
    sg = lax.all_gather(s2[:, :1], axis, axis=0, tiled=False)  # [W, nb2, 1]
    full = _dequantize_parts(qg, sg)[:, :shard].reshape(-1)[:n]
    out = full.reshape(shape)

    nb1 = world * (shard_p // b1)
    nb2 = shard_p // b1
    wire = (world * shard_p + 4 * nb1) + (shard_p + 4 * nb2)
    _log("quantized_all_reduce", _nbytes(x), wire, link, axes=axis,
         impl=("int8_ef" if feedback is not None
               else "int8_sr" if stochastic else "int8"))
    if feedback is not None:
        return out, type(feedback)(worker_error=new_worker,
                                   server_error=new_server)
    return out


def hierarchical_quantized_all_reduce(x, inner_axis: Axis, outer_axis: Axis,
                                      **kwargs):
    """Two-level mean all-reduce (the Big-Send-off shape, reusing
    ``zeropp.hierarchical_all_gather``'s axis split): the INNER mesh axis —
    the ICI-local hop, where bandwidth is cheap — reduces EXACT; only the
    outer hops (cross-slice / DCN) carry quantized payloads. Error model:
    one quantization round-trip regardless of inner axis size.

    Note the inner hop here is a full-width all-reduce — every rank moves
    the WHOLE tensor twice over ICI before the outer hop sees it. The
    planner-synthesized program form (:func:`run_collective_program`) is
    strictly better when the mesh distinguishes DCN axes: exact
    reduce-scatter over ICI shrinks the DCN payload by the inner span
    before the quantized outer hop, and an all-gather restores it after."""
    from . import comm as dist

    inner_mean = dist.all_reduce(x, inner_axis, op="mean")
    return quantized_all_reduce(inner_mean, outer_axis, **kwargs)


# ---------------------------------------------------------------------------
# multi-phase collective programs (comm/planner plan-IR execution)
# ---------------------------------------------------------------------------


def _phase_sizes(n: int, phase_op: str, p: int) -> tuple:
    """(padded_in, out_len) for one phase on an ``n``-element payload over a
    ``p``-rank span. Reduce-scatter pads to the ``p * 128`` quantum so every
    downstream shard stays 128-lane aligned for the quantized hops."""
    if phase_op == "reduce_scatter":
        quantum = p * 128
        n_p = -(-n // quantum) * quantum
        return n_p, n_p // p
    if phase_op == "all_gather":
        return n, n * p
    return n, n  # all_reduce / all_to_all keep the payload width


def _is_pow2(p: int) -> bool:
    return p > 0 and (p & (p - 1)) == 0


def _quantize_wire(v, block, stochastic, key):
    """flat fp32 -> (int8 [nb, block], one-lane scales [nb, 1], wire bytes)
    — the per-round tree-exchange wire format (int8 payload + one fp32
    scale lane per block, the :func:`_quantize_parts` convention)."""
    q, s, _ = quantize_int8(v, block, stochastic=stochastic, key=key)
    nb = int(q.shape[0])
    return q, s[:, :1], nb * block + 4 * nb


def _butterfly_perm(p: int, bit: int):
    return [(i, i ^ bit) for i in range(p)]


def _tree_key(key, axis_name, r):
    """Per-(rank, round) dither stream for stochastic tree rounds — the
    :func:`quantized_all_reduce` decorrelation rule, folded per round so
    re-quantizations don't reuse thresholds."""
    if key is None:
        return None
    return jax.random.fold_in(jax.random.fold_in(key, r),
                              lax.axis_index((axis_name,)))


def _tree_all_reduce_axis(v, axis_name: str, *, wire_dtype: str, block: int,
                          key):
    """Recursive-doubling all-SUM over one power-of-two axis: log2(p)
    full-vector pairwise-exchange rounds (partner = rank XOR 2^r) instead
    of the ring's 2(p-1) hops — the alpha-dominated DCN shape. The exact
    wire is a butterfly summation tree: deterministic, but a different
    association than the fused XLA collective, so parity vs ``lax.psum``
    is allclose-not-bitwise. Quantized wires re-quantize the running sum
    each round (log2(p) quantization stages). Returns ``(sum, wire_bytes)``
    — the caller owns the mean division."""
    p = _axis_size((axis_name,))
    if p <= 1:
        return v, 0
    if not _is_pow2(p):
        raise ValueError(f"via='tree' needs a power-of-two span on "
                         f"{axis_name!r}, got {p}")
    n = int(v.shape[0])
    quant = wire_dtype in ("int8", "int8_sr")
    sr = wire_dtype == "int8_sr" and key is not None
    wire = 0
    bit, r = 1, 0
    while bit < p:
        perm = _butterfly_perm(p, bit)
        if quant:
            q, s1, w = _quantize_wire(v, block, sr,
                                      _tree_key(key, axis_name, r) if sr
                                      else None)
            qt = lax.ppermute(q, axis_name, perm)
            st = lax.ppermute(s1, axis_name, perm)
            v = v + dequantize_int8(qt, st, (n,))
            wire += w
        else:
            v = v + lax.ppermute(v, axis_name, perm)
            wire += 4 * n
        bit <<= 1
        r += 1
    return v, wire


def _tree_reduce_scatter_axis(v, axis_name: str, *, wire_dtype: str,
                              block: int, key):
    """Recursive-halving reduce-SUM-scatter over one power-of-two axis:
    each of the log2(p) rounds keeps the half of the running segment this
    rank's index bit owns and exchanges the other half with the partner
    (total bytes = the ring's n(p-1)/p, in log2(p) alphas). Rank placement
    matches ``lax.psum_scatter(tiled=True)`` — segment i lands on rank i —
    with a butterfly association (allclose parity). ``len(v)`` must be
    divisible by p (the caller's ``_phase_sizes`` padding guarantees it).
    Returns ``(sum_shard, wire_bytes)``."""
    p = _axis_size((axis_name,))
    if p <= 1:
        return v, 0
    if not _is_pow2(p):
        raise ValueError(f"via='tree' needs a power-of-two span on "
                         f"{axis_name!r}, got {p}")
    idx = lax.axis_index((axis_name,))
    quant = wire_dtype in ("int8", "int8_sr")
    sr = wire_dtype == "int8_sr" and key is not None
    wire = 0
    half, r = p, 0
    while half > 1:
        half //= 2
        seg = v.reshape(2, -1)
        m = int(seg.shape[1])
        bit = (idx // half) % 2
        mine = jnp.take(seg, bit, axis=0)
        send = jnp.take(seg, 1 - bit, axis=0)
        perm = _butterfly_perm(p, half)
        if quant:
            q, s1, w = _quantize_wire(send, block, sr,
                                      _tree_key(key, axis_name, r) if sr
                                      else None)
            qt = lax.ppermute(q, axis_name, perm)
            st = lax.ppermute(s1, axis_name, perm)
            v = mine + dequantize_int8(qt, st, (m,))
            wire += w
        else:
            v = mine + lax.ppermute(send, axis_name, perm)
            wire += 4 * m
        r += 1
    return v, wire


def _tree_all_gather_axis(v, axis_name: str, *, wire_dtype: str, block: int,
                          key):
    """Recursive-doubling all-gather over one power-of-two axis: the shard
    doubles each round (log2(p) alphas, ring-equivalent n(p-1) bytes).
    Movement-only, so the exact wire is BITWISE-identical to
    ``lax.all_gather(tiled=True)``. Quantized wires re-quantize the grown
    piece each round. Returns ``(gathered, wire_bytes)``."""
    p = _axis_size((axis_name,))
    if p <= 1:
        return v, 0
    if not _is_pow2(p):
        raise ValueError(f"via='tree' needs a power-of-two span on "
                         f"{axis_name!r}, got {p}")
    idx = lax.axis_index((axis_name,))
    quant = wire_dtype in ("int8", "int8_sr")
    sr = wire_dtype == "int8_sr" and key is not None
    wire = 0
    bit, r = 1, 0
    while bit < p:
        perm = _butterfly_perm(p, bit)
        n = int(v.shape[0])
        if quant:
            q, s1, w = _quantize_wire(v, block, sr,
                                      _tree_key(key, axis_name, r) if sr
                                      else None)
            qt = lax.ppermute(q, axis_name, perm)
            st = lax.ppermute(s1, axis_name, perm)
            other = dequantize_int8(qt, st, (n,))
            wire += w
        else:
            other = lax.ppermute(v, axis_name, perm)
            wire += 4 * n
        own_bit = (idx // bit) % 2
        v = jnp.where(own_bit == 0,
                      jnp.concatenate([v, other]),
                      jnp.concatenate([other, v]))
        bit <<= 1
        r += 1
    return v, wire


def _chunk_bounds(m: int, k: int):
    """K roughly-equal contiguous [lo, hi) pieces of an m-element span."""
    k = max(1, min(int(k), m)) if m else 1
    step = -(-m // k)
    return [(lo, min(lo + step, m)) for lo in range(0, m, step)]


def program_feedback_layout(n: int, program, axis_sizes) -> Optional[tuple]:
    """``(worker_shape, server_shape)`` of the ``ErrorFeedbackState`` the
    program's ``int8_ef`` phase carries for a flat ``n``-element input, or
    ``None`` when no phase uses error feedback. ``axis_sizes`` maps axis
    name -> size (host-side mesh facts — the engine calls this at compile
    time to allocate the cross-step residual buffers). Mirrors
    :func:`run_collective_program`'s padding exactly; a drifting copy of
    this arithmetic would silently zero the residual every step."""
    cur = int(n)
    for st in program:
        p = 1
        for a in st.axes:
            p *= int(axis_sizes.get(a, 1) if hasattr(axis_sizes, "get")
                     else axis_sizes(a))
        if p <= 1:
            continue
        if st.phase_op == "all_reduce" and st.wire_dtype == "int8_ef":
            return ((cur,), (-(-cur // p),))
        cur = _phase_sizes(cur, st.phase_op, p)[1]
    return None


def program_feedback_init(n: int, program, axis_sizes):
    """Zero ``ErrorFeedbackState`` matching :func:`program_feedback_layout`
    (``None`` for a feedback-free program)."""
    from ..compression.onebit import ErrorFeedbackState

    layout = program_feedback_layout(n, program, axis_sizes)
    if layout is None:
        return None
    w, s = layout
    return ErrorFeedbackState(worker_error=jnp.zeros(w, jnp.float32),
                              server_error=jnp.zeros(s, jnp.float32))


def bind_fused_tiles(program, n: int, axis_sizes):
    """Stamp each fused phase's ``FusedCompute.tile`` with the ACTUAL
    per-hop chunk element count for a flat ``n``-element payload — the
    planner synthesizes fused phases with ``tile=0`` (the site's flat size
    is known but the phase algebra's intermediate widths are this
    function's job), and the engine binds them at compile time so the
    flight ring's per-hop ``detail`` and the doctor's divergence report
    name real chunk sizes. Walks the same ``_phase_sizes`` arithmetic as
    :func:`run_collective_program`; non-fused phases pass through
    untouched, so a fused-free program binds to itself."""
    import dataclasses

    out = []
    cur = int(n)
    for st in program:
        p = 1
        for a in st.axes:
            p *= int(axis_sizes.get(a, 1) if hasattr(axis_sizes, "get")
                     else axis_sizes(a))
        if p <= 1:
            out.append(st)
            continue
        n_p, out_len = _phase_sizes(cur, st.phase_op, p)
        if getattr(st, "fused", False) and st.compute is not None:
            # the circulating chunk: the output shard for a reduce-scatter
            # ring, the input shard for an all-gather ring
            tile = out_len if st.phase_op == "reduce_scatter" else cur
            st = dataclasses.replace(
                st, compute=dataclasses.replace(st.compute, tile=int(tile)))
        out.append(st)
        cur = out_len
    return tuple(out)


def run_collective_program(x, program, *, feedback=None, key=None):
    """Execute a planner-synthesized multi-phase MEAN all-reduce program on
    a per-shard tensor (called inside ``shard_map``, the ``comm.comm``
    calling convention).

    ``program`` is an ordered tuple of ``planner.ir.PhaseStep``; the
    canonical shape is the DCN-aware hierarchy — exact reduce-scatter over
    the ICI (slice-local) axes, int8(+error-feedback) all-reduce over the
    DCN axis on the 1/p_inner-sized shard, all-gather back over ICI — but
    any composition whose phase algebra nets out to a full mean reduction
    runs. Phases with ``via="fused_matmul"`` dispatch through the
    compute-bound chunk rings (``ops/collective_matmul.py``
    ``fused_ring_reduce_scatter`` / ``fused_ring_all_gather``): their
    ppermute hops ride between the bound matmul's tile steps instead of
    running as exposed transport, with an optional int8 wire dtype per
    hop. Each phase logs its own comms-ledger entry tagged with the
    phase's ``link`` class, so ``hop_totals()`` reports ICI- vs DCN-class
    wire bytes separately (fused phases additionally land in the hidden
    bucket — ``hop_exposure()``).

    ``feedback`` (an ``ErrorFeedbackState`` shaped by
    :func:`program_feedback_init`) feeds the ``int8_ef`` phase; pass
    ``None`` to run that phase as plain int8 (microbench probes, degraded
    mode). Returns ``(out, new_feedback)`` — ``new_feedback`` is ``None``
    unless feedback was both requested by the program and supplied.
    """
    shape = x.shape
    n0 = int(np.prod(shape)) if shape else 1
    cur = x.astype(jnp.float32).reshape(-1)
    new_fb = None
    logical = n0  # the phase-algebra output length (rs shrinks, ag grows)
    # net scatter/gather balance, tracked exactly: a balanced program (an
    # all-reduce site's shell mirror) must restore the caller's width even
    # when a ragged payload ceil-pads through the scatter levels (1111 ->
    # rs(2) 556 -> ag(2) 1112 would otherwise misread as a gather site)
    net_num = net_den = 1
    for st in program:
        names = tuple(st.axes)
        p = _axis_size(names)
        if p <= 1:
            continue
        n = int(cur.shape[0])
        sr = st.wire_dtype == "int8_sr"
        fused = getattr(st, "via", "xla") == "fused_matmul"
        tree = getattr(st, "via", "xla") == "tree"
        chunks = int(getattr(st, "chunks", 1) or 1)
        ftag = (st.compute.tag() if fused and st.compute is not None
                else "fused")
        fblk = st.block or compression_block()
        if st.phase_op == "reduce_scatter":
            logical = -(-logical // p)
            net_den *= p
            n_p, out_len = _phase_sizes(n, "reduce_scatter", p)
            padded = jnp.pad(cur, (0, n_p - n))
            if fused:
                # compute-bound chunk ring (per-axis chain, same bytes as
                # the fused scatter): the producing matmul's tiles hide
                # the hops; exact wire is bit-identical to the sequenced
                # ring reduction, int8 narrows each hop's payload
                from ..ops.collective_matmul import fused_ring_reduce_scatter

                shard = padded
                for a in names:
                    if _axis_size((a,)) <= 1:
                        continue
                    shard = fused_ring_reduce_scatter(
                        shard, a, wire_dtype=st.wire_dtype, block=fblk,
                        stochastic=sr, key=key, link=st.link, tag=ftag)
                cur = shard / p
            elif tree:
                # recursive halving, per-axis chain (first-to-last nests
                # segment placement identically to the flat tuple scatter)
                shard, wire = padded, 0
                for a in names:
                    shard, w = _tree_reduce_scatter_axis(
                        shard, a, wire_dtype=st.wire_dtype, block=fblk,
                        key=key)
                    wire += w
                cur = shard / p
                moved = 4 * n_p * (p - 1) // p
                _log("program_reduce_scatter", moved, wire, st.link,
                     axes=names, impl=f"tree:{st.wire_dtype}")
            elif st.wire_dtype == "exact":
                if chunks > 1:
                    # column pipelining: [p, cols] view, scatter each
                    # column piece — rank placement (and bits) identical
                    # to the flat scatter, but phase N+1 can start on
                    # piece 1 while piece 2 streams
                    cols = padded.reshape(p, n_p // p)
                    outs = [lax.psum_scatter(
                        cols[:, lo:hi].reshape(-1), names,
                        scatter_dimension=0, tiled=True)
                        for lo, hi in _chunk_bounds(n_p // p, chunks)]
                    cur = jnp.concatenate(outs) / p
                else:
                    cur = lax.psum_scatter(padded, names,
                                           scatter_dimension=0,
                                           tiled=True) / p
                moved = 4 * n_p * (p - 1) // p
                _log("program_reduce_scatter", moved, moved, st.link,
                     axes=names, impl="exact")
            elif chunks > 1:
                cols = padded.reshape(p, n_p // p)
                outs = [quantized_reduce_scatter(
                    cols[:, lo:hi].reshape(-1), names, block=st.block,
                    stochastic=sr, key=key, link=st.link)
                    for lo, hi in _chunk_bounds(n_p // p, chunks)]
                cur = jnp.concatenate(outs)
            else:
                cur = quantized_reduce_scatter(padded, names, block=st.block,
                                               stochastic=sr, key=key,
                                               link=st.link)
        elif st.phase_op == "all_reduce":
            if tree:
                total, wire = cur, 0
                for a in names:
                    total, w = _tree_all_reduce_axis(
                        total, a, wire_dtype=st.wire_dtype, block=fblk,
                        key=key)
                    wire += w
                cur = total / p
                moved = 2 * 4 * n * (p - 1) // p
                _log("program_all_reduce", moved, wire, st.link,
                     axes=names, impl=f"tree:{st.wire_dtype}")
            elif st.wire_dtype == "exact":
                if chunks > 1:
                    outs = [lax.pmean(cur[lo:hi], names)
                            for lo, hi in _chunk_bounds(n, chunks)]
                    cur = jnp.concatenate(outs)
                else:
                    cur = lax.pmean(cur, names)
                moved = 2 * 4 * n * (p - 1) // p
                _log("program_all_reduce", moved, moved, st.link,
                     axes=names, impl="exact")
            else:
                fb = feedback if st.wire_dtype == "int8_ef" else None
                if chunks > 1:  # int8_ef never chunks (IR validation)
                    outs = [quantized_all_reduce(cur[lo:hi], names,
                                                 block=st.block,
                                                 stochastic=sr, key=key,
                                                 link=st.link)
                            for lo, hi in _chunk_bounds(n, chunks)]
                    cur = jnp.concatenate(outs)
                else:
                    out = quantized_all_reduce(cur, names, block=st.block,
                                               stochastic=sr, key=key,
                                               feedback=fb, link=st.link)
                    if fb is not None:
                        cur, new_fb = out
                    else:
                        cur = out
        elif st.phase_op == "all_gather":
            logical = logical * p
            net_num *= p
            if fused:
                # compute-bound gather ring: the consuming matmul's tiles
                # hide the hops (data movement only — exact wire is
                # bitwise; int8 decodes rank-invariantly on arrival).
                # Last-axis-first chain: the tuple collective's tiled
                # placement (and the inverse of the rs chain's nesting)
                from ..ops.collective_matmul import fused_ring_all_gather

                for a in reversed(names):
                    if _axis_size((a,)) <= 1:
                        continue
                    cur = fused_ring_all_gather(
                        cur, a, wire_dtype=st.wire_dtype, block=fblk,
                        link=st.link, tag=ftag)
            elif tree:
                wire = 0
                for a in reversed(names):
                    cur, w = _tree_all_gather_axis(
                        cur, a, wire_dtype=st.wire_dtype, block=fblk,
                        key=key)
                    wire += w
                moved = 4 * n * (p - 1)
                _log("program_all_gather", moved, wire, st.link,
                     axes=names, impl=f"tree:{st.wire_dtype}")
            elif st.via in ("ring", "bidir_ring"):
                from ..ops.collective_matmul import ring_all_gather
                from .comm import get_comms_logger

                for a in reversed(names):  # per-axis chain, tuple placement
                    if st.link is not None:
                        # the ring logs its own chunked per-op ledger entry
                        # without hop awareness; bucket its wire bytes here
                        # so hop_totals() still sees this phase's traffic
                        pa = _axis_size((a,))
                        get_comms_logger().log_hop_bytes(
                            st.link, 4 * int(cur.shape[0]) * (pa - 1))
                    cur = ring_all_gather(cur, a,
                                          bidirectional=st.via == "bidir_ring")
            elif st.wire_dtype == "exact":
                if chunks > 1:
                    outs = [lax.all_gather(cur[lo:hi], names, axis=0,
                                           tiled=True).reshape(p, -1)
                            for lo, hi in _chunk_bounds(n, chunks)]
                    cur = jnp.concatenate(outs, axis=1).reshape(-1)
                else:
                    cur = lax.all_gather(cur, names, axis=0, tiled=True)
                moved = 4 * n * (p - 1)
                _log("program_all_gather", moved, moved, st.link,
                     axes=names, impl="exact")
            elif chunks > 1:
                outs = [quantized_all_gather(cur[lo:hi], names,
                                             block=st.block,
                                             link=st.link).reshape(p, -1)
                        for lo, hi in _chunk_bounds(n, chunks)]
                cur = jnp.concatenate(outs, axis=1).reshape(-1)
            else:
                cur = quantized_all_gather(cur, names, block=st.block,
                                           link=st.link).reshape(-1)
        elif st.phase_op == "all_to_all":
            if n % p:
                raise ValueError(
                    f"all_to_all phase needs a payload divisible by its "
                    f"span ({n} % {p}); the compiler gates on this")
            rows = cur.reshape(p, n // p)
            if st.wire_dtype == "exact":
                outs = [lax.all_to_all(rows[:, lo:hi].reshape(-1), names,
                                       split_axis=0, concat_axis=0,
                                       tiled=True).reshape(p, -1)
                        for lo, hi in _chunk_bounds(n // p, chunks)]
                cur = jnp.concatenate(outs, axis=1).reshape(-1)
                moved = 4 * n * (p - 1) // p
                _log("program_all_to_all", moved, moved, st.link,
                     axes=names, impl="exact")
            else:
                outs = [quantized_all_to_all(
                    rows[:, lo:hi], names, split_dim=0, concat_dim=0,
                    block=st.block, stochastic=sr, key=key)
                    for lo, hi in _chunk_bounds(n // p, chunks)]
                cur = jnp.concatenate(outs, axis=1).reshape(-1)
    if net_num == net_den:
        return cur[:n0].reshape(shape), new_fb
    # a gather/scatter/exchange-site program: the flat phase-algebra result
    # (callers at such sites pass flat payloads — the probe convention)
    return cur[:logical], new_fb


# ---------------------------------------------------------------------------
# keyed error-feedback registry
# ---------------------------------------------------------------------------
#
# allreduce_feedback_init builds a FRESH zero state — a call site that
# re-invokes it each step (or each retrace) silently resets the residual and
# the error-feedback carry never happens. Host-side callers that cannot
# thread the state through their own signatures (imperative loops, drill
# scripts) register it here under a stable key instead: the first fetch
# creates the zeros, every later fetch returns the LAST STORED state, and
# store_feedback() commits the post-reduction residual. (The engine's fused
# train step owns its residual explicitly — TrainState.comm_feedback — so
# it rides snapshots; the registry is for everything outside that loop.)

_FEEDBACK_REGISTRY: dict = {}


def feedback_state(name: str, shape=None, world: Optional[int] = None,
                   init=None):
    """The registered residual for ``name``, created on first use from
    ``init()`` (or :func:`allreduce_feedback_init`\\ ``(shape, world)``)."""
    if name not in _FEEDBACK_REGISTRY:
        if init is not None:
            _FEEDBACK_REGISTRY[name] = init()
        else:
            if shape is None or world is None:
                raise ValueError(
                    f"feedback_state({name!r}): first use needs shape+world "
                    "(or an init callable) to build the zero state")
            _FEEDBACK_REGISTRY[name] = allreduce_feedback_init(shape, world)
    return _FEEDBACK_REGISTRY[name]


def store_feedback(name: str, state) -> None:
    """Commit the post-reduction residual for ``name`` (the write half of
    the carry; the next :func:`feedback_state` fetch returns it)."""
    _FEEDBACK_REGISTRY[name] = state


def clear_feedback(name: Optional[str] = None) -> None:
    """Drop one registered residual (or all of them): degraded mode and
    rollback paths must not re-inject a residual from an abandoned
    trajectory."""
    if name is None:
        _FEEDBACK_REGISTRY.clear()
    else:
        _FEEDBACK_REGISTRY.pop(name, None)


# ---------------------------------------------------------------------------
# quantized all-to-all (MoE EP dispatch/combine, Ulysses head exchange)
# ---------------------------------------------------------------------------


def _qa2a_impl(x, axis: str, split_dim: int, concat_dim: int, block: int,
               stochastic: bool, key):
    world = _axis_size(axis)
    sd = x.shape[split_dim]
    if sd % world:
        raise ValueError(f"all_to_all split dim {split_dim} of {x.shape} not "
                         f"divisible by axis size {world}")
    xm = jnp.moveaxis(x, split_dim, 0)             # [sd, *rest]
    rest = xm.shape[1:]
    chunk = sd // world
    n_part = chunk * int(np.prod(rest)) if rest else chunk
    _, part_p, b = _shard_layout(n_part * world, world, block)
    parts = jnp.pad(xm.astype(jnp.float32).reshape(world, n_part),
                    ((0, 0), (0, part_p - n_part)))
    q, s1 = _quantize_parts(parts, b, stochastic, key)
    qt = lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=False)
    st = lax.all_to_all(s1, axis, split_axis=0, concat_axis=0, tiled=False)
    deq = _dequantize_parts(qt, st)[:, :n_part]
    blocks = deq.reshape((world, chunk) + rest)    # [W, sd/W, *rest]
    # restore each received block to the original dim order, concat in rank
    # order along concat_dim — exactly lax.all_to_all(tiled=True) semantics
    out = jnp.concatenate(
        [jnp.moveaxis(blocks[w], 0, split_dim) for w in range(world)],
        axis=concat_dim).astype(x.dtype)
    nb = world * (part_p // b)
    _log("quantized_all_to_all", _nbytes(x), world * part_p + 4 * nb,
         axes=axis, impl="int8_sr" if stochastic else "int8")
    return out


def quantized_all_to_all(x, axis: str, *, split_dim: int, concat_dim: int,
                         block: Optional[int] = None,
                         stochastic: bool = False, key=None):
    """``lax.all_to_all(tiled=True)`` with int8 payload + one-lane scales on
    the wire — the MoE expert exchange and Ulysses head/sequence exchange
    transport. Requires ``x.shape[split_dim] % world == 0`` (even splits;
    callers fall back to the exact collective otherwise).

    Differentiable by straight-through estimation: forward quantizes, the
    backward is the EXACT transposed all-to-all of the cotangent (int8
    rounding has no useful gradient; an exact reverse keeps the activation
    gradient unbiased and costs the bytes only in backward).
    """
    block = compression_block() if block is None else block
    world = _axis_size(axis)
    if world == 1:
        return lax.all_to_all(x, axis, split_axis=split_dim,
                              concat_axis=concat_dim, tiled=True)

    @jax.custom_vjp
    def qa2a(x):
        return _qa2a_impl(x, axis, split_dim, concat_dim, block, stochastic, key)

    def fwd(x):
        return qa2a(x), None

    def bwd(_, ct):
        return (lax.all_to_all(ct, axis, split_axis=concat_dim,
                               concat_axis=split_dim, tiled=True),)

    qa2a.defvjp(fwd, bwd)
    return qa2a(x)


# ---------------------------------------------------------------------------
# ZeRO++ one-shots (qwZ / qgZ), unified onto this library
# ---------------------------------------------------------------------------


def quantized_all_gather(x, axis: Axis, block: Optional[int] = None, *,
                         stochastic: bool = False, key=None,
                         link: Optional[str] = None):
    """qwZ int8 weight allgather: quantize the local shard once, gather int8
    payload + one-lane scales, dequantize on arrival. Returns
    ``[world, *x.shape]`` fp32. One ledger entry with on-wire bytes."""
    block = compression_block() if block is None else block
    n = int(np.prod(x.shape)) if x.shape else 1
    nb = -(-n // block)
    _log("quantized_all_gather", _nbytes(x), nb * block + 4 * nb, link,
         axes=axis, impl="int8_sr" if stochastic else "int8")
    from ..ops.pallas.quant import quantized_all_gather as _qag

    return _qag(x, axis, block, stochastic=stochastic, key=key)


def quantized_reduce_scatter(x, axis: Axis, block: Optional[int] = None, *,
                             stochastic: bool = False, key=None,
                             link: Optional[str] = None):
    """qgZ int8 gradient reduce-scatter (mean): quantize the full local
    grad, all-to-all the int8 shards, dequantize + average locally. Returns
    this rank's ``[ceil(n/world)]`` fp32 mean shard — arbitrary sizes pad to
    the block quantum (see ``ops/pallas/quant.py``). One ledger entry."""
    block = compression_block() if block is None else block
    world = _axis_size(axis)
    n = int(np.prod(x.shape)) if x.shape else 1
    _, shard_p, b = _shard_layout(n, world, block)
    nb = world * (shard_p // b)
    _log("quantized_reduce_scatter", _nbytes(x), world * shard_p + 4 * nb,
         link, axes=axis, impl="int8_sr" if stochastic else "int8")
    from ..ops.pallas.quant import quantized_reduce_scatter as _qrs

    return _qrs(x, axis, block, stochastic=stochastic, key=key)
