"""TCCL — the TPU collective communication layer.

TPU-native re-design of ``deepspeed.comm`` (reference ``comm/comm.py:222-520``)
and its ``TorchBackend``/NCCL stack. The "process group" concept is replaced by
**named mesh axes** (see ``parallel/topology.py``); collectives lower to XLA
collectives (``psum``/``all_gather``/``psum_scatter``/``all_to_all``/
``ppermute``) that ride ICI within a slice and DCN across slices — XLA picks
the routing, we pick the axes.

Two usage contexts:

* **Functional (hot path)** — inside ``jit``/``shard_map``: ``all_reduce(x,
  axis='dp')`` etc. These are traced once; the comms ledger records them at
  trace time with exact message sizes (shapes are static under XLA).
* **Host control-plane** — ``init_distributed()``, ``barrier()``,
  ``broadcast_host_data()``: multi-process bootstrap via ``jax.distributed``
  (the analogue of the reference's env/MPI rendezvous, ``comm.py:619,688``).
"""

import os
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..telemetry.collective import record_launch
from ..utils.comms_logging import CommsLogger, timed_op
from ..utils.logging import logger

Axis = Union[str, Sequence[str]]

_INITIALIZED = False
_COMMS_LOGGER = CommsLogger()


def get_comms_logger() -> CommsLogger:
    return _COMMS_LOGGER


def configure(comms_logger=None, **kwargs):
    """Reference ``dist.configure`` (``comm/comm.py``): enable comms logging."""
    if comms_logger is not None:
        _COMMS_LOGGER.configure(enabled=comms_logger.enabled, verbose=comms_logger.verbose,
                                prof_all=comms_logger.prof_all, prof_ops=comms_logger.prof_ops,
                                debug=comms_logger.debug)
    if kwargs:
        _COMMS_LOGGER.configure(**kwargs)


def log_summary(show_straggler: bool = False):
    return _COMMS_LOGGER.log_summary(world_size=get_world_size(), show_straggler=show_straggler)


# ---------------------------------------------------------------------------
# Bootstrap / host control-plane
# ---------------------------------------------------------------------------


def init_distributed(dist_backend: str = "tccl",
                     auto_mpi_discovery: bool = True,
                     timeout: Optional[float] = None,
                     init_method: Optional[str] = None,
                     rank: int = -1,
                     world_size: int = -1) -> None:
    """Bootstrap multi-host JAX (reference ``init_distributed``, ``comm.py:619``).

    Single-process (including single-host multi-chip) needs no rendezvous.
    Multi-host reads the coordinator from args or env (``DSTPU_COORDINATOR`` /
    launcher-set vars), mirroring the reference's env-rendezvous at
    MASTER_ADDR, and falls back to OpenMPI env discovery like
    ``mpi_discovery`` (``comm.py:688``).
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    coordinator = init_method or os.environ.get("DSTPU_COORDINATOR")
    nprocs = world_size if world_size > 0 else int(os.environ.get("DSTPU_NUM_PROCESSES", "1"))
    proc_id = rank if rank >= 0 else int(os.environ.get("DSTPU_PROCESS_ID", "0"))
    if auto_mpi_discovery and nprocs == 1 and "OMPI_COMM_WORLD_SIZE" in os.environ:
        proc_id, nprocs = mpi_discovery()
        logger.info(f"MPI discovery: process {proc_id}/{nprocs}")
    if nprocs > 1:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=nprocs, process_id=proc_id)
        logger.info(f"jax.distributed initialized: process {jax.process_index()} of "
                    f"{jax.process_count()}, {jax.local_device_count()} local devices")
    _INITIALIZED = True


def is_initialized() -> bool:
    return _INITIALIZED


def get_world_size(axis: Optional[Axis] = None) -> int:
    """Device-level world size (reference rank==GPU; here rank==chip), or the
    size of a mesh-axis 'group' when ``axis`` is given."""
    if axis is None:
        return jax.device_count()
    from ..parallel.topology import get_topology

    names = (axis,) if isinstance(axis, str) else tuple(axis)
    return get_topology().axis_size(*names)


def get_rank() -> int:
    """Host process index (control-plane rank)."""
    return jax.process_index()


def get_local_rank() -> int:
    return 0  # one process drives all local chips under JAX


def barrier(name: str = "barrier"):
    # eager host collective: recorded with its NAME — two ranks both "at a
    # barrier" may be at different barriers, which is exactly a desync
    record_launch("barrier", eager=True, detail=name)
    with timed_op(_COMMS_LOGGER, "barrier", 0):
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(name)
        else:
            jax.effects_barrier()


def broadcast_host_data(data: Any, src: int = 0) -> Any:
    """Broadcast a host-side pytree from process ``src`` to all processes
    (reference object broadcast). No-op in single-process mode."""
    if jax.process_count() == 1:
        return data
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(data, is_source=jax.process_index() == src)


# ---------------------------------------------------------------------------
# Functional collectives (inside jit / shard_map)
# ---------------------------------------------------------------------------


def _nbytes(x) -> int:
    return int(np.prod(x.shape)) * jnp.dtype(x.dtype).itemsize if hasattr(x, "shape") else 0


def _axis_tuple(axis: Axis) -> Tuple[str, ...]:
    return (axis,) if isinstance(axis, str) else tuple(axis)


def _log_traced(op: str, x, axes: Optional[Sequence[str]] = None) -> None:
    _COMMS_LOGGER.append(op, _nbytes(x), traced=True)
    # collective flight recorder (telemetry/collective.py): one launch
    # record at trace time — shape/dtype are exact under XLA, and the
    # doctor aligns the per-rank streams by the seq this stamps
    record_launch(op, shape=getattr(x, "shape", ()),
                  dtype=getattr(x, "dtype", None), axes=axes)


def log_chunked(op: str, nbytes: int, wire_bytes: Optional[int] = None,
                axes: Optional[Sequence[str]] = None) -> None:
    """Trace-time ledger entry for ring-chunked collectives
    (``ops/collective_matmul.py``): the chunk hops of one ring pass are
    recorded as a single entry covering the full ``(p-1)/p`` wire traffic,
    so ledger totals match what a fused collective would have reported."""
    _COMMS_LOGGER.append(op, int(nbytes), traced=True, wire_bytes=wire_bytes)
    record_launch(op, shape=(int(nbytes),), axes=axes, impl="ring")


def log_local(op: str, nbytes: int) -> None:
    """Trace-time ledger entry for LOCAL (HBM-side) traffic an
    implementation choice implies — e.g. the paged-decode pool bytes
    (``inference/v2/model.py``: the einsum path's materialized gather copy
    vs the Pallas kernel's in-place page reads). No collective launches, so
    nothing is recorded in the collective flight ring: the doctor's
    cross-rank seq alignment must only ever see real launches."""
    _COMMS_LOGGER.append(op, int(nbytes), traced=True)


def log_compressed(op: str, logical_bytes: int, wire_bytes: int,
                   link: Optional[str] = None,
                   axes: Optional[Sequence[str]] = None,
                   impl: Optional[str] = None) -> None:
    """Trace-time ledger entry for a compressed collective
    (``comm/compressed.py``): ``logical_bytes`` is what the exact collective
    would have moved, ``wire_bytes`` what the int8 payload + scale lanes
    actually ride the links with — ``log_summary`` reports the ratio.
    ``link`` (ici/dcn/host) buckets the wire bytes per hop class for
    multi-phase program phases (``CommsLogger.hop_totals``)."""
    _COMMS_LOGGER.append(op, int(logical_bytes), traced=True,
                         wire_bytes=int(wire_bytes), hop_class=link)
    record_launch(op, shape=(int(logical_bytes),), axes=axes,
                  impl=impl, link=link)


def log_fused(op: str, logical_bytes: int, wire_bytes: int,
              link: Optional[str] = None) -> None:
    """Trace-time ledger entry for a compute-bound FUSED ring phase
    (``ops/collective_matmul.py`` fused primitives): like
    :func:`log_compressed`, but the wire bytes additionally land in the
    HIDDEN hop bucket — their hops ride between the bound matmul's tile
    steps, so ``CommsLogger.hop_exposure()`` counts them as overlapped
    rather than exposed transport (the t3 bench's exposed-collective
    fraction). No flight-ring record here: the fused primitives record one
    launch PER HOP themselves (the doctor needs hop-granular seq
    alignment, not one record per phase)."""
    _COMMS_LOGGER.append(op, int(logical_bytes), traced=True,
                         wire_bytes=int(wire_bytes), hop_class=link,
                         hop_hidden=True)


def all_reduce(x, axis: Axis, op: str = "sum"):
    """SUM/MAX/MIN/MEAN allreduce over a mesh axis (reference ``comm.py:497``)."""
    names = _axis_tuple(axis)
    _log_traced("all_reduce", x, names)
    if op == "sum":
        return lax.psum(x, names)
    if op == "mean":
        return lax.pmean(x, names)
    if op == "max":
        return lax.pmax(x, names)
    if op == "min":
        return lax.pmin(x, names)
    raise ValueError(f"unsupported reduce op {op}")


def all_gather(x, axis: Axis, *, tiled: bool = True, gather_dim: int = 0):
    """Allgather shards over a mesh axis (reference ``all_gather_into_tensor``).
    ``tiled=True`` concatenates along ``gather_dim`` (NCCL semantics)."""
    names = _axis_tuple(axis)
    _log_traced("all_gather", x, names)
    return lax.all_gather(x, names, axis=gather_dim, tiled=tiled)


def reduce_scatter(x, axis: Axis, *, scatter_dim: int = 0, op: str = "sum"):
    """Reduce+scatter over a mesh axis (reference ``reduce_scatter_tensor``)."""
    names = _axis_tuple(axis)
    _log_traced("reduce_scatter", x, names)
    if op == "mean":
        return lax.psum_scatter(x, names, scatter_dimension=scatter_dim, tiled=True) / get_axis_size(names)
    return lax.psum_scatter(x, names, scatter_dimension=scatter_dim, tiled=True)


def all_to_all(x, axis: str, *, split_dim: int, concat_dim: int, tiled: bool = True):
    """All-to-all over one mesh axis (reference ``all_to_all_single``). The
    Ulysses/MoE workhorse — a native ICI collective on TPU."""
    _log_traced("all_to_all", x, (axis,))
    return lax.all_to_all(x, axis, split_axis=split_dim, concat_axis=concat_dim, tiled=tiled)


def broadcast(x, axis: Axis, src: int = 0):
    """Broadcast the value from rank ``src`` of the axis to all ranks."""
    names = _axis_tuple(axis)
    _log_traced("broadcast", x, names)
    idx = lax.axis_index(names)
    sel = jnp.where(idx == src, x, jnp.zeros_like(x))
    return lax.psum(sel, names)


def ppermute(x, axis: str, perm: Sequence[Tuple[int, int]]):
    """Point-to-point permutation (reference p2p ``send``/``recv``,
    ``runtime/pipe/p2p.py``): pipeline activations ride this."""
    _log_traced("ppermute", x, (axis,))
    return lax.ppermute(x, axis, perm=list(perm))


def send_next_recv_prev(x, axis: str):
    """Ring shift by +1 over the axis (pipeline forward sends)."""
    n = get_axis_size((axis,))
    return ppermute(x, axis, [(i, (i + 1) % n) for i in range(n)])


def send_prev_recv_next(x, axis: str):
    n = get_axis_size((axis,))
    return ppermute(x, axis, [(i, (i - 1) % n) for i in range(n)])


def axis_index(axis: Axis):
    return lax.axis_index(_axis_tuple(axis))


def get_axis_size(names: Tuple[str, ...]) -> int:
    from ..utils.shard_map_compat import axis_size

    s = 1
    for n in names:
        s *= axis_size(n)
    return s


# ---------------------------------------------------------------------------
# Rank-subset groups (reference ``new_group`` / ProcessGroup, comm.py:360)
# ---------------------------------------------------------------------------


class MeshGroup:
    """A rank subset of a mesh-axis scope — the reference's ProcessGroup,
    made XLA-shaped. On TPU a 'group' is data, not a communicator: the
    subset becomes a membership mask inside the traced collective (see
    ``group_all_reduce``). Durable axis-structured subsets (MiCS shard
    groups, ZeRO++ hpZ) are better expressed as their own mesh axes —
    this type serves the reference's ad-hoc ``new_group(ranks)`` calls."""

    def __init__(self, axis: Axis, ranks: Sequence[int], axis_size: int):
        self.axis = axis
        self.ranks = tuple(int(r) for r in ranks)
        if len(set(self.ranks)) != len(self.ranks):
            raise ValueError(f"duplicate ranks in group: {ranks}")
        if self.ranks and (min(self.ranks) < 0 or max(self.ranks) >= axis_size):
            raise ValueError(f"ranks {ranks} outside axis of size {axis_size}")

    def size(self) -> int:
        return len(self.ranks)


def new_group(ranks: Sequence[int], axis: Optional[Axis] = None) -> MeshGroup:
    """Reference ``dist.new_group(ranks)``: a collective scope over a rank
    subset. ``axis`` defaults to the topology's (flattened) data axes; pass
    an explicit mesh-axis name to subset any other axis."""
    from ..parallel.topology import get_topology

    topo = get_topology()
    if axis is None:
        axis = topo.dp_axes
    return MeshGroup(axis, ranks, topo.axis_size(*_axis_tuple(axis)))


def get_world_group() -> MeshGroup:
    """All devices — the full mesh scope, matching ``get_world_size()``
    (NOT just the data axes: under pp/sp/tp the world spans those too)."""
    from ..parallel.topology import get_topology

    topo = get_topology()
    axis = topo.all_axes
    size = topo.axis_size(*axis)
    return MeshGroup(axis, range(size), size)


def get_all_ranks_from_group(group: Optional[MeshGroup] = None) -> list:
    return list((group or get_world_group()).ranks)


def get_global_rank(group: Optional[MeshGroup] = None, group_rank: int = 0) -> int:
    return (group or get_world_group()).ranks[group_rank]


def group_all_reduce(x, axis: Axis, op: str = "sum",
                     group: Optional[MeshGroup] = None):
    """``all_reduce`` over a rank subset (reference allreduce on a
    ``new_group``): ranks outside ``group`` pass through unchanged.

    The subset is expressed as membership mask → full-axis reduce → member
    select (``axis_index_groups`` is pmap-era and unsupported under
    shard_map): same semantics, one full-axis collective. Contributions
    from non-members are the op's neutral element."""
    names = _axis_tuple(axis)
    _log_traced("all_reduce", x, names)
    fn = {"sum": lax.psum, "mean": lax.pmean, "max": lax.pmax,
          "min": lax.pmin}.get(op)
    if fn is None:
        raise ValueError(f"unsupported reduce op {op}")
    if group is None:
        return fn(x, names)
    idx = lax.axis_index(names)
    member = jnp.isin(idx, jnp.asarray(group.ranks))
    if op in ("sum", "mean"):
        neutral = jnp.zeros_like(x)
    elif jnp.issubdtype(x.dtype, jnp.integer):
        info = jnp.iinfo(x.dtype)  # +/-inf would int-cast to garbage
        neutral = jnp.full_like(x, info.min if op == "max" else info.max)
    else:
        neutral = jnp.full_like(x, -jnp.inf if op == "max" else jnp.inf)
    contrib = jnp.where(member, x, neutral)
    if op == "mean":
        total = lax.psum(contrib, names) / group.size()
    else:
        total = fn(contrib, names)
    return jnp.where(member, total, x)


# ---------------------------------------------------------------------------
# Rooted collectives (reference reduce/gather/scatter, comm.py:430-470).
# SPMD note: every rank traces the same program, so 'rooted' means the
# non-root ranks receive zeros (reduce/gather) or their slice (scatter) —
# the torch contract of "output only valid on dst" made explicit.
# ---------------------------------------------------------------------------


def reduce(x, axis: Axis, dst: int = 0, op: str = "sum"):
    """Reduce to rank ``dst`` of the axis; other ranks get zeros."""
    names = _axis_tuple(axis)
    _log_traced("reduce", x, names)  # one ledger entry: lax, not all_reduce
    fn = {"sum": lax.psum, "mean": lax.pmean, "max": lax.pmax,
          "min": lax.pmin}.get(op)
    if fn is None:
        raise ValueError(f"unsupported reduce op {op}")
    total = fn(x, names)
    return jnp.where(lax.axis_index(names) == dst, total, jnp.zeros_like(total))


def gather(x, axis: Axis, dst: int = 0, gather_dim: int = 0):
    """Gather all shards onto rank ``dst``; other ranks get zeros."""
    names = _axis_tuple(axis)
    _log_traced("gather", x, names)
    full = lax.all_gather(x, names, axis=gather_dim, tiled=True)
    return jnp.where(lax.axis_index(names) == dst, full, jnp.zeros_like(full))


def scatter(x, axis: Axis, src: int = 0, scatter_dim: int = 0):
    """Each rank receives its ``scatter_dim`` slice of rank ``src``'s tensor
    (reference ``dist.scatter`` with a stacked input list)."""
    names = _axis_tuple(axis)
    _log_traced("scatter", x, names)  # one entry: inline the src-select psum
    n = get_axis_size(names)
    if x.shape[scatter_dim] % n:
        raise ValueError(f"scatter dim {scatter_dim} of {x.shape} not "
                         f"divisible by axis size {n}")
    idx = lax.axis_index(names)
    src_val = lax.psum(jnp.where(idx == src, x, jnp.zeros_like(x)), names)
    width = x.shape[scatter_dim] // n
    return lax.dynamic_slice_in_dim(src_val, idx * width,
                                    width, axis=scatter_dim)


# ---------------------------------------------------------------------------
# Coalesced collectives (reference *_coalesced + coalescing manager,
# comm.py:300-340): XLA collectives are pytree-native, so one traced call
# covers the whole bucket and the compiler fuses the transfers.
# ---------------------------------------------------------------------------


def all_reduce_coalesced(xs, axis: Axis, op: str = "sum"):
    names = _axis_tuple(axis)
    for leaf in jax.tree.leaves(xs):
        _log_traced("all_reduce", leaf, names)
    fn = {"sum": lax.psum, "mean": lax.pmean, "max": lax.pmax,
          "min": lax.pmin}.get(op)
    if fn is None:
        raise ValueError(f"unsupported reduce op {op}")
    return fn(xs, names)


def all_gather_coalesced(xs, axis: Axis, *, tiled: bool = True,
                         gather_dim: int = 0):
    names = _axis_tuple(axis)
    for leaf in jax.tree.leaves(xs):
        _log_traced("all_gather", leaf, names)
    return jax.tree.map(
        lambda t: lax.all_gather(t, names, axis=gather_dim, tiled=tiled), xs)


# ---------------------------------------------------------------------------
# Backend lifecycle / capability probes (reference comm.py:200-260)
# ---------------------------------------------------------------------------


def is_available() -> bool:
    return True


def has_all_gather_into_tensor() -> bool:
    return True  # lax.all_gather(tiled=True) is the native form


def has_reduce_scatter_tensor() -> bool:
    return True


def has_all_reduce_coalesced() -> bool:
    return True


def has_coalescing_manager() -> bool:
    return True  # pytree collectives; XLA fuses the bucket


def monitored_barrier(group=None, timeout=None, wait_all_ranks: bool = False,
                      name: str = "monitored_barrier"):
    """Reference ``monitored_barrier(group=None, timeout=...)``
    (``comm.py:412``), with the ``timeout`` actually ENFORCED: the barrier
    runs on a helper thread and a barrier that does not complete in time
    raises :class:`TimeoutError` naming the barrier — a wedged host then
    surfaces as a catchable, restartable failure instead of an eternal
    stall. ``timeout`` is seconds or a ``datetime.timedelta`` (the torch
    signature); ``None`` keeps the plain blocking barrier. The leading
    ``group`` is accepted positionally so it is not silently consumed as
    ``timeout``.

    CONTRACT: after a timeout the caller must ESCALATE — snapshot and exit
    (e.g. with the launcher's watchdog-hang code) so the restart policy
    relaunches the world. The helper thread is daemonic and abandoned still
    inside the barrier; continuing to issue collectives (or retrying the
    barrier) from this process while a stale participant is parked in the
    old one desynchronizes the cross-host collective order — undefined
    behavior under jax.distributed. Timeout-then-exit is the only safe
    sequence, which is exactly what the fleet tier automates."""
    if timeout is None:
        barrier(name)
        return
    secs = (timeout.total_seconds() if hasattr(timeout, "total_seconds")
            else float(timeout))
    import threading

    done = threading.Event()
    err: list = []

    def _run():
        try:
            barrier(name)
        except BaseException as e:  # surfaced on the caller's thread
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=_run, daemon=True,
                         name=f"dstpu-monitored-barrier-{name}")
    t.start()
    if not done.wait(max(0.0, secs)):
        raise TimeoutError(
            f"monitored_barrier {name!r} did not complete within {secs:g}s "
            f"— a rank is missing or a collective is wedged (process "
            f"{jax.process_index()}/{jax.process_count()})")
    if err:
        raise err[0]


def destroy_process_group():
    """Tear down the control plane (reference ``destroy_process_group``)."""
    global _INITIALIZED
    if jax.process_count() > 1:
        jax.distributed.shutdown()
    _INITIALIZED = False


def mpi_discovery() -> Tuple[int, int]:
    """OpenMPI env discovery (reference ``comm.py:688``): returns
    ``(process_id, num_processes)``, (0, 1) outside an mpirun launch."""
    return (int(os.environ.get("OMPI_COMM_WORLD_RANK", "0")),
            int(os.environ.get("OMPI_COMM_WORLD_SIZE", "1")))


def initialize_mesh_device(mesh_shape: Sequence[int],
                           mesh_dim_names: Sequence[str]):
    """Reference ``initialize_mesh_device`` (torch DeviceMesh): returns a
    ``jax.sharding.Mesh`` over all devices with the requested shape/names."""
    devs = np.array(jax.devices()).reshape(tuple(mesh_shape))
    return jax.sharding.Mesh(devs, tuple(mesh_dim_names))


# reference-compat aliases ---------------------------------------------------
allreduce_fn = all_reduce
allgather_fn = all_gather
reduce_scatter_fn = reduce_scatter
inference_all_reduce = all_reduce
all_gather_into_tensor = all_gather
reduce_scatter_tensor = reduce_scatter
all_to_all_single = all_to_all
