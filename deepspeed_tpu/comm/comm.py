"""TCCL — the TPU collective communication layer.

TPU-native re-design of ``deepspeed.comm`` (reference ``comm/comm.py:222-520``)
and its ``TorchBackend``/NCCL stack. The "process group" concept is replaced by
**named mesh axes** (see ``parallel/topology.py``); collectives lower to XLA
collectives (``psum``/``all_gather``/``psum_scatter``/``all_to_all``/
``ppermute``) that ride ICI within a slice and DCN across slices — XLA picks
the routing, we pick the axes.

Two usage contexts:

* **Functional (hot path)** — inside ``jit``/``shard_map``: ``all_reduce(x,
  axis='dp')`` etc. These are traced once; the comms ledger records them at
  trace time with exact message sizes (shapes are static under XLA).
* **Host control-plane** — ``init_distributed()``, ``barrier()``,
  ``broadcast_host_data()``: multi-process bootstrap via ``jax.distributed``
  (the analogue of the reference's env/MPI rendezvous, ``comm.py:619,688``).
"""

import os
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..utils.comms_logging import CommsLogger, timed_op
from ..utils.logging import logger

Axis = Union[str, Sequence[str]]

_INITIALIZED = False
_COMMS_LOGGER = CommsLogger()


def get_comms_logger() -> CommsLogger:
    return _COMMS_LOGGER


def configure(comms_logger=None, **kwargs):
    """Reference ``dist.configure`` (``comm/comm.py``): enable comms logging."""
    if comms_logger is not None:
        _COMMS_LOGGER.configure(enabled=comms_logger.enabled, verbose=comms_logger.verbose,
                                prof_all=comms_logger.prof_all, prof_ops=comms_logger.prof_ops,
                                debug=comms_logger.debug)
    if kwargs:
        _COMMS_LOGGER.configure(**kwargs)


def log_summary(show_straggler: bool = False):
    return _COMMS_LOGGER.log_summary(world_size=get_world_size(), show_straggler=show_straggler)


# ---------------------------------------------------------------------------
# Bootstrap / host control-plane
# ---------------------------------------------------------------------------


def init_distributed(dist_backend: str = "tccl",
                     auto_mpi_discovery: bool = True,
                     timeout: Optional[float] = None,
                     init_method: Optional[str] = None,
                     rank: int = -1,
                     world_size: int = -1) -> None:
    """Bootstrap multi-host JAX (reference ``init_distributed``, ``comm.py:619``).

    Single-process (including single-host multi-chip) needs no rendezvous.
    Multi-host reads the coordinator from args or env (``DSTPU_COORDINATOR`` /
    launcher-set vars), mirroring the reference's env-rendezvous at
    MASTER_ADDR, and falls back to OpenMPI env discovery like
    ``mpi_discovery`` (``comm.py:688``).
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    coordinator = init_method or os.environ.get("DSTPU_COORDINATOR")
    nprocs = world_size if world_size > 0 else int(os.environ.get("DSTPU_NUM_PROCESSES", "1"))
    proc_id = rank if rank >= 0 else int(os.environ.get("DSTPU_PROCESS_ID", "0"))
    if auto_mpi_discovery and nprocs == 1 and "OMPI_COMM_WORLD_SIZE" in os.environ:
        nprocs = int(os.environ["OMPI_COMM_WORLD_SIZE"])
        proc_id = int(os.environ["OMPI_COMM_WORLD_RANK"])
        logger.info(f"MPI discovery: process {proc_id}/{nprocs}")
    if nprocs > 1:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=nprocs, process_id=proc_id)
        logger.info(f"jax.distributed initialized: process {jax.process_index()} of "
                    f"{jax.process_count()}, {jax.local_device_count()} local devices")
    _INITIALIZED = True


def is_initialized() -> bool:
    return _INITIALIZED


def get_world_size(axis: Optional[Axis] = None) -> int:
    """Device-level world size (reference rank==GPU; here rank==chip), or the
    size of a mesh-axis 'group' when ``axis`` is given."""
    if axis is None:
        return jax.device_count()
    from ..parallel.topology import get_topology

    names = (axis,) if isinstance(axis, str) else tuple(axis)
    return get_topology().axis_size(*names)


def get_rank() -> int:
    """Host process index (control-plane rank)."""
    return jax.process_index()


def get_local_rank() -> int:
    return 0  # one process drives all local chips under JAX


def barrier(name: str = "barrier"):
    with timed_op(_COMMS_LOGGER, "barrier", 0):
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(name)
        else:
            jax.effects_barrier()


def broadcast_host_data(data: Any, src: int = 0) -> Any:
    """Broadcast a host-side pytree from process ``src`` to all processes
    (reference object broadcast). No-op in single-process mode."""
    if jax.process_count() == 1:
        return data
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(data, is_source=jax.process_index() == src)


# ---------------------------------------------------------------------------
# Functional collectives (inside jit / shard_map)
# ---------------------------------------------------------------------------


def _nbytes(x) -> int:
    return int(np.prod(x.shape)) * jnp.dtype(x.dtype).itemsize if hasattr(x, "shape") else 0


def _axis_tuple(axis: Axis) -> Tuple[str, ...]:
    return (axis,) if isinstance(axis, str) else tuple(axis)


def _log_traced(op: str, x) -> None:
    _COMMS_LOGGER.append(op, _nbytes(x), traced=True)


def all_reduce(x, axis: Axis, op: str = "sum"):
    """SUM/MAX/MIN/MEAN allreduce over a mesh axis (reference ``comm.py:497``)."""
    _log_traced("all_reduce", x)
    names = _axis_tuple(axis)
    if op == "sum":
        return lax.psum(x, names)
    if op == "mean":
        return lax.pmean(x, names)
    if op == "max":
        return lax.pmax(x, names)
    if op == "min":
        return lax.pmin(x, names)
    raise ValueError(f"unsupported reduce op {op}")


def all_gather(x, axis: Axis, *, tiled: bool = True, gather_dim: int = 0):
    """Allgather shards over a mesh axis (reference ``all_gather_into_tensor``).
    ``tiled=True`` concatenates along ``gather_dim`` (NCCL semantics)."""
    _log_traced("all_gather", x)
    return lax.all_gather(x, _axis_tuple(axis), axis=gather_dim, tiled=tiled)


def reduce_scatter(x, axis: Axis, *, scatter_dim: int = 0, op: str = "sum"):
    """Reduce+scatter over a mesh axis (reference ``reduce_scatter_tensor``)."""
    _log_traced("reduce_scatter", x)
    names = _axis_tuple(axis)
    if op == "mean":
        return lax.psum_scatter(x, names, scatter_dimension=scatter_dim, tiled=True) / get_axis_size(names)
    return lax.psum_scatter(x, names, scatter_dimension=scatter_dim, tiled=True)


def all_to_all(x, axis: str, *, split_dim: int, concat_dim: int, tiled: bool = True):
    """All-to-all over one mesh axis (reference ``all_to_all_single``). The
    Ulysses/MoE workhorse — a native ICI collective on TPU."""
    _log_traced("all_to_all", x)
    return lax.all_to_all(x, axis, split_axis=split_dim, concat_axis=concat_dim, tiled=tiled)


def broadcast(x, axis: Axis, src: int = 0):
    """Broadcast the value from rank ``src`` of the axis to all ranks."""
    _log_traced("broadcast", x)
    names = _axis_tuple(axis)
    idx = lax.axis_index(names)
    sel = jnp.where(idx == src, x, jnp.zeros_like(x))
    return lax.psum(sel, names)


def ppermute(x, axis: str, perm: Sequence[Tuple[int, int]]):
    """Point-to-point permutation (reference p2p ``send``/``recv``,
    ``runtime/pipe/p2p.py``): pipeline activations ride this."""
    _log_traced("ppermute", x)
    return lax.ppermute(x, axis, perm=list(perm))


def send_next_recv_prev(x, axis: str):
    """Ring shift by +1 over the axis (pipeline forward sends)."""
    n = get_axis_size((axis,))
    return ppermute(x, axis, [(i, (i + 1) % n) for i in range(n)])


def send_prev_recv_next(x, axis: str):
    n = get_axis_size((axis,))
    return ppermute(x, axis, [(i, (i - 1) % n) for i in range(n)])


def axis_index(axis: Axis):
    return lax.axis_index(_axis_tuple(axis))


def get_axis_size(names: Tuple[str, ...]) -> int:
    s = 1
    for n in names:
        s *= lax.axis_size(n)
    return s


# reference-compat aliases ---------------------------------------------------
allreduce_fn = all_reduce
allgather_fn = all_gather
reduce_scatter_fn = reduce_scatter
inference_all_reduce = all_reduce
