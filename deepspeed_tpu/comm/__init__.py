from .comm import (all_gather, all_reduce, all_to_all, axis_index, barrier, broadcast,
                   broadcast_host_data, configure, get_comms_logger, get_local_rank, get_rank,
                   get_world_size, init_distributed, is_initialized, log_summary, ppermute,
                   reduce_scatter, send_next_recv_prev, send_prev_recv_next)
