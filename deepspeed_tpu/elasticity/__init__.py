"""Elasticity: elastic batch-size math + restart supervision.

Reference: ``deepspeed/elasticity/`` — config (``config.py``), batch/chip
compatibility solver (``elasticity.py:233``), torchelastic agent
(``elastic_agent.py:32``; here, launcher-level supervision in
``launcher/launch.py:_supervise``).
"""

from .elasticity import (ElasticityConfig, ElasticityConfigError, ElasticityError,
                         ElasticityIncompatibleWorldSize, compute_elastic_config,
                         get_compatible_chips, valid_chip_counts)

__all__ = [
    "ElasticityConfig", "ElasticityConfigError", "ElasticityError",
    "ElasticityIncompatibleWorldSize", "compute_elastic_config",
    "get_compatible_chips", "valid_chip_counts",
]
