"""Elasticity: elastic batch-size math + membership-change rescale agent.

Reference: ``deepspeed/elasticity/`` — config (``config.py``), batch/chip
compatibility solver (``elasticity.py:233``), torchelastic agent
(``elastic_agent.py:32``). The rescale loop (detect membership change →
retopologize via ``compute_elastic_config`` → resume from the reshardable
checkpoint) is :class:`ElasticAgent`; crash-only restart supervision also
lives in ``launcher/launch.py:_supervise``.
"""

from .elastic_agent import ElasticAgent, RescaleDecision, decide_world
from .elasticity import (ElasticityConfig, ElasticityConfigError, ElasticityError,
                         ElasticityIncompatibleWorldSize, compute_elastic_config,
                         get_compatible_chips, valid_chip_counts)

__all__ = [
    "ElasticAgent", "ElasticityConfig", "ElasticityConfigError",
    "ElasticityError", "ElasticityIncompatibleWorldSize", "RescaleDecision",
    "compute_elastic_config", "decide_world", "get_compatible_chips",
    "valid_chip_counts",
]
