"""Elastic training: batch-size / chip-count compatibility math.

Analogue of the reference elasticity module (``deepspeed/elasticity/
elasticity.py:233`` ``compute_elastic_config``): given an acceptable batch
ceiling and candidate micro-batch sizes, choose one global batch size that
stays valid across a whole range of chip counts, so a job can be rescaled
(slice shrink/grow, preemption) without retuning hyperparameters. Runtime
recovery is checkpoint-based restart (launcher ``--elastic_training``
supervision + UCP resharding in ``checkpoint/``).
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


@dataclass
class ElasticityConfig:
    """Typed view of the ``elasticity`` config block (reference
    ``elasticity/config.py``)."""
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = field(default_factory=lambda: [2, 4, 6])
    min_chips: int = 1
    max_chips: int = 10000
    min_time: int = 0
    prefer_larger_batch: bool = True
    ignore_non_elastic_batch_info: bool = False
    version: float = 0.2

    @classmethod
    def from_dict(cls, d: Dict) -> "ElasticityConfig":
        d = dict(d)
        # accept the reference's GPU-flavored key names
        renames = {"min_gpus": "min_chips", "max_gpus": "max_chips"}
        for old, new in renames.items():
            if old in d:
                d[new] = d.pop(old)
        known = {f for f in cls.__dataclass_fields__}
        cfg = cls(**{k: v for k, v in d.items() if k in known})
        if cfg.max_train_batch_size < 1:
            raise ElasticityConfigError("max_train_batch_size must be >= 1")
        if not cfg.micro_batch_sizes or any(m < 1 for m in cfg.micro_batch_sizes):
            raise ElasticityConfigError(f"bad micro_batch_sizes {cfg.micro_batch_sizes}")
        if cfg.min_chips < 1 or cfg.max_chips < cfg.min_chips:
            raise ElasticityConfigError(
                f"bad chip range [{cfg.min_chips}, {cfg.max_chips}]")
        return cfg


def valid_chip_counts(batch_size: int, micro_batches: List[int], min_chips: int,
                      max_chips: int) -> List[int]:
    """Chip counts ``c`` for which some micro-batch ``m`` gives an integer
    gradient-accumulation: ``batch_size % (m * c) == 0``. No ``c`` beyond
    ``batch_size // min(micro_batches)`` can qualify, so the scan is bounded
    there rather than at ``max_chips``."""
    out = []
    hi = min(max_chips, batch_size // min(micro_batches))
    for c in range(min_chips, hi + 1):
        if any(batch_size % (m * c) == 0 for m in micro_batches):
            out.append(c)
    return out


def _candidate_batch_sizes(max_batch: int, micro_batches: List[int]) -> List[int]:
    cands = set()
    for m in micro_batches:
        cands.update(range(m, max_batch + 1, m))
    return sorted(cands)


def get_compatible_chips(max_batch: int, micro_batches: List[int], min_chips: int,
                         max_chips: int,
                         prefer_larger: bool = True) -> Tuple[int, List[int]]:
    """Pick the batch size maximizing the number of valid chip counts
    (reference v0.1/v0.2 algorithms, ``elasticity.py:83,126``); ties broken
    toward larger (or smaller) batch per ``prefer_larger``."""
    best: Tuple[int, List[int]] = (0, [])
    best_score = -1
    for b in _candidate_batch_sizes(max_batch, micro_batches):
        valid = valid_chip_counts(b, micro_batches, min_chips, max_chips)
        score = len(valid)
        better = score > best_score or (
            score == best_score and ((b > best[0]) if prefer_larger else (b < best[0])))
        if better:
            best, best_score = (b, valid), score
    if best_score <= 0:
        raise ElasticityError(
            f"no batch size <= {max_batch} is divisible by any micro-batch in "
            f"{micro_batches} over chips [{min_chips}, {max_chips}]")
    return best


def resolve_elasticity_config(ds_config) -> ElasticityConfig:
    """Normalize every accepted config shape to an :class:`ElasticityConfig`:
    an instance, a foreign config model with ``to_dict`` (the runtime
    config's section keeps the reference's GPU-flavored key names; from_dict
    renames them), or a ds_config dict with an ``elasticity`` block."""
    if isinstance(ds_config, ElasticityConfig):
        return ds_config
    if hasattr(ds_config, "to_dict"):
        return ElasticityConfig.from_dict(ds_config.to_dict())
    block = ds_config.get("elasticity")
    if block is None:
        raise ElasticityConfigError("config has no 'elasticity' section")
    return (block if isinstance(block, ElasticityConfig)
            else ElasticityConfig.from_dict(block))


def micro_for_world(cfg: ElasticityConfig, final_batch: int,
                    world_size: int) -> int:
    """Largest configured micro-batch dividing the per-chip batch — the rule
    ``compute_elastic_config`` applies for a concrete world size."""
    per_chip = final_batch // world_size
    fits = [m for m in cfg.micro_batch_sizes if per_chip % m == 0]
    if not fits:
        raise ElasticityIncompatibleWorldSize(
            f"no micro-batch in {cfg.micro_batch_sizes} divides "
            f"per-chip batch {per_chip}")
    return max(fits)


def compute_elastic_config(ds_config: Dict, world_size: int = 0
                           ) -> Tuple[int, List[int], Optional[int]]:
    """Resolve (final_batch_size, valid_chip_counts, micro_batch_for_world).

    Reference ``compute_elastic_config`` (``elasticity/elasticity.py:233``):
    ``world_size=0`` resolves only the schedule; a concrete world size also
    picks the largest micro-batch that divides ``final_batch / world``.
    """
    cfg = resolve_elasticity_config(ds_config)
    if isinstance(cfg, ElasticityConfig) and not cfg.enabled:
        raise ElasticityConfigError("elasticity is not enabled "
                                    "(set elasticity.enabled = true)")
    final_batch, valid = get_compatible_chips(cfg.max_train_batch_size,
                                              sorted(set(cfg.micro_batch_sizes)),
                                              cfg.min_chips, cfg.max_chips,
                                              prefer_larger=cfg.prefer_larger_batch)
    micro = None
    if world_size > 0:
        if world_size not in valid:
            raise ElasticityIncompatibleWorldSize(
                f"world size {world_size} not in the valid set for elastic batch "
                f"{final_batch}: {valid[:16]}{'...' if len(valid) > 16 else ''}")
        micro = micro_for_world(cfg, final_batch, world_size)
    return final_batch, valid, micro
