"""Membership-change elastic agent: detect → retopologize → resume.

Analogue of the reference ``DSElasticAgent._invoke_run``
(``elasticity/elastic_agent.py:127``), which monitors the worker group and,
on a failure or membership change, restarts it against the rendezvous's
CURRENT world. The TPU-native decomposition:

- **detect**: the agent supervises the worker group; a non-zero exit or a
  membership probe reporting fewer/more healthy hosts triggers a rescale
  round (the reference gets this from the torch-elastic rendezvous; here the
  probe is pluggable — hostfile reachability, k8s endpoints, a scheduler
  API).
- **retopologize**: ``compute_elastic_config`` re-derives the one batch
  schedule that stays valid across chip counts, and the agent clamps the
  new world to the schedule's valid set (largest valid <= available), so
  the relaunched job needs no hyperparameter retuning.
- **resume**: checkpoints are reshardable by construction (orbax logical
  global arrays — ``checkpoint/engine.py``), so the relaunched workers
  ``load_checkpoint`` under the new topology and the loss curve continues.
  This replaces the reference's 3D-reshape machinery as the recovery path.

The worker side needs no agent-specific code beyond resuming from the last
checkpoint at startup; world size and the rescaled batch arrive through the
ordinary ``DSTPU_*`` bootstrap env plus ``elasticity.enabled`` config (see
``runtime/config.py`` ``finalize``).
"""

import time
from dataclasses import dataclass
from typing import Callable, List, Optional

from ..utils.logging import logger
from .elasticity import (ElasticityIncompatibleWorldSize,
                         compute_elastic_config, micro_for_world,
                         resolve_elasticity_config)


@dataclass
class RescaleDecision:
    """One relaunch round: the world to run at and its batch schedule."""
    world_size: int
    final_batch: int
    micro_batch: int

    @property
    def gradient_accumulation(self) -> int:
        return self.final_batch // (self.micro_batch * self.world_size)


def decide_world(ds_config, available: int) -> RescaleDecision:
    """Clamp ``available`` ranks to the elastic schedule's valid set:
    the largest valid world <= available (the reference declines invalid
    worlds with ``ElasticityIncompatibleWorldSize``; an agent must instead
    pick a world it CAN run so the job survives the membership change)."""
    final_batch, valid, _ = compute_elastic_config(ds_config, world_size=0)
    fits = [w for w in valid if w <= available]
    if not fits:
        raise ElasticityIncompatibleWorldSize(
            f"no valid elastic world <= {available} (valid set "
            f"{valid[:16]}{'...' if len(valid) > 16 else ''})")
    world = max(fits)
    # world is in `valid`, so a dividing micro-batch exists — deriving it
    # from the already-solved schedule avoids re-solving it
    micro = micro_for_world(resolve_elasticity_config(ds_config),
                            final_batch, world)
    return RescaleDecision(world_size=world, final_batch=final_batch,
                           micro_batch=micro)


class ElasticAgent:
    """Supervision loop composing detect → retopologize → resume.

    ``membership_fn() -> int``: currently-available rank count.
    ``spawn_fn(decision, restart) -> int``: launch the worker group at
    ``decision.world_size`` (blocking) and return its exit code; workers are
    expected to resume from the latest checkpoint themselves.

    Mirrors ``DSElasticAgent._invoke_run``: run the group; exit 0 ends the
    job; a failure re-probes membership, re-decides the world, and relaunches
    with backoff until ``max_restarts`` consecutive quick failures.
    """

    def __init__(self, ds_config, membership_fn: Callable[[], int],
                 spawn_fn: Callable[[RescaleDecision, int], int],
                 max_restarts: int = 100, backoff_s: float = 3.0,
                 min_uptime_s: float = 10.0):
        self.ds_config = ds_config
        self.membership_fn = membership_fn
        self.spawn_fn = spawn_fn
        self.max_restarts = max_restarts
        self.backoff_s = backoff_s
        self.min_uptime_s = min_uptime_s
        self.history: List[RescaleDecision] = []  # one entry per launch round

    def run(self) -> int:
        restarts = 0
        while True:
            available = int(self.membership_fn())
            try:
                decision = decide_world(self.ds_config, available)
            except ElasticityIncompatibleWorldSize as e:
                # transient capacity dip (node rebooting, probe glitch) must
                # consume the restart budget and re-probe, not kill the agent
                restarts += 1
                if restarts > self.max_restarts:
                    logger.error(f"elastic agent: {e}; restart budget exhausted")
                    raise
                logger.warning(f"elastic agent: {e}; re-probing membership "
                               f"({restarts}/{self.max_restarts}) "
                               f"in {self.backoff_s}s")
                time.sleep(self.backoff_s)
                continue
            if self.history and decision != self.history[-1]:
                logger.warning(
                    f"elastic rescale: world {self.history[-1].world_size} -> "
                    f"{decision.world_size} (batch {decision.final_batch}, "
                    f"micro {decision.micro_batch})")
            self.history.append(decision)
            start = time.time()
            rc = int(self.spawn_fn(decision, len(self.history) - 1))
            if rc == 0:
                return 0
            if time.time() - start > self.min_uptime_s:
                restarts = 0  # healthy uptime resets the budget
            restarts += 1
            if restarts > self.max_restarts:
                logger.error(f"elastic agent: rc={rc}, restart budget exhausted")
                return rc
            logger.warning(f"elastic agent: worker group rc={rc}; "
                           f"restart {restarts}/{self.max_restarts} "
                           f"in {self.backoff_s}s")
            time.sleep(self.backoff_s)
