"""Dynamic loss scaling as functional state.

Reference: ``DynamicLossScaler`` (``runtime/fp16/loss_scaler.py:91``) — the
mutable scaler becomes a small pytree updated inside the compiled train step
with ``jnp.where`` (no Python branching), so overflow-skip steps stay on
device.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class LossScaleState(NamedTuple):
    scale: jnp.ndarray          # f32 scalar
    good_steps: jnp.ndarray     # i32 consecutive non-overflow steps
    hysteresis: jnp.ndarray     # i32 remaining tolerated overflows before backoff


def make_loss_scale_state(initial_scale_power: int = 16, static_scale: float = 0.0,
                          hysteresis: int = 2) -> LossScaleState:
    scale = static_scale if static_scale > 0 else 2.0 ** initial_scale_power
    return LossScaleState(scale=jnp.asarray(scale, jnp.float32),
                          good_steps=jnp.zeros([], jnp.int32),
                          hysteresis=jnp.asarray(hysteresis, jnp.int32))


def has_overflow(grads) -> jnp.ndarray:
    """Global non-finite check over a grad pytree (reference ``CheckOverflow``,
    ``runtime/utils.py:181``)."""
    leaves = jax.tree.leaves(grads)
    if not leaves:
        return jnp.zeros([], jnp.bool_)
    flags = [~jnp.isfinite(g.astype(jnp.float32)).all() for g in leaves]
    return jnp.stack(flags).any()


def update_loss_scale(state: LossScaleState, overflow: jnp.ndarray, *,
                      dynamic: bool = True, scale_window: int = 1000,
                      scale_factor: float = 2.0, min_scale: float = 1.0,
                      max_hysteresis: int = 2,
                      consecutive_hysteresis: bool = False) -> LossScaleState:
    """One step of the reference's update_scale logic (loss_scaler.py:91),
    branch-free."""
    if not dynamic:
        return state
    hys_exhausted = state.hysteresis <= 1
    backoff_scale = jnp.maximum(state.scale / scale_factor, min_scale)
    new_scale = jnp.where(overflow & hys_exhausted, backoff_scale, state.scale)
    new_hys = jnp.where(overflow & ~hys_exhausted, state.hysteresis - 1, state.hysteresis)
    good = jnp.where(overflow, 0, state.good_steps + 1)
    grow = (~overflow) & (good % scale_window == 0) & (good > 0)
    new_scale = jnp.where(grow, new_scale * scale_factor, new_scale)
    # Reference loss_scaler.py:194-201: consecutive_hysteresis replenishes on
    # every good step; otherwise hysteresis replenishes only at growth windows.
    replenish = (~overflow) if consecutive_hysteresis else grow
    new_hys = jnp.where(replenish, jnp.asarray(max_hysteresis, jnp.int32), new_hys)
    return LossScaleState(scale=new_scale, good_steps=good, hysteresis=new_hys)
