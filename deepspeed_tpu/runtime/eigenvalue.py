"""Hessian max-eigenvalue estimation by power iteration.

Reference ``Eigenvalue`` (``runtime/eigenvalue.py:13``): per-block Hessian
eigenvalues modulate MoQ quantization periods (layers with sharp curvature
quantize later). TPU-native: Hessian-vector products via ``jax.jvp`` over
``jax.grad`` (double-backward, exact), power-iteration loop in
``lax.fori_loop`` — no materialized Hessian.
"""

from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp


def hvp(loss_fn: Callable, params, batch, vec):
    """Hessian-vector product: H(params) @ vec."""
    g = lambda p: jax.grad(loss_fn)(p, batch)
    return jax.jvp(g, (params,), (vec,))[1]


class Eigenvalue:
    @classmethod
    def from_config(cls, ec) -> "Eigenvalue":
        """Build from an ``eigenvalue`` config node (reference section
        vocabulary, ``runtime/constants.py:340``)."""
        return cls(max_iter=ec.max_iter, tol=ec.tol, stability=ec.stability)

    def __init__(self, max_iter: int = 20, tol: float = 1e-2,
                 stability: float = 1e-6, seed: int = 0):
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.seed = seed

    def compute_eigenvalue(self, loss_fn: Callable, params, batch) -> float:
        """Dominant eigenvalue of the loss Hessian at ``params``."""
        rng = jax.random.PRNGKey(self.seed)
        leaves, treedef = jax.tree.flatten(params)
        keys = jax.random.split(rng, len(leaves))
        v = jax.tree.unflatten(treedef, [
            jax.random.normal(k, l.shape, l.dtype) for k, l in zip(keys, leaves)])

        def norm(t):
            return jnp.sqrt(sum(jnp.sum(jnp.square(x)) for x in jax.tree.leaves(t)))

        def normalize(t):
            n = norm(t) + self.stability
            return jax.tree.map(lambda x: x / n, t)

        v = normalize(v)
        eig = jnp.asarray(0.0)
        for _ in range(self.max_iter):
            hv = hvp(loss_fn, params, batch, v)
            new_eig = sum(jnp.sum(a * b) for a, b in
                          zip(jax.tree.leaves(v), jax.tree.leaves(hv)))
            v = normalize(hv)
            if abs(float(new_eig) - float(eig)) < self.tol * max(1.0, abs(float(eig))):
                eig = new_eig
                break
            eig = new_eig
        return float(eig)

    def compute_layer_eigenvalues(self, loss_fn: Callable, params,
                                  batch) -> Dict[str, float]:
        """Per-top-level-block eigenvalues (reference computes per layer to
        order MoQ quantization)."""
        out = {}
        for name in params:
            def block_loss(block_params, b, _name=name):
                merged = {**params, _name: block_params}
                return loss_fn(merged, b)

            out[name] = Eigenvalue(self.max_iter, self.tol, self.stability,
                                   self.seed).compute_eigenvalue(
                lambda p, b: block_loss(p, b), params[name], batch)
        return out
