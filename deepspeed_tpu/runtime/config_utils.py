"""Config base machinery: dict -> typed dataclass trees with unknown-key checks.

Plays the role of the reference's pydantic ``DeepSpeedConfigModel``
(``runtime/config_utils.py:17``) using stdlib dataclasses: every config node
supports ``from_dict`` with strict unknown-key detection, deprecated-key
remapping, and ``"auto"`` passthrough values.
"""

import dataclasses
from typing import Any, Dict, Mapping, Optional, Type, TypeVar, Union

T = TypeVar("T", bound="ConfigModel")

AUTO = "auto"


class ConfigError(ValueError):
    pass


def _is_auto(v: Any) -> bool:
    return isinstance(v, str) and v.lower() == AUTO


@dataclasses.dataclass
class ConfigModel:
    """Base for all config nodes. Subclasses are plain dataclasses."""

    #: maps old key -> new key (reference: ``DeepSpeedConfigModel`` deprecated fields)
    _deprecated: Dict[str, str] = dataclasses.field(default=None, repr=False, compare=False)

    @classmethod
    def field_names(cls):
        return {f.name for f in dataclasses.fields(cls) if not f.name.startswith("_")}

    @classmethod
    def _migrate_legacy(cls, d: Dict[str, Any]) -> Dict[str, Any]:
        """Hook for structural legacy-key rewrites that a flat old->new
        rename cannot express (e.g. ``cpu_offload: true`` becoming a nested
        ``offload_optimizer`` node). Default: identity."""
        return d

    @classmethod
    def from_dict(cls: Type[T], d: Optional[Mapping[str, Any]], path: str = "") -> T:
        if d is None:
            d = {}
        if not isinstance(d, Mapping):
            raise ConfigError(f"Config node {path or cls.__name__} must be a mapping, got {type(d)}")
        d = dict(d)
        deprecated = getattr(cls, "_DEPRECATED_KEYS", {})
        for old, new in deprecated.items():
            if old in d:
                if new is not None and new not in d:
                    d[new] = d.pop(old)
                else:
                    d.pop(old)
        d = cls._migrate_legacy(d)
        names = cls.field_names()
        unknown = set(d) - names
        if unknown:
            raise ConfigError(f"Unknown config keys at {path or cls.__name__}: {sorted(unknown)}; "
                              f"valid keys: {sorted(names)}")
        kwargs = {}
        hints = {f.name: f for f in dataclasses.fields(cls)}
        for k, v in d.items():
            f = hints[k]
            sub = _subconfig_type(f.type)
            if sub is not None and isinstance(v, Mapping):
                v = sub.from_dict(v, path=f"{path}.{k}" if path else k)
            kwargs[k] = v
        return cls(**kwargs)

    def to_dict(self) -> Dict[str, Any]:
        out = {}
        for f in dataclasses.fields(self):
            if f.name.startswith("_"):
                continue
            v = getattr(self, f.name)
            if isinstance(v, ConfigModel):
                v = v.to_dict()
            out[f.name] = v
        return out


_SUBCONFIG_REGISTRY: Dict[str, type] = {}


def register_config(cls):
    _SUBCONFIG_REGISTRY[cls.__name__] = cls
    return cls


def _subconfig_type(tp) -> Optional[type]:
    """Resolve a dataclass field annotation to a ConfigModel subclass if it is one."""
    if isinstance(tp, type) and issubclass(tp, ConfigModel):
        return tp
    if isinstance(tp, str):
        name = tp.strip()
        for tok in ("Optional[", "]", '"', "'"):
            name = name.replace(tok, "")
        return _SUBCONFIG_REGISTRY.get(name)
    # typing.Optional[X]
    args = getattr(tp, "__args__", None)
    if args:
        for a in args:
            r = _subconfig_type(a)
            if r is not None:
                return r
    return None


def get_scalar(v: Any, default: Any) -> Any:
    """Resolve an ``"auto"`` config value to a default."""
    return default if _is_auto(v) or v is None else v
