"""Data loading utilities.

Reference: ``DeepSpeedDataLoader`` + ``RepeatingLoader``
(``runtime/dataloader.py:41,:17``). On TPU the loader yields *global* batches
(numpy/jnp pytrees); the engine shards them over the dp/sp mesh axes at
dispatch, so there is no per-rank DistributedSampler — every host feeds its
local shard of the global array via ``jax.make_array_from_process_local_data``
in multi-host runs.
"""

from typing import Any, Callable, Iterable, Iterator, Optional

import jax
import numpy as np


class RepeatingLoader:
    """Wrap an iterator to restart on StopIteration (reference ``:17``)."""

    def __init__(self, loader: Iterable):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


class DeepSpeedDataLoader:
    """Minimal batch loader over an indexable dataset of pytrees.

    Supports a ``collate_fn`` and curriculum hooks (``data_pipeline``): when a
    ``curriculum_fn`` is set, it maps ``(epoch, step) -> effective seq length``
    and the loader truncates sequence-like leaves accordingly (legacy
    curriculum learning, reference ``curriculum_scheduler.py:11``).
    """

    def __init__(self, dataset, batch_size: int, shuffle: bool = True, seed: int = 0,
                 collate_fn: Optional[Callable] = None, drop_last: bool = True,
                 curriculum_fn: Optional[Callable] = None,
                 sampler=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.collate_fn = collate_fn or _default_collate
        self.drop_last = drop_last
        self.curriculum_fn = curriculum_fn
        # difficulty-driven index selection (data_pipeline
        # DeepSpeedDataSampler — reference deepspeed_io wires its sampler
        # into the torch DataLoader the same way); overrides shuffle order
        self.sampler = sampler
        self.epoch = 0
        self.global_step = 0
        self.batch_in_epoch = 0   # batches YIELDED in the current epoch
        self._resume_offset = 0   # batches to fast-forward on next __iter__
        n = len(dataset)
        self.len = n // batch_size if drop_last else (n + batch_size - 1) // batch_size

    def __len__(self):
        return self.len

    # -- resumable data stream (recorded in snapshot meta) ---------------
    def state_dict(self) -> dict:
        """The loader's position: restoring it into a FRESH loader over the
        same dataset/seed and iterating reproduces the exact batch sequence
        an uninterrupted run would have yielded from here."""
        return {"epoch": self.epoch, "batch_in_epoch": self.batch_in_epoch,
                "seed": self.seed, "global_step": self.global_step}

    def load_state_dict(self, state: dict) -> None:
        self.epoch = int(state["epoch"])
        self.seed = int(state.get("seed", self.seed))
        self.global_step = int(state.get("global_step", 0))
        self.batch_in_epoch = 0
        self._resume_offset = int(state.get("batch_in_epoch", 0))

    def __iter__(self) -> Iterator[Any]:
        n = len(self.dataset)
        start, self._resume_offset = self._resume_offset, 0
        if self.sampler is None:
            order = np.arange(n)
            if self.shuffle:
                rng = np.random.default_rng(self.seed + self.epoch)
                rng.shuffle(order)
        elif start:
            # curriculum sampler: fast-forward by consuming (and discarding)
            # the skipped draws — the sampler's stream is deterministic, so
            # position IS the resume state
            for _ in range(start):
                self.sampler.next_batch()
        self.batch_in_epoch = start
        for i in range(start, self.len):
            if self.sampler is not None:
                idx = self.sampler.next_batch()
            else:
                idx = order[i * self.batch_size:(i + 1) * self.batch_size]
            batch = self.collate_fn([self.dataset[int(j)] for j in idx])
            if self.curriculum_fn is not None:
                seqlen = int(self.curriculum_fn(self.epoch, self.global_step))
                batch = _truncate_seq(batch, seqlen)
            self.global_step += 1
            self.batch_in_epoch = i + 1
            yield batch
        self.epoch += 1
        self.batch_in_epoch = 0


class PrefetchLoader:
    """Double-buffered device prefetch over any batch iterator.

    The TPU input-pipeline analogue of the reference dataloader's pinned
    memory + worker prefetch (``DeepSpeedDataLoader(pin_memory=...,
    num_local_io_workers=...)``): while step ``t`` computes, batch ``t+1`` is
    already being transferred host->device asynchronously (``jax.device_put``
    returns immediately; the copy overlaps the running computation). With a
    sharding, leaves land directly in their dispatch layout so the engine's
    jit does no re-placement.

    ``depth`` batches are kept in flight (2 = classic double buffering;
    remote-attached TPUs with long H2D RTTs benefit from 3-4).

    Re-iterability and ``len()`` follow the WRAPPED loader: a list or
    ``DeepSpeedDataLoader`` gives a sized, re-iterable prefetcher; a one-shot
    generator gives a one-shot prefetcher whose ``len()`` raises (same
    ``TypeError`` the generator itself would).
    """

    def __init__(self, loader: Iterable, sharding=None, depth: int = 2):
        self.loader = loader
        self.sharding = sharding
        self.depth = max(1, int(depth))
        self._inflight = 0  # batches drawn from the wrapped loader, not yet yielded

    # -- resumable data stream: delegate, corrected for prefetch depth ---
    def state_dict(self) -> dict:
        """Wrapped-loader state at the CONSUMED position: batches sitting in
        the prefetch queue were drawn but never reached the trainer, so the
        wrapped position is rolled back by the in-flight count (wrapping an
        epoch boundary when needed)."""
        inner = getattr(self.loader, "state_dict", None)
        if inner is None:
            raise TypeError("PrefetchLoader wraps a loader without "
                            "state_dict(); wrap a DeepSpeedDataLoader for "
                            "resumable iteration")
        state = dict(inner())
        bi = int(state.get("batch_in_epoch", 0)) - self._inflight
        gs = int(state.get("global_step", 0)) - self._inflight
        if bi < 0:
            state["epoch"] = int(state["epoch"]) - 1
            bi += len(self.loader)
        state["batch_in_epoch"] = bi
        state["global_step"] = max(0, gs)
        return state

    def load_state_dict(self, state: dict) -> None:
        self.loader.load_state_dict(state)
        self._inflight = 0

    def _put(self, batch):
        if self.sharding is None:
            return jax.tree.map(jax.device_put, batch)
        return jax.tree.map(lambda x: jax.device_put(x, self.sharding), batch)

    def __iter__(self):
        import collections

        queue = collections.deque()
        it = iter(self.loader)
        self._inflight = 0
        try:
            for _ in range(self.depth):
                queue.append(self._put(next(it)))
                self._inflight += 1
        except StopIteration:
            pass
        while queue:
            out = queue.popleft()
            try:
                queue.append(self._put(next(it)))
                self._inflight += 1
            except StopIteration:
                pass
            self._inflight -= 1
            yield out

    def __len__(self):
        try:
            return len(self.loader)
        except TypeError:
            raise TypeError("PrefetchLoader wraps an unsized iterator; "
                            "wrap a sized loader (list, DeepSpeedDataLoader) "
                            "if len() is needed") from None


def _default_collate(items):
    first = items[0]
    if isinstance(first, dict):
        return {k: np.stack([it[k] for it in items]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(np.stack([it[i] for it in items]) for i in range(len(first)))
    return np.stack(items)


def _truncate_seq(batch, seqlen: int):
    def trunc(x):
        if hasattr(x, "ndim") and x.ndim >= 2 and x.shape[1] > seqlen:
            return x[:, :seqlen]
        return x

    return jax.tree.map(trunc, batch)
