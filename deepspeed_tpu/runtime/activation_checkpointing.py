"""Activation checkpointing API (reference ``deepspeed.checkpointing``).

Reference: ``deepspeed/runtime/activation_checkpointing/checkpointing.py`` —
``configure()`` + ``checkpoint()`` wrap Megatron-style activation
checkpointing (CPU checkpointing, partitioned activations across MP ranks,
contiguous buffers, RNG state tracking).

TPU-native mapping: rematerialization IS ``jax.checkpoint`` — XLA re-runs the
wrapped computation in the backward pass; there is no autograd tape, no RNG
state to save/restore (threefry keys are pure inputs), and "partitioned
activations" falls out of the mesh sharding of whatever the wrapped function
produces. ``configure()`` therefore only selects a rematerialization POLICY
(which intermediates may be kept) and records the knob vocabulary for
``ds_report``-style introspection; the storage-tier knobs the reference uses
to shuffle activations to CPU are handled by the engine's offload states API
instead.
"""

from typing import Any, Callable, Optional

import jax

_config = {
    "partition_activations": False,
    "contiguous_memory_optimization": False,
    "cpu_checkpointing": False,
    "number_checkpoints": None,
    "synchronize_checkpoint_boundary": False,
    "profile": False,
    "policy": None,
}
_configured = False
_KEYS = ("partition_activations", "cpu_checkpointing",
         "contiguous_memory_optimization", "number_checkpoints",
         "synchronize_checkpoint_boundary", "profile", "policy")


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None,
              policy: Optional[str] = None):
    """Record the reference knob vocabulary and pick a remat policy.

    ``policy`` names a ``jax.checkpoint_policies`` entry (e.g.
    ``"dots_saveable"``, ``"nothing_saveable"``,
    ``"save_anything_except_these_names"`` callers should pass a policy
    object instead). The storage knobs are accepted for config compatibility;
    on TPU their work is done by XLA (rematerialization) and the engine
    offload tiers, so they do not change the compiled program here.
    """
    if deepspeed_config is not None:
        act = getattr(deepspeed_config, "activation_checkpointing", None)
        if isinstance(deepspeed_config, dict):
            act = deepspeed_config.get("activation_checkpointing")
        if act is not None and not isinstance(act, dict):
            act = {f: getattr(act, f) for f in _KEYS if hasattr(act, f)}
        if act:
            for key in _KEYS:
                if key in act and act[key] is not None:
                    _config[key] = act[key]
    for key, val in (("partition_activations", partition_activations),
                     ("contiguous_memory_optimization", contiguous_checkpointing),
                     ("number_checkpoints", num_checkpoints),
                     ("cpu_checkpointing", checkpoint_in_cpu),
                     ("synchronize_checkpoint_boundary", synchronize),
                     ("profile", profile), ("policy", policy)):
        if val is not None:
            _config[key] = val
    global _configured
    _configured = True


def is_configured() -> bool:
    """Whether :func:`configure` has run (reference lazy-config idiom:
    ``if not is_configured(): configure(...)``)."""
    return _configured


def get_config() -> dict:
    return dict(_config)


def checkpoint(function: Callable, *args) -> Any:
    """Reference ``checkpointing.checkpoint(fn, *args)``: run ``fn`` now,
    rematerialize its intermediates in the backward pass.

    With ``cpu_checkpointing`` (reference ``checkpoint_in_cpu``) and no
    explicit policy, saved dot-product activations are OFFLOADED to pinned
    host memory instead of kept in HBM
    (``jax.checkpoint_policies.offload_dot_with_no_batch_dims``) — the true
    analogue of the reference's CPU-checkpointing storage tier, not just a
    recorded knob."""
    policy = _config.get("policy")
    if policy is None and _config.get("cpu_checkpointing"):
        policy = jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            "device", "pinned_host")
    return checkpoint_wrapper(function, policy)(*args)


def model_parallel_reconfigure_tp_seed(seed):
    """Reference ``model_parallel_reconfigure_tp_seed`` reseeds a hidden
    per-TP-rank RNG stream so dropout differs across ranks. JAX RNG is
    functional — there is NO global stream this function could mutate, so the
    caller MUST thread the returned key (the reference's call-for-side-effect
    idiom cannot work here and would silently de-correlate nothing). Inside
    ``shard_map`` over a 'tp' axis the key is folded with the rank's axis
    index; outside, the base key is returned."""
    key = jax.random.PRNGKey(seed)
    try:
        return jax.random.fold_in(key, jax.lax.axis_index("tp"))
    except NameError:  # not inside a mapped 'tp' axis
        return key


def checkpoint_wrapper(function: Callable, policy: Optional[Any] = None):
    """Return a remat-wrapped callable (decorator form)."""
    if isinstance(policy, str):
        policy = getattr(jax.checkpoint_policies, policy)
    return jax.checkpoint(function, policy=policy) if policy is not None \
        else jax.checkpoint(function)
