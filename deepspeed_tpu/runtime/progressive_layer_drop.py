"""Progressive layer drop (PLD).

Reference ``ProgressiveLayerDrop`` (``runtime/progressive_layer_drop.py:40``;
engine hook ``engine.py:348``): keep probability theta(t) anneals from 1
toward ``theta`` with rate ``gamma``; deeper layers drop more (the i/L
scaling of the PLD paper). ``pld_apply`` wraps a residual layer with the
stochastic skip; at eval the layer always runs (outputs are scaled during
training so eval needs no rescale, inverted-dropout style).
"""

import math
from typing import Callable

import jax
import jax.numpy as jnp


class ProgressiveLayerDrop:
    @classmethod
    def from_config(cls, pld) -> "ProgressiveLayerDrop":
        """Build from the top-level ``progressive_layer_drop`` config node."""
        return cls(theta=pld.theta, gamma=pld.gamma)

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def get_theta(self, global_step: int) -> float:
        """theta(t) = (1 - theta_min) * exp(-gamma t) + theta_min."""
        return (1.0 - self.theta) * math.exp(-self.gamma * global_step) + self.theta

    def update_state(self, global_step: int) -> float:
        self.current_theta = self.get_theta(global_step)
        return self.current_theta

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.current_theta}

    def keep_prob(self, layer_idx: int, num_layers: int) -> float:
        """Deeper layers drop more: p_i = 1 - i/L * (1 - theta(t))."""
        return 1.0 - (layer_idx / max(1, num_layers)) * (1.0 - self.current_theta)


def pld_apply(layer_fn: Callable, x: jnp.ndarray, rng, keep_prob: float,
              deterministic: bool = False) -> jnp.ndarray:
    """Stochastic residual-layer skip: with prob ``1-keep_prob`` the layer's
    contribution is dropped; kept contributions are scaled by 1/keep_prob so
    eval (always-on) needs no rescaling."""
    residual = layer_fn(x) - x  # layer contribution (layer_fn includes +x)
    if deterministic or keep_prob >= 1.0:
        return x + residual
    keep = jax.random.bernoulli(rng, keep_prob)
    return x + jnp.where(keep, residual / keep_prob, jnp.zeros_like(residual))
