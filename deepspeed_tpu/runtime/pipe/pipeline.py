"""Pipeline parallelism, TPU-native.

Reference: ``PipelineModule``/``PipelineEngine``
(``runtime/pipe/module.py:86``, ``engine.py:61``) run an imperative 1F1B
instruction schedule (``schedule.py:189``) with eager NCCL p2p sends between
stage processes. Under XLA there is no eager p2p: the whole pipeline is one
SPMD program over the ``pp`` mesh axis in which activations circulate via
``lax.ppermute`` — microbatch ``m`` occupies stage ``s`` at step ``m + s``,
giving the same fill/drain bubble as GPipe (``(P-1)/M`` overhead), and
reverse-mode autodiff of the circulating loop *is* the backward pipeline, so
1F1B-style interleaving falls out of XLA's schedule rather than an
instruction list.

Composition: the engine's gradient-accumulation microbatches become the
pipeline microbatches (as in the reference, where ``train_batch`` consumes
``gas`` microbatches through the pipe).

Weight layout: per-layer params stacked on a leading dim, reshaped
``[P, L/P, ...]`` and sharded over ``pp`` — each stage holds only its layers
(the analogue of ``PipelineModule`` partitioning). Tied embeddings: the
embed/head params live replicated over ``pp``; their gradient contributions
are psum'd over the axis, which is exactly the reference's tied-weight
allreduce (``_exec_reduce_tied_grads``, pipe/engine.py:275).
"""

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ...parallel.topology import PP_AXIS, get_topology


def partition_balanced(weights, num_parts: int):
    """Greedy prefix-sum balance of layer weights into contiguous parts
    (reference ``partition_balanced``, ``runtime/utils.py:583``). Returns
    part boundaries [num_parts + 1]."""
    weights = np.asarray(weights, np.float64)
    if num_parts > len(weights):
        raise ValueError(f"cannot split {len(weights)} layers into {num_parts} parts")
    total = weights.sum()
    cum = np.concatenate([[0.0], np.cumsum(weights)])
    bounds = [0]
    for p in range(1, num_parts):
        target = total * p / num_parts
        idx = int(np.searchsorted(cum, target))
        idx = max(bounds[-1] + 1, min(idx, len(weights) - (num_parts - p)))
        bounds.append(idx)
    bounds.append(len(weights))
    return bounds


def spmd_pipeline(stage_fn: Callable, stage_params: Any, microbatches: jnp.ndarray,
                  *, last_stage_fn: Optional[Callable] = None,
                  first_stage_fn: Optional[Callable] = None,
                  extra_params: Any = None, virtual_stages: int = 1):
    """Run the circulating-microbatch pipeline. Call INSIDE shard_map over pp.

    stage_fn(stage_params, x) -> x            applied at every stage
    first_stage_fn(extra, mb) -> x            stage 0 input transform (embed)
    last_stage_fn(extra, x, mb) -> per-mb output (e.g. loss scalar)
    microbatches: [M, ...] (replicated across pp)

    ``virtual_stages=v > 1`` is the interleaved schedule (Megatron's
    virtual-pipeline / the reference's ``1f1b`` bubble-reduction goal,
    ``schedule.py:189``): every rank holds ``v`` NON-adjacent layer chunks
    (``stage_params`` leaves lead with ``[v]``; chunk ``c`` of stage ``s``
    covers global layers ``c*p .. c*p + 1`` blocks) and each activation laps
    the ring ``v`` times. Bubble shrinks from ``(p-1)/m`` to ``(p-1)/(v*m)``
    at the cost of ``v``x ppermute latency — on ICI the permutes are
    near-free, so deeper models win. Requires ``m % p == 0`` (microbatches
    run in waves of ``p``).

    Returns [M, ...] of last-stage outputs (psum'd over pp so every rank holds
    them).
    """
    stage = lax.axis_index(PP_AXIS)
    from ...utils.shard_map_compat import axis_size

    n_stages = axis_size(PP_AXIS)
    v = int(virtual_stages)
    m = jax.tree.leaves(microbatches)[0].shape[0]
    if v > 1 and m % n_stages:
        raise ValueError(f"interleaved schedule needs microbatches ({m}) "
                         f"divisible by stages ({n_stages})")
    total = m * v + n_stages - 1

    def chunk_params(c):
        if v == 1:
            return stage_params
        return jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, c, 0, keepdims=False),
            stage_params)

    def embed(mb):
        return first_stage_fn(extra_params, mb) if first_stage_fn else mb

    x0 = embed(jax.tree.map(lambda a: a[0], microbatches))
    buf_shape = jax.eval_shape(lambda p, x: stage_fn(p, x), chunk_params(0), x0)
    recv = jnp.zeros(buf_shape.shape, buf_shape.dtype)

    def head(x, mb):
        return last_stage_fn(extra_params, x, mb) if last_stage_fn else x

    out0 = jax.eval_shape(head, recv, jax.tree.map(lambda a: a[0], microbatches))
    outputs = jnp.zeros((m,) + out0.shape, out0.dtype)

    def step(t, carry):
        recv, outputs = carry
        # schedule position: rank `stage` at time t works on lap (chunk) c of
        # microbatch i — waves of p microbatches, v laps per wave
        u = t - stage
        valid = (u >= 0) & (u < m * v)
        uc = jnp.clip(u, 0, m * v - 1)
        wave = uc // (n_stages * v)
        r = uc % (n_stages * v)
        c = r // n_stages
        i = jnp.clip(r % n_stages + wave * n_stages, 0, m - 1)
        mb = jax.tree.map(lambda a: a[i], microbatches)
        x_in = jnp.where((stage == 0) & (c == 0),
                         embed(mb).astype(recv.dtype),
                         recv)
        y = stage_fn(chunk_params(c), x_in)
        # last stage emits microbatch i after its final lap
        is_emitting = (stage == n_stages - 1) & (c == v - 1) & valid
        o = head(y, mb)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(is_emitting, o, outputs[i]), i, 0)
        # circulate: stage s -> s+1 (stage p-1's send starts the next lap at
        # stage 0; after the final lap it is discarded there)
        recv = lax.ppermute(y, PP_AXIS,
                            [(j, (j + 1) % n_stages) for j in range(n_stages)])
        return recv, outputs

    recv, outputs = lax.fori_loop(0, total, step, (recv, outputs))
    # every rank returns the outputs: only the last stage's slots are real;
    # psum with masking broadcasts them (tied-grad allreduce in reverse-mode)
    outputs = jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(outputs, PP_AXIS)


def resolve_partition(num_layers: int, num_stages: int, partition_method: str,
                      layer_costs=None):
    """Consume the reference ``partition_method`` knob (``module.py:86``,
    ``partition_balanced`` ``utils.py:583``) under the SPMD constraint that
    every stage runs the same program (equal layer counts).

    ``uniform`` splits evenly; ``parameters`` balances ``layer_costs`` (per
    -layer parameter counts; homogeneous stacked blocks make these equal, so
    the balanced split IS the uniform one) — if the costs are so skewed that
    the balanced boundaries are non-uniform, that's unexpressible in the
    stacked-SPMD layout and we fail loudly rather than silently unbalance.
    """
    if num_layers % num_stages:
        raise ValueError(f"num_layers={num_layers} must divide into {num_stages} stages")
    per = num_layers // num_stages
    uniform = list(range(0, num_layers + 1, per))
    if partition_method in ("uniform", None):
        return uniform
    if partition_method == "parameters":
        costs = layer_costs if layer_costs is not None else [1.0] * num_layers
        bounds = partition_balanced(costs, num_stages)
        if bounds != uniform:
            raise ValueError(
                f"partition_method='parameters' balanced the layer costs to "
                f"boundaries {bounds}, but the SPMD pipeline stacks layers "
                f"[{num_stages}, {per}] and needs a uniform split {uniform}; "
                "heterogeneous per-stage layer counts are a per-process "
                "(GPU-style) layout — restructure the costs or use 'uniform'")
        return bounds
    raise ValueError(
        f"partition_method={partition_method!r} is not supported: the SPMD "
        "pipeline has no module graph to regex over (reference 'type:' "
        "matching); use 'uniform' or 'parameters'")


def interleave_pipeline_params(params: Any, num_stages: int,
                               virtual_stages: int) -> Any:
    """Re-layout stacked blocks ``[L, ...]`` for the interleaved schedule:
    ``[p, v, L/(p*v), ...]`` where chunk ``c`` of stage ``s`` holds global
    layers ``(c*p + s) * Lg ..`` (Megatron virtual-pipeline placement). Run
    ONCE at setup — storing the permuted layout is what keeps the per-step
    program free of weight resharding."""
    p, v = num_stages, virtual_stages
    L = jax.tree.leaves(params["blocks"])[0].shape[0]
    if L % (p * v):
        raise ValueError(f"{L} layers not divisible by stages*virtual={p * v}")
    lg = L // (p * v)

    def relayout(a):
        # [L] -> [v, p, lg] (chunk-major) -> [p, v, lg]
        a = a.reshape((v, p, lg) + a.shape[1:])
        return jnp.swapaxes(a, 0, 1)

    out = dict(params)
    out["blocks"] = jax.tree.map(relayout, params["blocks"])
    return out


def make_pipeline_loss_fn(embed_fn: Callable, block_fn: Callable, head_loss_fn: Callable,
                          *, num_layers: int, num_stages: int, num_microbatches: int,
                          partition_method: str = "uniform",
                          activation_checkpoint_interval: int = 0,
                          layer_costs=None, virtual_stages: int = 1,
                          tied_head: Optional[bool] = None):
    """Build an engine-compatible ``loss = f(params, batch)`` running an SPMD
    pipeline (the analogue of wrapping a model in ``PipelineModule``).

    params structure: {"embed": ..., "blocks": <stacked [L, ...]>, "head": ...}
    block_fn(block_params, x) -> x applies ONE layer given its [L]-indexed slice.
    ``activation_checkpoint_interval=k`` rematerializes activations every k
    layers within a stage (reference ``PipelineModule`` knob, ``module.py:86``).

    ``virtual_stages > 1`` selects the interleaved schedule; ``params`` must
    then hold blocks in the ``interleave_pipeline_params`` layout
    ``[p, v, L/(p*v), ...]``.

    ``tied_head=True`` (reference ``TiedLayerSpec``): ``head_loss_fn``
    receives the FULL extra tree ``{"embed": ..., "head": ...}`` instead of
    just the head params, so a tied lm head can re-read the embedding table;
    both stages' gradient contributions psum over pp via the replicated-input
    transpose (the reference's tied-weight allreduce). Default ``None``
    derives it from ``head_loss_fn._tied_head`` when the head declares one
    (the transformer bridge does), so the model flag and the calling
    convention cannot disagree; an explicit value that contradicts the
    declaration raises.
    """
    declared = getattr(head_loss_fn, "_tied_head", None)
    if tied_head is None:
        tied_head = bool(declared)
    elif declared is not None and bool(tied_head) != bool(declared):
        raise ValueError(
            f"tied_head={tied_head} contradicts head_loss_fn's declared "
            f"_tied_head={declared} (set by the transformer bridge from "
            "cfg.tie_embeddings) — drop the explicit argument")
    v = int(virtual_stages)
    resolve_partition(num_layers, num_stages * v, partition_method, layer_costs)
    layers_per_stage = num_layers // (num_stages * v)
    ack = activation_checkpoint_interval
    if ack and layers_per_stage % ack:
        raise ValueError(f"activation_checkpoint_interval={ack} must divide "
                         f"layers_per_stage={layers_per_stage}")

    def stage_fn(stage_blocks, x):
        def body(x, layer_params):
            return block_fn(layer_params, x), None

        if ack:
            # remat groups of `ack` layers: forward stores only group
            # boundaries, backward recomputes within each group
            def group(x, group_params):
                y, _ = lax.scan(body, x, group_params)
                return y

            def outer(x, group_params):
                return jax.checkpoint(group)(x, group_params), None

            grouped = jax.tree.map(
                lambda a: a.reshape((layers_per_stage // ack, ack) + a.shape[1:]),
                stage_blocks)
            y, _ = lax.scan(outer, x, grouped)
            return y
        y, _ = lax.scan(body, x, stage_blocks)
        return y

    def loss_fn(params, batch):
        topo = get_topology()
        if topo.pp_size != num_stages:
            raise ValueError(
                f"pipeline was built for {num_stages} stages but the mesh has "
                f"pp={topo.pp_size}; a mismatch would silently drop layers")
        mesh = topo.mesh
        dp = topo.dp_axes

        def split_mb(leaf):
            b = leaf.shape[0]
            if b % num_microbatches:
                raise ValueError(f"batch {b} not divisible by {num_microbatches} microbatches")
            return leaf.reshape((num_microbatches, b // num_microbatches) + leaf.shape[1:])

        mbs = jax.tree.map(split_mb, batch)

        if v == 1:
            def reshape_blocks(leaf):
                return leaf.reshape((num_stages, layers_per_stage) + leaf.shape[1:])

            blocks = jax.tree.map(reshape_blocks, params["blocks"])
        else:
            blocks = params["blocks"]  # pre-permuted [p, v, lg, ...]
            lead = jax.tree.leaves(blocks)[0].shape[:3]
            if lead != (num_stages, v, layers_per_stage):
                raise ValueError(
                    f"interleaved pipeline expects blocks laid out "
                    f"[{num_stages}, {v}, {layers_per_stage}, ...] (see "
                    f"interleave_pipeline_params); got leading dims {lead}")

        def pipe_body(blocks_, embed_, head_, mbs_):
            last = head_loss_fn if tied_head \
                else (lambda extra, x, mb: head_loss_fn(extra["head"], x, mb))
            losses = spmd_pipeline(
                stage_fn, jax.tree.map(lambda a: a[0], blocks_), mbs_,
                first_stage_fn=lambda extra, mb: embed_fn(extra["embed"], mb),
                last_stage_fn=last,
                extra_params={"embed": embed_, "head": head_},
                virtual_stages=v)
            # per-mb losses are local-batch-shard means; average over dp here
            # (the grads' dp reduction follows from reverse-mode of this pmean)
            return lax.pmean(losses, dp)

        blocks_spec = jax.tree.map(lambda _: P(PP_AXIS), blocks)  # spec-ok: pipeline shard_map wiring: stage-major blocks
        rep = jax.tree.map(lambda _: P(), params["embed"])  # spec-ok: pipeline shard_map wiring: embed replicates
        rep_h = jax.tree.map(lambda _: P(), params["head"])  # spec-ok: pipeline shard_map wiring: head replicates
        mb_spec = jax.tree.map(lambda _: P(None, dp), mbs)  # spec-ok: pipeline shard_map wiring: microbatch over dp
        # ALL mesh axes manual: grad-of-checkpoint inside a partial shard_map
        # emits residual specs over the auto axes and trips the out_specs
        # check; unused axes (sp/tp here) just see replicated values
        from ...utils.shard_map_compat import shard_map_nocheck_manual

        losses = shard_map_nocheck_manual(
            pipe_body, mesh,
            in_specs=(blocks_spec, rep, rep_h, mb_spec),
            out_specs=P(),  # spec-ok: pipeline shard_map wiring: scalar loss out
            axis_names=set(mesh.axis_names))(
                blocks, params["embed"], params["head"], mbs)
        return jnp.mean(losses)

    # metadata for initialize() to cross-check against PipelineConfig
    loss_fn._pipeline_meta = {"num_stages": num_stages,
                              "num_microbatches": num_microbatches,
                              "num_layers": num_layers,
                              "virtual_stages": v,
                              "tied_head": tied_head}
    return loss_fn


def from_pipeline_config(embed_fn, block_fn, head_loss_fn, *, num_layers: int,
                         config, layer_costs=None, tied_head: Optional[bool] = None):
    """Build the pipeline loss from a DeepSpeedTPUConfig (wires the reference
    config keys: ``pipeline.stages``, ``pipeline.micro_batches`` with the
    reference default of ``gradient_accumulation_steps``,
    ``partition_method``, ``activation_checkpoint_interval``)."""
    pc = config.pipeline
    if pc.schedule not in ("gpipe", "interleaved"):
        raise ValueError(
            f"pipeline.schedule={pc.schedule!r}: the SPMD pipeline runs ONE "
            "circulating program and reverse-mode autodiff interleaves "
            "fwd/bwd under XLA's scheduler — there is no instruction list to "
            "reorder, so '1f1b' is not a separate schedule here. Use "
            "'gpipe', or 'interleaved' (+ pipeline.virtual_stages >= 2) for "
            "the Megatron virtual-stage bubble reduction")
    v = getattr(pc, "virtual_stages", 1) or 1
    if pc.schedule == "interleaved" and v < 2:
        raise ValueError("schedule='interleaved' needs pipeline.virtual_stages >= 2")
    if pc.schedule == "gpipe" and v > 1:
        raise ValueError(
            f"pipeline.virtual_stages={v} has no effect under schedule="
            "'gpipe' — set schedule='interleaved' to enable the virtual-"
            "stage bubble reduction (silently ignoring the knob would run "
            "the full (p-1)/m bubble the user tried to shrink)")
    micro = pc.micro_batches or config.gradient_accumulation_steps or 1
    return make_pipeline_loss_fn(
        embed_fn, block_fn, head_loss_fn, num_layers=num_layers,
        num_stages=pc.stages, num_microbatches=micro,
        partition_method=pc.partition_method,
        activation_checkpoint_interval=pc.activation_checkpoint_interval,
        layer_costs=layer_costs, virtual_stages=v, tied_head=tied_head)


def pipeline_param_specs(params, topo=None) -> Any:
    """PartitionSpec tree for pipeline params: blocks sharded over pp on the
    stacked dim, embed/head replicated (ZeRO adds dp sharding on top)."""
    if topo is not None:
        n_layers = jax.tree.leaves(params["blocks"])[0].shape[0]
        if n_layers % topo.pp_size:
            raise ValueError(f"{n_layers} layers not divisible by pp={topo.pp_size}")
    return {
        "embed": jax.tree.map(lambda _: None, params["embed"]),
        "blocks": jax.tree.map(lambda p: P(PP_AXIS) if p.ndim >= 1 else P(),  # spec-ok: pipeline base specs: stage-major blocks else replicated
                               params["blocks"]),
        "head": jax.tree.map(lambda _: None, params["head"]),
    }
