"""Hybrid engine: one engine flipping between training and generation (RLHF).

Reference ``DeepSpeedHybridEngine`` (``runtime/hybrid_engine.py:30``):
``generate:168`` gathers ZeRO-3 params into injected inference kernels,
``eval:376``/``train:418`` flip modes, LoRA is fused for generation and
unfused for training. TPU-native: training state (fp32 master, ZeRO
shardings) and the inference program (compute dtype, TP shardings) are two
*views* of one parameter pytree — mode flips are a cast + ``device_put``
resharding collective, not module surgery. The actor's RLHF loop is:

    engine.train_batch(...)        # ZeRO-sharded training step
    out = engine.generate(prompts) # inference view of the CURRENT weights
"""

import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ..inference.config import DeepSpeedInferenceConfig
from ..models.transformer import TransformerLM
from ..utils.logging import log_dist
from .engine import DeepSpeedTPUEngine


def lm_loss_fn(model: TransformerLM) -> Callable:
    """Next-token cross-entropy for a ``TransformerLM`` (the default actor
    loss; RLHF losses wrap/replace this)."""
    def loss_fn(params, batch, rng=None):
        tokens = batch["tokens"] if isinstance(batch, dict) else batch
        logits = model.apply({"params": params}, tokens[:, :-1])
        targets = tokens[:, 1:]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return jnp.mean(nll)

    return loss_fn


class DeepSpeedHybridEngine(DeepSpeedTPUEngine):
    """Training engine + on-demand generation over the live weights."""

    def __init__(self, model: TransformerLM, params: Any, config,
                 loss_fn: Optional[Callable] = None,
                 inference_config: Optional[DeepSpeedInferenceConfig] = None,
                 lora_config=None, lora_fused_generate: bool = False, **kw):
        self._model = model
        self._lora_fused = lora_fused_generate
        self._lora_config = lora_config
        if lora_fused_generate and lora_config is None:
            raise ValueError("lora_fused_generate needs lora_config "
                             "(its alpha/r scales the fusion)")
        self._infer = None
        self._training = True
        self.generate_time = 0.0
        self.generate_count = 0
        from .config import load_config

        cfg = load_config(config)
        if inference_config is None:
            inference_config = DeepSpeedInferenceConfig()
            he = cfg.hybrid_engine
            if he.enabled:
                # the reference hybrid_engine JSON section shapes the default
                # inference view (runtime/config.py:544) — only when enabled
                inference_config.max_out_tokens = he.max_out_tokens
                if he.inference_tp_size > 1:
                    inference_config.tensor_parallel.enabled = True
                    inference_config.tensor_parallel.tp_size = he.inference_tp_size
        self._inference_config = inference_config
        super().__init__(loss_fn=loss_fn or lm_loss_fn(model), params=params,
                         config=cfg, **kw)

    # mode flips (reference eval:376 / train:418) -----------------------
    def train(self, mode: bool = True):
        self._training = mode
        return self

    def eval(self):
        return self.train(False)

    @property
    def is_training(self) -> bool:
        return self._training

    # ------------------------------------------------------------------
    def _inference_engine(self):
        if self._infer is None:
            from ..inference.engine import InferenceEngine

            self._infer = InferenceEngine(self._model, self._inference_params(),
                                          self._inference_config)
            log_dist("hybrid engine: inference view initialized "
                     f"(tp={self._infer.topo.tp_size})")
        return self._infer

    def _inference_params(self):
        params = self.state.params
        if self._lora_fused:
            from ..linear import fuse_lora

            lc = self._lora_config
            # fuse_lora is pure jnp — stays on device, no host round-trip
            params = fuse_lora(params, lc.lora_alpha / lc.lora_r)
        return params

    def _refresh_inference_params(self):
        """Push the CURRENT training weights into the inference view: cast to
        the inference dtype and reshard onto the inference topology (a
        collective, the analogue of the reference's param gather,
        ``hybrid_engine.py:generate:168``). Skipped when no train step has
        happened since the last refresh."""
        if getattr(self, "_refreshed_at_step", None) == self.global_steps:
            return
        inf = self._inference_engine()
        params = self._inference_params()
        self._refreshed_at_step = self.global_steps
        dtype = self._inference_config.jnp_dtype
        cast = jax.tree.map(
            lambda x: x.astype(dtype) if jnp.issubdtype(
                jnp.asarray(x).dtype, jnp.floating) else x, params)
        inf.params = jax.device_put(cast, inf._param_shardings)

    def generate(self, tokens, prompt_lengths=None, max_new_tokens=None, **kw):
        """Generate with the live weights (reference ``generate:168``)."""
        t0 = time.perf_counter()
        self._refresh_inference_params()
        out = self._inference_engine().generate(
            tokens, prompt_lengths=prompt_lengths,
            max_new_tokens=max_new_tokens, **kw)
        self.generate_time = time.perf_counter() - t0
        self.generate_count += 1
        return out

    def forward_logits(self, tokens):
        """Full-sequence logits under the inference view (reward/critic
        scoring in RLHF loops)."""
        self._refresh_inference_params()
        return self._inference_engine().forward(tokens)
