"""Typed configuration tree.

TPU-native re-design of the reference's JSON config system
(``runtime/config.py:706`` ``DeepSpeedConfig`` and the per-subsystem pydantic
models). Keeps the same knob vocabulary — ``train_batch_size``,
``train_micro_batch_size_per_gpu``, ``gradient_accumulation_steps``,
``optimizer``, ``scheduler``, ``fp16``/``bf16``, ``zero_optimization``,
``gradient_clipping``, ``pipeline``, ``moe``, ``sequence_parallel_size``,
``tensor_parallel`` — so a DeepSpeed JSON config ports with minimal edits.
"""

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Union

from .config_utils import AUTO, ConfigError, ConfigModel, register_config
from ..utils.logging import logger

# ---------------------------------------------------------------------------
# Precision
# ---------------------------------------------------------------------------


@register_config
@dataclass
class FP16Config(ConfigModel):
    """fp16 + dynamic loss scaling (reference ``runtime/fp16/loss_scaler.py:91``)."""
    enabled: bool = False
    loss_scale: float = 0.0  # 0 => dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0
    auto_cast: bool = False


@register_config
@dataclass
class BF16Config(ConfigModel):
    enabled: bool = False
    # keep fp32 master weights + fp32 grad accumulation (reference bf16_optimizer.py:34)
    master_weights: bool = True

    # loss-scaling keys copied from an fp16 section are meaningless under
    # bf16 (fp32 exponent range — no overflow to scale around); the
    # reference tolerates them in configs (tests/torch_compile/ds_config),
    # so accept-and-drop rather than reject
    _DEPRECATED_KEYS = {k: None for k in
                        ("loss_scale", "initial_scale_power",
                         "loss_scale_window", "hysteresis",
                         "min_loss_scale", "consecutive_hysteresis",
                         "fp16_master_weights_and_grads", "auto_cast")}


# ---------------------------------------------------------------------------
# Optimizer / scheduler
# ---------------------------------------------------------------------------


@register_config
@dataclass
class OptimizerConfig(ConfigModel):
    type: str = "adamw"
    params: Dict[str, Any] = field(default_factory=dict)


@register_config
@dataclass
class SchedulerConfig(ConfigModel):
    type: Optional[str] = None
    params: Dict[str, Any] = field(default_factory=dict)


# ---------------------------------------------------------------------------
# ZeRO
# ---------------------------------------------------------------------------


@register_config
@dataclass
class OffloadOptimizerConfig(ConfigModel):
    """Reference ``runtime/zero/offload_config.py``. ``device`` in {none,cpu,nvme}."""
    device: str = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    pin_memory: bool = True
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    ratio: float = 1.0
    # D2H gradient transport dtype for the host-Adam tier. The reference
    # ZeRO-Offload ships the compute-dtype (fp16/bf16) grads to the CPU
    # optimizer (zero/stage_1_and_2.py copy_grads_in_partition); "bfloat16"
    # matches that and halves the host-link bytes. Accumulation and the
    # grad-norm/clip math stay fp32 on device; only the final transfer
    # narrows. "float32" (default) keeps full-width transport.
    grad_dtype: str = "float32"


@register_config
@dataclass
class OffloadParamConfig(ConfigModel):
    device: str = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    max_in_cpu: int = 1_000_000_000
    pin_memory: bool = True


@register_config
@dataclass
class ZeroConfig(ConfigModel):
    """ZeRO knobs (reference ``runtime/zero/config.py:84``).

    On TPU the stages lower to sharding rules over the ``dp`` mesh axis:
      stage 0 — replicate everything, psum grads
      stage 1 — shard optimizer state
      stage 2 — + reduce_scatter grads (grads materialized sharded)
      stage 3 — + shard parameters, allgather-on-use
    """
    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = 500_000_000
    allgather_partitions: bool = True
    allgather_bucket_size: int = 500_000_000
    overlap_comm: bool = True
    round_robin_gradients: bool = False
    offload_optimizer: OffloadOptimizerConfig = field(default_factory=OffloadOptimizerConfig)
    offload_param: OffloadParamConfig = field(default_factory=OffloadParamConfig)
    sub_group_size: int = 1_000_000_000
    stage3_max_live_parameters: int = 1_000_000_000
    stage3_max_reuse_distance: int = 1_000_000_000
    stage3_prefetch_bucket_size: int = 50_000_000
    stage3_param_persistence_threshold: int = 100_000
    stage3_gather_16bit_weights_on_model_save: bool = False
    stage3_module_granularity_threshold: int = 0

    @classmethod
    def _migrate_legacy(cls, d):
        # pre-0.3.16 vocabulary (reference deprecated it the same way:
        # runtime/zero/config.py read_zero_config_deprecated)
        if d.pop("cpu_offload", False):
            off = dict(d.get("offload_optimizer") or {})
            off.setdefault("device", "cpu")
            d["offload_optimizer"] = off
        if d.pop("cpu_offload_params", False):
            offp = dict(d.get("offload_param") or {})
            offp.setdefault("device", "cpu")
            d["offload_param"] = offp
        pin = d.pop("cpu_offload_use_pin_memory", None)
        if pin is not None:
            for key in ("offload_optimizer", "offload_param"):
                if key in d:
                    node = dict(d[key])
                    node.setdefault("pin_memory", bool(pin))
                    d[key] = node
        return d
    # ZeRO++ (hpZ secondary shard / quantized weights / quantized gradients).
    # hpZ's no-second-gather guarantee is realized as a remat policy in the
    # explicit path: zeropp_train_step_factory(remat="hpz") saves gathered
    # weights across fwd->bwd (runtime/zero/zeropp.py hpz_remat_policy)
    zero_hpz_partition_size: int = 1
    zero_quantized_weights: bool = False
    zero_quantized_gradients: bool = False
    # MiCS-style sub-group sharding: shard params over groups of this size (<= dp size)
    mics_shard_size: int = -1
    mics_hierarchical_params_gather: bool = False
    elastic_checkpoint: bool = False
    ignore_unused_parameters: bool = True


# ---------------------------------------------------------------------------
# Parallel topology
# ---------------------------------------------------------------------------


@register_config
@dataclass
class PipelineConfig(ConfigModel):
    """Pipeline parallelism (reference ``runtime/pipe/``)."""
    stages: int = 1
    partition_method: str = "parameters"  # uniform | parameters | type:<regex>
    micro_batches: Optional[int] = None  # default = gradient_accumulation_steps
    activation_checkpoint_interval: int = 0
    # 'gpipe' or 'interleaved': the SPMD circulating pipeline has no
    # instruction list to reorder — 1F1B-style fwd/bwd interleaving is XLA's
    # scheduling job. 'interleaved' (+ virtual_stages) is the Megatron
    # virtual-pipeline bubble reduction: v layer chunks per stage, bubble
    # (p-1)/(v*m) instead of (p-1)/m.
    schedule: str = "gpipe"
    virtual_stages: int = 1


@register_config
@dataclass
class TensorParallelConfig(ConfigModel):
    """Training tensor parallelism (reference AutoTP / external mpu)."""
    enabled: bool = False
    tp_size: int = 1
    # latency-hiding collective matmul (ops/collective_matmul.py): run the
    # column/row-parallel linears, the Ulysses projection exchange, and the
    # exact ZeRO-3 gather/scatter as ppermute rings overlapped with the
    # partial matmuls (T3, arxiv 2401.16677). Ragged shapes fall back to
    # the declarative GSPMD composition per call site.
    overlap_collective_matmul: bool = False


@register_config
@dataclass
class CompressedCollectivesConfig(ConfigModel):
    """EQuARX-style quantized collectives (``comm/compressed.py``).

    ``mode``: ``none`` (default — every wired site stays the bit-identical
    exact path), ``int8`` (block-quantized payloads, nearest rounding), or
    ``int8_sr`` (stochastic rounding on gradient reductions — unbiased
    compression). Per-site toggles gate the four consumers independently;
    ``hierarchical`` switches the DP gradient all-reduce to the two-level
    form (inner mesh hop exact, outer hops quantized). Also accepted as a
    bare string: ``"compressed_collectives": "int8"``.
    """
    mode: str = "none"           # none | int8 | int8_sr
    block: int = 2048            # quantization block (elements per scale)
    hierarchical: bool = False
    # per-site toggles (only meaningful when mode != none)
    dp_gradients: bool = True    # engine DP gradient reduction
    zero_weights: bool = True    # ZeRO++ qwZ param gather
    zero_gradients: bool = True  # ZeRO++ qgZ gradient reduce-scatter
    moe_alltoall: bool = True    # MoE EP dispatch/combine exchange
    ulysses_alltoall: bool = True  # Ulysses head/sequence exchanges

    def site_map(self):
        return {"dp_gradients": self.dp_gradients,
                "zero_weights": self.zero_weights,
                "zero_gradients": self.zero_gradients,
                "moe": self.moe_alltoall,
                "ulysses": self.ulysses_alltoall}


@register_config
@dataclass
class TrainingFastpathConfig(ConfigModel):
    """Fused training hot path (``ops/fastpath.py`` fleet knobs).

    ``attn_impl``: ``auto`` (flash on a real accelerator for eligible
    shapes), ``flash`` (force the Pallas kernel; alibi/window sites warn
    once and fall back), or ``xla`` (the reference attention everywhere).
    ``loss_impl``: ``auto`` / ``fused`` (Pallas online-softmax LM loss,
    ``ops/pallas/fused_loss.py`` — the ``[B, S, V]`` logits tensor is never
    materialized) / ``xla``. ``embedding_overlap``: ``auto`` (planner
    decides per topology) / ``ring`` (ring-overlapped vocab-sharded
    embedding gather, ``ops/collective_matmul.py``) / ``xla``. Model-level
    ``TransformerConfig`` fields (non-auto) win over these fleet defaults;
    the all-``xla`` setting is bit-identical to the pre-fastpath tree.
    """
    attn_impl: str = "auto"          # auto | xla | flash
    loss_impl: str = "auto"          # auto | xla | fused
    embedding_overlap: str = "auto"  # auto | xla | ring


@register_config
@dataclass
class CommPlannerConfig(ConfigModel):
    """Collective planner (``comm/planner/``): topology-aware per-site
    selection of the PR1/PR2 fast paths.

    ``mode``: ``off`` (default — every wired site behaves bit-identically
    to a planner-less tree), ``static`` (alpha-beta cost model picks each
    site's implementation from the mesh fingerprint, deterministic), or
    ``measure`` (cost-model pruning then microbenchmarks pick the winner;
    results cache on disk keyed by mesh fingerprint so tuning runs once per
    topology). Explicitly-set raw knobs (``compressed_collectives``,
    ``overlap_collective_matmul``) always win at their sites. Also accepted
    as a bare string: ``"comm_planner": "static"``.

    ``dcn_axes`` force-marks mesh axes as cross-slice (DCN) in the planner's
    fingerprint — the multi-slice rehearsal knob: a single-host (or CPU)
    mesh plans exactly as the target fleet would (hierarchical multi-phase
    programs with int8+error-feedback DCN hops become eligible for the
    DP-grad site; see ``docs/multislice.md``). On a real multi-slice mesh
    leave it unset — DCN axes are detected from process boundaries.
    """
    mode: str = "off"            # off | static | measure
    cache_dir: Optional[str] = None  # default ~/.cache/deepspeed_tpu/comm_plans
    use_cache: bool = True
    margin: float = 3.0          # cost-model pruning margin (x best estimate)
    measure_reps: int = 4        # chained executions per timed probe
    measure_max_elems: int = 1 << 16  # probe tensor cap (elements)
    dcn_axes: Optional[List[str]] = None  # force-mark axes as DCN (simulation)
    # program-compiler beam width: how many searched multi-phase programs
    # survive slot pruning to compete with the flat impls (and, in measure
    # mode, get microbenched). None = compiler default.
    beam_width: Optional[int] = None
    # fused/chunked overlap credit override (0..0.95): the fraction of a
    # phase's wire time hidden behind the bound matmul tiles / the next
    # chunk's compute. None = the calibrated/compiled-in default; planners
    # can also measure it (CollectivePlanner.calibrate_overlap_credit).
    overlap_credit: Optional[float] = None


@register_config
@dataclass
class MoEConfig(ConfigModel):
    """Expert parallelism (reference ``deepspeed/moe/``)."""
    enabled: bool = False
    ep_size: int = 1
    num_experts: int = 1
    top_k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None  # None | 'Jitter' | 'RSample'
    drop_tokens: bool = True
    use_residual: bool = False
    aux_loss_weight: float = 0.01


# ---------------------------------------------------------------------------
# Diagnostics / aux subsystems
# ---------------------------------------------------------------------------


@register_config
@dataclass
class FlopsProfilerConfig(ConfigModel):
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 3
    detailed: bool = True
    output_file: Optional[str] = None


@register_config
@dataclass
class CommsLoggerConfig(ConfigModel):
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = field(default_factory=list)


@register_config
@dataclass
class TensorBoardConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJob"


@register_config
@dataclass
class WandbConfig(ConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed_tpu"


@register_config
@dataclass
class CSVConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJob"


@register_config
@dataclass
class CometConfig(ConfigModel):
    """Reference ``monitor/config.py`` CometConfig (monitor/comet.py:23)."""
    enabled: bool = False
    samples_log_interval: int = 100
    project: Optional[str] = None
    workspace: Optional[str] = None
    api_key: Optional[str] = None
    experiment_name: Optional[str] = None
    experiment_key: Optional[str] = None
    online: Optional[bool] = None
    mode: Optional[str] = None


@register_config
@dataclass
class MonitorConfig(ConfigModel):
    tensorboard: TensorBoardConfig = field(default_factory=TensorBoardConfig)
    wandb: WandbConfig = field(default_factory=WandbConfig)
    csv_monitor: CSVConfig = field(default_factory=CSVConfig)
    comet: CometConfig = field(default_factory=CometConfig)


@register_config
@dataclass
class ActivationCheckpointingConfig(ConfigModel):
    """Rematerialization knobs; maps to jax.checkpoint policies."""
    partition_activations: bool = False
    number_checkpoints: Optional[int] = None
    contiguous_memory_optimization: bool = False
    cpu_checkpointing: bool = False
    profile: bool = False
    # jax-native: remat policy name ('nothing_saveable','dots_saveable',...)
    policy: Optional[str] = None
    # apply ``policy`` as one jax.checkpoint wrap around the WHOLE loss at
    # the engine (the control plane's remat actuator / autotune 'remat'
    # dim). Opt-in: models using the per-layer compat API
    # (``deepspeed_tpu.checkpointing.checkpoint``) read the same ``policy``
    # field, and wrapping the engine on top would double-rematerialize.
    engine_wrap: bool = False


@register_config
@dataclass
class ElasticityConfig(ConfigModel):
    """Elastic batch config (reference ``elasticity/elasticity.py:233``)."""
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = field(default_factory=lambda: [2, 4, 6])
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    version: float = 0.2
    ignore_non_elastic_batch_info: bool = False
    prefer_larger_batch: bool = True


@register_config
@dataclass
class CompressionConfig(ConfigModel):
    """QAT / pruning knobs (reference ``compression/``)."""
    weight_quantization: Dict[str, Any] = field(default_factory=dict)
    activation_quantization: Dict[str, Any] = field(default_factory=dict)
    sparse_pruning: Dict[str, Any] = field(default_factory=dict)
    row_pruning: Dict[str, Any] = field(default_factory=dict)
    head_pruning: Dict[str, Any] = field(default_factory=dict)
    channel_pruning: Dict[str, Any] = field(default_factory=dict)
    layer_reduction: Dict[str, Any] = field(default_factory=dict)


@register_config
@dataclass
class DataEfficiencyConfig(ConfigModel):
    enabled: bool = False
    seed: int = 1234
    data_sampling: Dict[str, Any] = field(default_factory=dict)
    data_routing: Dict[str, Any] = field(default_factory=dict)


@register_config
@dataclass
class AutotuningConfig(ConfigModel):
    enabled: bool = False
    results_dir: str = "autotuning_results"
    exps_dir: str = "autotuning_exps"
    metric: str = "throughput"
    start_profile_step: int = 3
    end_profile_step: int = 5
    fast: bool = True
    max_train_batch_size: Optional[int] = None
    mp_size: int = 1
    num_tuning_micro_batch_sizes: int = 3
    tuner_type: str = "gridsearch"
    tuner_early_stopping: int = 5
    tuner_num_trials: int = 50
    # launcher-arg rewrites per tuned knob (reference autotuning docs);
    # consumed by the autotuner CLI when re-launching trials
    arg_mappings: Optional[Dict[str, str]] = None


@register_config
@dataclass
class SentinelConfig(ConfigModel):
    """Divergence sentinel (``runtime/resilience/sentinel.py``): NaN/inf-loss
    streaks and grad-norm spikes trip ``policy``."""
    enabled: bool = True          # within an enabled resilience block
    nan_streak: int = 3           # consecutive non-finite steps to trip
    spike_factor: float = 10.0    # grad_norm > factor * rolling median
    spike_streak: int = 2         # consecutive spike steps to trip
    spike_window: int = 64        # rolling-median history length
    min_history: int = 8          # samples before spike verdicts start
    policy: str = "rollback"      # rollback | warn | halt
    lr_drop_factor: float = 1.0   # <1.0 multiplies the LR on each rollback


@register_config
@dataclass
class PreemptionConfig(ConfigModel):
    """Preemption watcher (``runtime/resilience/preempt.py``)."""
    enabled: bool = True
    install_signal_handler: bool = True
    signals: List[str] = field(default_factory=lambda: ["SIGTERM"])
    probe_file: Optional[str] = None  # also honors $DSTPU_PREEMPT_FILE


@register_config
@dataclass
class FaultInjectionConfig(ConfigModel):
    """Deterministic fault harness (``runtime/resilience/faults.py``) —
    test/chaos-drill use only; every injection is off by default."""
    enabled: bool = False
    nan_loss_at_steps: List[int] = field(default_factory=list)
    grad_spike_at_steps: List[int] = field(default_factory=list)
    spike_magnitude: float = 1e6
    preempt_at_step: Optional[int] = None
    torn_write_at_steps: List[int] = field(default_factory=list)
    crash_before_commit_at_steps: List[int] = field(default_factory=list)
    hang_at_step: Optional[int] = None      # step wedges; watchdog must fire
    slow_rank: Optional[int] = None         # steady straggler rank
    slow_step_s: float = 0.25               # per-step sleep on slow_rank
    heartbeat_loss_at_steps: List[int] = field(default_factory=list)
    # silent-data-corruption drills (chaos classes sdc_bitflip_transient /
    # sdc_bitflip_sticky): flip ``sdc_bit`` of one param element on rank
    # ``sdc_rank`` — once at each listed step (transient) or on every step
    # from ``sdc_sticky_from_step`` (sticky host)
    sdc_transient_at_steps: List[int] = field(default_factory=list)
    sdc_sticky_from_step: Optional[int] = None
    sdc_rank: int = -1                      # -1 = every rank (single-rank tests)
    sdc_bit: int = 17                       # bit index flipped in the leaf


@register_config
@dataclass
class ChaosConfig(ConfigModel):
    """Full-stack chaos engine (``runtime/resilience/chaos.py``, see
    ``docs/fleet_robustness.md``): deterministic, seeded fault schedules
    across the transport layer (object-store heartbeat PUT/GET errors,
    torn beacons, plan-cache read errors, snapshot-commit I/O errors), the
    serving layer (replica kill, KV-pool exhaustion, slow prefill, dropped
    token delivery, fleet replica spawn failure, slow replica warm-up),
    and the control layer (stale health rows, flapping
    straggler verdicts) — drill/test use only. Disabled by default:
    nothing is constructed, every injection site is a single None check,
    and the stack is bitwise identical to a tree without the subsystem."""
    enabled: bool = False
    seed: int = 0
    # explicit deterministic schedule: [{kind, site, at, count, param}...]
    events: List[Dict[str, Any]] = field(default_factory=list)
    # seeded auto-generation: events_per_class arming indices per listed
    # fault class, drawn from random.Random(seed) over [0, horizon)
    classes: List[str] = field(default_factory=list)
    horizon: int = 64
    events_per_class: int = 1
    # training-layer injections (NaN loss, grad spikes, hang, ...) ride
    # along as the existing FaultPlan; the ResilienceManager adopts it
    # when resilience.faults itself is not enabled
    training: FaultInjectionConfig = field(
        default_factory=FaultInjectionConfig)


@register_config
@dataclass
class WatchdogConfig(ConfigModel):
    """Step watchdog (``runtime/resilience/watchdog.py``): a deadline
    derived from the rolling median step time; on expiry all-thread stacks
    are dumped to ``hangdump-<rank>.txt`` and the process exits with the
    distinctive watchdog code so the launcher restarts it."""
    enabled: bool = False
    factor: float = 8.0        # deadline = factor * rolling median step time
    floor_s: float = 30.0      # never below (short steps jitter)
    cap_s: float = 600.0       # never above (also the pre-history deadline)
    window: int = 32           # rolling-median history length
    dump_dir: Optional[str] = None  # default: resilience.snapshot_dir


@register_config
@dataclass
class HeartbeatConfig(ConfigModel):
    """Cross-host health beacons (``runtime/resilience/heartbeat.py``):
    per-host files in a shared dir carrying step/step-time; readers derive
    dead-host and straggler verdicts and emit Resilience/* events."""
    enabled: bool = False
    interval_steps: int = 1         # beacon (and table check) cadence
    dir: Optional[str] = None       # default: <snapshot_dir>/heartbeats
    dead_after_s: float = 60.0      # beacon older than this = dead host
    straggler_factor: float = 3.0   # step_time > k * fleet median


@register_config
@dataclass
class DegradedModeConfig(ConfigModel):
    """Degraded-mode collective fallback: after ``rollback_threshold``
    sentinel rollbacks within ``window_s`` seconds, the run drops every
    approximate collective (compressed int8 paths, planner decisions) back
    to exact XLA collectives. Persisted in snapshot meta so restarts
    inherit it; re-escalation only via operator action
    (``ResilienceManager.clear_degraded()``)."""
    enabled: bool = False
    rollback_threshold: int = 2
    window_s: float = 600.0


@register_config
@dataclass
class IntegrityConfig(ConfigModel):
    """Silent-corruption integrity tier
    (``runtime/resilience/integrity.py``, see ``docs/fleet_robustness.md``):
    periodic cross-rank fingerprints of DP-replicated state, shadow-step
    replay to call transient-vs-sticky, verified snapshot stamping, and SDC
    quarantine through the control supervisor's ``integrity`` rule.
    Disabled by default — nothing is constructed and stepping is bitwise
    identical to a tree without the subsystem."""
    enabled: bool = False
    interval_steps: int = 32        # fingerprint cadence (detection latency)
    chunks: int = 8                 # digest words; more = finer localization
    shadow_replay: bool = True      # replay-classify divergences
    resolve_timeout_steps: int = 8  # quorum / peer-verdict wait, in steps
    dir: Optional[str] = None       # fp exchange dir; default <snapshot_dir>/integrity
    rank: int = -1                  # -1 = engine artifact rank
    world: int = 0                  # voters expected; <2 = detect-only (no vote)
    quarantine: bool = True         # demote/replan around a sticky minority
    rollback: bool = True           # roll back to newest VERIFIED snapshot


@register_config
@dataclass
class ResilienceConfig(ConfigModel):
    """Resilience subsystem (``runtime/resilience/``): async snapshots,
    divergence sentinel with rollback, preemption drain, restore-on-restart.
    Disabled by default — the engine step is then bit-identical to a tree
    without the subsystem."""
    enabled: bool = False
    snapshot_dir: Optional[str] = None  # REQUIRED when enabled
    snapshot_interval: int = 100        # steps between cadence snapshots
    async_snapshot: bool = True         # background writer thread
    keep_snapshots: int = 2             # manifest entries retained
    shard_mb: int = 256                 # target checksummed-shard size
    restore_on_start: bool = True       # resume from latest valid at init
    sentinel: SentinelConfig = field(default_factory=SentinelConfig)
    preemption: PreemptionConfig = field(default_factory=PreemptionConfig)
    faults: FaultInjectionConfig = field(default_factory=FaultInjectionConfig)
    # fleet-robustness block (all off by default — stepping stays bit-identical)
    watchdog: WatchdogConfig = field(default_factory=WatchdogConfig)
    heartbeat: HeartbeatConfig = field(default_factory=HeartbeatConfig)
    degraded_mode: DegradedModeConfig = field(default_factory=DegradedModeConfig)
    integrity: IntegrityConfig = field(default_factory=IntegrityConfig)


@register_config
@dataclass
class AnalysisConfig(ConfigModel):
    """Static graph auditor (``deepspeed_tpu/analysis/``, see
    ``docs/static_analysis.md``): at ``engine.compile()`` time the staged
    train step is audited — unplanned collectives reconciled against the
    planner's plan table / comms ledger / jaxpr, precision leaks, donation
    misses, host-sync hazards — with findings logged as plan-table rows
    and ``Analysis/*`` monitor events.  Disabled by default: nothing runs
    and the compiled program is bit-identical (the audit never edits the
    program either way — it only reads it).  Also accepted as a bare bool
    (``"analysis": true``) or a severity string (``"analysis": "error"``
    == enabled + ``fail_on: error``)."""
    enabled: bool = False
    # raise at compile() when findings at/above this severity exist
    # (None = report only); same ladder as the CLI --fail-on
    fail_on: Optional[str] = None     # None | info | warning | error
    strict: bool = False              # unmatched reductions become warnings
    small_bytes: int = 64 << 10       # gather-class unplanned: info below
    big_bytes: int = 1 << 20          # gather-class unplanned: error at/above
    precision_min_elems: int = 4096   # smaller upcasts never reported
    precision_big_elems: int = 1 << 20  # upcast warning -> error at/above
    donation_min_bytes: int = 1 << 20   # smaller non-donated inputs ignored
    # regexes vs HLO metadata op_name/source: a hit marks the collective
    # planned (the annotation escape hatch for intentional reshards)
    collective_allowlist: List[str] = field(default_factory=list)
    # regexes vs named-scope paths: allowed f32 accumulation scopes
    precision_allowlist: List[str] = field(default_factory=list)
    # where audit-report.json lands (the doctor cross-reads it from the
    # dump dir); default: resilience.snapshot_dir when set, else unwritten
    report_dir: Optional[str] = None


@register_config
@dataclass
class TelemetryConfig(ConfigModel):
    """Unified telemetry spine (``deepspeed_tpu/telemetry/``, see
    ``docs/observability.md``): step-phase span tracing, the crash flight
    recorder, and the pull-based metrics registry with Prometheus
    exposition. Disabled by default — nothing is constructed and stepping
    is bit-identical to a tree without the subsystem. Also accepted as a
    bare bool (``"telemetry": true``) or a string flight-dump directory
    (``"telemetry": "<dir>"``)."""
    enabled: bool = False
    spans: bool = True                # span tracer (engine/serving phases)
    max_spans: int = 8192             # bounded closed-span buffer
    # every N steps the engine drains the device INSIDE a compute/drain
    # span, attributing device work to the timeline without a per-span
    # sync; 0 = never (spans measure host/dispatch time only)
    drain_interval_steps: int = 0
    trace_dir: Optional[str] = None   # Chrome-trace export dir (on close())
    flight_steps: int = 32            # flight-recorder ring size (0 = off)
    flight_dir: Optional[str] = None  # default: resilience.snapshot_dir or .
    # collective flight recorder: bounded ring of every collective launch
    # (seq/op/axes/shape/dtype/impl/phase), recorded host-side at trace/
    # dispatch time in the comm wrappers and dumped with the flightdump —
    # the stream `python -m deepspeed_tpu.doctor` aligns across ranks to
    # name a desync. 0 = off.
    collective_ring: int = 256
    # per-step device-memory gauges from device.memory_stats() (bytes in
    # use / peak / limit), folded into the flight ring and exported as
    # dstpu_mem_* — auto-disables where the backend reports nothing (CPU)
    memory: bool = True
    # AOT-compile each train-step variant once to record its compile-time
    # memory_analysis() (arg/output/temp/generated bytes) in the plan table
    # and registry; the measured executable then serves the steps, so the
    # compile is paid once, not twice. Program + numerics are identical.
    memory_analysis: bool = False
    prometheus_port: Optional[int] = None  # serve /metrics + /healthz (0 = ephemeral)
    monitor_bridge: bool = False      # registry -> Monitor events each print


@register_config
@dataclass
class ControlGuardConfig(ConfigModel):
    """Flap guard for automated actions (``control/guard.py``): an action
    fires only after ``trigger_streak`` consecutive asserted observations,
    re-arms only after ``clear_streak`` consecutive clear ones, waits
    ``cooldown_s`` between firings of the same rule, and the whole
    supervisor stops acting once ``budget`` actions fired within
    ``budget_window_s`` (observing and ledgering continue)."""
    trigger_streak: int = 2
    clear_streak: int = 2
    cooldown_s: float = 120.0
    budget: int = 8
    budget_window_s: float = 3600.0


@register_config
@dataclass
class ControlAutotuneConfig(ConfigModel):
    """Autotuner v2 (``control/autotune.py``): the generalized knob search
    {GAS, remat, training_fastpath, compressed_collectives, +stage/
    micro_batch}, probed with the in-process engine-warmup path and cached
    per mesh-fingerprint digest beside the comm-plan cache. Invoked
    explicitly — never implicitly at ``initialize()``: this block
    parameterizes ``ControlAutotuner.from_config(ds_config)`` (or pass the
    knobs directly to ``ControlAutotuner(...)``)."""
    enabled: bool = False
    dims: List[str] = field(default_factory=lambda: [
        "gas", "remat", "fastpath", "compression"])
    metric: str = "throughput"
    warmup_steps: int = 1
    measure_steps: int = 2
    tuner_type: str = "model"     # model | gridsearch | random
    early_stop: int = 3           # model/random tuner early-stop patience
    use_cache: bool = True        # per-mesh winner cache (DSTPU_PLAN_CACHE)
    cache_dir: Optional[str] = None  # default: the comm-plan cache dir
    probe_programs: bool = True   # microbench the dp-grad program variants


@register_config
@dataclass
class ControlSupervisorConfig(ConfigModel):
    """Online supervisor policy (``control/supervisor.py``): the rule book
    reacting to live signals. Rule toggles gate each signal->action edge
    independently; ``replan_axes`` overrides which mesh axes a straggler
    re-plan treats as the slow link (default: fingerprint DCN axes, else
    the outermost dp axis of a multi-axis span)."""
    enabled: bool = True              # within an enabled control block
    interval_steps: int = 1           # rule-evaluation cadence (steps)
    straggler_replan: bool = True
    straggler_penalty: float = 4.0    # slow-link cost multiplier floor
    replan_axes: Optional[List[str]] = None
    memory_guard: bool = True
    mem_watermark: float = 0.92       # bytes_in_use / bytes_limit trigger
    sla_guard: bool = True
    sla_violation_rate: float = 0.5   # violations / tracked per tick
    sla_min_tracked: int = 8          # finishes per tick before judging
    rollback_degrade: bool = True
    rollback_threshold: int = 2
    rollback_window_s: float = 600.0
    integrity_guard: bool = True      # act on fingerprint-divergence verdicts


@register_config
@dataclass
class ControlConfig(ConfigModel):
    """Control-plane subsystem (``deepspeed_tpu/control/``, see
    ``docs/autotuning.md``): Autotuner v2 + the online supervisor policy,
    sharing one decision ledger that rides flight dumps, the Prometheus
    registry (``dstpu_control_actions_total``), ``Control/*`` monitor
    events, and the doctor's post-mortem. Disabled by default — nothing is
    constructed and engine stepping is bit-identical. Also accepted as a
    bare bool (``"control": true``)."""
    enabled: bool = False
    ledger_size: int = 256
    autotune: ControlAutotuneConfig = field(
        default_factory=ControlAutotuneConfig)
    supervisor: ControlSupervisorConfig = field(
        default_factory=ControlSupervisorConfig)
    guard: ControlGuardConfig = field(default_factory=ControlGuardConfig)


@register_config
@dataclass
class ServingConfig(ConfigModel):
    """Serving tier (``deepspeed_tpu/serving/``): continuous-batching
    ``LLMServer`` over the ``inference/v2`` ragged engine.

    ``policy`` orders admission: ``fcfs`` (arrival), ``priority``
    (``Request.priority``, with preempt-and-requeue of lower-priority
    prefills when the KV pool runs dry), or ``deadline`` (earliest SLA
    deadline first). ``engine`` holds ``RaggedInferenceEngineConfig``
    overrides (token_budget, num_kv_blocks, kv_block_size,
    kv_cache_dtype, ...) — notably ``enable_prefix_cache`` (content-
    addressed prefix KV reuse: repeated system prompts map already-written
    pages instead of re-prefilling; resumed/migrated requests pay only the
    uncached tail) and ``spec_decode_k``/``spec_ngram`` (n-gram
    speculative decoding, greedy-only; the server runs the verify path
    automatically whenever every live sequence is in steady decode). See
    docs/serving.md. ``heartbeat_dir`` enables the PR 5 beacon transport
    for replica health (``ReplicaRouter``)."""
    enabled: bool = False
    policy: str = "fcfs"                 # fcfs | priority | deadline
    preempt: bool = True                 # preempt prefills under block pressure
    max_queue: int = 256                 # bounded ingress (overload sheds)
    # fused multi-token decode chunk (engine.decode_batch — the pallas
    # paged flash-decode fast path): when > 1 and every live sequence is in
    # steady decode, one server step runs a whole chunk in ONE compiled
    # dispatch; tokens stream in chunk-sized bursts. 0 = off.
    fused_decode_chunk: int = 0
    # resumable requests: every N generated tokens a response checkpoints
    # its generation state; a replica-loss requeue then resumes from the
    # last checkpoint (one prefill over prompt+generated, stream delivery
    # deduped) instead of replaying from scratch. 0 = full replays.
    # MUST mirror serving/request.py DEFAULT_RESUME_CHECKPOINT_TOKENS
    # (config cannot import the serving tier); change both together.
    resume_checkpoint_tokens: int = 16
    default_deadline_s: Optional[float] = None  # SLA stamped when unset
    idle_s: float = 0.001                # engine-thread sleep when idle
    metrics_interval_steps: int = 50     # Serving/* monitor event cadence
    replica_id: int = 0
    heartbeat_dir: Optional[str] = None  # shared dir for replica beacons
    heartbeat_interval_s: float = 2.0
    dead_after_s: float = 10.0           # beacon staler than this = dead
    # multi-tenant SLA classes (``deepspeed_tpu/fleet/tenancy.py``
    # TenancyMap.from_config; see docs/fleet_serving.md):
    #   {"classes": {"gold": {"weight": 4, "deadline_s": 2.0}, "bronze": 1},
    #    "tenants": {"acme": "gold"}, "default": "bronze"}
    # With the deadline policy, admission sorts by arrival + deadline/weight
    # and the control-plane shed door scales per class (low classes shed
    # first). None = tenancy off (single-tenant behavior unchanged).
    tenancy: Optional[Dict[str, Any]] = None
    # integrity canary probe (ISSUE 20, see docs/fleet_robustness.md):
    # every ``canary_interval_steps`` engine steps the replica runs a
    # seeded greedy canary request through its own admission path and
    # hashes the generated tokens. A hash that differs from the recorded
    # expectation (``canary_expect``, or the first probe's result when
    # unset) marks the replica failed via the router health path — a
    # replica that silently computes wrong bits stops taking traffic.
    canary_interval_steps: int = 0       # 0 = canary off
    canary_prompt: List[int] = field(default_factory=lambda: [3, 1, 4, 1, 5])
    canary_max_tokens: int = 8
    canary_expect: Optional[str] = None  # known-good token hash (hex)
    engine: Dict[str, Any] = field(default_factory=dict)


@register_config
@dataclass
class CheckpointConfig(ConfigModel):
    tag_validation: str = "Warn"  # Ignore | Warn | Fail
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write: Dict[str, Any] = field(default_factory=dict)
    async_save: bool = False


@register_config
@dataclass
class AIOConfig(ConfigModel):
    """Host async-IO knobs for the NVMe offload tier (reference ``csrc/aio``)."""
    block_size: int = 1048576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True


# ---------------------------------------------------------------------------
# Root config
# ---------------------------------------------------------------------------


@register_config
@dataclass
class EigenvalueConfig(ConfigModel):
    """Hessian power-iteration knobs for MoQ (reference ``eigenvalue``
    section, ``runtime/constants.py:340``); consumed by
    ``runtime/eigenvalue.Eigenvalue.from_config``."""
    enabled: bool = False
    verbose: bool = False
    max_iter: int = 100
    tol: float = 1e-2
    stability: float = 1e-6
    gas_boundary_resolution: int = 1
    layer_name: str = "bert.encoder.layer"
    layer_num: int = 0
    model_name: Optional[str] = None  # appears in reference test configs


@register_config
@dataclass
class QuantizeBitsConfig(ConfigModel):
    start_bits: int = 16
    target_bits: int = 8


@register_config
@dataclass
class QuantizeScheduleConfig(ConfigModel):
    quantize_period: int = 1000
    schedule_offset: int = 1000


@register_config
@dataclass
class FP16MixedQuantizeConfig(ConfigModel):
    enabled: bool = False
    quantize_change_ratio: float = 0.001


@register_config
@dataclass
class QuantizeTrainingConfig(ConfigModel):
    """MoQ vocabulary (reference ``quantize_training`` section,
    ``runtime/config.py:567``); ``runtime/quantize.MoQQuantizer.from_config``
    builds the annealing quantizer from it."""
    enabled: bool = True  # presence of the section implies it in the reference
    quantize_bits: QuantizeBitsConfig = field(default_factory=QuantizeBitsConfig)
    quantize_type: str = "symmetric"
    quantize_schedule: QuantizeScheduleConfig = field(
        default_factory=QuantizeScheduleConfig)
    quantize_groups: int = 1
    fp16_mixed_quantize: FP16MixedQuantizeConfig = field(
        default_factory=FP16MixedQuantizeConfig)
    quantize_verbose: bool = False
    quantize_eigenvalue: bool = False
    quantize_algo: Optional[Dict[str, Any]] = None
    rounding: str = "nearest"


@register_config
@dataclass
class ProgressiveLayerDropConfig(ConfigModel):
    """PLD knobs (reference top-level ``progressive_layer_drop`` section,
    ``runtime/config.py`` PLD group); consumed by
    ``runtime/progressive_layer_drop.ProgressiveLayerDrop.from_config``."""
    enabled: bool = False
    theta: float = 0.5
    gamma: float = 0.001


@register_config
@dataclass
class HybridEngineConfig(ConfigModel):
    """RLHF train/generate engine knobs (reference ``hybrid_engine``
    section, ``runtime/config.py:544``)."""
    enabled: bool = False
    max_out_tokens: int = 512
    inference_tp_size: int = 1
    release_inference_cache: bool = False
    pin_parameters: bool = True
    tp_gather_partition_size: int = 8


@register_config
@dataclass
class DeepSpeedTPUConfig(ConfigModel):
    """Root config (reference ``DeepSpeedConfig``, ``runtime/config.py:706``)."""

    train_batch_size: Union[int, str, None] = None
    train_micro_batch_size_per_gpu: Union[int, str, None] = None
    gradient_accumulation_steps: Union[int, str, None] = None

    steps_per_print: int = 10
    wall_clock_breakdown: bool = False
    # reference memory_breakdown / see_memory_usage: log device+host memory
    # at engine init and the compiled step's XLA accounting at step 1
    memory_breakdown: bool = False
    dump_state: bool = False
    prescale_gradients: bool = False
    gradient_predivide_factor: float = 1.0
    gradient_clipping: float = 0.0
    disable_allgather: bool = False

    seed: int = 42

    optimizer: OptimizerConfig = field(default_factory=OptimizerConfig)
    scheduler: SchedulerConfig = field(default_factory=SchedulerConfig)
    fp16: FP16Config = field(default_factory=FP16Config)
    bf16: BF16Config = field(default_factory=BF16Config)
    zero_optimization: ZeroConfig = field(default_factory=ZeroConfig)
    pipeline: PipelineConfig = field(default_factory=PipelineConfig)
    tensor_parallel: TensorParallelConfig = field(default_factory=TensorParallelConfig)
    moe: MoEConfig = field(default_factory=MoEConfig)
    compressed_collectives: CompressedCollectivesConfig = field(
        default_factory=CompressedCollectivesConfig)
    comm_planner: CommPlannerConfig = field(default_factory=CommPlannerConfig)
    training_fastpath: TrainingFastpathConfig = field(
        default_factory=TrainingFastpathConfig)

    # topology: sizes multiply to world size; dp is inferred
    sequence_parallel_size: int = 1
    data_parallel_size: Optional[int] = None

    activation_checkpointing: ActivationCheckpointingConfig = field(
        default_factory=ActivationCheckpointingConfig)
    flops_profiler: FlopsProfilerConfig = field(default_factory=FlopsProfilerConfig)
    comms_logger: CommsLoggerConfig = field(default_factory=CommsLoggerConfig)
    monitor: MonitorConfig = field(default_factory=MonitorConfig)
    tensorboard: TensorBoardConfig = field(default_factory=TensorBoardConfig)
    wandb: WandbConfig = field(default_factory=WandbConfig)
    csv_monitor: CSVConfig = field(default_factory=CSVConfig)
    comet: CometConfig = field(default_factory=CometConfig)
    elasticity: ElasticityConfig = field(default_factory=ElasticityConfig)
    compression_training: CompressionConfig = field(default_factory=CompressionConfig)
    data_efficiency: DataEfficiencyConfig = field(default_factory=DataEfficiencyConfig)
    autotuning: AutotuningConfig = field(default_factory=AutotuningConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    chaos: ChaosConfig = field(default_factory=ChaosConfig)
    serving: ServingConfig = field(default_factory=ServingConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    analysis: AnalysisConfig = field(default_factory=AnalysisConfig)
    control: ControlConfig = field(default_factory=ControlConfig)
    aio: AIOConfig = field(default_factory=AIOConfig)
    eigenvalue: EigenvalueConfig = field(default_factory=EigenvalueConfig)
    quantize_training: Optional[QuantizeTrainingConfig] = None
    hybrid_engine: HybridEngineConfig = field(default_factory=HybridEngineConfig)
    progressive_layer_drop: ProgressiveLayerDropConfig = field(
        default_factory=ProgressiveLayerDropConfig)

    @classmethod
    def _migrate_legacy(cls, d):
        # legacy top-level curriculum_learning (reference
        # curriculum_enabled_legacy, docs/_tutorials/curriculum-learning.md)
        # is the same scheduler the data_efficiency form configures — move
        # it to the modern location the engine reads
        # string shorthand: "compressed_collectives": "int8" == {"mode": "int8"}
        cc = d.get("compressed_collectives")
        if isinstance(cc, str):
            d["compressed_collectives"] = {"mode": cc}
        # string shorthand: "comm_planner": "static" == {"mode": "static"}
        cp = d.get("comm_planner")
        if isinstance(cp, str):
            d["comm_planner"] = {"mode": cp}
        # string shorthand: "resilience": "<dir>" enables snapshots there
        rz = d.get("resilience")
        if isinstance(rz, str):
            d["resilience"] = {"enabled": True, "snapshot_dir": rz}
        # string shorthand: "serving": "priority" == {"enabled": true,
        # "policy": "priority"}
        sv = d.get("serving")
        if isinstance(sv, str):
            d["serving"] = {"enabled": True, "policy": sv}
        # bool/string shorthand: "telemetry": true enables the spine with
        # defaults; "telemetry": "<dir>" additionally aims flight dumps there
        tl = d.get("telemetry")
        if isinstance(tl, bool):
            d["telemetry"] = {"enabled": tl}
        elif isinstance(tl, str):
            d["telemetry"] = {"enabled": True, "flight_dir": tl}
        # bool/string shorthand: "analysis": true runs the compile-time
        # audit report-only; "analysis": "error" additionally fails
        # compile() on findings at/above that severity
        an = d.get("analysis")
        if isinstance(an, bool):
            d["analysis"] = {"enabled": an}
        elif isinstance(an, str):
            d["analysis"] = {"enabled": True, "fail_on": an}
        # bool shorthand: "control": true arms the supervisor policy (and
        # the autotuner API) with defaults
        ct = d.get("control")
        if isinstance(ct, bool):
            d["control"] = {"enabled": ct}
        cl = d.pop("curriculum_learning", None)
        if cl:
            de = dict(d.get("data_efficiency") or {})
            ds = dict(de.get("data_sampling") or {})
            ds.setdefault("curriculum_learning", dict(cl))
            de["data_sampling"] = ds
            # the reference legacy default is disabled; only an explicit
            # "enabled": true switches the scheduler on
            de.setdefault("enabled", bool(cl.get("enabled", False)))
            d["data_efficiency"] = de
        return d

    # free-form escape hatch for experiments
    extra: Dict[str, Any] = field(default_factory=dict)

    _DEPRECATED_KEYS = {
        "train_micro_batch_size_per_device": "train_micro_batch_size_per_gpu",
        "zero_allow_untested_optimizer": None,
        "zero_force_ds_cpu_optimizer": None,
        "communication_data_type": None,
        "amp": None,
    }

    # ------------------------------------------------------------------
    def __post_init__(self):
        # Keep the raw user-specified triangle so finalize() can re-resolve at
        # the true dp world size without conflicting with defaults filled here.
        self._user_batch = (self.train_batch_size, self.train_micro_batch_size_per_gpu,
                            self.gradient_accumulation_steps)
        self._resolve_batch_sizes(strict=False)

    def _resolve_batch_sizes(self, world_dp_size: int = 1, strict: bool = True):
        """Reference ``config.py`` batch-size triangle: tbs = mbs * gas * dp.

        ``strict=False`` (config load time, before the engine knows the real
        dp size) keeps a fully-specified but dp-inconsistent triangle as-is;
        ``finalize(world_dp_size)`` re-resolves strictly."""
        raw_tbs, raw_mbs, raw_gas = self._user_batch
        tbs = raw_tbs if isinstance(raw_tbs, int) else None
        mbs = raw_mbs if isinstance(raw_mbs, int) else None
        gas = raw_gas if isinstance(raw_gas, int) else None
        if tbs and mbs and gas:
            if tbs != mbs * gas * world_dp_size:
                if not strict:
                    return  # defer to finalize() with the true dp size
                raise ConfigError(
                    f"train_batch_size({tbs}) != micro_batch({mbs}) * gas({gas}) * dp({world_dp_size})")
        elif tbs and mbs:
            gas = tbs // (mbs * world_dp_size)
        elif tbs and gas:
            mbs = tbs // (gas * world_dp_size)
        elif mbs and gas:
            tbs = mbs * gas * world_dp_size
        elif tbs:
            mbs = max(1, tbs // world_dp_size)
            gas = tbs // (mbs * world_dp_size)
        elif mbs:
            gas = 1
            tbs = mbs * world_dp_size
        else:
            mbs, gas = 1, 1
            tbs = world_dp_size
        if not (tbs and mbs and gas) or tbs != mbs * gas * world_dp_size:
            raise ConfigError(
                f"Inconsistent batch config: train_batch_size={tbs}, micro={mbs}, gas={gas}, "
                f"dp={world_dp_size}")
        self.train_batch_size = tbs
        self.train_micro_batch_size_per_gpu = mbs
        self.gradient_accumulation_steps = gas

    def finalize(self, world_dp_size: int) -> "DeepSpeedTPUConfig":
        """Re-resolve batch sizes once the dp world size is known.

        With ``elasticity.enabled`` the elastic schedule OWNS the batch
        triangle (reference ``config.py`` elasticity integration over
        ``elasticity/elasticity.py:233``): the final batch and micro-batch
        come from ``compute_elastic_config`` at the CURRENT world size, so a
        rescaled relaunch picks consistent sizes with no retuning. User
        batch keys then conflict unless ``ignore_non_elastic_batch_info``
        says to drop them (reference ``elasticity/constants.py``)."""
        if self.elasticity.enabled:
            from ..elasticity import compute_elastic_config

            # conflict-check against the ORIGINAL user keys, not a previous
            # finalize's elastic resolution — finalize must stay idempotent
            # and re-resolvable at a NEW world size (the rescale flow)
            if not hasattr(self, "_pre_elastic_batch"):
                self._pre_elastic_batch = self._user_batch
            user_keys = [v for v in self._pre_elastic_batch
                         if isinstance(v, int)]
            if user_keys and not self.elasticity.ignore_non_elastic_batch_info:
                raise ConfigError(
                    "elasticity is enabled but the config also pins "
                    "train_batch_size / micro_batch / gradient_accumulation; "
                    "remove them or set elasticity.ignore_non_elastic_batch_info")
            final_batch, _, micro = compute_elastic_config(
                self.elasticity, world_size=world_dp_size)
            # a supervised relaunch carries the launcher's rescale decision
            # (launcher/launch.py::make_rescale_fn → DSTPU_ELASTIC_BATCH/
            # _MICRO): the SUPERVISOR's schedule wins over a local recompute
            # so every host of the relaunch runs the same triangle even if
            # their capacity probes disagree transiently — but only when it
            # is consistent with the world this engine actually formed
            env_b = os.environ.get("DSTPU_ELASTIC_BATCH")
            env_m = os.environ.get("DSTPU_ELASTIC_MICRO")
            if env_b and env_m:
                try:
                    eb, em = int(env_b), int(env_m)
                except ValueError:
                    eb = em = 0
                if eb > 0 and em > 0 and eb % (em * world_dp_size) == 0:
                    final_batch, micro = eb, em
                    logger.info(
                        f"elasticity: batch schedule from the supervisor's "
                        f"rescale decision (DSTPU_ELASTIC_BATCH={eb}, "
                        f"micro={em}, dp={world_dp_size})")
                else:
                    logger.warning(
                        f"elasticity: ignoring DSTPU_ELASTIC_BATCH={env_b}/"
                        f"MICRO={env_m} — inconsistent with the actual dp "
                        f"world {world_dp_size}; recomputed locally")
            self._user_batch = (final_batch, micro, None)
        self._resolve_batch_sizes(world_dp_size)
        if self.fp16.enabled and self.bf16.enabled:
            raise ConfigError("fp16 and bf16 cannot both be enabled")
        return self

    # convenience ------------------------------------------------------
    @property
    def compute_dtype(self):
        import jax.numpy as jnp

        if self.bf16.enabled:
            return jnp.bfloat16
        if self.fp16.enabled:
            return jnp.float16
        return jnp.float32

    @property
    def zero_stage(self) -> int:
        return self.zero_optimization.stage


def _fold_monitor_keys(cfg: DeepSpeedTPUConfig) -> DeepSpeedTPUConfig:
    # The reference accepts monitor configs both top-level ("tensorboard": {...})
    # and the MonitorConfig grouping; fold top-level into cfg.monitor (idempotent).
    import copy

    for key in ("tensorboard", "wandb", "csv_monitor", "comet"):
        top = getattr(cfg, key)
        if top.enabled and not getattr(cfg.monitor, key).enabled:
            setattr(cfg.monitor, key, copy.deepcopy(top))
    return cfg


def load_config(config: Union[str, Mapping[str, Any], DeepSpeedTPUConfig, None]) -> DeepSpeedTPUConfig:
    """Accept a path to a JSON file, a dict, an existing config, or None."""
    if config is None:
        return DeepSpeedTPUConfig()
    if isinstance(config, DeepSpeedTPUConfig):
        return _fold_monitor_keys(config)
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    return _fold_monitor_keys(DeepSpeedTPUConfig.from_dict(config))
