"""``deepspeed.zero`` surface (reference ``deepspeed/runtime/zero/__init__.py``):
partitioning rules, memory estimators, ZeRO++ pieces, tiling, NVMe swapper.

The reference's ``zero.Init`` context manager intercepts ``torch.nn`` module
construction to shard parameters at creation. JAX construction is a pure
function, so the analogue is the **init-closure form of
``deepspeed.initialize``**: pass ``model_parameters=lambda: model.init(...)``
and each leaf materializes directly into its ZeRO shard
(``runtime/engine.py:316``, reference ``partition_parameters.py:816``).
``Init`` below adapts reference-shaped code to that idiom.
"""

import contextlib

from .memory_estimators import (estimate_zero2_model_states_mem_needs_all_live,
                                estimate_zero3_model_states_mem_needs_all_live,
                                estimate_zero_model_states_mem_needs)
from .sharding import ZeroShardingRules, shard_param_spec
from .swapper import AsyncTensorSwapper
from .tiling import TiledLinear, tiled_matmul
from .zeropp import (ZeroPPState, hierarchical_all_gather, hpz_remat_policy,
                     zeropp_train_step_factory)

__all__ = ["Init", "ZeroShardingRules", "shard_param_spec",
           "estimate_zero_model_states_mem_needs",
           "estimate_zero2_model_states_mem_needs_all_live",
           "estimate_zero3_model_states_mem_needs_all_live",
           "AsyncTensorSwapper", "TiledLinear", "tiled_matmul",
           "ZeroPPState", "hierarchical_all_gather", "hpz_remat_policy",
           "zeropp_train_step_factory"]


class Init(contextlib.AbstractContextManager):
    """Adapter for the reference ``with deepspeed.zero.Init(): model = M()``
    idiom. JAX cannot intercept construction, so this wraps the init
    CLOSURE instead::

        params = zero.Init(lambda: model.init(key, dummy)["params"])
        engine, *_ = deepspeed_tpu.initialize(model=loss_fn,
                                              model_parameters=params, ...)

    ``initialize`` recognizes the wrapper (it is itself the zero-arg
    closure) and materializes every leaf directly into its ZeRO-3 shard —
    no full-size copy ever exists on host or a single device. Entering it
    as a context manager raises with this guidance, because silently
    building the model unsharded would defeat the point.
    """

    def __init__(self, init_closure=None, config_dict_or_path=None, **_ignored):
        if init_closure is not None and not callable(init_closure):
            raise TypeError("zero.Init takes a zero-arg init closure, e.g. "
                            "zero.Init(lambda: model.init(key, dummy)['params'])")
        self._closure = init_closure

    def __call__(self):
        if self._closure is None:
            raise ValueError("zero.Init was built without an init closure")
        return self._closure()

    def __enter__(self):
        raise RuntimeError(
            "JAX has no construction hook to intercept: instead of "
            "`with zero.Init(): model = M()`, pass the init closure — "
            "model_parameters=zero.Init(lambda: M().init(key, dummy)"
            "['params']) or the bare lambda — to deepspeed_tpu.initialize; "
            "leaves then materialize pre-sharded (engine.py:316)")

    def __exit__(self, *exc):
        return False
