"""ZeRO as sharding rules.

The reference implements ZeRO with explicit partition bookkeeping, grad-hook
bucketing, and stream-overlapped collectives (``runtime/zero/stage_1_and_2.py``,
``stage3.py``, ``partition_parameters.py``). On TPU the same memory layout is
expressed declaratively: a ``PartitionSpec`` per tensor over the mesh, and XLA
inserts + schedules (prefetches, overlaps) the allgathers/reduce-scatters the
hooks performed imperatively.

Stage semantics (all over the "fsdp" axes = dp_outer × ep × sp):
  0: replicate params, grads, optimizer state (plain DP)
  1: shard optimizer state (+ fp32 master params — they are optimizer state)
  2: + accumulated gradients sharded (reduce_scatter materialization)
  3: + parameters sharded (allgather-on-use, scheduled by XLA)

MiCS (``zero/mics.py:64``) maps to sharding over a *subset* of the fsdp axes —
shard over ep only (size = mics_shard_size) and replicate over dp_outer — the
hierarchical allgather then naturally rides the inner axis first.

Model-parallel dims (tp / expert ep) come in via a user/model-provided spec
tree; ZeRO claims the largest *free* dim divisible by the fsdp axis size, and
falls back to replication for small/indivisible params (the analogue of
stage3's ``param_persistence_threshold``).
"""

from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...parallel.topology import Topology


def _spec_tuple(spec: Optional[P], ndim: int) -> Tuple:
    t = tuple(spec) if spec is not None else ()
    return t + (None,) * (ndim - len(t))


def _axes_in_spec(spec: Tuple) -> set:
    used = set()
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            used.update(entry)
        else:
            used.add(entry)
    return used


def shard_param_spec(shape: Sequence[int],
                     base_spec: Optional[P],
                     shard_axes: Tuple[str, ...],
                     axis_size: int,
                     min_size_to_shard: int = 2 ** 11) -> P:
    """Add ZeRO sharding over ``shard_axes`` to ``base_spec``.

    Picks the largest dim divisible by ``axis_size`` that the base (model
    parallel) spec leaves free, preferring earlier dims on ties. Params smaller
    than ``min_size_to_shard`` stay as-is (persistent-param analogue of
    ``stage3_param_persistence_threshold``).
    """
    ndim = len(shape)
    base = _spec_tuple(base_spec, ndim)
    if axis_size == 1 or int(np.prod(shape or (1,))) < min_size_to_shard:
        return P(*base)  # spec-ok: ZeRO free-dim surgery: below-threshold leaves keep the base spec
    used = _axes_in_spec(base)
    if set(shard_axes) & used:
        return P(*base)  # already sharded over (some of) these axes by the model  # spec-ok: ZeRO free-dim surgery: model already claimed these axes
    best = -1
    best_size = 0
    for d in range(ndim):
        if base[d] is None and shape[d] % axis_size == 0 and shape[d] > best_size:
            best, best_size = d, shape[d]
    if best < 0:
        return P(*base)  # spec-ok: ZeRO free-dim surgery: no divisible free dim
    new = list(base)
    new[best] = shard_axes if len(shard_axes) > 1 else shard_axes[0]
    return P(*new)  # spec-ok: ZeRO free-dim surgery: claim the best free dim


class ZeroShardingRules:
    """Resolved sharding policy for one engine instance."""

    def __init__(self, stage: int, topo: Topology, *,
                 mics_shard_size: int = -1,
                 min_size_to_shard: int = 2 ** 11):
        self.stage = stage
        self.topo = topo
        self.min_size_to_shard = min_size_to_shard
        # MiCS: restrict the sharding group to the inner (ep) axis slice
        if mics_shard_size and mics_shard_size > 0:
            if topo.ep_size != mics_shard_size:
                raise ValueError(
                    "MiCS shard size is expressed by sizing the ep axis: set "
                    f"TopologySpec(ep={mics_shard_size}); got ep={topo.ep_size}")
            self.fsdp_axes: Tuple[str, ...] = ("ep",)
        else:
            self.fsdp_axes = tuple(topo.fsdp_axes)
        self.fsdp_size = topo.axis_size(*self.fsdp_axes)

    # -- per-tensor specs ------------------------------------------------
    def param_spec(self, shape, base_spec: Optional[P]) -> P:
        if self.stage >= 3:
            return shard_param_spec(shape, base_spec, self.fsdp_axes, self.fsdp_size,
                                    self.min_size_to_shard)
        return P(*_spec_tuple(base_spec, len(shape)))  # spec-ok: stage<3 params keep the model-parallel base spec

    def opt_state_spec(self, shape, base_spec: Optional[P]) -> P:
        if self.stage >= 1:
            return shard_param_spec(shape, base_spec, self.fsdp_axes, self.fsdp_size,
                                    self.min_size_to_shard)
        return P(*_spec_tuple(base_spec, len(shape)))  # spec-ok: stage 0 optimizer state keeps the base spec

    def grad_accum_spec(self, shape, base_spec: Optional[P]) -> P:
        if self.stage >= 2:
            return shard_param_spec(shape, base_spec, self.fsdp_axes, self.fsdp_size,
                                    self.min_size_to_shard)
        return P(*_spec_tuple(base_spec, len(shape)))  # spec-ok: stage<2 grad accumulators keep the base spec

    # -- tree-level helpers ----------------------------------------------
    def param_spec_tree(self, params, base_specs=None):
        return self._map_tree(params, base_specs, self.param_spec)

    def opt_spec_tree(self, params, base_specs=None):
        return self._map_tree(params, base_specs, self.opt_state_spec)

    def grad_spec_tree(self, params, base_specs=None):
        return self._map_tree(params, base_specs, self.grad_accum_spec)

    def _map_tree(self, params, base_specs, fn):
        if base_specs is None:
            return jax.tree.map(lambda p: fn(p.shape, None), params)
        return jax.tree.map(lambda p, s: fn(p.shape, s), params, base_specs,
                            is_leaf=lambda x: x is None or isinstance(x, P))

    def shardings(self, spec_tree):
        return jax.tree.map(lambda s: NamedSharding(self.topo.mesh, s), spec_tree,
                            is_leaf=lambda x: isinstance(x, P))
