"""TiledLinear: bound activation memory for huge linears.

Reference ``TiledLinear`` (``runtime/zero/tiling.py:32``): splits a linear
into an in_splits × out_splits grid of sub-linears so no full-size activation
ever materializes. TPU-native: one weight tensor, the *computation* is tiled
with ``lax.scan`` over output tiles (+ optional ``jax.checkpoint`` per tile);
XLA keeps at most one tile's activation live.
"""

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn


def tiled_matmul(x: jnp.ndarray, w: jnp.ndarray, out_splits: int = 1,
                 in_splits: int = 1, remat: bool = False) -> jnp.ndarray:
    """y = x @ w computed in tiles. x: [..., K]; w: [K, N].

    ``out_splits`` scans over column tiles of ``w`` (bounds the live output
    activation); ``in_splits`` accumulates over row tiles (bounds the live
    input slice in the backward)."""
    k, n = w.shape
    if n % out_splits or k % in_splits:
        raise ValueError(f"w {w.shape} not divisible by splits "
                         f"({in_splits}, {out_splits})")
    wt = w.reshape(k, out_splits, n // out_splits).transpose(1, 0, 2)  # [O,K,n']

    def one_tile(w_tile):
        def inner(acc_x):
            xs = jnp.split(acc_x, in_splits, axis=-1)
            ws = jnp.split(w_tile, in_splits, axis=0)
            out = xs[0] @ ws[0]
            for xi, wi in zip(xs[1:], ws[1:]):
                out = out + xi @ wi
            return out

        fn = jax.checkpoint(inner) if remat else inner
        return fn(x)

    tiles = jax.lax.map(one_tile, wt)                # [O, ..., n']
    return jnp.moveaxis(tiles, 0, -2).reshape(x.shape[:-1] + (n,))


class TiledLinear(nn.Module):
    """Reference-shaped module; forward runs :func:`tiled_matmul`."""
    in_features: int
    out_features: int
    in_splits: int = 1
    out_splits: int = 1
    use_bias: bool = True
    remat: bool = False

    @nn.compact
    def __call__(self, x):
        w = self.param("kernel", nn.initializers.lecun_normal(),
                       (self.in_features, self.out_features), jnp.float32)
        y = tiled_matmul(x, w.astype(x.dtype), self.out_splits, self.in_splits,
                         self.remat)
        if self.use_bias:
            y = y + self.param("bias", nn.initializers.zeros,
                               (self.out_features,), jnp.float32).astype(x.dtype)
        return y
