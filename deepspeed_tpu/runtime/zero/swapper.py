"""SSD swap tier for optimizer state / parameters (ZeRO-Infinity analogue).

Reference: ``deepspeed/runtime/swap_tensor/`` (``AsyncPartitionedParameterSwapper``
``partitioned_param_swapper.py:37``, optimizer swapper) over the csrc AIO
threadpool. TPU-native shape: pytrees are flattened into one packed file per
swap key (+ an in-memory manifest of offsets/shapes/dtypes); writes/reads stripe across
the native ``dstpu_aio`` threadpool and can overlap compute — the device
round-trip is ``jax.device_get``/``device_put`` at the swap boundary, the
hot loop never sees host IO.
"""

import os
from typing import Any, Dict, Optional

import jax
import numpy as np


def _dtype_name(dt) -> str:
    return str(np.dtype(dt))


def _resolve_dtype(name: str) -> np.dtype:
    """np.dtype lookup that also resolves ml_dtypes names (bfloat16, fp8s)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _key_str(path) -> str:
    out = []
    for e in path:
        for attr in ("key", "name", "idx"):
            if hasattr(e, attr):
                out.append(str(getattr(e, attr)))
                break
        else:
            out.append(str(e))
    return "/".join(out)


class AsyncTensorSwapper:
    """Swap pytrees device↔SSD. ``swap_out`` is async (call ``synchronize``
    or let ``swap_in`` wait); ``swap_in`` restores the tree with original
    structure/dtypes and optional shardings."""

    def __init__(self, swap_dir: str, num_threads: int = 8,
                 block_size: int = 1 << 20, use_o_direct: bool = False):
        from ...ops.aio import AsyncIOHandle

        self.swap_dir = swap_dir
        os.makedirs(swap_dir, exist_ok=True)
        self.handle = AsyncIOHandle(num_threads=num_threads, block_size=block_size,
                                    use_o_direct=use_o_direct)
        self._manifests: Dict[str, dict] = {}
        self._pending: Dict[str, list] = {}
        self._treedefs: Dict[str, Any] = {}
        self._keepalive: Dict[str, list] = {}

    def _data_path(self, name: str) -> str:
        return os.path.join(self.swap_dir, f"{name}.swp")

    # ------------------------------------------------------------------
    def swap_out(self, name: str, tree: Any):
        """Write a pytree to SSD (async). Leaves are device-fetched first;
        the arrays stay referenced until ``synchronize``."""
        if name in self._pending:
            # never delete the file under in-flight writes of a prior swap_out
            self.synchronize(name)
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        self._treedefs[name] = treedef
        path = self._data_path(name)
        if os.path.exists(path):
            os.remove(path)
        # one batched D2H fetch: lets JAX overlap the transfers instead of
        # serializing a blocking device_get per leaf
        arrs = jax.device_get([leaf for _, leaf in flat])
        arrs = [np.ascontiguousarray(a) for a in arrs]
        manifest, reqs, keep = [], [], []
        offset = 0
        for (kp, _), arr in zip(flat, arrs):
            manifest.append({"key": _key_str(kp), "shape": list(arr.shape),
                             "dtype": _dtype_name(arr.dtype), "offset": offset,
                             "nbytes": int(arr.nbytes)})
            if arr.nbytes:
                reqs.append(self.handle.async_pwrite(arr, path, offset))
            keep.append(arr)
            offset += arr.nbytes
        self._manifests[name] = {"entries": manifest, "total": offset}
        self._pending[name] = reqs
        self._keepalive[name] = keep

    def synchronize(self, name: Optional[str] = None):
        names = [name] if name else list(self._pending)
        for n in names:
            for rid in self._pending.pop(n, []):
                self.handle.wait(rid)
            self._keepalive.pop(n, None)

    # ------------------------------------------------------------------
    def swap_in(self, name: str, shardings: Any = None, delete: bool = False) -> Any:
        """Read a swapped tree back; ``shardings`` (optional pytree or single
        sharding) re-places leaves on device."""
        self.synchronize(name)
        man = self._manifests.get(name)
        treedef = self._treedefs.get(name)
        if man is None or treedef is None:
            raise RuntimeError(f"swap_in({name!r}): unknown swap name — "
                               "swap_out must happen in this process "
                               f"(known: {self.swapped_names()})")
        path = self._data_path(name)
        bufs, reqs = [], []
        for e in man["entries"]:
            buf = np.empty(tuple(e["shape"]), dtype=_resolve_dtype(e["dtype"]))
            if buf.nbytes:
                reqs.append((self.handle.async_pread(buf, path, e["offset"]), e))
            bufs.append(buf)
        for rid, e in reqs:
            got = self.handle.wait(rid)
            if got != e["nbytes"]:
                raise OSError(
                    f"swap_in({name!r}): short read for {e['key']} — got {got} "
                    f"of {e['nbytes']} bytes (truncated/corrupt {path})")
        tree = jax.tree_util.tree_unflatten(treedef, bufs)
        if shardings is not None:
            tree = jax.device_put(tree, shardings)
        if delete:
            self.release(name)
        return tree

    def release(self, name: str):
        self.synchronize(name)
        p = self._data_path(name)
        if os.path.exists(p):
            os.remove(p)
        self._manifests.pop(name, None)
        self._treedefs.pop(name, None)

    def swapped_names(self):
        return sorted(self._manifests)
