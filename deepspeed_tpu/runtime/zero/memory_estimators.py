"""ZeRO memory-need estimators.

Reference API parity: ``estimate_zero2_model_states_mem_needs_all_live``
(``runtime/zero/stage_1_and_2.py``) and the zero3 variant
(``stage3.py``) — sizing helpers users call before picking a stage. Model
state accounting (per chip, bf16 compute + fp32 master + Adam m/v):

* stage 0: 2P (weights) + 4P master + 8P optim + 4P grads
* stage 1: optimizer+master sharded over dp
* stage 2: + fp32 grads sharded
* stage 3: + weights sharded
"""

from typing import Any, Dict

import jax
import numpy as np


def _param_count(params_or_count) -> int:
    if isinstance(params_or_count, (int, np.integer)):
        return int(params_or_count)
    return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params_or_count)
               if hasattr(l, "shape"))


def estimate_zero_model_states_mem_needs(params_or_count, zero_stage: int,
                                         dp_size: int,
                                         compute_bytes: int = 2) -> Dict[str, float]:
    """Per-chip model-state bytes for a given stage/dp (activations excluded)."""
    p = _param_count(params_or_count)
    d = max(1, dp_size)
    weights = compute_bytes * p
    master = 4 * p
    optim = 8 * p   # adam m+v fp32
    grads = 4 * p
    if zero_stage >= 1:
        master, optim = master / d, optim / d
    if zero_stage >= 2:
        grads = grads / d
    if zero_stage >= 3:
        weights = weights / d
    total = weights + master + optim + grads
    return {"params": p, "weights_bytes": weights, "master_bytes": master,
            "optimizer_bytes": optim, "grad_bytes": grads,
            "total_bytes": total, "total_gb": total / 1024**3}


def estimate_zero2_model_states_mem_needs_all_live(model_params, num_gpus_per_node=1,
                                                   num_nodes=1):
    """Reference-named helper (``stage_1_and_2.py``)."""
    return estimate_zero_model_states_mem_needs(
        model_params, 2, num_gpus_per_node * num_nodes)


def estimate_zero3_model_states_mem_needs_all_live(model_params, num_gpus_per_node=1,
                                                   num_nodes=1):
    """Reference-named helper (``stage3.py``)."""
    return estimate_zero_model_states_mem_needs(
        model_params, 3, num_gpus_per_node * num_nodes)
