"""ZeRO++ — explicit sharded training with quantized collectives.

Reference: ZeRO++ (``zero/config.py`` knobs ``zero_quantized_weights`` qwZ,
``zero_quantized_gradients`` qgZ, ``zero_hpz_partition_size`` hpZ; kernels
``csrc/quantization/*``). The declarative engine path (``sharding.py``) lets
XLA insert *exact* collectives; this module is the explicit counterpart for
bandwidth-constrained meshes: parameters live as flat fp32 shards, the train
step gathers them with **int8-quantized allgather** (qwZ), and gradients
return to shards via **quantized reduce-scatter** (qgZ) — 4x less traffic on
the gather and the reduction, with error bounded by blockwise scales.

hpZ (``zero_hpz_partition_size``): the reference keeps a secondary
intra-node fp16 copy so the backward gather stays off the inter-node links.
Under XLA the analogue is a remat policy that saves the gathered weights
between fwd and bwd — :func:`hpz_remat_policy`, wired into the factory as
``remat="hpz"``: the gather runs INSIDE the checkpointed forward, activations
are rematerialized in backward, but the gathered weights are pinned as
residuals, so the compiled step contains exactly ONE gather per parameter
(``remat="nothing"`` trades that for memory and re-gathers in backward;
``tests/unit/test_zeropp.py`` counts the all-gathers in the compiled HLO).
The hierarchical gather for MiCS-style meshes is ``hierarchical_all_gather``.
"""

from functools import partial
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding

from ...comm.compressed import quantized_all_gather, quantized_reduce_scatter
from ...sharding import sites
from ...utils.shard_map_compat import shard_map_nocheck as _sm

_PAD_QUANTUM = 128  # quantized_reduce_scatter block alignment


def hierarchical_all_gather(x, inner_axis: str, outer_axis: str, tiled: bool = True):
    """MiCS/hpZ-style two-hop gather: inner (ICI-local) first, then outer
    (reference ``mics_hierarchical_params_gather``, ``mics.py``)."""
    inner = lax.all_gather(x, inner_axis, tiled=tiled)
    return lax.all_gather(inner, outer_axis, tiled=tiled)


HPZ_NAME = "hpz_gathered_weights"


def hpz_remat_policy():
    """Checkpoint policy realizing hpZ (reference ``utils/groups.py:531``
    secondary-partition groups): under activation rematerialization, save
    ONLY the gathered full weights (tagged ``HPZ_NAME``) across fwd→bwd, so
    backward never repeats the inter-chip gather while activations still
    recompute."""
    return jax.checkpoint_policies.save_only_these_names(HPZ_NAME)


class ZeroPPState(NamedTuple):
    step: jnp.ndarray
    shards: Any        # fp32 master shards: each leaf [dp, padded_n/dp]
    opt_state: Any     # optimizer state over the shards


def _shard_leaf(p, dp: int) -> jnp.ndarray:
    n = int(np.prod(p.shape)) if p.ndim else 1
    pad = (-n) % (dp * _PAD_QUANTUM)
    flat = jnp.pad(jnp.ravel(p).astype(jnp.float32), (0, pad))
    return flat.reshape(dp, -1)


def zeropp_train_step_factory(loss_fn: Callable, tx, mesh: Mesh,
                              dp_axis: str = "dp",
                              quantized_weights: Optional[bool] = None,
                              quantized_gradients: Optional[bool] = None,
                              compute_dtype=jnp.float32,
                              quant_block: int = _PAD_QUANTUM,
                              remat: Optional[str] = None,
                              overlap_collective_matmul: Optional[bool] = None,
                              stochastic_rounding: Optional[bool] = None):
    """Build (init, step) for ZeRO-3 training with ZeRO++ collectives.

    ``init(params) -> ZeroPPState`` (shards placed over ``dp_axis``);
    ``step(state, batch) -> (state, loss)``. Weight gathers use int8
    quantization when ``quantized_weights`` (qwZ), gradient reduction uses
    quantized reduce-scatter when ``quantized_gradients`` (qgZ); exact XLA
    collectives otherwise.

    ``remat``: ``None`` keeps the gather outside autodiff (gathered weights
    and activations both live to backward); ``"hpz"`` checkpoints the
    forward with :func:`hpz_remat_policy` — activations recompute, gathered
    weights are saved, ONE gather per param per step (the hpZ guarantee);
    ``"nothing"`` saves neither — minimum memory, backward re-gathers. In
    the remat modes gradients return through the gather's AD transpose
    (an exact sum reduce-scatter; with qwZ the quantized gather uses a
    straight-through estimator), so qgZ does not apply there.

    ``overlap_collective_matmul``: route the EXACT (unquantized) param
    gather and gradient reduction through the ring-chunked collectives of
    ``ops/collective_matmul.py`` (``ring_all_gather`` /
    ``ring_reduce_scatter``) — same numerics, but each tensor's transfer
    is p-1 ppermute chunk hops XLA can interleave with another tensor's
    matmuls (the T3-style latency hiding the fused primitives give TP).
    ``None`` (default) follows the fleet-wide
    ``TensorParallelConfig.overlap_collective_matmul`` knob set by
    ``initialize()``. The quantized (qwZ/qgZ) paths are unaffected.

    ``stochastic_rounding``: dither the qgZ gradient quantization
    (``compressed_collectives: int8_sr``) so the int8 reduction is unbiased
    per element — rounding drift can't accumulate in the master shards over
    steps. It applies ONLY to that reduction: weight gathers (qwZ) keep
    nearest rounding (fresh masters re-quantize each step, no residual to
    carry), and the remat modes have no qgZ reduction at all (gradients
    return through the gather's exact AD transpose), so the flag is inert
    there.

    ``quantized_weights`` / ``quantized_gradients`` / ``stochastic_rounding``
    default to ``None`` = follow the fleet-wide ``compressed_collectives``
    knobs set by ``initialize()``: the ``zero_weights`` / ``zero_gradients``
    site toggles gate qwZ/qgZ and ``int8_sr`` turns the dither on. With no
    compression configured (mode ``none``) and the collective planner
    INACTIVE the legacy factory default — both quantized paths ON —
    applies; with the planner active (``comm_planner: static|measure``) the
    zeropp gather/scatter sites resolve through ``planner.resolve`` at
    ``init(params)`` time, when the true flat sizes are known. Explicit
    booleans always win over both.
    """
    from ...comm.compressed import compression_mode
    from ...comm.planner import planner_active

    legacy = compression_mode() == "none"  # knob untouched: factory default
    # every knob left to default + planner on: the planner owns the choice,
    # resolved lazily in init() where the flat param sizes are known
    plan_pending = (legacy and planner_active()
                    and quantized_weights is None
                    and quantized_gradients is None
                    and stochastic_rounding is None)
    if quantized_weights is None:
        quantized_weights = (not plan_pending
                             and (legacy
                                  or compression_mode("zero_weights") != "none"))
    if quantized_gradients is None:
        quantized_gradients = (not plan_pending
                               and (legacy
                                    or compression_mode("zero_gradients") != "none"))
    if stochastic_rounding is None:
        stochastic_rounding = compression_mode("zero_gradients") == "int8_sr"
    if overlap_collective_matmul is None:
        from ...ops.collective_matmul import overlap_enabled

        overlap_collective_matmul = overlap_enabled()
    if remat not in (None, "hpz", "nothing"):
        raise ValueError(f"remat must be None|'hpz'|'nothing', got {remat!r}")
    if remat is not None and quantized_gradients:
        raise ValueError(
            "remat modes return gradients through the gather's AD transpose "
            "(an exact reduce-scatter); the qgZ quantized reduction cannot "
            "run there — pass quantized_gradients=False with remat")
    dp = mesh.shape[dp_axis]
    state_box = {"shapes": None, "treedef": None}
    # the live knob state closures read: filled from the explicit/legacy
    # resolution above, overwritten by the planner in init() when pending.
    # fused_g/fused_s: the planner resolved the site to "fused_matmul" —
    # the compute-bound int8 chunk ring (ops/collective_matmul.py
    # fused_ring_*): the qwZ gather's hops hide behind the consuming
    # projection's tiles, the qgZ scatter's behind the producing backward
    # matmuls, and each hop's payload is int8 + one-lane scales
    kn = {"qw": quantized_weights, "qg": quantized_gradients,
          "sr": stochastic_rounding, "ring_g": overlap_collective_matmul,
          "ring_s": overlap_collective_matmul, "bidir": False,
          "fused_g": False, "fused_s": False, "fblock": quant_block,
          "pending": plan_pending}

    def shard_spec_tree(tree):
        return jax.tree.map(
            lambda l: sites.zero_flat_shard(dp_axis)
            if getattr(l, "ndim", 0) >= 1 and l.shape[:1] == (dp,)
            else sites.replicated(), tree)

    def init(params):
        flat, treedef = jax.tree.flatten(params)
        state_box["shapes"] = [tuple(p.shape) for p in flat]
        state_box["treedef"] = treedef
        shards = jax.tree.map(lambda p: _shard_leaf(p, dp), params)
        if kn["pending"]:
            # comm-planner zeropp sites: the qwZ gather and qgZ scatter each
            # resolve to one implementation for the ACTUAL flat sizes
            kn["pending"] = False
            from ...comm.planner import resolve_site

            total = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(shards))
            dg = resolve_site(op="all_gather", shape=(max(1, total // dp),),
                              dtype="float32", axes=(dp_axis,),
                              consumer="zeropp", axis_size=dp)
            kn["qw"] = dg.impl == "int8"
            kn["ring_g"] = dg.impl in ("ring", "bidir_ring")
            kn["bidir"] = dg.impl == "bidir_ring"
            kn["fused_g"] = dg.impl == "fused_matmul"
            if dg.impl == "fused_matmul" and dg.block:
                kn["fblock"] = dg.block
            if remat is None:  # remat modes have no qgZ reduction at all
                ds_ = resolve_site(op="reduce_scatter", shape=(total,),
                                   dtype="float32", axes=(dp_axis,),
                                   consumer="zeropp", axis_size=dp)
                kn["qg"] = ds_.impl in ("int8", "int8_sr")
                kn["sr"] = ds_.impl == "int8_sr"
                kn["ring_s"] = ds_.impl == "ring"
                kn["fused_s"] = ds_.impl == "fused_matmul"
        shards = jax.device_put(
            shards, jax.tree.map(
                lambda s: NamedSharding(mesh, sites.zero_flat_shard(dp_axis)),
                shards))
        opt_state = tx.init(shards)
        return ZeroPPState(step=jnp.zeros([], jnp.int32), shards=shards,
                           opt_state=opt_state)

    def _gather(local_1d, shape):
        """shard [m] -> full param [shape] at compute dtype (qwZ)."""
        n = int(np.prod(shape)) if shape else 1
        if kn["fused_g"]:
            # the fused form of qwZ: int8 chunk hops that ride between the
            # consuming projection's tile steps — quantized wire AND the
            # gather latency hidden behind the matmuls it feeds
            from ...ops.collective_matmul import fused_ring_all_gather

            full = fused_ring_all_gather(local_1d, dp_axis,
                                         wire_dtype="int8",
                                         block=kn["fblock"],
                                         tag="zeropp/qwZ")
        elif kn["qw"]:
            full = quantized_all_gather(local_1d, dp_axis, block=quant_block)
        elif kn["ring_g"]:
            # ring-chunked exact gather: p-1 ppermute hops the scheduler can
            # overlap with neighbouring params' matmuls
            from ...ops.collective_matmul import ring_all_gather

            full = ring_all_gather(local_1d, dp_axis,
                                   bidirectional=kn["bidir"])
        else:
            full = lax.all_gather(local_1d, dp_axis)
        return full.reshape(-1)[:n].reshape(shape).astype(compute_dtype)

    def _scatter_sum(grad_full, m):
        """full cotangent -> this rank's SUM shard [m] fp32 — the exact
        transpose of the gather (shared by _reduce and the STE backward)."""
        flat = jnp.ravel(grad_full).astype(jnp.float32)
        flat = jnp.pad(flat, (0, dp * m - flat.shape[0]))
        if kn["ring_s"]:
            from ...ops.collective_matmul import ring_reduce_scatter

            return ring_reduce_scatter(flat, dp_axis)
        return lax.psum_scatter(flat, dp_axis, tiled=True)

    def _reduce(grad_full, m, sr_key=None):
        """full grad -> this rank's mean shard [m] fp32 (qgZ)."""
        if kn["fused_s"]:
            # the fused form of qgZ: the reduction's int8 chunk hops ride
            # between the producing backward matmuls' tile steps
            from ...ops.collective_matmul import fused_ring_reduce_scatter

            flat = jnp.ravel(grad_full).astype(jnp.float32)
            flat = jnp.pad(flat, (0, dp * m - flat.shape[0]))
            return fused_ring_reduce_scatter(
                flat, dp_axis, wire_dtype="int8", block=kn["fblock"],
                stochastic=sr_key is not None, key=sr_key,
                tag="zeropp/qgZ") / dp
        if kn["qg"]:
            flat = jnp.ravel(grad_full).astype(jnp.float32)
            flat = jnp.pad(flat, (0, dp * m - flat.shape[0]))
            return quantized_reduce_scatter(
                flat, dp_axis, block=quant_block,
                stochastic=sr_key is not None, key=sr_key)
        return _scatter_sum(grad_full, m) / dp

    def _ste_gather(m: int, shape):
        """qwZ gather differentiable by straight-through estimation: forward
        is the int8-quantized allgather (_gather), backward the EXACT gather
        transpose (sum reduce-scatter) — int8 rounding has no useful
        gradient."""
        @jax.custom_vjp
        def g(l):
            return _gather(l, shape)

        def fwd(l):
            return _gather(l, shape), None

        def bwd(_, ct):
            return (_scatter_sum(ct, m),)

        g.defvjp(fwd, bwd)
        return g

    def step(state: ZeroPPState, batch):
        flat_shapes = state_box["shapes"]
        # read at trace time (first call, after init resolved any pending
        # plan); remat needs no term: remat + explicit qgZ already raised.
        # The fused scatter ALWAYS dithers: it re-quantizes the gradient
        # accumulator once per hop, so nearest rounding would compound a
        # deterministic bias per hop per step — exactly what int8_sr
        # exists to prevent on gradient paths
        use_sr = (kn["sr"] and kn["qg"]) or kn["fused_s"]

        def body(shards, opt_state, mb, step_ctr):
            local = jax.tree.map(lambda s: s[0], shards)   # [1, m] -> [m]
            leaves, tdef = jax.tree.flatten(local)
            sr_base = None
            if use_sr:
                # per-(step, leaf, rank) dither streams decorrelate the
                # rounding noise; unbiasedness needs none of that, but
                # correlated dither would make the residual coherent
                sr_base = jax.random.fold_in(
                    jax.random.fold_in(jax.random.PRNGKey(0x51), step_ctr),
                    lax.axis_index(dp_axis))

            if remat is None:
                # gather OUTSIDE autodiff: the gather is data movement, not
                # part of the loss — differentiating through all_gather would
                # add its transpose (a second reduce-scatter) on top of the
                # explicit qgZ reduction below
                full = [_gather(jax.lax.stop_gradient(l), shp)
                        for l, shp in zip(leaves, flat_shapes)]

                def forward(full_leaves):
                    return loss_fn(jax.tree.unflatten(tdef, full_leaves), mb)

                loss, grads_full = jax.value_and_grad(forward)(full)
                grad_shards = [
                    _reduce(g, l.shape[0],
                            None if sr_base is None
                            else jax.random.fold_in(sr_base, i))
                    for i, (g, l) in enumerate(zip(grads_full, leaves))]
            else:
                from jax.ad_checkpoint import checkpoint_name

                # hpZ: gather INSIDE the checkpointed forward; the policy
                # decides whether backward re-gathers ("nothing") or reads
                # the saved full weights ("hpz"). Gradients return through
                # the gather transpose: per-shard SUMS over dp.
                def forward(leaves_local):
                    full = []
                    for l, shp in zip(leaves_local, flat_shapes):
                        # _gather's exact branch is lax.all_gather — its AD
                        # transpose is exactly _scatter_sum; the quantized
                        # branch needs the explicit STE vjp (the fused ring
                        # carries its OWN exact-transpose STE vjp)
                        f = (_ste_gather(l.shape[0], shp)(l)
                             if kn["qw"] else _gather(l, shp))
                        full.append(checkpoint_name(f, HPZ_NAME))
                    return loss_fn(jax.tree.unflatten(tdef, full), mb)

                policy = (hpz_remat_policy() if remat == "hpz"
                          else jax.checkpoint_policies.nothing_saveable)
                loss, grads_local = jax.value_and_grad(
                    jax.checkpoint(forward, policy=policy))(leaves)
                grad_shards = [g / dp for g in grads_local]  # sum -> mean

            grad_tree = jax.tree.unflatten(tdef, [g[None] for g in grad_shards])
            updates, new_opt = tx.update(grad_tree, opt_state, shards)
            new_shards = jax.tree.map(jnp.add, shards, updates)
            return new_shards, new_opt, lax.pmean(loss, dp_axis)

        sh_spec = shard_spec_tree(state.shards)
        opt_spec = shard_spec_tree(state.opt_state)
        new_shards, new_opt, loss = _sm(
            body, mesh,
            in_specs=(sh_spec, opt_spec, sites.zero_flat_shard(dp_axis),
                      sites.replicated()),
            out_specs=(sh_spec, opt_spec, sites.replicated()))(
                state.shards, state.opt_state, batch, state.step)
        return ZeroPPState(step=state.step + 1, shards=new_shards,
                           opt_state=new_opt), loss

    def gather_params(state: ZeroPPState):
        """Materialize full fp32 params from shards (checkpoint export)."""
        flat = jax.tree.leaves(state.shards)
        full = [jnp.ravel(s)[:int(np.prod(shp) if shp else 1)].reshape(shp)
                for s, shp in zip(flat, state_box["shapes"])]
        return jax.tree.unflatten(state_box["treedef"], full)

    return init, jax.jit(step, donate_argnums=(0,)), gather_params
