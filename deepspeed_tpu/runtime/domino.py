"""Domino: tensor-parallel communication/compute overlap.

Reference ``DominoModule``/``DominoTransformerLayer``
(``runtime/domino/transformer.py:19``): splits each batch into two
micro-chunks so the TP allreduce of chunk 0's attention overlaps chunk 1's
attention compute (hand-scheduled async NCCL handles). TPU-native: the same
dependency-breaking chunk split, but the *overlap itself is XLA's job* —
with two independent chunk pipelines in one program, XLA's async collective
scheduler hides each chunk's tp-allreduce behind the other chunk's compute.
No handles, no streams; the transformation is purely structural.
"""

from typing import Callable

import jax
import jax.numpy as jnp


def domino_chunked(layer_fn: Callable, x: jnp.ndarray, num_chunks: int = 2
                   ) -> jnp.ndarray:
    """Run ``layer_fn`` (a TP-parallel block containing row-parallel
    allreduces) over ``num_chunks`` batch chunks as independent dataflow
    branches; XLA interleaves chunk i's collectives with chunk j's compute."""
    if x.shape[0] % num_chunks:
        return layer_fn(x)
    chunks = jnp.split(x, num_chunks, axis=0)
    return jnp.concatenate([layer_fn(c) for c in chunks], axis=0)


class DominoTransformerLayer:
    """Callable wrapper pairing a transformer block with the chunk split
    (reference ``DominoTransformerLayer``)."""

    def __init__(self, block_fn: Callable, num_chunks: int = 2):
        self.block_fn = block_fn
        self.num_chunks = num_chunks

    def __call__(self, x, *args, **kwargs):
        return domino_chunked(lambda c: self.block_fn(c, *args, **kwargs),
                              x, self.num_chunks)
