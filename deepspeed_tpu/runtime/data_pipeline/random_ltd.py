"""Random layerwise token dropping (random-LTD).

Reference: ``data_routing/basic_layer.py:14`` (``RandomLayerTokenDrop``) +
``csrc/random_ltd/*`` (token sort/gather/scatter kernels). Each wrapped
transformer layer processes only a random subset of tokens; dropped tokens
bypass the layer unchanged, and the kept-token count ramps up over training.

TPU-native: the kept count is static per schedule stage (one XLA program per
stage — the scheduler quantizes to keep that set small); select/restore are
``jnp.take_along_axis`` / scatter, which XLA fuses — no custom kernels needed.
"""

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp


def random_ltd_select(x: jax.Array, rng: jax.Array, keep: int
                      ) -> Tuple[jax.Array, jax.Array]:
    """Pick ``keep`` random token positions per batch row.

    x: [B, S, H] → (selected [B, keep, H], indices [B, keep] sorted ascending
    so relative order — and thus causal masks/positions — are preserved).
    """
    b, s, _ = x.shape
    scores = jax.random.uniform(rng, (b, s))
    idx = jnp.argsort(scores, axis=-1)[:, :keep]
    idx = jnp.sort(idx, axis=-1)
    sel = jnp.take_along_axis(x, idx[:, :, None], axis=1)
    return sel, idx


def random_ltd_restore(x_full: jax.Array, x_processed: jax.Array,
                       idx: jax.Array) -> jax.Array:
    """Scatter processed tokens back into the full sequence; dropped tokens
    keep their input values (the reference's bypass semantics)."""
    b = x_full.shape[0]
    batch_idx = jnp.arange(b)[:, None]
    return x_full.at[batch_idx, idx].set(x_processed.astype(x_full.dtype))


def random_ltd_apply(layer_fn: Callable, x: jax.Array, rng: jax.Array,
                     keep: int, *args, **kwargs) -> jax.Array:
    """Run ``layer_fn`` on a random ``keep``-token subset of ``x``."""
    if keep >= x.shape[1]:
        return layer_fn(x, *args, **kwargs)
    sel, idx = random_ltd_select(x, rng, keep)
    out = layer_fn(sel, *args, **kwargs)
    return random_ltd_restore(x, out, idx)


class RandomLTDScheduler:
    """Ramp the kept-token count from ``min_value`` to ``max_value`` over
    ``total_layer_drop_step`` steps in ``step_size`` increments (reference
    scheduler config vocabulary: ``random_ltd_schedule``)."""

    def __init__(self, config: Dict):
        r = config.get("random_ltd", config)
        self.min_value = int(r.get("random_ltd_schedule", r).get("min_value", 128))
        sched = r.get("random_ltd_schedule", r)
        self.max_value = int(sched.get("max_value", 2048))
        cfg = sched.get("schedule_config", sched)
        self.total_steps = int(cfg.get("total_layer_drop_step", 10000))
        self.step_size = int(cfg.get("step_size", 16))
        self.current_value = self.min_value

    def get_value(self, global_step: int) -> int:
        frac = min(1.0, global_step / max(1, self.total_steps))
        v = self.min_value + frac * (self.max_value - self.min_value)
        v = int(v // self.step_size) * self.step_size
        return max(self.min_value, min(self.max_value, v))

    def update(self, global_step: int) -> int:
        self.current_value = self.get_value(global_step)
        return self.current_value

    def state_dict(self):
        return {"current_value": self.current_value}

    def load_state_dict(self, sd):
        self.current_value = sd["current_value"]
