"""Offline difficulty analysis (map-reduce).

Reference ``DataAnalyzer`` (``data_sampling/data_analyzer.py``, ~2.5k LoC
distributed map-reduce): a corpus pass computing per-sample "difficulty"
metrics (seqlen, vocab rarity, ...) sharded over workers, then a reduce that
merges shards and emits, per metric:

* ``<out>/<metric>_sample_to_metric.npy`` — metric value per sample index
* ``<out>/<metric>_index_to_sample.npz`` — for each distinct metric value,
  the sample indices having it (the curriculum buckets the sampler draws from)
* the same two tables in the reference's MMAP INDEXED-DATASET format
  (``<metric>_sample_to_metric.bin/.idx``, ``<metric>_index_to_sample.bin/
  .idx`` — item i of the latter holds the sample indices of the i-th
  distinct metric value, with the values themselves in
  ``<metric>_metric_values.npy``), so reference-style samplers can mmap the
  buckets without loading them.

The map phase runs multi-process (``run(num_procs=N)`` forks workers; the
reference uses torch.distributed ranks the same way). Metric fns are
numpy-level; the analysis is host-side (no TPU involvement).
"""

import multiprocessing
import os
from typing import Callable, Dict, List, Sequence

import numpy as np

from ...utils.logging import logger
from .indexed_dataset import MMapIndexedDataset, MMapIndexedDatasetBuilder

METRIC_SEQLEN = "seqlen"


def metric_seqlen(sample) -> int:
    return int(np.asarray(sample).shape[-1])


def metric_vocab_rarity(vocab_freq: np.ndarray) -> Callable:
    """Lower = more common tokens. Difficulty = -mean log frequency."""
    logf = np.log(np.maximum(vocab_freq.astype(np.float64), 1.0))

    def fn(sample) -> int:
        toks = np.asarray(sample).reshape(-1)
        return int(-logf[toks].mean() * 100)  # scaled to int difficulty

    return fn


class DataAnalyzer:
    def __init__(self, dataset, metric_names: Sequence[str] = (METRIC_SEQLEN,),
                 metric_fns: Dict[str, Callable] = None, output_dir: str = "./analysis",
                 num_workers: int = 1, worker_id: int = 0):
        self.dataset = dataset
        self.metric_names = list(metric_names)
        self.metric_fns = dict(metric_fns or {METRIC_SEQLEN: metric_seqlen})
        for m in self.metric_names:
            if m not in self.metric_fns:
                raise ValueError(f"no metric fn for {m!r}")
        self.output_dir = output_dir
        self.num_workers = num_workers
        self.worker_id = worker_id

    # map ---------------------------------------------------------------
    def _shard_range(self):
        n = len(self.dataset)
        per = (n + self.num_workers - 1) // self.num_workers
        lo = self.worker_id * per
        return lo, min(n, lo + per)

    def run_map(self):
        """Compute this worker's shard; writes partial npy files."""
        os.makedirs(self.output_dir, exist_ok=True)
        lo, hi = self._shard_range()
        results = {m: np.empty(hi - lo, np.int64) for m in self.metric_names}
        for i in range(lo, hi):
            sample = self.dataset[i]
            for m in self.metric_names:
                results[m][i - lo] = self.metric_fns[m](sample)
        for m, vals in results.items():
            np.save(self._part_path(m, self.worker_id), vals)

    def _part_path(self, metric: str, worker: int) -> str:
        return os.path.join(self.output_dir, f"{metric}_part{worker}.npy")

    # reduce ------------------------------------------------------------
    def run_reduce(self):
        """Merge worker shards into sample_to_metric + index_to_sample, in
        both npy/npz (quick local loads) and the reference's mmap
        indexed-dataset format (sampler-facing)."""
        for m in self.metric_names:
            parts = [np.load(self._part_path(m, w)) for w in range(self.num_workers)]
            sample_to_metric = np.concatenate(parts)
            np.save(os.path.join(self.output_dir, f"{m}_sample_to_metric.npy"),
                    sample_to_metric)
            values = np.unique(sample_to_metric)
            buckets = {str(v): np.nonzero(sample_to_metric == v)[0] for v in values}
            np.savez(os.path.join(self.output_dir, f"{m}_index_to_sample.npz"),
                     **buckets)

            b = MMapIndexedDatasetBuilder(
                os.path.join(self.output_dir, f"{m}_sample_to_metric"),
                dtype=np.int64)
            b.add_item(sample_to_metric)  # one row, sample-indexed
            b.finalize()
            b = MMapIndexedDatasetBuilder(
                os.path.join(self.output_dir, f"{m}_index_to_sample"),
                dtype=np.int64)
            for v in values:  # item i = sample indices of i-th metric value
                b.add_item(buckets[str(v)])
            b.finalize()
            np.save(os.path.join(self.output_dir, f"{m}_metric_values.npy"),
                    values)

    def run(self, num_procs: int = 1, mp_context: str = "fork"):
        """Map all shards (forked workers when ``num_procs > 1`` — the
        reference's rank-parallel map phase) then reduce.

        The default ``fork`` context keeps closure metric fns usable but is
        only safe BEFORE any accelerator backend initializes in this process
        (forking a live XLA client can deadlock) — run the analysis as its
        own offline step, or pass ``mp_context='spawn'`` with picklable
        metric fns, or ``num_procs=1``.
        """
        if num_procs > 1 and mp_context == "fork":
            # fail CLOSED: forking a process with a live XLA client can
            # deadlock, and if the probe itself breaks (private attr moved in
            # a jax upgrade) we must assume the backend is live
            try:
                import jax

                backend_live = jax._src.xla_bridge._default_backend is not None
            except Exception:
                backend_live = True
            if backend_live:
                logger.warning(
                    "DataAnalyzer.run(num_procs>1): an XLA backend may be "
                    "initialized — fork is unsafe; falling back to in-process "
                    "map (pass mp_context='spawn' with picklable metric fns "
                    "to parallelize)")
                num_procs = 1
        if num_procs > 1:
            from multiprocessing.connection import wait as mp_wait

            ctx = multiprocessing.get_context(mp_context)
            procs = []
            for w in range(self.num_workers):
                a = DataAnalyzer(self.dataset, self.metric_names, self.metric_fns,
                                 self.output_dir, self.num_workers, w)
                procs.append(ctx.Process(target=a.run_map))
            running: List = []
            for p in procs:
                p.start()
                running.append(p)
                if len(running) >= num_procs:  # reap whichever exits FIRST
                    done = mp_wait([r.sentinel for r in running])
                    for r in [r for r in running if r.sentinel in done]:
                        r.join()
                        running.remove(r)
            for p in running:
                p.join()
            for p in procs:
                if p.exitcode:
                    raise RuntimeError(f"analyzer map worker failed rc={p.exitcode}")
        else:
            for w in range(self.num_workers):
                DataAnalyzer(self.dataset, self.metric_names, self.metric_fns,
                             self.output_dir, self.num_workers, w).run_map()
        self.run_reduce()

    # load --------------------------------------------------------------
    @staticmethod
    def load_sample_to_metric(output_dir: str, metric: str) -> np.ndarray:
        return np.load(os.path.join(output_dir, f"{metric}_sample_to_metric.npy"))

    @staticmethod
    def load_indexed_buckets(output_dir: str, metric: str):
        """mmap the index_to_sample buckets (values[i] -> dataset[i])."""
        values = np.load(os.path.join(output_dir, f"{metric}_metric_values.npy"))
        ds = MMapIndexedDataset(os.path.join(output_dir, f"{metric}_index_to_sample"))
        return values, ds
