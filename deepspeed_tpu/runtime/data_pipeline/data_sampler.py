"""Difficulty-ordered curriculum sampling.

Reference ``DeepSpeedDataSampler`` (``data_sampling/data_sampler.py``): at
each step, draw the global batch from the pool of samples whose analyzed
difficulty is within the curriculum's current threshold, deterministically
across hosts (same seed → same indices everywhere; each host then feeds its
dp shard). Consumed samples recycle when the eligible pool is exhausted.

Difficulty comes from :class:`DataAnalyzer` metric files — one metric
(classic) or several (reference ``curriculum_metrics`` schema: a sample is
eligible only while EVERY metric is within its own curriculum threshold).
:func:`build_curriculum_sampler` wires the ``data_efficiency.data_sampling``
config block to the analyzer outputs; ``initialize(training_data=...)``
hands the result to the dataloader (reference ``deepspeed_io`` path).
"""

from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from .curriculum_scheduler import CurriculumScheduler


class DeepSpeedDataSampler:
    def __init__(self, sample_to_metric: Optional[np.ndarray] = None,
                 batch_size: int = 1,
                 curriculum: Optional[CurriculumScheduler] = None,
                 seed: int = 1234, drop_last: bool = True,
                 metrics: Optional[Dict[str, Tuple[np.ndarray,
                                                   CurriculumScheduler]]] = None,
                 draws_per_opt_step: int = 1):
        """Single-metric form: ``(sample_to_metric, batch_size, curriculum)``.
        Multi-metric form: ``metrics={name: (values, scheduler)}`` — the
        eligible pool is the intersection of the per-metric thresholds.

        ``draws_per_opt_step``: how many batches the engine pulls per
        optimizer step (= gradient accumulation steps); curriculum schedules
        are written in OPTIMIZER steps, so difficulty advances every
        ``draws_per_opt_step`` draws, keeping the schedule aligned with the
        engine-side (seqlen) scheduler under gas > 1."""
        if metrics is None:
            if sample_to_metric is None:
                raise ValueError("need sample_to_metric or metrics")
            metrics = {"metric": (np.asarray(sample_to_metric), curriculum)}
        elif sample_to_metric is not None:
            raise ValueError("pass either sample_to_metric or metrics, not both")
        self.metrics = {k: (np.asarray(v), s) for k, (v, s) in metrics.items()}
        first = next(iter(self.metrics.values()))[0]
        self.n_samples = len(first)
        for name, (arr, _) in self.metrics.items():
            if len(arr) != self.n_samples:
                raise ValueError(f"metric {name!r} has {len(arr)} entries, "
                                 f"expected {self.n_samples}")
        # easy→hard order by the first metric: the pool top-up rule (training
        # must always be able to draw one batch) follows it
        self.metric = first
        self.order = np.argsort(self.metric, kind="stable")
        self._sorted_metric = self.metric[self.order]
        self.batch_size = batch_size
        self.seed = seed
        self.drop_last = drop_last
        self.draws_per_opt_step = max(1, int(draws_per_opt_step))
        self.global_step = 0
        self._consumed = 0
        self._perm = None
        self._perm_size = 0
        self._perm_step = 0  # step whose seed generated the live permutation
        self._pool = None
        self._pool_key = None  # difficulty tuple the cached pool was built at
        self._resume_pool_sig = None  # (len, checksum) of the pre-save pool

    def __len__(self):
        return self.n_samples // self.batch_size

    def _pool_sig(self, pool) -> Tuple[int, int]:
        """Cheap content fingerprint: single-metric pools are prefixes of
        one fixed order (length suffices); multi-metric intersections need a
        checksum since content can change at constant size."""
        if len(self.metrics) == 1:
            return (len(pool), 0)
        return (len(pool), int(np.bitwise_xor.reduce((pool + 1) * 2654435761
                                                     % (2 ** 31))))

    def _eligible_pool(self) -> np.ndarray:
        """Sample indices within every metric's current threshold, easy→hard
        by the first metric; topped up with the easiest remaining samples
        when smaller than one batch. Cached keyed on the difficulty tuple —
        the O(n_samples) masks rebuild only when a threshold actually moves
        (and a moved threshold also invalidates the live permutation, since
        the pool's CONTENT may change even at constant size)."""
        opt_step = self.global_step // self.draws_per_opt_step
        key = tuple(None if sched is None else sched.update_difficulty(opt_step)
                    for _, sched in self.metrics.values())
        if key == self._pool_key:
            return self._pool
        floor = min(self.batch_size, self.n_samples)
        if len(self.metrics) == 1:
            # single metric: the pool is a PREFIX of the sorted order —
            # O(log n) per threshold move, no mask rebuild
            k = (self.n_samples if key[0] is None else
                 int(np.searchsorted(self._sorted_metric, key[0], side="right")))
            pool = self.order[:max(k, floor)]
        else:
            mask = np.ones(self.n_samples, bool)
            for diff, (arr, _) in zip(key, self.metrics.values()):
                if diff is not None:
                    mask &= arr <= diff
            in_pool = mask[self.order]
            pool = self.order[in_pool]
            if len(pool) < floor:
                extra = self.order[~in_pool][:floor - len(pool)]
                pool = np.concatenate([pool, extra])
        prev_sig = (self._pool_sig(self._pool) if self._pool is not None
                    else self._resume_pool_sig)  # pre-save pool, if resuming
        self._resume_pool_sig = None
        if prev_sig is not None:
            same = prev_sig == self._pool_sig(pool)
            if not same:
                # the pool's CONTENT changed (not merely a threshold value
                # that admitted nothing new — smooth schedules move nearly
                # every step): never reuse consumed offsets. Content-keying
                # also makes resume exact: at save time the live pool always
                # equals the permutation's pool (a content change would have
                # reset it), so a load_state_dict-restored permutation pairs
                # with the pool re-derived at the resumed step.
                self._perm = None
        self._pool = pool
        self._pool_key = key
        return pool

    def next_batch(self) -> np.ndarray:
        """Global batch of sample indices for the current step."""
        pool = self._eligible_pool()
        n = len(pool)
        if self._perm is None or self._perm_size != n or \
                self._consumed + self.batch_size > len(self._perm):
            rng = np.random.default_rng(self.seed + self.global_step)
            self._perm = rng.permutation(n)
            self._perm_size = n
            self._perm_step = self.global_step
            self._consumed = 0
        sel = self._perm[self._consumed:self._consumed + self.batch_size]
        self._consumed += self.batch_size
        self.global_step += 1
        return pool[sel]

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            yield self.next_batch()

    # checkpoint --------------------------------------------------------
    def state_dict(self):
        return {"global_step": self.global_step, "consumed": self._consumed,
                "seed": self.seed, "perm_step": self._perm_step,
                "perm_size": self._perm_size,
                "pool_sig": (None if self._pool is None
                             else list(self._pool_sig(self._pool)))}

    def load_state_dict(self, sd):
        """Resume exactly: regenerate the live permutation from the seed of
        the step that created it, so the post-resume draw sequence matches an
        uninterrupted run (no replay of consumed samples)."""
        self.global_step = sd["global_step"]
        self._consumed = sd["consumed"]
        self.seed = sd["seed"]
        self._perm_step = sd.get("perm_step", 0)
        self._perm_size = sd.get("perm_size", 0)
        # drop any live pool from draws made BEFORE the restore (rollback
        # into a used sampler): stale pool state must not invalidate the
        # restored permutation. The SAVED pool's fingerprint survives so the
        # first post-resume draw still detects a content change at the
        # resume boundary exactly like an uninterrupted run would.
        self._pool = None
        self._pool_key = None
        sig = sd.get("pool_sig")
        self._resume_pool_sig = None if sig is None else tuple(sig)
        if self._perm_size > 0:
            rng = np.random.default_rng(self.seed + self._perm_step)
            self._perm = rng.permutation(self._perm_size)
        else:
            self._perm = None


def build_curriculum_sampler(data_sampling_cfg: dict, batch_size: int,
                             seed: int = 1234, draws_per_opt_step: int = 1
                             ) -> Optional[DeepSpeedDataSampler]:
    """Wire the ``data_efficiency.data_sampling`` config block to a sampler
    over :class:`DataAnalyzer` metric files (reference
    ``curriculum_learning.curriculum_metrics`` schema,
    ``data_sampling/data_sampler.py``)::

        {"curriculum_learning": {
            "enabled": true,
            "curriculum_metrics": {
                "vocab_rarity": {
                    "sample_to_metric_path": "<analyzer output dir>",
                    "min_difficulty": 10, "max_difficulty": 600,
                    "schedule_type": "fixed_linear",
                    "schedule_config": {"total_curriculum_step": 100}}}}}

    ``sample_to_metric_path`` is the DataAnalyzer output dir (the metric
    name keys the file) or a direct ``.npy`` path. Returns None when no
    metric is configured — the engine's seqlen truncation hook then stands
    alone (``runtime/engine.py`` ``train_batch``).
    """
    from .data_analyzer import DataAnalyzer

    cl = data_sampling_cfg.get("curriculum_learning", {})
    if not cl.get("enabled"):
        return None
    metrics_cfg = cl.get("curriculum_metrics") or {}
    if not metrics_cfg:
        return None
    metrics = {}
    for name, mc in metrics_cfg.items():
        path = mc["sample_to_metric_path"]
        arr = (np.load(path) if path.endswith(".npy")
               else DataAnalyzer.load_sample_to_metric(path, name))
        if np.issubdtype(arr.dtype, np.floating):
            # CurriculumScheduler difficulties are integers (reference
            # semantics); a float metric in (0,1) would silently truncate
            # its thresholds to 0 and disable the curriculum
            raise ValueError(
                f"curriculum metric {name!r} is float-valued ({arr.dtype}); "
                "scale it to integers in the DataAnalyzer metric fn (e.g. "
                "metric_vocab_rarity multiplies by 100)")
        sched = CurriculumScheduler({**mc, "curriculum_type": name})
        metrics[name] = (arr, sched)
    return DeepSpeedDataSampler(metrics=metrics, batch_size=batch_size,
                                seed=cl.get("seed", seed),
                                draws_per_opt_step=draws_per_opt_step)
