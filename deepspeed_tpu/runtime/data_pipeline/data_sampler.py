"""Difficulty-ordered curriculum sampling.

Reference ``DeepSpeedDataSampler`` (``data_sampling/data_sampler.py``): at
each step, draw the global batch from the pool of samples whose analyzed
difficulty is within the curriculum's current threshold, deterministically
across hosts (same seed → same indices everywhere; each host then feeds its
dp shard). Consumed samples recycle when the eligible pool is exhausted.
"""

from typing import Iterator, Optional, Sequence

import numpy as np

from .curriculum_scheduler import CurriculumScheduler


class DeepSpeedDataSampler:
    def __init__(self, sample_to_metric: np.ndarray, batch_size: int,
                 curriculum: Optional[CurriculumScheduler] = None,
                 seed: int = 1234, drop_last: bool = True):
        self.metric = np.asarray(sample_to_metric)
        self.order = np.argsort(self.metric, kind="stable")  # easy → hard
        self.sorted_metric = self.metric[self.order]
        self.batch_size = batch_size
        self.curriculum = curriculum
        self.seed = seed
        self.drop_last = drop_last
        self.global_step = 0
        self._consumed = 0
        self._perm = None
        self._perm_size = 0
        self._perm_step = 0  # step whose seed generated the live permutation

    def __len__(self):
        return len(self.metric) // self.batch_size

    def _eligible_count(self) -> int:
        if self.curriculum is None:
            return len(self.metric)
        difficulty = self.curriculum.update_difficulty(self.global_step)
        # all samples with metric <= current difficulty threshold
        return int(np.searchsorted(self.sorted_metric, difficulty, side="right"))

    def next_batch(self) -> np.ndarray:
        """Global batch of sample indices for the current step."""
        n = max(self._eligible_count(), min(self.batch_size, len(self.metric)))
        if self._perm is None or self._perm_size != n or \
                self._consumed + self.batch_size > len(self._perm):
            rng = np.random.default_rng(self.seed + self.global_step)
            self._perm = rng.permutation(n)
            self._perm_size = n
            self._perm_step = self.global_step
            self._consumed = 0
        sel = self._perm[self._consumed:self._consumed + self.batch_size]
        self._consumed += self.batch_size
        self.global_step += 1
        return self.order[sel]

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            yield self.next_batch()

    # checkpoint --------------------------------------------------------
    def state_dict(self):
        return {"global_step": self.global_step, "consumed": self._consumed,
                "seed": self.seed, "perm_step": self._perm_step,
                "perm_size": self._perm_size}

    def load_state_dict(self, sd):
        """Resume exactly: regenerate the live permutation from the seed of
        the step that created it, so the post-resume draw sequence matches an
        uninterrupted run (no replay of consumed samples)."""
        self.global_step = sd["global_step"]
        self._consumed = sd["consumed"]
        self.seed = sd["seed"]
        self._perm_step = sd.get("perm_step", 0)
        self._perm_size = sd.get("perm_size", 0)
        if self._perm_size > 0:
            rng = np.random.default_rng(self.seed + self._perm_step)
            self._perm = rng.permutation(self._perm_size)
        else:
            self._perm = None
