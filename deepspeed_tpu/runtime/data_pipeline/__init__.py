"""Data pipeline: curriculum learning, difficulty-based sampling, mmap
datasets, offline difficulty analysis, random-LTD token dropping.

Reference: ``deepspeed/runtime/data_pipeline/`` — ``curriculum_scheduler.py``,
``data_sampling/{data_sampler,data_analyzer,indexed_dataset}.py``,
``data_routing/basic_layer.py`` (RandomLTD).
"""

from .curriculum_scheduler import CurriculumScheduler
from .data_analyzer import DataAnalyzer
from .data_sampler import DeepSpeedDataSampler, build_curriculum_sampler
from .indexed_dataset import MMapIndexedDataset, MMapIndexedDatasetBuilder
from .random_ltd import RandomLTDScheduler, random_ltd_apply, random_ltd_select

__all__ = [
    "CurriculumScheduler", "DataAnalyzer", "DeepSpeedDataSampler",
    "build_curriculum_sampler",
    "MMapIndexedDataset", "MMapIndexedDatasetBuilder",
    "RandomLTDScheduler", "random_ltd_apply", "random_ltd_select",
]
