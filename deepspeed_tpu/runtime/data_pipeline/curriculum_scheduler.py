"""Curriculum learning scheduler.

Reference ``CurriculumScheduler`` (``runtime/data_pipeline/
curriculum_scheduler.py:11``): maps the global training step to a
"difficulty" (canonically the effective sequence length), increasing it over
training per a configured schedule. The engine/dataloader truncate or filter
batches to the current difficulty. On TPU each distinct difficulty is a new
static shape → one XLA recompile; ``fixed_discrete`` and the rounded
``fixed_linear``/``fixed_root`` schedules keep that set small.
"""

import math
from typing import Any, Callable, Dict, Optional

FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:
    """Config keys follow the reference vocabulary::

        {"curriculum_type": "seqlen", "min_difficulty": 64,
         "max_difficulty": 1024, "schedule_type": "fixed_linear",
         "schedule_config": {"total_curriculum_step": 10000,
                             "difficulty_step": 8}}

    ``fixed_root`` adds ``root_degree``; ``fixed_discrete`` instead takes
    ``{"difficulty": [...], "max_step": [...]}``; ``custom`` takes a callable
    via :meth:`set_custom_get_difficulty`.
    """

    def __init__(self, config: Dict[str, Any]):
        self.state: Dict[str, Any] = {}
        self.curriculum_type = config.get("curriculum_type", "seqlen")
        self.min_difficulty = int(config.get("min_difficulty", 1))
        self.max_difficulty = int(config.get("max_difficulty", self.min_difficulty))
        self.schedule_type = config.get("schedule_type", FIXED_LINEAR)
        self.schedule = dict(config.get("schedule_config", {}))
        self.custom_get_difficulty: Optional[Callable[[int], int]] = None
        self.current_difficulty = self.min_difficulty
        self.first_step = True

        if self.schedule_type in (FIXED_LINEAR, FIXED_ROOT):
            if "total_curriculum_step" not in self.schedule:
                raise ValueError(f"{self.schedule_type} needs schedule_config."
                                 "total_curriculum_step")
            self.schedule.setdefault("difficulty_step", 1)
            if self.schedule_type == FIXED_ROOT:
                self.schedule.setdefault("root_degree", 2)
        elif self.schedule_type == FIXED_DISCRETE:
            diffs = self.schedule.get("difficulty")
            steps = self.schedule.get("max_step")
            if not diffs or steps is None or len(steps) != len(diffs) - 1:
                raise ValueError("fixed_discrete needs schedule_config.difficulty "
                                 "(N values) and max_step (N-1 boundaries)")
        elif self.schedule_type != CUSTOM:
            raise ValueError(f"unknown curriculum schedule_type {self.schedule_type!r}")

    # ------------------------------------------------------------------
    def set_custom_get_difficulty(self, fn: Callable[[int], int]):
        self.custom_get_difficulty = fn

    def get_current_difficulty(self) -> int:
        return self.current_difficulty

    def update_difficulty(self, global_step: int) -> int:
        self.current_difficulty = self.get_difficulty(global_step)
        return self.current_difficulty

    # ------------------------------------------------------------------
    def get_difficulty(self, global_step: int) -> int:
        if self.schedule_type == FIXED_LINEAR:
            return self._fixed_linear(global_step)
        if self.schedule_type == FIXED_ROOT:
            return self._fixed_root(global_step)
        if self.schedule_type == FIXED_DISCRETE:
            return self._fixed_discrete(global_step)
        if self.custom_get_difficulty is None:
            raise RuntimeError("custom curriculum schedule needs "
                               "set_custom_get_difficulty(fn)")
        return int(self.custom_get_difficulty(global_step))

    def _quantize(self, diff: float) -> int:
        step = int(self.schedule["difficulty_step"])
        d = int(diff // step) * step
        return max(self.min_difficulty, min(self.max_difficulty, d))

    def _fixed_linear(self, global_step: int) -> int:
        total = self.schedule["total_curriculum_step"]
        frac = min(1.0, global_step / total)
        diff = self.min_difficulty + frac * (self.max_difficulty - self.min_difficulty)
        return self._quantize(diff)

    def _fixed_root(self, global_step: int) -> int:
        total = self.schedule["total_curriculum_step"]
        degree = self.schedule["root_degree"]
        frac = min(1.0, global_step / total) ** (1.0 / degree)
        diff = self.min_difficulty + frac * (self.max_difficulty - self.min_difficulty)
        return self._quantize(diff)

    def _fixed_discrete(self, global_step: int) -> int:
        diffs = self.schedule["difficulty"]
        bounds = self.schedule["max_step"]
        for d, bound in zip(diffs, bounds):
            if global_step <= bound:
                return int(d)
        return int(diffs[-1])
