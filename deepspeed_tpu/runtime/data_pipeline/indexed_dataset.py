"""Memory-mapped indexed dataset.

Reference: ``data_sampling/indexed_dataset.py`` (Megatron-style ``.bin`` +
``.idx`` pair). Re-designed minimal format (not a byte-level copy):

``<path>.bin`` — all documents' tokens, flat, one dtype.
``<path>.idx`` — header ``DSTPUIDX`` + version u32 + dtype code u32 +
doc count u64, then ``sizes`` (u32[count]) and ``pointers`` (u64[count],
byte offsets into .bin).

Reads are ``np.memmap`` slices — zero-copy host RAM paging, which feeds
``jax.device_put`` per batch without materializing the corpus.
"""

import os
import struct
from typing import Sequence

import numpy as np

_MAGIC = b"DSTPUIDX"
_VERSION = 1

_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
           5: np.int64, 6: np.float32, 7: np.float64, 8: np.uint16,
           9: np.uint32}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    """Streaming writer: ``add_item(tokens)`` per document, ``finalize()``."""

    def __init__(self, path_prefix: str, dtype=np.int32):
        self.prefix = path_prefix
        self.dtype = np.dtype(dtype)
        if self.dtype not in _DTYPE_CODES:
            raise ValueError(f"unsupported dtype {dtype}")
        self._data = open(data_file_path(path_prefix), "wb")
        self._sizes = []
        self._pointers = []
        self._offset = 0

    def add_item(self, tokens: Sequence[int]):
        arr = np.asarray(tokens, dtype=self.dtype)
        self._data.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)
        self._pointers.append(self._offset)
        self._offset += arr.nbytes

    def merge_file_(self, other_prefix: str):
        """Append another builder's finalized files (the reduce step of
        multi-worker dataset building, reference ``merge_file_``)."""
        other = MMapIndexedDataset(other_prefix)
        for i in range(len(other)):
            self.add_item(other[i])

    def finalize(self):
        self._data.close()
        with open(index_file_path(self.prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<II", _VERSION, _DTYPE_CODES[self.dtype]))
            f.write(struct.pack("<Q", len(self._sizes)))
            f.write(np.asarray(self._sizes, np.uint32).tobytes())
            f.write(np.asarray(self._pointers, np.uint64).tobytes())


class MMapIndexedDataset:
    """Random-access reader over the ``.bin``/``.idx`` pair."""

    def __init__(self, path_prefix: str):
        idx_path = index_file_path(path_prefix)
        with open(idx_path, "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(f"{idx_path}: bad magic {magic!r}")
            version, dtype_code = struct.unpack("<II", f.read(8))
            if version != _VERSION:
                raise ValueError(f"{idx_path}: unsupported version {version}")
            (count,) = struct.unpack("<Q", f.read(8))
            header = f.tell()
        self.dtype = np.dtype(_DTYPES[dtype_code])
        idx = np.memmap(idx_path, mode="r", offset=header, dtype=np.uint8)
        self.sizes = idx[:count * 4].view(np.uint32)
        self.pointers = idx[count * 4:count * 4 + count * 8].view(np.uint64)
        self._data = np.memmap(data_file_path(path_prefix), mode="r", dtype=np.uint8)

    def __len__(self) -> int:
        return len(self.sizes)

    def __getitem__(self, i: int) -> np.ndarray:
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        ptr, size = int(self.pointers[i]), int(self.sizes[i])
        nbytes = size * self.dtype.itemsize
        return self._data[ptr:ptr + nbytes].view(self.dtype)

    def get(self, i: int, offset: int = 0, length: int = None) -> np.ndarray:
        doc = self[i]
        return doc[offset:None if length is None else offset + length]

    @staticmethod
    def exists(path_prefix: str) -> bool:
        return (os.path.exists(data_file_path(path_prefix))
                and os.path.exists(index_file_path(path_prefix)))
