"""MoQ — Mixture of Quantization (training-time weight quantization).

Reference ``Quantizer`` (``runtime/quantize.py``) + ``WeightQuantization``
(``runtime/weight_quantizer.py``): anneal weight precision from
``start_bits`` to ``target_bits`` every ``quantize_period`` steps, optionally
modulated per-layer by Hessian eigenvalues (sharp layers quantize later).
Built on the compression QAT primitives; this class owns the schedule.
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..compression.basic_layer import quantize_weight
from ..utils.logging import logger


class MoQQuantizer:
    @classmethod
    def from_config(cls, qt) -> "MoQQuantizer":
        """Build from a ``quantize_training`` config node (the reference MoQ
        JSON vocabulary, ``runtime/config.py:567``)."""
        return cls(q_type=qt.quantize_type,
                   start_bits=qt.quantize_bits.start_bits,
                   target_bits=qt.quantize_bits.target_bits,
                   quantize_period=qt.quantize_schedule.quantize_period,
                   schedule_offset=qt.quantize_schedule.schedule_offset,
                   quantize_groups=qt.quantize_groups)

    def __init__(self, q_type: str = "symmetric", start_bits: int = 16,
                 target_bits: int = 8, quantize_period: int = 100,
                 quantize_groups: int = 1, eigenvalue_scale: Optional[Dict[str, float]] = None,
                 schedule_offset: int = 0):
        self.symmetric = q_type == "symmetric"
        self.start_bits = start_bits
        self.target_bits = target_bits
        self.period = quantize_period
        self.offset = schedule_offset  # steps at full precision before annealing
        self.groups = quantize_groups
        # larger eigenvalue -> longer effective period (quantize later)
        self.eigenvalue_scale = eigenvalue_scale or {}
        self.current_bits = start_bits

    def bits_at(self, step: int, key: str = "") -> int:
        if step < self.offset:
            # reference schedule_offset warmup: NO quantization at all before
            # the offset (quantize() skips bits >= 16), even when start_bits
            # is already narrow
            return 16
        period = self.period
        scale = self.eigenvalue_scale.get(key)
        if scale is not None:
            period = int(period * max(1.0, scale))
        bits, s = self.start_bits, step - self.offset
        while bits > self.target_bits and s >= period:
            bits = max(self.target_bits, bits // 2)
            s -= period
        return bits

    def update(self, step: int) -> int:
        self.current_bits = self.bits_at(step)
        return self.current_bits

    def quantize(self, params, step: int, training: bool = True,
                 bits: Optional[int] = None):
        """Fake-quantize every >=2-D floating leaf at its scheduled bits;
        ``bits`` overrides the schedule (the engine passes the precomputed
        width so the compiled step stays static)."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        out = []
        for kp, leaf in flat:
            key = "/".join(str(getattr(e, "key", getattr(e, "name", e))) for e in kp)
            if hasattr(leaf, "ndim") and leaf.ndim >= 2 and \
                    jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
                b = self.bits_at(step, key) if bits is None else bits
                if b < 16:
                    leaf = quantize_weight(leaf, b, self.groups,
                                           self.symmetric, training)
            out.append(leaf)
        return jax.tree_util.tree_unflatten(treedef, out)


class WeightQuantization(MoQQuantizer):
    """Reference-named alias (``runtime/weight_quantizer.py``)."""
