"""Sparse gradients for embeddings.

Reference ``SparseTensor`` (``runtime/sparse_tensor.py:69``) +
``engine.sparse_allreduce:2564``: embedding grads shipped as (indices,
values) pairs so the allreduce moves only touched rows. In JAX embedding
grads come out dense; the sparse path pays off when few vocabulary rows are
touched per step — ``from_dense`` extracts the touched rows (static capacity
``max_rows`` for XLA), ``sparse_all_reduce`` allgathers the compact pairs and
re-accumulates locally.
"""

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax


class SparseTensor(NamedTuple):
    indices: jnp.ndarray    # [R] row ids (may repeat; -1 = empty slot)
    values: jnp.ndarray     # [R, D] row values
    dense_shape: tuple

    @property
    def sparse_size(self) -> int:
        return int(self.indices.shape[0]) * int(self.values.shape[-1])

    def to_dense(self) -> jnp.ndarray:
        out = jnp.zeros(self.dense_shape, self.values.dtype)
        safe = jnp.where(self.indices < 0, 0, self.indices)
        mask = (self.indices >= 0).reshape((-1,) + (1,) * (self.values.ndim - 1))
        return out.at[safe].add(jnp.where(mask, self.values,
                                          jnp.zeros_like(self.values)))


def from_dense(grad: jnp.ndarray, max_rows: int) -> SparseTensor:
    """Extract the top-``max_rows`` rows by L1 mass (static shape for XLA).
    Exact whenever at most ``max_rows`` rows are nonzero — the embedding-grad
    case this path exists for; beyond capacity the smallest rows are
    dropped (size the capacity at the per-step token count to avoid that)."""
    mass = jnp.sum(jnp.abs(grad), axis=tuple(range(1, grad.ndim)))
    top = jax.lax.top_k(mass, max_rows)
    idx = jnp.where(top[0] > 0, top[1].astype(jnp.int32), -1)
    mask = (idx >= 0).reshape((-1,) + (1,) * (grad.ndim - 1))
    vals = jnp.where(mask, grad[jnp.where(idx < 0, 0, idx)], 0)
    return SparseTensor(indices=idx, values=vals, dense_shape=tuple(grad.shape))


def sparse_all_reduce(st: SparseTensor, axis) -> jnp.ndarray:
    """Mean-reduce a sparse grad across ``axis`` (inside shard_map/jit):
    allgather the compact (indices, values), densify once, divide by world —
    comm volume is R·D per rank instead of V·D (reference
    ``sparse_allreduce_bucket``)."""
    from ..utils.shard_map_compat import axis_size

    world = axis_size(axis)
    all_idx = lax.all_gather(st.indices, axis)          # [W, R]
    all_val = lax.all_gather(st.values, axis)           # [W, R, D]
    merged = SparseTensor(indices=all_idx.reshape(-1),
                          values=all_val.reshape(-1, st.values.shape[-1]),
                          dense_shape=st.dense_shape)
    return merged.to_dense() / world
