"""The training engine.

TPU-native re-design of ``DeepSpeedEngine`` (reference ``runtime/engine.py:183``).
The reference wraps a torch ``nn.Module`` and orchestrates mixed precision,
gradient accumulation, ZeRO collectives, and the optimizer step imperatively
(hooks + streams). Here the whole training step — microbatch scan, grad
accumulation, loss scaling, clipping, optimizer update, overflow skip — is one
pure function compiled by XLA over the device mesh; ZeRO stages are sharding
rules (``runtime/zero/sharding.py``) on the state pytree, and XLA schedules the
allgather/reduce-scatter traffic the reference issued by hand.

API surface preserved from the reference:
  ``initialize(...) -> engine`` (``deepspeed/__init__.py:69``);
  ``engine.train_batch`` / ``engine.eval_batch``;
  compat ``forward``/``backward``/``step`` (``engine.py:1848,2007,2204``);
  ``save_checkpoint``/``load_checkpoint`` (``engine.py:3140,2794``).
"""

import contextlib
import inspect
import json
import os
import time
from functools import partial
from typing import Any, Callable, Dict, Iterable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import comm as dist
from ..ops.optimizers import build_optimizer
from ..telemetry.spans import span
from ..parallel.topology import Topology, TopologySpec, get_topology, set_topology
from ..utils.logging import log_dist, logger
from .config import DeepSpeedTPUConfig, load_config
from .config_utils import ConfigError
from .loss_scaler import (LossScaleState, has_overflow, make_loss_scale_state,
                          update_loss_scale)
from .lr_schedules import build_lr_schedule
from .zero.sharding import ZeroShardingRules

try:
    from flax import struct
except ImportError:  # pragma: no cover
    struct = None

# the control plane's remat escalation ladder (engine.raise_remat): no
# remat -> keep only matmul outputs -> keep nothing (max memory headroom,
# max recompute). Each entry names a jax.checkpoint_policies member
# (None = unwrapped); a custom configured policy escalates straight to
# the last rung.
REMAT_LADDER = (None, "dots_saveable", "nothing_saveable")


def artifact_rank() -> int:
    """The rank stamped on per-rank post-mortem artifacts (flightdumps,
    hangdumps, heartbeat beacons, doctor reports). ``jax.process_index()``
    when the control plane is genuinely multi-process; otherwise the
    launcher's ``DSTPU_PROCESS_ID`` env — fake-fleet drills run N
    *independent* single-process jax instances against one dump dir, and
    they must not all claim rank 0 — defaulting to 0."""
    if jax.process_count() > 1:
        return jax.process_index()
    try:
        return int(os.environ.get("DSTPU_PROCESS_ID", "0") or 0)
    except ValueError:
        return 0


@struct.dataclass
class TrainState:
    """Engine state pytree. ``params`` are fp32 master weights (reference
    FP16/BF16 optimizer master copies, ``runtime/fp16/fused_optimizer.py:33``,
    ``bf16_optimizer.py:34``) unless master weights are disabled.

    ``comm_feedback`` is the cross-step error-feedback residual of a
    DCN-compressed gradient program (``comm/compressed.py``
    ``run_collective_program`` with an ``int8_ef`` hop): engine-OWNED state,
    threaded through the jitted step like the optimizer state, so one
    residual accumulates across steps (instead of a fresh zero per trace)
    and it rides resilience snapshots — a rollback restores the snapshot's
    residual rather than replaying the abandoned trajectory's. Empty
    (``()`` — zero pytree leaves) whenever feedback is off, which keeps
    every default-off path structurally and bitwise identical."""
    step: jnp.ndarray
    params: Any
    opt_state: Any
    loss_scale: LossScaleState
    comm_feedback: Any = ()


def _tree_where(pred, a, b):
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


def global_grad_norm(grads) -> jnp.ndarray:
    """L2 norm across the whole grad pytree (reference ``clip_grad_norm_``,
    ``runtime/utils.py:315`` — the cross-rank reduction is implicit in SPMD)."""
    leaves = [jnp.sum(jnp.square(g.astype(jnp.float32))) for g in jax.tree.leaves(grads)]
    return jnp.sqrt(jnp.sum(jnp.stack(leaves)))


def _path_key(entry) -> str:
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def _struct_congruent_specs(state_shapes, params, param_spec_tree):
    """Build a PartitionSpec tree congruent to an optimizer-state pytree.

    Optimizer states are built of params-congruent subtrees (momenta, master
    copies) plus scalars (step counters). A state leaf whose key-path *suffix*
    and shape match a param gets that param's spec; everything else is
    replicated. Works for arbitrarily nested optax chain states.
    """
    param_leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    spec_leaves = jax.tree.leaves(param_spec_tree, is_leaf=lambda x: isinstance(x, P))
    lookup = {}
    for (path, leaf), spec in zip(param_leaves, spec_leaves):
        lookup[(tuple(_path_key(e) for e in path), leaf.shape)] = spec

    max_plen = max((len(k[0]) for k in lookup), default=0)

    def spec_for(path, leaf):
        if not hasattr(leaf, "shape") or leaf.shape == ():
            return P()  # spec-ok: scalar leaves replicate
        keys = tuple(_path_key(e) for e in path)
        for take in range(min(len(keys), max_plen), 0, -1):
            spec = lookup.get((keys[-take:], leaf.shape))
            if spec is not None:
                return spec
        return P()  # spec-ok: lookup fallback: replicate unknown leaves

    flat, treedef = jax.tree_util.tree_flatten_with_path(state_shapes)
    return jax.tree_util.tree_unflatten(treedef, [spec_for(p, l) for p, l in flat])


def _abstract_params(params):
    """Shape tree for possibly-lazy params (the zero.Init closure form)."""
    return (jax.eval_shape(params)
            if callable(params) and not hasattr(params, "shape") else params)


def _frozen_label_tree(params, patterns: Sequence[str]):
    """'freeze'/'train' label per leaf: a leaf freezes when any pattern hits
    its '/'-joined path at a name-component boundary (same matching contract
    as AutoTP's name vocabulary). A pattern matching NOTHING is an error —
    a typo'd pattern silently training everything (and materializing full
    Adam state) is exactly what the user asked to avoid."""
    import re

    def hit(pattern: str, path: str) -> bool:
        return re.search(rf"(^|[/_.\-]){re.escape(pattern)}([/_.\-]|$)",
                         path) is not None

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    paths = ["/".join(str(getattr(e, "key", getattr(e, "name", e))) for e in kp)
             for kp, _ in flat]
    unmatched = [p for p in patterns if not any(hit(p, path) for path in paths)]
    if unmatched:
        raise ValueError(f"frozen_params patterns {unmatched} match no "
                         f"parameter path; available paths include "
                         f"{paths[:8]}...")
    labels = ["freeze" if any(hit(p, path) for p in patterns) else "train"
              for path in paths]
    return jax.tree_util.tree_unflatten(treedef, labels)


class DeepSpeedTPUEngine:
    def __init__(self,
                 loss_fn: Callable,
                 params: Any,
                 config: DeepSpeedTPUConfig,
                 topology: Optional[Topology] = None,
                 param_specs: Any = None,
                 batch_spec: Any = None,
                 optimizer: Optional[optax.GradientTransformation] = None,
                 lr_scheduler: Optional[Callable] = None,
                 donate_state: bool = True,
                 autotp_example_batch: Any = None,
                 frozen_params: Optional[Sequence[str]] = None):
        self.config = config
        self.topo = topology or get_topology()
        set_topology(self.topo)
        config.finalize(world_dp_size=self.topo.dp_size)
        # compressed collectives: flip the fleet-wide default the wiring
        # reads (comm/compressed.py — the set_overlap_enabled pattern)
        cc = config.compressed_collectives
        from ..comm.compressed import configure_compression
        configure_compression(cc.mode, block=cc.block,
                              hierarchical=cc.hierarchical,
                              sites=cc.site_map())
        # collective planner (comm/planner): snapshot the explicitly-set
        # raw knobs (they keep winning at their sites) and stand up the
        # fleet planner in the configured mode — off is inert
        from ..comm.planner import configure_from_config
        configure_from_config(config, topology=self.topo)
        # training fast path (ops/fastpath.py): flip the fleet defaults the
        # attention/loss/embedding wirings read when the model config says
        # 'auto' — same pattern as configure_compression above
        tf = config.training_fastpath
        from ..ops.fastpath import configure_fastpath
        configure_fastpath(attn_impl=tf.attn_impl, loss_impl=tf.loss_impl,
                           embedding_overlap=tf.embedding_overlap)
        # engine-level rematerialization: with activation_checkpointing
        # .engine_wrap, ``policy`` names a jax.checkpoint_policies entry
        # applied around the whole loss fn (None never wraps — bit-
        # identical). engine_wrap is opt-in because the per-layer compat
        # API (checkpointing.checkpoint) reads the SAME policy field —
        # wrapping the engine on top would double-rematerialize those
        # models. Read at trace time: the control plane's raise_remat()
        # actuator climbs REMAT_LADDER and invalidates the compiled steps.
        ac = config.activation_checkpointing
        self._remat_policy = ac.policy if ac.engine_wrap else None
        if (optimizer is not None and callable(optimizer)
                and not hasattr(optimizer, "update")):
            # reference DeepSpeedOptimizerCallable (deepspeed/__init__.py:112):
            # a client factory taking model parameters; here it must return
            # an optax GradientTransformation. The factory sees the ABSTRACT
            # tree (shapes/dtypes/structure) so the zero.Init closure form
            # stays lazy — masked/multi_transform-style factories only need
            # the structure anyway
            optimizer = optimizer(_abstract_params(params))
            if not hasattr(optimizer, "update"):
                raise TypeError(
                    "optimizer callable must return an optax "
                    f"GradientTransformation, got {type(optimizer).__name__}")
            log_dist("using client callable to create basic optimizer")
        self._client_optimizer = optimizer is not None  # resilience lr_drop warning
        self.loss_fn_raw = loss_fn
        self._loss_takes_rng = _accepts_rng(loss_fn)
        self._loss_takes_ltd = _accepts_kw(loss_fn, "ltd_keep")
        self.gas = config.gradient_accumulation_steps
        self.micro_batch_size = config.train_micro_batch_size_per_gpu
        self.train_batch_size = config.train_batch_size

        zc = config.zero_optimization
        self.rules = ZeroShardingRules(zc.stage, self.topo, mics_shard_size=zc.mics_shard_size)
        from ..sharding.rules import (ForeignModelShardingError, RuleSet,
                                      spec_tree_axis_sizes)
        if isinstance(param_specs, RuleSet):
            # declarative sharding: match the rule set over the (possibly
            # lazy) param tree; axis_sizes validates mesh membership and
            # downgrades indivisible dims instead of failing at compile
            param_specs = param_specs.match(
                _abstract_params(params),
                axis_sizes=spec_tree_axis_sizes(self.topo))
        if (param_specs is None and self.topo.tp_size > 1
                and not getattr(loss_fn, "_sharding_native", False)):
            # a foreign apply_fn + param tree at tp>1 with no specs would
            # silently replicate every parameter over the tp axis — dense
            # compute on every rank, none of the TP fast paths. Refuse.
            raise ForeignModelShardingError(
                "tp_size={} with no param_specs and a non-TransformerLM "
                "model: parameters would silently replicate over the tp "
                "axis. Pass param_specs='auto' (AutoTP inference), a "
                "sharding.RuleSet (e.g. sharding.get_pack(...) or "
                "sharding.derive_rules(...)), an explicit spec tree, or "
                "load the checkpoint through "
                "sharding.autotp_initialize().".format(self.topo.tp_size))
        if isinstance(param_specs, str) and param_specs == "auto":
            # AutoTP (reference module_inject/auto_tp.py:189): infer TP
            # PartitionSpecs from the param tree. With an example batch the
            # jaxpr dataflow analysis classifies col/row from the program;
            # otherwise the reference's name vocabulary decides.
            from ..module_inject import tp_parser
            abstract = _abstract_params(params)
            if autotp_example_batch is not None:
                if self._loss_takes_rng:
                    trace_fn = lambda p, b: loss_fn(p, b, jax.random.PRNGKey(0))  # noqa: E731
                else:
                    trace_fn = loss_fn
                param_specs = tp_parser(
                    abstract, apply_fn=trace_fn,
                    example_inputs=(autotp_example_batch,),
                    tp_size=self.topo.tp_size)
            else:
                param_specs = tp_parser(abstract, tp_size=self.topo.tp_size)
        self.param_specs_base = param_specs
        self._offload_optimizer = zc.offload_optimizer.device in ("cpu", "nvme")
        # True host-offload (ZeRO-Offload): device=cpu + an adam-family config
        # optimizer runs the update ON HOST via the native kernel
        # (csrc/adam/cpu_adam.cpp); optimizer state never exists on device.
        # A custom optax optimizer or non-adam type falls back to pinned-host
        # storage with on-device compute (the previous tier).
        self._host_adam = None
        self._host_adam_mode = (
            zc.offload_optimizer.device == "cpu" and optimizer is None
            and config.optimizer.type.lower().replace("_", "") in
            ("adam", "adamw", "fusedadam", "cpuadam", "deepspeedcpuadam"))
        if self._host_adam_mode and config.fp16.enabled:
            raise ValueError(
                "fp16 dynamic loss scaling is not supported with "
                "offload_optimizer.device='cpu' (the host Adam step runs "
                "outside the scaled program); use bf16 — the TPU default")
        if self._host_adam_mode and jax.process_count() > 1:
            # host Adam needs fully-addressable grads; on a multi-process
            # mesh fall back to the pinned-host storage tier
            log_dist("offload_optimizer.device=cpu: multi-process mesh — "
                     "falling back to pinned-host optimizer state with "
                     "on-device compute")
            self._host_adam_mode = False

        # --- precision ---------------------------------------------------
        self.compute_dtype = config.compute_dtype
        self.fp16 = config.fp16.enabled
        self.master_weights = (config.bf16.master_weights if config.bf16.enabled else True)

        # --- optimizer ---------------------------------------------------
        sched_params = dict(config.scheduler.params)
        opt_params = dict(config.optimizer.params)
        base_lr = opt_params.get("lr", 1e-3)
        if lr_scheduler is not None:
            self.lr_schedule = lr_scheduler
        else:
            self.lr_schedule = build_lr_schedule(config.scheduler.type, sched_params, base_lr)
        # resilience rollback may drop the LR (sentinel lr_drop_factor):
        # the scale is a trace-time constant read when a step (re)compiles;
        # ResilienceManager invalidates the compiled steps when it changes.
        # Only wrapped when the subsystem is on — off stays byte-for-byte
        # the schedule the optimizer was always built with.
        self._lr_scale = 1.0
        if config.resilience.enabled:
            _base_schedule = self.lr_schedule
            self.lr_schedule = lambda step: _base_schedule(step) * self._lr_scale
        if optimizer is not None:
            self.tx = optimizer
        else:
            # with resilience on, the optimizer must see the WRAPPED schedule
            # even when no scheduler is configured — a constant base_lr float
            # here would make the sentinel's lr_drop_factor a silent no-op on
            # the actual updates while the metrics reported the drop
            use_schedule = config.scheduler.type or config.resilience.enabled
            opt_params["lr"] = self.lr_schedule if use_schedule else base_lr
            self.tx = build_optimizer(config.optimizer.type, opt_params)

        # --- frozen parameters (reference requires_grad=False / the
        # SimpleFrozenModel tier): path patterns select leaves that get NO
        # update and NO optimizer state (multi_transform routes them to
        # set_to_zero, so Adam moments for frozen leaves never exist —
        # the memory-relevant half of freezing under ZeRO) -----------------
        self.frozen_patterns = tuple(frozen_params or ())
        if self.frozen_patterns:
            if self._host_adam_mode:
                log_dist("frozen_params: host-Adam offload tier does not "
                         "mask updates — using pinned-host state with "
                         "on-device compute instead")
                self._host_adam_mode = False
            self._frozen_labels = _frozen_label_tree(_abstract_params(params),
                                                     self.frozen_patterns)
            self.tx = optax.multi_transform(
                {"train": self.tx, "freeze": optax.set_to_zero()},
                self._frozen_labels)

        # --- place state on the mesh ------------------------------------
        self._build_state(params)
        self._build_specs(batch_spec)
        # kept for reconfigure_step(): a control-plane knob change (gas,
        # micro-batch, a re-planned dp-grad transport) re-runs _compile
        self._donate_state = donate_state
        # the training dataloader, when initialize() built one — its batch
        # shape is fixed outside the engine, so halve_micro_batch refuses
        # while one is attached (set regardless of resilience)
        self._train_dataloader = None
        self._compile(donate_state)

        # compat-path buffers (forward/backward/step API)
        self._compat_acc = None
        self._compat_batch = None
        self._compat_pending = None
        self._compat_count = 0
        self._no_sync_depth = 0
        self._micro_step_fn = None
        self._apply_fn = None
        self._eval_fn = None

        self.global_steps = 0
        self._skipped_base = 0
        self._skipped_dev = jnp.zeros([], jnp.int32)
        self._metrics_dev: Optional[Dict[str, Any]] = None
        self._metrics_host: Optional[Dict[str, float]] = {}
        self.monitor = None
        if any(m.enabled for m in (config.monitor.tensorboard, config.monitor.wandb,
                                   config.monitor.csv_monitor, config.monitor.comet)):
            from ..monitor import MonitorMaster

            self.monitor = MonitorMaster(config.monitor)
        self.flops_profiler = None
        self._last_batch = None
        self._step_times = []

        # data-efficiency hooks (reference engine.py:354-358, 1887-1890)
        self.curriculum_scheduler = None
        self.random_ltd_scheduler = None
        de = config.data_efficiency
        if de.enabled:
            cl = de.data_sampling.get("curriculum_learning", {})
            # legacy single-schedule form builds the engine-side scheduler
            # (seqlen truncation in train_batch); the curriculum_metrics
            # form instead drives sample SELECTION through the dataloader's
            # DeepSpeedDataSampler (see initialize/build_curriculum_sampler)
            if cl.get("enabled") and any(
                    k in cl for k in ("curriculum_type", "schedule_type",
                                      "schedule_config")):
                from .data_pipeline import CurriculumScheduler

                self.curriculum_scheduler = CurriculumScheduler(cl)
            rl = de.data_routing.get("random_ltd", {})
            if rl.get("enabled"):
                from .data_pipeline import RandomLTDScheduler

                self.random_ltd_scheduler = RandomLTDScheduler(de.data_routing)
                if not self._loss_takes_ltd:
                    logger.warning(
                        "random_ltd is enabled but the loss fn does not accept an "
                        "'ltd_keep' kwarg — token dropping will NOT be applied. "
                        "Accept ltd_keep (tokens to keep per layer) and wrap layers "
                        "with data_pipeline.random_ltd_apply.")
        # MoQ (reference quantize_training section): fake-quantize weights in
        # the forward at the scheduler's current bit-width; each distinct
        # width is one compiled program (bounded by the bit halvings)
        self.moq = None
        qt = config.quantize_training
        if qt is not None and qt.enabled:
            from .quantize import MoQQuantizer

            self.moq = MoQQuantizer.from_config(qt)
        if config.progressive_layer_drop.enabled:
            logger.warning(
                "progressive_layer_drop is enabled in the config, but layer "
                "drop needs model cooperation (as in the reference): build "
                "the schedule with ProgressiveLayerDrop.from_config and gate "
                "layers with progressive_layer_drop.pld_apply in the loss fn")
        # telemetry spine (deepspeed_tpu/telemetry/): span tracer + flight
        # recorder + metrics registry. Constructed BEFORE resilience so the
        # restore-on-restart path is already on the timeline; attached after
        # so flight dumps ride the watchdog/rollback/drain paths. Off by
        # default: nothing constructed, stepping bit-identical.
        self.telemetry = None
        self.artifact_rank = artifact_rank()
        if config.telemetry.enabled:
            from ..telemetry import TelemetryManager

            self.telemetry = TelemetryManager(
                config.telemetry, rank=self.artifact_rank,
                default_dir=config.resilience.snapshot_dir)
        # chaos engine (runtime/resilience/chaos.py): deterministic fault
        # schedules across transport/serving/control. Installed BEFORE
        # resilience so the manager can adopt the schedule's training
        # FaultPlan. Off by default: the global stays None and every
        # injection site is a single attribute test — bitwise off-identity.
        if config.chaos.enabled:
            from .resilience.chaos import install_chaos_from_config

            install_chaos_from_config(config.chaos)
        else:
            # an engine built WITHOUT a chaos block must not inherit a
            # schedule a previous drill ENGINE installed in this process
            # (the off-identity contract is per-config); schedules
            # installed manually via configure_chaos are left alone
            from .resilience.chaos import clear_config_chaos

            clear_config_chaos()
        # resilience (runtime/resilience/): snapshots + sentinel + preemption.
        # Constructed only when enabled, restore-on-restart runs before the
        # first step so a relaunch continues where the last snapshot left off.
        self.resilience = None
        if config.resilience.enabled:
            from .resilience import ResilienceManager

            self.resilience = ResilienceManager(self, config.resilience)
            if config.resilience.restore_on_start:
                self.resilience.maybe_restore()
        if self.telemetry is not None:
            self.telemetry.attach_engine(self)
        # control plane (deepspeed_tpu/control/): the supervisor policy
        # closing telemetry -> knobs. Constructed AFTER resilience and
        # telemetry so it can tap the health table, the memory gauges, and
        # ride the flight dumps. Off by default: a None attribute the step
        # path checks once — stepping stays bit-identical.
        self.control = None
        if config.control.enabled and config.control.supervisor.enabled:
            from ..control import ControlSupervisor

            self.control = ControlSupervisor.for_engine(self, config.control)
        log_dist(f"engine initialized: {self.topo}, zero_stage={zc.stage}, "
                 f"gas={self.gas}, micro_bs={self.micro_batch_size}, "
                 f"dtype={jnp.dtype(self.compute_dtype).name}")
        from ..utils.memory import see_memory_usage

        see_memory_usage("after engine init", force=config.memory_breakdown)

    # ------------------------------------------------------------------
    def _build_state(self, params):
        rules, topo = self.rules, self.topo
        store_dtype = jnp.float32 if self.master_weights else self.compute_dtype
        if callable(params) and not hasattr(params, "shape"):
            # zero.Init analogue (reference partition_parameters.py:816):
            # ``params`` is a zero-arg init closure. jax.eval_shape derives
            # the tree abstractly (nothing materializes), the ZeRO specs are
            # computed from the abstract shapes, and jitting the closure with
            # out_shardings materializes every leaf DIRECTLY into its shard —
            # no full-size host or device buffer ever exists, so models
            # larger than host RAM can initialize. Per-shard randomness comes
            # from partitionable threefry (XLA generates only local shards).
            init_fn = params

            def cast_init():
                return jax.tree.map(
                    lambda p: p.astype(store_dtype) if jnp.issubdtype(
                        p.dtype, jnp.floating) else p, init_fn())

            abstract = jax.eval_shape(cast_init)
            self.param_spec_tree = rules.param_spec_tree(abstract, self.param_specs_base)
            param_sh = rules.shardings(self.param_spec_tree)
            params = jax.jit(cast_init, out_shardings=param_sh)()
        else:
            # jnp.array (copy=True), NOT asarray: device_put can alias the
            # caller's buffers, and the donated train step would then delete
            # the user's own model_parameters arrays out from under them
            params = jax.tree.map(
                lambda p: jnp.array(p, store_dtype) if jnp.issubdtype(
                    jnp.asarray(p).dtype, jnp.floating) else jnp.array(p), params)
            self.param_spec_tree = rules.param_spec_tree(params, self.param_specs_base)
            param_sh = rules.shardings(self.param_spec_tree)
            params = jax.device_put(params, param_sh)

        if self._host_adam_mode:
            # ZeRO-Offload: fp32 master + moments live on HOST (native SIMD
            # Adam, csrc/adam/cpu_adam.cpp); the device keeps only the
            # compute-dtype working copy. Reference cpu_adam_impl.cpp flow.
            from ..ops.adam import DeepSpeedCPUAdam

            op = dict(self.config.optimizer.params)
            self._host_adam = DeepSpeedCPUAdam(
                jax.device_get(params),  # sync-ok: one-time offload init
                lr=op.get("lr", 1e-3), betas=tuple(op.get("betas", (0.9, 0.999))),
                eps=op.get("eps", 1e-8),
                weight_decay=op.get("weight_decay", 0.0),
                adamw_mode=op.get("adam_w_mode", op.get("adamw_mode", True)),
                bias_correction=op.get("bias_correction", True))
            if self.compute_dtype != jnp.dtype(jnp.float32):
                cast_sh = param_sh

                def to_compute(t):
                    return jax.tree.map(
                        lambda x: x.astype(self.compute_dtype) if jnp.issubdtype(
                            x.dtype, jnp.floating) else x, t)

                params = jax.jit(to_compute, out_shardings=cast_sh,
                                 donate_argnums=(0,))(params)
            opt_state, opt_sh = (), ()
        else:
            opt_shapes = jax.eval_shape(self.tx.init, params)
            # master/optimizer state shards at stage>=1 even when params don't
            opt_param_specs = rules.opt_spec_tree(params, self.param_specs_base)
            opt_spec_tree = _struct_congruent_specs(opt_shapes, params, opt_param_specs)
            opt_sh = jax.tree.map(lambda s: NamedSharding(topo.mesh, s), opt_spec_tree,
                                  is_leaf=lambda x: isinstance(x, P))
            opt_state = jax.jit(self.tx.init, out_shardings=opt_sh)(params)
            if self._offload_optimizer:
                if _host_memory_jit_supported(topo.mesh):
                    # opt_sh updates to pinned-host kinds so every later
                    # device_put (checkpoint load, reload_states) restores
                    # host residency
                    opt_state, opt_sh = _to_host_memory(opt_state, opt_sh)
                else:
                    log_dist("offload_optimizer: this backend cannot compile "
                             "pinned-host operands — optimizer state stays "
                             "device-resident (graceful degradation)")

        ls = make_loss_scale_state(self.config.fp16.initial_scale_power,
                                   self.config.fp16.loss_scale,
                                   self.config.fp16.hysteresis)
        self.state = TrainState(step=jnp.zeros([], jnp.int32), params=params,
                                opt_state=opt_state, loss_scale=ls)
        self._opt_shardings = opt_sh
        self._param_shardings = param_sh

    def _build_specs(self, batch_spec):
        topo = self.topo
        dp_axes = topo.dp_axes
        if batch_spec is None:
            if topo.sp_size > 1:
                batch_spec = P(dp_axes, "sp")  # spec-ok: default batch layout when none configured (dp x sp)
            else:
                batch_spec = P(dp_axes)  # spec-ok: default batch layout when none configured (dp)
        self.batch_spec = batch_spec
        self.batch_sharding = NamedSharding(topo.mesh, batch_spec)
        self.grad_spec_tree = self.rules.grad_spec_tree(self.state.params, self.param_specs_base)

    # ------------------------------------------------------------------
    def _loss(self, params, batch, rng, ltd_keep=None, moq_bits=None):
        p = jax.tree.map(
            lambda x: x.astype(self.compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
        if moq_bits is not None and moq_bits < 16:
            # MoQ fake-quantize at the schedule's current width (static under
            # jit; the step cache keys on it)
            p = self.moq.quantize(p, step=0, training=True, bits=moq_bits)
        kw = {}
        if ltd_keep is not None and self._loss_takes_ltd:
            kw["ltd_keep"] = ltd_keep
        if self._loss_takes_rng:
            call = lambda p_, b_: self.loss_fn_raw(p_, b_, rng, **kw)  # noqa: E731
        else:
            call = lambda p_, b_: self.loss_fn_raw(p_, b_, **kw)  # noqa: E731
        if self._remat_policy is not None:
            # engine-level remat (activation_checkpointing.policy / the
            # control plane's raise_remat): the backward pass recomputes
            # this forward instead of keeping its intermediates — values
            # identical, activation memory traded for recompute. Trace-time
            # read; a policy change invalidates the compiled steps.
            from .activation_checkpointing import checkpoint_wrapper

            call = checkpoint_wrapper(call, self._remat_policy)
        out = call(p, batch)
        if isinstance(out, tuple):
            return out[0].astype(jnp.float32), out[1]
        return out.astype(jnp.float32), None

    def _opt_to_device(self, opt_state):
        """Pinned-host STORAGE tier (the host-Adam decline path: frozen
        params / custom optimizer / multi-process): optimizer state lives in
        host memory between steps; stream it to device memory for the update
        (XLA overlaps the transfer), and the host-kind out_shardings stream
        the new state back. No-op when the optimizer is device-resident."""
        if not (self._offload_optimizer and jax.tree.leaves(opt_state)):
            return opt_state
        return jax.tree.map(
            lambda x, sh: (jax.device_put(x, sh.with_memory_kind("device"))
                           if sh.memory_kind == "pinned_host" else x),
            opt_state, self._opt_shardings)

    def _compile(self, donate_state):
        config, topo, rules = self.config, self.topo, self.rules
        gas, fp16 = self.gas, self.fp16
        clip = config.gradient_clipping
        fp16_dynamic = fp16 and config.fp16.loss_scale == 0
        gd_raw = config.zero_optimization.offload_optimizer.grad_dtype.lower()
        gd_table = {"bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
                    "float32": jnp.float32, "fp32": jnp.float32}
        if gd_raw not in gd_table:
            # fp16 is deliberately absent: transport narrowing happens after
            # the finite check, so an fp16 overflow (|g| > 65504) would slip
            # inf past _apply_host_adam's grad_norm gate into the masters;
            # bf16 shares the fp32 exponent range and cannot overflow
            raise ValueError(
                f"offload_optimizer.grad_dtype={gd_raw!r}: use 'float32' or "
                "'bfloat16' (fp16 transport would need its own overflow "
                "gate — bf16 is the range-safe narrow dtype on TPU)")
        offload_grad_dtype = jnp.dtype(gd_table[gd_raw])
        if config.prescale_gradients:
            # Reference predivide-then-SUM-allreduce (engine.py:2533) nets out
            # to the mean; SPMD grads here are already global means, so the
            # knob is accepted but has no additional effect.
            log_dist("prescale_gradients is subsumed by SPMD mean-reduction; ignoring")

        # compressed DP gradient reduction (comm/compressed.py): compute
        # PER-SHARD grads under shard_map and reduce them with the int8
        # two-stage all-reduce instead of letting SPMD insert the exact
        # fp32 psum. Pure-DP stage-0 only: sharded params (ZeRO 1-3), model
        # parallel axes, and MoE expert grads keep the exact path — their
        # reductions live inside the declarative program. With the knob off
        # this branch doesn't exist and the step is bit-identical to before.
        # fp16 is excluded: the quantizer's where(absmax > 0) maps NaN grads
        # to finite zeros, so an overflow would slip past the loss-scale
        # skip gate — the exact psum propagates NaN and skips correctly
        cc = config.compressed_collectives
        site_eligible = (config.zero_optimization.stage == 0
                         and topo.pp_size == 1 and topo.tp_size == 1
                         and topo.sp_size == 1 and not config.moe.enabled
                         and topo.dp_size > 1 and self._host_adam is None
                         and not fp16)
        # remembered for replan_dp_grad: the control plane must not claim
        # a re-plan on an engine whose reductions are declarative
        self._dp_grad_site_eligible = site_eligible
        dp_grad_impl = None  # (mode, block, hierarchical) when compressed
        if cc.mode != "none":  # raw knob explicitly set: it wins as before
            compressed_dp = cc.dp_gradients and site_eligible
            if cc.dp_gradients and not compressed_dp:
                log_dist("compressed_collectives: DP gradient site needs pure "
                         "data parallelism at ZeRO stage 0 without fp16 loss "
                         "scaling — keeping the exact reduction (ZeRO++/MoE/"
                         "Ulysses sites gate separately)")
            if compressed_dp:
                cc_hier = (cc.hierarchical and topo.ep_size > 1
                           and topo.dp_outer_size > 1)
                dp_grad_impl = (cc.mode, cc.block, cc_hier)
        else:
            # comm-planner dp-grad site: with no raw knob set, the planner
            # (mode static|measure) picks the reduction implementation per
            # mesh + message size; off keeps the exact psum (bit-identical)
            compressed_dp = False
            from ..comm.planner import planner_active, resolve_site
            if planner_active() and site_eligible:
                n_elems = sum(int(np.prod(p.shape)) if p.shape else 1
                              for p in jax.tree.leaves(self.state.params))
                d = resolve_site(op="all_reduce", shape=(n_elems,),
                                 dtype="float32", axes=topo.dp_axes,
                                 consumer="dp-grad")
                if d.impl == "program":
                    # planner-synthesized multi-phase program (the DCN
                    # shape: exact reduce-scatter over ICI, int8+error-
                    # feedback all-reduce over the cross-slice axis,
                    # all-gather back) — executed per step by
                    # comm.compressed.run_collective_program. Fused phases
                    # (via="fused_matmul": the ICI hops riding between the
                    # backward matmuls' tile steps) get their compute
                    # descriptors bound to the REAL chunk sizes here, so
                    # the flight ring's per-hop detail and the doctor's
                    # divergence report name what actually moves
                    from ..comm.compressed import bind_fused_tiles
                    program = bind_fused_tiles(d.program, n_elems,
                                               dict(topo.mesh.shape))
                    dp_grad_impl = ("program", d.block or cc.block,
                                    program)
                    compressed_dp = True
                elif d.impl in ("int8", "int8_sr", "hierarchical"):
                    hier = (d.impl == "hierarchical" and topo.ep_size > 1
                            and topo.dp_outer_size > 1)
                    mode_ = "int8" if d.impl == "hierarchical" else d.impl
                    dp_grad_impl = (mode_, d.block or cc.block, hier)
                    compressed_dp = True
        if compressed_dp:
            mode_, block_, hier_ = dp_grad_impl
            if mode_ == "program":
                from ..comm.planner import program_summary
                fused_n = sum(1 for s in hier_
                              if getattr(s, "via", "xla") == "fused_matmul")
                log_dist(f"DP gradients ride a planner program: "
                         f"{program_summary(hier_)}"
                         + (f" ({fused_n} phase(s) fused into the "
                            f"producing/consuming matmul tiles)"
                            if fused_n else ""))
            else:
                log_dist(f"DP gradients ride the {mode_} all-reduce "
                         f"(block={block_}{', hierarchical' if hier_ else ''})")
        self._compressed_dp = compressed_dp  # imperative backward() reads it
        self._dp_grad_impl = dp_grad_impl

        # cross-step error-feedback residual for a program with an int8_ef
        # hop: engine-owned (TrainState.comm_feedback — global arrays with
        # the per-rank layout on the leading dp dim) so the GAS step carries
        # ONE residual across steps, snapshots include it, and rollback
        # restores the snapshot's copy instead of replaying a stale one
        fb = ()
        if dp_grad_impl is not None and dp_grad_impl[0] == "program":
            from ..comm.compressed import program_feedback_init

            # n_elems comes from the planner-resolution branch above — the
            # only producer of a program decision, so it is always bound here
            per_rank = program_feedback_init(n_elems, dp_grad_impl[2],
                                             dict(topo.mesh.shape))
            if per_rank is not None:
                fb_sh = NamedSharding(topo.mesh, P(topo.dp_axes))  # spec-ok: comm-feedback state is per-dp-rank
                fb = type(per_rank)(
                    worker_error=jax.device_put(
                        jnp.zeros((topo.dp_size,)
                                  + per_rank.worker_error.shape, jnp.float32),
                        fb_sh),
                    server_error=jax.device_put(
                        jnp.zeros((topo.dp_size,)
                                  + per_rank.server_error.shape, jnp.float32),
                        fb_sh))
        # () vs a 2-field NamedTuple: length check only, no array compares
        self._dp_feedback = fb != ()
        self.state = self.state.replace(comm_feedback=fb)

        def train_step(state: TrainState, batch, rng, *, ltd_keep=None,
                       moq_bits=None):
            scale = state.loss_scale.scale if fp16 else jnp.asarray(1.0, jnp.float32)

            def micro(carry, xs):
                acc = carry
                mb, mb_rng = xs

                def scaled_loss(p):
                    loss, aux = self._loss(p, mb, mb_rng, ltd_keep=ltd_keep,
                                           moq_bits=moq_bits)
                    return loss * scale, loss

                grads, loss = jax.grad(scaled_loss, has_aux=True)(state.params)
                grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
                grads = jax.lax.with_sharding_constraint(
                    grads, rules.shardings(self.grad_spec_tree))
                acc = jax.tree.map(jnp.add, acc, grads)
                return acc, loss

            rngs = jax.random.split(rng, gas)
            # trace-time read of the ATTRIBUTE (not the _compile-time local):
            # degraded mode flips it off and invalidates compiled steps, and
            # the retrace must land on the exact psum path
            if self._compressed_dp:
                grads, losses, new_fb = self._compressed_grad_phase(
                    state.params, batch, rngs, rng, scale,
                    feedback=(state.comm_feedback if self._dp_feedback
                              else None),
                    ltd_keep=ltd_keep, moq_bits=moq_bits)
            else:
                new_fb = None
                zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), state.params)
                zeros = jax.lax.with_sharding_constraint(zeros, rules.shardings(self.grad_spec_tree))
                acc, losses = lax.scan(micro, zeros, (batch, rngs))

                # unscale (+ average over gas; per-microbatch losses are
                # already global-batch means under SPMD — matches reference
                # GAS loss scaling, engine.py:2023)
                denom = scale * gas
                grads = jax.tree.map(lambda g: g / denom, acc)
            if self.frozen_patterns:
                # requires_grad=False semantics: frozen grads are zeroed
                # BEFORE the norm so clipping of trained params matches an
                # unfrozen-free run exactly (the optimizer masking alone
                # would leave them inflating grad_norm)
                grads = jax.tree.map(
                    lambda g, lbl: jnp.zeros_like(g) if lbl == "freeze" else g,
                    grads, self._frozen_labels)

            grad_norm = global_grad_norm(grads)
            overflow = ~jnp.isfinite(grad_norm) if fp16 else jnp.zeros([], jnp.bool_)
            if clip and clip > 0:
                coef = jnp.minimum(1.0, clip / (grad_norm + 1e-6))
                grads = jax.tree.map(lambda g: g * coef, grads)

            # bound once: the overflow select below must also see the
            # device copy — mixing a pinned-host leaf into compiled math is
            # the crash _opt_to_device exists to prevent
            opt_in = self._opt_to_device(state.opt_state)
            updates, new_opt = self.tx.update(grads, opt_in, state.params)
            new_params = jax.tree.map(
                lambda p, u: (p.astype(jnp.float32) + u.astype(jnp.float32)).astype(p.dtype),
                state.params, updates)
            if fp16:
                new_params = _tree_where(overflow, state.params, new_params)
                new_opt = _tree_where(overflow, opt_in, new_opt)
            new_ls = update_loss_scale(
                state.loss_scale, overflow,
                dynamic=fp16_dynamic,
                scale_window=config.fp16.loss_scale_window,
                min_scale=config.fp16.min_loss_scale,
                max_hysteresis=config.fp16.hysteresis,
                consecutive_hysteresis=config.fp16.consecutive_hysteresis)
            new_state = TrainState(step=state.step + 1, params=new_params,
                                   opt_state=new_opt, loss_scale=new_ls,
                                   comm_feedback=(state.comm_feedback
                                                  if new_fb is None
                                                  else new_fb))
            metrics = {
                "loss": jnp.mean(losses),
                "grad_norm": grad_norm,
                "lr": jnp.asarray(self.lr_schedule(state.step + 1), jnp.float32),
                "loss_scale": state.loss_scale.scale,
                "overflow": overflow,
            }
            return new_state, metrics

        def grad_step(params, batch, rng, step, *, ltd_keep=None,
                      moq_bits=None):
            # ZeRO-Offload device half: grads + metrics only; the optimizer
            # update happens on host (engine._host_adam). fp16 loss scaling
            # is rejected at init in this mode (bf16/fp32 only), so the
            # micro scan needs no scale factor.
            def micro(carry, xs):
                acc = carry
                mb, mb_rng = xs
                loss, grads = jax.value_and_grad(
                    lambda p: self._loss(p, mb, mb_rng, ltd_keep=ltd_keep,
                                         moq_bits=moq_bits)[0]
                )(params)
                grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
                grads = jax.lax.with_sharding_constraint(
                    grads, rules.shardings(self.grad_spec_tree))
                acc = jax.tree.map(jnp.add, acc, grads)
                return acc, loss

            zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
            zeros = jax.lax.with_sharding_constraint(zeros, rules.shardings(self.grad_spec_tree))
            rngs = jax.random.split(rng, gas)
            acc, losses = lax.scan(micro, zeros, (batch, rngs))
            grads = jax.tree.map(lambda g: g / gas, acc)
            if self.frozen_patterns:  # same masking as the fused step
                grads = jax.tree.map(
                    lambda g, lbl: jnp.zeros_like(g) if lbl == "freeze" else g,
                    grads, self._frozen_labels)
            grad_norm = global_grad_norm(grads)
            if clip and clip > 0:
                coef = jnp.minimum(1.0, clip / (grad_norm + 1e-6))
                grads = jax.tree.map(lambda g: g * coef, grads)
            if offload_grad_dtype != jnp.dtype(jnp.float32):
                # transport-dtype narrowing happens AFTER fp32 accumulation,
                # norm and clip — only the D2H bytes shrink (reference
                # ZeRO-Offload ships compute-dtype grads to the CPU optimizer)
                grads = jax.tree.map(
                    lambda g: g.astype(offload_grad_dtype), grads)
            metrics = {"loss": jnp.mean(losses), "grad_norm": grad_norm,
                       "lr": jnp.asarray(self.lr_schedule(step + 1), jnp.float32),
                       "loss_scale": jnp.asarray(1.0, jnp.float32),
                       "overflow": ~jnp.isfinite(grad_norm)}
            return grads, metrics

        state_sh = TrainState(
            step=NamedSharding(topo.mesh, P()),  # spec-ok: step counter replicates
            params=self._param_shardings,
            opt_state=self._opt_shardings,
            loss_scale=jax.tree.map(lambda _: NamedSharding(topo.mesh, P()), self.state.loss_scale),  # spec-ok: loss scale replicates
            comm_feedback=jax.tree.map(
                lambda _: NamedSharding(topo.mesh, P(topo.dp_axes)),  # spec-ok: comm-feedback state is per-dp-rank
                self.state.comm_feedback))

        if self._host_adam is not None:
            grad_sh = jax.tree.map(lambda s: NamedSharding(topo.mesh, s),
                                   self.grad_spec_tree,
                                   is_leaf=lambda x: isinstance(x, P))

            def make_train_step(ltd_keep, moq_bits=None):
                return jax.jit(partial(grad_step, ltd_keep=ltd_keep,
                                       moq_bits=moq_bits),
                               in_shardings=(self._param_shardings, None, None, None),
                               out_shardings=(grad_sh, None))
        else:
            def make_train_step(ltd_keep, moq_bits=None):
                # one compiled program per (random-LTD stage, MoQ bit-width)
                # pair — both schedules quantize their steps, bounding the set
                return jax.jit(
                    partial(train_step, ltd_keep=ltd_keep, moq_bits=moq_bits),
                    in_shardings=(state_sh, None, None),
                    out_shardings=(state_sh, None),
                    donate_argnums=(0,) if donate_state else ())

        self._make_train_step = make_train_step
        self._train_steps = {(None, None): make_train_step(None)}
        self._compile_finish(state_sh)

    def _compressed_grad_phase(self, params, batch, rngs, step_rng, scale,
                               *, feedback=None, ltd_keep=None,
                               moq_bits=None):
        """GAS scan + quantized mean all-reduce, per-shard under shard_map.

        The exact path lets SPMD insert fp32 psums where replicated params
        meet dp-sharded batches; here each dp rank accumulates LOCAL grads
        over the microbatch scan, flattens the whole tree into one vector
        (one collective per step, the flat-buffer transport of
        ``compression/onebit.py``), and reduces it with
        ``comm.compressed.quantized_all_reduce`` — int8 payloads + one-lane
        scales on the wire, ~3.5x fewer bytes than the psum pair. ``int8_sr``
        dithers the rounding so the compressed mean is unbiased. Returns
        (replicated fp32 grads — already unscaled and gas-averaged — and the
        per-micro global-mean losses).

        Semantics note: the reduction equal-weights the RANKS. A loss that
        normalizes by a data-dependent count (e.g. a ragged valid-token
        mask) is averaged as mean-of-per-rank-means here, while the exact
        SPMD path computes the global count-weighted mean — identical for
        the engine's fixed-shape microbatches, different when per-rank valid
        counts diverge (the same contract as ``compression/onebit.py``'s
        per-shard reduction).

        ``feedback`` (the engine-owned ``TrainState.comm_feedback`` — per-
        rank residuals stacked on a leading dp dim) rides the shard_map as
        an extra sharded operand when a program with an ``int8_ef`` hop is
        resolved; the per-shard slice feeds the reduction and the updated
        residual comes back out. Returns ``(grads, losses, new_feedback)``
        — ``new_feedback`` is ``None`` on the feedback-free paths."""
        from ..utils.shard_map_compat import shard_map_nocheck

        topo, gas = self.topo, self.gas
        dpaxes = topo.dp_axes
        sr_key = jax.random.fold_in(step_rng, 0x0151)
        fb_in = feedback if feedback else None  # () and None both mean "off"

        def accumulate(p, b_l, rngs_l):
            def micro_l(acc, xs):
                mb, mb_rng = xs

                def scaled_loss(pp):
                    loss, _ = self._loss(pp, mb, mb_rng, ltd_keep=ltd_keep,
                                         moq_bits=moq_bits)
                    return loss * scale, loss

                g, loss = jax.grad(scaled_loss, has_aux=True)(p)
                g = jax.tree.map(lambda t: t.astype(jnp.float32), g)
                return jax.tree.map(jnp.add, acc, g), loss

            zeros = jax.tree.map(lambda l: jnp.zeros(l.shape, jnp.float32), p)
            acc, losses = lax.scan(micro_l, zeros, (b_l, rngs_l))
            return jax.tree.map(lambda g: g / (scale * gas), acc), losses

        if fb_in is None:
            def per_shard(p, b_l, rngs_l, k):
                acc, losses = accumulate(p, b_l, rngs_l)
                return (self._quantized_grad_reduce(acc, k)[0],
                        lax.pmean(losses, dpaxes))

            grads, losses = shard_map_nocheck(
                per_shard, topo.mesh,
                in_specs=(P(), P(None, dpaxes), P(), P()),  # spec-ok: shard_map wiring for the quantized-grad body
                out_specs=(P(), P()))(params, batch, rngs, sr_key)  # spec-ok: shard_map wiring for the quantized-grad body
            return grads, losses, None

        fb_spec = jax.tree.map(lambda _: P(dpaxes), fb_in)  # spec-ok: comm-feedback slices are per-dp-rank

        def per_shard_fb(p, b_l, rngs_l, k, fb_l):
            acc, losses = accumulate(p, b_l, rngs_l)
            fb0 = jax.tree.map(lambda t: t[0], fb_l)  # [1, n] -> [n]
            red, nfb = self._quantized_grad_reduce(acc, k, feedback=fb0)
            nfb = jax.tree.map(lambda t: t[None], nfb)
            return red, lax.pmean(losses, dpaxes), nfb

        return shard_map_nocheck(
            per_shard_fb, topo.mesh,
            in_specs=(P(), P(None, dpaxes), P(), P(), fb_spec),  # spec-ok: shard_map wiring for the feedback-carrying body
            out_specs=(P(), P(), fb_spec))(params, batch, rngs, sr_key, fb_in)  # spec-ok: shard_map wiring for the feedback-carrying body

    def _quantized_grad_reduce(self, grads, sr_key, feedback=None):
        """Flatten a per-shard fp32 grad tree into ONE vector (the
        flat-buffer transport — one collective per reduction, padding paid
        once), mean-reduce it with the resolved transport, unflatten.
        Called INSIDE shard_map over the dp axes; shared by the GAS-scan
        and imperative-backward() paths.

        Transports: flat ``quantized_all_reduce`` (int8/int8_sr), the
        legacy hand-wired two-level knob (inner ``ep`` exact, outer
        ``dp_outer`` quantized), or a planner-synthesized multi-phase
        PROGRAM (``run_collective_program`` — exact ICI reduce-scatter,
        int8+feedback DCN hop, ICI all-gather) when the decision carries
        one. Returns ``(grad_tree, new_feedback)``; ``new_feedback`` is
        ``None`` unless a program's ``int8_ef`` hop consumed ``feedback``."""
        from ..comm.compressed import (hierarchical_quantized_all_reduce,
                                       quantized_all_reduce,
                                       run_collective_program)

        mode_, block_, extra_ = self._dp_grad_impl  # knob- or planner-resolved
        flat, tdef = jax.tree.flatten(grads)
        sizes = [int(np.prod(g.shape)) for g in flat]
        shapes = [g.shape for g in flat]
        vec = jnp.concatenate([jnp.ravel(g) for g in flat])
        new_fb = None
        if mode_ == "program":
            red, new_fb = run_collective_program(vec, extra_,
                                                 feedback=feedback,
                                                 key=sr_key)
        else:
            sr = mode_ == "int8_sr"
            kw = dict(block=block_, stochastic=sr, key=sr_key if sr else None)
            if extra_:
                # inner (ICI-local) hop exact, only the outer hops quantize
                red = hierarchical_quantized_all_reduce(vec, "ep", "dp_outer",
                                                        **kw)
            else:
                red = quantized_all_reduce(vec, self.topo.dp_axes, **kw)
        offs = np.cumsum([0] + sizes)
        return jax.tree.unflatten(tdef, [
            red[offs[i]:offs[i + 1]].reshape(shapes[i])
            for i in range(len(sizes))]), new_fb

    def _compile_finish(self, state_sh):
        self._train_step = self._train_steps[(None, None)]
        self._aot_step = None  # (executable, batch fingerprint) from compile()
        # (key, batch fingerprint) -> measured AOT executable, filled when
        # telemetry.memory_analysis records each variant's compile-time
        # memory breakdown (a curriculum reshape is a new fingerprint)
        self._mem_execs = {}
        self._state_shardings = state_sh
        self._rng = jax.random.PRNGKey(self.config.seed)

    def _measured_exec(self, step_fn, key, batch, step_rng):
        """AOT-compile one train-step variant, record its
        ``memory_analysis()`` breakdown, and return the executable (which
        then serves matching steps — same program, same numerics)."""
        fp = (key, self._batch_fingerprint(batch))
        exe = self._mem_execs.get(fp)
        if exe is None:
            exe = step_fn.lower(self.state, batch, step_rng).compile()
            self._mem_execs[fp] = exe
            label = ("train_step" if key == (None, None)
                     else f"train_step{key}")
            self._record_memory_analysis(exe, label)
        return exe

    def _record_memory_analysis(self, exe, label: str) -> None:
        """Fold one compiled executable's ``memory_analysis()`` into the
        comms ledger's plan table and (when telemetry is live) the
        ``dstpu_mem_exec_bytes`` registry gauges. Best-effort: a backend
        without the surface records nothing."""
        try:
            ma = exe.memory_analysis()
        except Exception:
            return
        if ma is None:
            return
        info = {}
        for kind in ("argument_size_in_bytes", "output_size_in_bytes",
                     "temp_size_in_bytes", "generated_code_size_in_bytes",
                     "alias_size_in_bytes"):
            v = getattr(ma, kind, None)
            if v is not None:
                info[kind] = int(v)
        if not info:
            return
        dist.get_comms_logger().record_memory(label, info)
        if self.telemetry is not None:
            self.telemetry.record_memory_analysis(label, info)

    # ------------------------------------------------------------------
    # control-plane actuators (deepspeed_tpu/control/) + retrace plumbing
    # ------------------------------------------------------------------
    def invalidate_compiled_steps(self) -> None:
        """A trace-time constant changed (LR scale, remat policy, degraded
        collectives): drop every compiled step — and the measured AOT
        executables, which bake the same constants — so the next call
        retraces. State, specs, and the resolved dp-grad plan are kept."""
        self._train_steps = {(None, None): self._make_train_step(None)}
        self._train_step = self._train_steps[(None, None)]
        self._aot_step = None
        self._apply_fn = None
        self._micro_step_fn = None
        self._eval_fn = None
        self._mem_execs = {}

    def reconfigure_step(self) -> None:
        """A structural knob changed (gas/micro-batch split, a re-planned
        dp-grad transport): re-run ``_compile`` — plan resolution, feedback
        state, and step closures are all rebuilt against the CURRENT
        attributes — preserving the training RNG stream (``_compile_finish``
        reseeds it for fresh engines; a mid-run reconfigure must not replay
        step 0's randomness)."""
        rng = self._rng
        self._compile(self._donate_state)
        self._rng = rng
        self._apply_fn = None
        self._micro_step_fn = None
        self._eval_fn = None

    def raise_remat(self) -> Optional[str]:
        """Climb one rung of :data:`REMAT_LADDER` (the control plane's
        memory-pressure actuator). Returns the new policy name, or None
        when already at the top (nothing left to trade)."""
        cur = self._remat_policy
        if cur in REMAT_LADDER:
            idx = REMAT_LADDER.index(cur)
            if idx + 1 >= len(REMAT_LADDER):
                return None
            nxt = REMAT_LADDER[idx + 1]
        elif cur != REMAT_LADDER[-1]:
            nxt = REMAT_LADDER[-1]  # custom policy: escalate to full remat
        else:
            return None
        self._remat_policy = nxt
        self.invalidate_compiled_steps()
        log_dist(f"engine: remat policy raised to {nxt} (next step retraces)")
        return nxt

    def halve_micro_batch(self) -> bool:
        """Halve the per-device micro-batch and double GAS — the global
        batch, the optimizer schedule, and the training math are unchanged
        (the GAS scan equal-weights fixed-size microbatches); per-microbatch
        activation residency halves. The caller passes whole-step batches
        (``[gas * micro_global, ...]`` leaves reshape against the new gas
        automatically); a registered dataloader owns its own batch shape —
        the control policy skips this actuator there. Returns False when
        the micro-batch cannot halve (already 1 / odd) or a dataloader
        owns the batch shape."""
        if self._train_dataloader is not None:
            return False
        if self.micro_batch_size < 2 or self.micro_batch_size % 2:
            return False
        self.micro_batch_size //= 2
        self.gas *= 2
        cfg = self.config
        cfg.train_micro_batch_size_per_gpu = self.micro_batch_size
        cfg.gradient_accumulation_steps = self.gas
        # keep the batch triangle consistent for any later finalize()
        cfg._user_batch = (cfg.train_batch_size, self.micro_batch_size,
                           self.gas)
        self.reconfigure_step()
        log_dist(f"engine: micro-batch halved to {self.micro_batch_size} "
                 f"(gas {self.gas}); next step retraces")
        return True

    def replan_dp_grad(self, slow_axes, penalty: float = 4.0
                       ) -> Optional[str]:
        """Re-plan the DP-gradient collective around a slow link (the
        control plane's straggler actuator): the planner demotes
        ``slow_axes`` to penalized DCN-class links and re-synthesizes
        (``CollectivePlanner.replan_around``), then the step recompiles so
        the new transport — typically a hierarchical program whose
        full-width phases exclude the slow axes — takes effect. Returns
        the re-resolved plan summary, or None when the planner is off, no
        axis matched, or this engine has no re-plannable DP-grad site
        (ZeRO>0 / model-parallel / fp16 configurations keep their
        declarative reductions — a 'successful' re-plan there would be a
        lie the ledger then repeats)."""
        from ..comm.planner import (get_planner, planner_active,
                                    program_summary)

        if not planner_active() or not getattr(
                self, "_dp_grad_site_eligible", False):
            return None
        if not get_planner().replan_around(slow_axes, penalty=penalty):
            return None
        self.reconfigure_step()
        impl = self._dp_grad_impl
        if impl is None:
            return "exact-xla"
        return (program_summary(impl[2]) if impl[0] == "program"
                else impl[0])

    # ------------------------------------------------------------------
    # primary API
    # ------------------------------------------------------------------
    def train_batch(self, batch=None, data_iter: Optional[Iterable] = None):
        """Run one full training step: ``gas`` microbatches + optimizer update
        (reference ``PipelineEngine.train_batch`` / engine fwd-bwd-step loop).

        ``batch`` leaves are either ``[gas, micro_global, ...]`` or
        ``[gas * micro_global, ...]`` (reshaped automatically).
        """
        if self._no_sync_depth > 0:
            raise RuntimeError(
                "train_batch() applies the optimizer unconditionally and is "
                "incompatible with an open no_sync() context; use the "
                "imperative backward()/step() path inside no_sync()")
        if self._compat_count > 0:
            # reference accumulate-then-batch pattern (no_sync + backward,
            # then train_batch for the boundary step): the fused step would
            # silently DROP the accumulated micro-grads — fail loudly and
            # point at the migration instead
            raise RuntimeError(
                f"train_batch() called with {self._compat_count} accumulated "
                "microbatch gradient(s) pending from backward(); the fused "
                "step would drop them. Finish the window with backward()+"
                "step() (the no_sync migration), or discard via "
                "zero_grad() before switching to train_batch()")
        if self.telemetry is not None:
            # stamp BEFORE the draw so every span of this call — including
            # data/draw — carries the step about to execute
            self.telemetry.tracer.set_step(self.global_steps)
        if batch is None:
            with span("data/draw"):
                batch = _draw_from_iter(data_iter, self.gas)
        if self.resilience is not None:
            # arm the step watchdog AFTER the batch draw (the routine
            # epoch-end StopIteration must not leave a deadline armed over
            # whatever the caller does next) but BEFORE dispatch: the
            # deadline then covers dispatch plus every blocking device sync
            # post_step performs — the window a wedged collective actually
            # hangs in. Exceptions the caller handles (XLA errors, shape
            # mismatches) disarm via abort_step instead of leaving a live
            # deadline behind.
            self.resilience.pre_step()
            try:
                return self._train_batch_armed(batch)
            except BaseException as e:
                self.resilience.abort_step()
                self._crash_flight_dump(e)
                raise
        try:
            return self._train_batch_armed(batch)
        except BaseException as e:
            self._crash_flight_dump(e)
            raise

    def _crash_flight_dump(self, exc: BaseException) -> None:
        """Crash hook: an unhandled train-loop exception would otherwise
        lose the flight ring (the watchdog/rollback/drain dumps only cover
        *their* paths) — dump it with ``reason="crash"`` and the exception
        summary before the raise propagates. StopIteration is the routine
        epoch-end signal, not a crash; everything else (including injected
        faults and XLA errors) leaves a post-mortem."""
        if (self.telemetry is not None
                and isinstance(exc, Exception)
                and not isinstance(exc, StopIteration)):
            self.telemetry.crash_dump(exc)

    def _train_batch_armed(self, batch):
        """Telemetry shell around the step body: opens the per-step ``step``
        span and folds the window into the flight ring / phase histograms at
        the end. With telemetry off this is a single attribute check."""
        tm = self.telemetry
        if tm is None:
            return self._train_batch_inner(batch)
        # the step EXECUTING is the pre-increment number: the same N the
        # watchdog armed with, the spans are stamped with, and a hangdump
        # reports — the flight ring must agree with all three
        step = self.global_steps
        with span("step"):
            out = self._train_batch_inner(batch)
        # _metrics_host is whatever already synced (lazy) — this hook must
        # never force a device round trip of its own
        tm.on_step_end(
            step,
            step_time_s=self._step_times[-1] if self._step_times else None,
            metrics=self._metrics_host)
        return out

    def state_fingerprint(self, chunks: int = 8) -> str:
        """Hex digest of the full TrainState (params + optimizer state) via
        the integrity tier's jitted fingerprint kernel
        (``runtime/resilience/integrity.py``). DP-replicated state must
        agree BITWISE across ranks, so equal digests mean equal state.
        This is the synchronous forensic entry point for drills, tests,
        and operator debugging — the ``resilience.integrity:`` block runs
        the same kernel on a cadence with a one-step-delayed fetch
        instead, keeping the hot path sync-free."""
        from .resilience.integrity import (fingerprint_hex,
                                           make_fingerprint_fn)

        fns = getattr(self, "_fp_fns", None)
        if fns is None:
            fns = self._fp_fns = {}
        fn = fns.get(chunks)
        if fn is None:
            fn = fns[chunks] = make_fingerprint_fn(chunks)
        return fingerprint_hex(np.asarray(fn(self.state)))

    def _train_batch_inner(self, batch):
        """The body of ``train_batch`` from batch shaping through the
        resilience post-step hook; runs with the step watchdog armed when
        resilience is enabled (``train_batch`` handles arm/abort)."""
        with span("data/shape"):
            batch = self._shape_batch(batch)
        if self.curriculum_scheduler is not None:
            # seqlen curriculum: truncate [gas, micro, seq] leaves to the
            # current difficulty. Each distinct difficulty is one recompile;
            # the scheduler's difficulty_step quantization bounds that set.
            diff = self.curriculum_scheduler.update_difficulty(self.global_steps)
            if self.curriculum_scheduler.curriculum_type == "seqlen":
                batch = jax.tree.map(
                    lambda x: x[:, :, :diff] if x.ndim >= 3 else x, batch)
        ltd_keep = None
        if self.random_ltd_scheduler is not None and self._loss_takes_ltd:
            ltd_keep = self.random_ltd_scheduler.update(self.global_steps)
        self._last_batch = batch  # reference only; sliced lazily by flops_profile
        self._rng, step_rng = jax.random.split(self._rng)
        # the integrity tier's shadow-step replay re-executes THIS step from
        # a retained pre-step state; the exact rng and step-fn cache key are
        # the rest of the recipe (runtime/resilience/integrity.py)
        self._last_step_rng = step_rng
        moq_bits = self.moq.update(self.global_steps) if self.moq else None
        if moq_bits is not None and moq_bits >= 16:
            moq_bits = None  # schedule_offset warmup: unquantized program
        executing_step = self.global_steps  # pre-increment: the N every
        # other post-mortem surface (spans, flight ring, watchdog) stamps
        key = (ltd_keep, moq_bits)
        self._last_step_key = key
        step_fn = self._train_steps.get(key)
        if step_fn is None:
            step_fn = self._train_steps[key] = self._make_train_step(
                ltd_keep, moq_bits)
        if (key == (None, None) and self._aot_step is not None
                and self._aot_step[1] == self._batch_fingerprint(batch)):
            step_fn = self._aot_step[0]  # AOT executable from compile()
        elif (self.telemetry is not None
              and self.telemetry.cfg.memory_analysis
              and self._host_adam is None):
            # telemetry.memory_analysis: AOT-compile this variant once so
            # its compile-time memory breakdown is recorded, then step
            # through the measured executable (the compile is paid once —
            # lower().compile() does not share the jit dispatch cache)
            step_fn = self._measured_exec(step_fn, key, batch, step_rng)
        t0 = time.perf_counter()
        with span("compute/dispatch"):
            if self._host_adam is not None:
                metrics = self._host_offload_step(step_fn, batch, step_rng)
            else:
                self.state, metrics = step_fn(self.state, batch, step_rng)
        if self.global_steps == 0 and self.config.memory_breakdown:
            self._log_memory_breakdown(step_fn, batch, step_rng)
        self.global_steps += 1
        if self.telemetry is not None and \
                self.telemetry.drain_due(self.global_steps):
            # once-per-window device drain: the span timeline gets one
            # interval that covers the step's actual device work (fwd/bwd,
            # grad reduce, optimizer all live inside the compiled program)
            # without paying a per-step pipeline stall
            with span("compute/drain"):
                jax.block_until_ready(metrics)  # sync-ok: opt-in windowed drain
        # Metrics stay on device; ``_last_metrics`` converts lazily. A per-step
        # device->host sync here would serialize the async dispatch pipeline
        # (one full RTT per step on remote-attached TPUs). Overflow-skip
        # accounting is a device-side counter for the same reason.
        self._metrics_dev = metrics
        self._metrics_host = None
        if self.fp16:
            self._skipped_dev = self._skipped_dev + metrics["overflow"].astype(jnp.int32)
        self._step_times.append(time.perf_counter() - t0)
        with span("metrics/report"):
            self._maybe_report()
        if self.resilience is not None:
            # fault injection -> preemption drain -> sentinel -> cadence
            # snapshot (runtime/resilience/supervisor.py). Not a hot-path
            # cost when disabled: the attribute is None and nothing runs.
            with span("resilience/post_step"):
                self.resilience.post_step()
        if self.control is not None:
            # supervisor policy: live signals -> flap-guarded knob actions
            # (deepspeed_tpu/control/). Runs AFTER the resilience hook so
            # it observes this step's rollback/health outcomes; host-only
            # work unless a fired rule actuates.
            with span("control/decide"):
                self.control.on_step(executing_step)
        at = self.config.autotuning
        if self.global_steps == at.end_profile_step:
            from ..autotuning.autotuner import AUTOTUNE_RESULT_ENV, report_autotune_result

            if os.environ.get(AUTOTUNE_RESULT_ENV):
                # steady-state only: skip the JIT-compile steps before
                # start_profile_step so compile time can't invert the ranking
                start = min(at.start_profile_step, at.end_profile_step - 1)
                times = self._step_times[max(0, start):]
                dt = float(np.mean(times)) if times else float("inf")
                report_autotune_result(self.train_batch_size / dt)
        return metrics["loss"]

    def _host_offload_step(self, step_fn, batch, step_rng):
        """ZeRO-Offload step: device grads → host SIMD Adam → device params.

        D2H transfers are started async for every leaf so they overlap the
        per-leaf kernel work; the update itself runs in the native library's
        thread pool (csrc/adam/cpu_adam.cpp). The fp32 master and moments
        never exist on device — only compute-dtype params and fp32 grads do.
        """
        state = self.state
        grads, metrics = step_fn(state.params, batch, step_rng, state.step)
        for leaf in jax.tree.leaves(grads):
            leaf.copy_to_host_async()
        self._apply_host_adam(grads, float(np.asarray(metrics["grad_norm"])),
                              already_clipped=True)
        return metrics

    def _apply_host_adam(self, grads, grad_norm: float,
                         already_clipped: bool = False):
        """Shared host-optimizer apply for train_batch and the compat step():
        finite check (skip on overflow), clip, lr lookup, native Adam, and
        the device upload of the new compute-dtype params."""
        state = self.state
        if not np.isfinite(grad_norm):
            self.state = state.replace(step=state.step + 1)
            return
        if not already_clipped:
            clip = self.config.gradient_clipping
            if clip and clip > 0:
                coef = min(1.0, clip / (grad_norm + 1e-6))
                grads = jax.tree.map(lambda g: g * coef, grads)
        lr_t = float(np.asarray(self.lr_schedule(self.global_steps + 1)))
        emit_bf16 = jnp.dtype(self.compute_dtype) == jnp.dtype(jnp.bfloat16)
        # sync-ok: ZeRO-Offload host optimizer step (opt-in offload path)
        new_np = self._host_adam.step(jax.device_get(grads), lr=lr_t,
                                      emit_bf16=emit_bf16)
        new_params = jax.device_put(new_np, self._param_shardings)
        self.state = TrainState(step=state.step + 1, params=new_params,
                                opt_state=(), loss_scale=state.loss_scale,
                                comm_feedback=state.comm_feedback)

    def _log_memory_breakdown(self, step_fn, batch, step_rng):
        """Step-1 memory report (reference ``see_memory_usage`` at the first
        step + ``memory_breakdown``): live device/host stats plus the
        compiled train step's XLA accounting (cache-hit lowering)."""
        from ..utils.memory import compiled_memory_analysis, see_memory_usage

        see_memory_usage("after first train step", force=True)
        if self._host_adam is not None:
            analysis = compiled_memory_analysis(step_fn, self.state.params,
                                                batch, step_rng, self.state.step)
        else:
            analysis = compiled_memory_analysis(step_fn, self.state, batch, step_rng)
        if analysis:
            log_dist("compiled train step memory: " +
                     "  ".join(f"{k}={v:.3f}" for k, v in analysis.items()))
        self._memory_analysis = analysis

    def memory_breakdown(self):
        """Programmatic access to the step-1 XLA memory analysis (None until
        the first step runs with config.memory_breakdown enabled)."""
        return getattr(self, "_memory_analysis", None)

    def eval_batch(self, batch, compute_loss: bool = True):
        if self._eval_fn is None:
            def eval_step(state, mb, rng):
                loss, aux = self._loss(state.params, mb, rng)
                return loss

            self._eval_fn = jax.jit(eval_step,
                                    in_shardings=(self._state_shardings, None, None))
        self._rng, r = jax.random.split(self._rng)
        return float(np.asarray(self._eval_fn(self.state, batch, r)))

    # ------------------------------------------------------------------
    # reference-compat imperative API: forward -> backward (xGAS) -> step
    # ------------------------------------------------------------------
    def _run_micro_step(self, batch):
        """One fused value-and-grad microbatch pass, returning the would-be
        new accumulator + the unscaled loss."""
        if self._micro_step_fn is None:
            def micro_step(state, acc, mb, rng):
                scale = state.loss_scale.scale if self.fp16 else jnp.asarray(1.0, jnp.float32)

                def scaled_loss(p):
                    l, aux = self._loss(p, mb, rng)
                    return l * scale, l

                if self._compressed_dp:
                    # imperative half of the compressed DP wiring: this
                    # microbatch's per-shard grads ride the int8 all-reduce
                    # (the site excludes fp16, so scale == 1 and the
                    # accumulator contract is unchanged)
                    grads, loss = self._compressed_micro_grads(
                        state.params, mb, rng)
                else:
                    grads, loss = jax.grad(scaled_loss, has_aux=True)(state.params)
                    grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
                acc = jax.tree.map(jnp.add, acc, grads)
                return acc, loss

            self._micro_step_fn = jax.jit(micro_step)
        if self._compat_acc is None:
            self._compat_acc = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                                            self.state.params)
        self._rng, r = jax.random.split(self._rng)
        return self._micro_step_fn(self.state, self._compat_acc, batch, r)

    def _compressed_micro_grads(self, params, mb, rng):
        """Imperative ``backward()`` analogue of ``_compressed_grad_phase``:
        ONE microbatch's per-shard grads, mean-reduced through the shared
        ``_quantized_grad_reduce`` flat-buffer transport. Same rank-mean
        semantics note as the GAS-scan path applies."""
        from ..utils.shard_map_compat import shard_map_nocheck

        dpaxes = self.topo.dp_axes

        def per_shard(p, mb_l, r):
            def loss_fn(pp):
                l, _ = self._loss(pp, mb_l, r)
                return l, l

            g, loss = jax.grad(loss_fn, has_aux=True)(p)
            g = jax.tree.map(lambda t: t.astype(jnp.float32), g)
            # feedback=None: the compat micro path reduces per MICROBATCH —
            # a residual per micro would be a different (noisier) carry than
            # the fused step's one-per-step; a program's int8_ef hop runs as
            # plain int8 here
            return (self._quantized_grad_reduce(
                        g, jax.random.fold_in(r, 0x0151))[0],
                    lax.pmean(loss, dpaxes))

        return shard_map_nocheck(
            per_shard, self.topo.mesh,
            in_specs=(P(), P(dpaxes), P()),  # spec-ok: shard_map wiring for the eval body
            out_specs=(P(), P()))(params, mb, rng)  # spec-ok: shard_map wiring for the eval body

    def forward(self, batch):
        """Compute the loss for one microbatch (reference ``engine.forward:1848``).

        Fused with the gradient pass: functional autodiff would otherwise
        recompute this forward inside ``backward()``, silently doubling a
        ported reference loop's compute. The grads are cached and committed
        by ``backward()``; a forward that is never followed by backward pays
        for them — use ``eval_batch`` for inference-only evaluation.
        """
        self._compat_batch = batch
        acc, loss = self._run_micro_step(batch)
        self._compat_pending = (acc, loss)
        return float(np.asarray(loss))

    def backward(self, loss=None, batch=None):
        """Accumulate grads for one microbatch (reference ``backward:2007``).
        ``loss`` is accepted for API compatibility; the grads cached by the
        fused ``forward`` are committed (or recomputed for an explicitly
        different ``batch``)."""
        if batch is not None and batch is not self._compat_batch:
            self._compat_pending = None  # different data: recompute
            self._compat_batch = batch
        if self._compat_batch is None:
            raise ValueError("backward() needs a microbatch: call forward(batch) first or "
                             "pass backward(batch=...) — grads are recomputed functionally, "
                             "a bare loss tensor is not enough on TPU")
        if self._compat_pending is None:
            self._compat_pending = self._run_micro_step(self._compat_batch)
        acc, loss_dev = self._compat_pending
        self._compat_acc = acc
        self._compat_pending = None
        self._compat_count += 1
        return float(np.asarray(loss_dev))

    @contextlib.contextmanager
    def no_sync(self):
        """Context manager suppressing the optimizer boundary while inside
        (reference ``engine.no_sync:1987``: skip gradient allreduce during
        accumulation micro-steps).

        On TPU the reduction itself is XLA's to schedule: the compiled
        ``train_batch`` GAS scan already accumulates before reducing, and the
        imperative ``backward()`` path's per-microbatch psum is inserted by
        SPMD where the grads are consumed. What the reference contract
        guarantees — and what this enforces — is that no optimizer step can
        fire on the imperative path while the context is open:
        ``is_gradient_accumulation_boundary`` reports False inside, so
        micro-steps keep accumulating regardless of
        ``gradient_accumulation_steps``. ``train_batch`` (a fused
        microbatch-scan + apply) is incompatible with an open context and
        raises.
        """
        self._no_sync_depth += 1
        try:
            yield
        finally:
            self._no_sync_depth -= 1

    def is_gradient_accumulation_boundary(self) -> bool:
        if self._no_sync_depth > 0:
            return False
        return self._compat_count >= self.gas

    def step(self):
        """Apply the optimizer with accumulated grads (reference ``step:2204``);
        no-op until the accumulation boundary like the reference."""
        if not self.is_gradient_accumulation_boundary():
            return
        if self._host_adam is not None:
            # route the accumulated grads through the host optimizer (the
            # jitted apply_step below assumes on-device optax state)
            grads = jax.tree.map(lambda g: g / self.gas, self._compat_acc)
            self._apply_host_adam(grads, float(np.asarray(global_grad_norm(grads))))
            self._compat_acc = None
            self._compat_count = 0
            # a forward() cached before this step holds grads computed
            # against the pre-step params/accumulator — drop it so a later
            # backward() cannot commit already-applied gradients
            self._compat_pending = None
            self.global_steps += 1
            return
        if self._apply_fn is None:
            config = self.config
            clip = config.gradient_clipping

            def apply_step(state, acc):
                scale = state.loss_scale.scale if self.fp16 else jnp.asarray(1.0, jnp.float32)
                grads = jax.tree.map(lambda g: g / (scale * self.gas), acc)
                grad_norm = global_grad_norm(grads)
                overflow = ~jnp.isfinite(grad_norm) if self.fp16 else jnp.zeros([], jnp.bool_)
                if clip and clip > 0:
                    coef = jnp.minimum(1.0, clip / (grad_norm + 1e-6))
                    grads = jax.tree.map(lambda g: g * coef, grads)
                opt_in = self._opt_to_device(state.opt_state)
                updates, new_opt = self.tx.update(grads, opt_in, state.params)
                new_params = jax.tree.map(
                    lambda p, u: (p.astype(jnp.float32) + u.astype(jnp.float32)).astype(p.dtype),
                    state.params, updates)
                if self.fp16:
                    new_params = _tree_where(overflow, state.params, new_params)
                    new_opt = _tree_where(overflow, opt_in, new_opt)
                new_ls = update_loss_scale(state.loss_scale, overflow,
                                           dynamic=self.fp16 and config.fp16.loss_scale == 0,
                                           scale_window=config.fp16.loss_scale_window,
                                           min_scale=config.fp16.min_loss_scale,
                                           max_hysteresis=config.fp16.hysteresis)
                return TrainState(step=state.step + 1, params=new_params,
                                  opt_state=new_opt, loss_scale=new_ls,
                                  comm_feedback=state.comm_feedback)

            # out_shardings keep the optimizer state's memory kind (pinned
            # host under the offload storage tier) across compat steps
            self._apply_fn = jax.jit(apply_step, donate_argnums=(1,),
                                     out_shardings=self._state_shardings)
        self.state = self._apply_fn(self.state, self._compat_acc)
        self._compat_acc = None
        self._compat_count = 0
        self._compat_pending = None  # see host-adam branch above
        self.global_steps += 1

    def compile(self, example_batch=None, backend: str = "xla",
                compile_kwargs=None):
        """Ahead-of-time compile of the fused train step (reference
        ``engine.compile``, ``runtime/engine.py:3696``; there the model is
        re-wrapped in torch.compile — here jit is already the execution
        model, so this EAGERLY lowers+compiles so the first ``train_batch``
        pays no JIT cost inside the loop). ``backend``/``compile_kwargs``
        are accepted for signature parity; only "xla" exists on TPU."""
        if isinstance(example_batch, str):
            # reference signature compile(backend, compile_kwargs)
            # (engine.py:3696): a string first positional arg IS the backend,
            # not an example batch — shift the arguments accordingly
            if compile_kwargs is None and not isinstance(backend, str):
                compile_kwargs = backend
            backend = example_batch
            example_batch = None
        if backend != "xla":
            log_dist(f"compile backend {backend!r} ignored: XLA is the only "
                     "execution model on TPU")
        if example_batch is None:
            return self  # nothing to shape the lowering with; lazy JIT stands
        batch = self._shape_batch(example_batch)
        rng = jax.random.PRNGKey(0)
        # keep the executable and route matching train_batch calls through
        # it — lower().compile() does NOT warm the jit dispatch cache, so
        # discarding it would pay the 20-40s JIT twice. trace() is the
        # same staging pipeline lower() runs internally; keeping the
        # Traced around gives the static auditor the jaxpr for free.
        if self._host_adam is not None:
            traced = self._train_step.trace(self.state.params, batch, rng,
                                            self.state.step)
        else:
            traced = self._train_step.trace(self.state, batch, rng)
        lowered = traced.lower()
        exe = lowered.compile()
        self._aot_step = (exe, self._batch_fingerprint(batch))
        # the AOT path holds a real executable: its compile-time memory
        # breakdown is free — record it in the plan table + registry
        self._record_memory_analysis(exe, "train_step")
        self._run_static_audit(traced, exe, "train_step", lowered=lowered)
        return self

    def _run_static_audit(self, traced, compiled, label: str, lowered=None):
        """Compile-time static audit (``deepspeed_tpu/analysis``, gated on
        the ``analysis:`` config block): reconcile the compiled program's
        collectives against the plan table / comms ledger / jaxpr, check
        precision, donation, and host-sync hazards — all on the already-
        staged objects, so the audit costs an HLO walk, not a recompile.
        Findings land in the ledger's plan table, ``Analysis/*`` monitor
        events, the telemetry registry, and (when a report dir is known)
        ``audit-report.json`` beside the resilience dumps so the doctor
        can cross-reference a hang against an unplanned collective."""
        acfg = self.config.analysis
        if not acfg.enabled:
            return None
        from ..analysis import AuditOptions, audit_step
        from ..analysis.report import REPORT_NAME, SEVERITIES

        if acfg.fail_on not in (None, "none") and acfg.fail_on not in SEVERITIES:
            # a typo'd threshold must not silently disable the gate the
            # user thinks is armed
            raise ConfigError(
                f"analysis.fail_on={acfg.fail_on!r}: use one of "
                f"{SEVERITIES} (or null for report-only)")

        opts = AuditOptions(
            small_bytes=acfg.small_bytes, big_bytes=acfg.big_bytes,
            precision_min_elems=acfg.precision_min_elems,
            precision_big_elems=acfg.precision_big_elems,
            donation_min_bytes=acfg.donation_min_bytes,
            collective_allowlist=tuple(acfg.collective_allowlist),
            precision_allowlist=tuple(acfg.precision_allowlist),
            strict=acfg.strict)
        ledger = dist.get_comms_logger()
        report = audit_step(traced, label=label, options=opts,
                            axis_sizes={str(k): int(v) for k, v in
                                        dict(self.topo.mesh.shape).items()},
                            plan_records=ledger.plan_records,
                            ledger=ledger, lowered=lowered,
                            compiled=compiled)
        counts = report.counts()
        summary = dict(counts)
        for key in ("hlo_collectives", "matched_collectives",
                    "unplanned_collectives", "unmatched_reductions"):
            if key in report.context:
                summary[key] = report.context[key]
        ledger.record_analysis(label, summary)
        if self.monitor is not None:
            step = self.global_steps
            events = [(f"Analysis/{label}/{sev}", counts[sev], step)
                      for sev in counts]
            events.append((f"Analysis/{label}/unplanned_collectives",
                           report.context.get("unplanned_collectives", 0),
                           step))
            self.monitor.write_events(events)
        if self.telemetry is not None:
            self.telemetry.count("analysis_findings", len(report.findings))
        report_dir = acfg.report_dir
        if report_dir is None and self.config.resilience.enabled:
            report_dir = self.config.resilience.snapshot_dir
        if report_dir:
            try:
                os.makedirs(report_dir, exist_ok=True)
                report.write(os.path.join(report_dir, REPORT_NAME))
            except OSError as e:
                log_dist(f"analysis: could not write {REPORT_NAME}: {e}")
        for line in report.render().splitlines():
            log_dist(f"analysis: {line}")
        if acfg.fail_on in SEVERITIES and report.at_or_above(acfg.fail_on):
            raise RuntimeError(
                f"static audit failed ({acfg.fail_on}+ findings present "
                f"and analysis.fail_on={acfg.fail_on!r}):\n"
                + report.render())
        return report

    @staticmethod
    def _batch_fingerprint(batch):
        return tuple((tuple(x.shape), jnp.dtype(x.dtype).name)
                     for x in jax.tree.leaves(batch))

    @property
    def is_compiled(self) -> bool:
        return True  # every executed step ran through XLA

    def zero_grad(self):
        """Discard accumulated compat-path micro-gradients (reference
        ``engine.zero_grad``). The fused ``train_batch`` manages its own
        accumulator, so this only matters when abandoning a
        ``backward()`` window, e.g. before switching back to
        ``train_batch``."""
        self._compat_acc = None
        self._compat_pending = None
        self._compat_count = 0

    # ------------------------------------------------------------------
    def _shape_batch(self, batch):
        gas = self.gas

        def reshape(x):
            x = jnp.asarray(x)
            if x.ndim >= 1 and x.shape[0] == gas:
                return x
            if x.shape[0] % gas != 0:
                raise ValueError(f"batch dim {x.shape[0]} not divisible by gas={gas}")
            return x.reshape((gas, x.shape[0] // gas) + x.shape[1:])

        return jax.tree.map(reshape, batch)

    def _maybe_report(self):
        if self.global_steps % self.config.steps_per_print == 0:
            m = self._last_metrics
            log_dist(f"step={self.global_steps} loss={m.get('loss', float('nan')):.4f} "
                     f"lr={m.get('lr', 0):.3e} grad_norm={m.get('grad_norm', 0):.3f}")
        if self.monitor is not None:
            events = [
                (f"Train/Samples/train_loss", self._last_metrics.get("loss"),
                 self.global_steps * self.train_batch_size),
                (f"Train/Samples/lr", self._last_metrics.get("lr"),
                 self.global_steps * self.train_batch_size)]
            # ledger -> monitor bridge: per-op logical/wire bytes + latency
            # totals reach TensorBoard/CSV, not just stdout
            from ..comm import get_comms_logger
            ledger = get_comms_logger()
            if ledger.enabled:
                events += ledger.monitor_events(self.global_steps)
            # registry -> monitor bridge: the telemetry spine's counters and
            # phase histograms reach the existing JSONL/TB/W&B sinks too
            if (self.telemetry is not None
                    and self.telemetry.cfg.monitor_bridge):
                events += self.telemetry.registry.monitor_events(
                    self.global_steps)
            self.monitor.write_events(events)
        fp_cfg = self.config.flops_profiler
        if fp_cfg.enabled and self.global_steps == fp_cfg.profile_step:
            self.flops_profile(output_file=fp_cfg.output_file,
                               top_modules=fp_cfg.top_modules,
                               depth=fp_cfg.module_depth)

    def flops_profile(self, batch=None, output_file=None, top_modules: int = 3,
                      depth: int = -1):
        """Profile one microbatch's loss FLOPs per named scope (reference
        engine hook ``engine.py:1877`` → ``FlopsProfiler``). fwd+bwd+update
        FLOPs ≈ 3× the forward count reported here."""
        from ..profiling import FlopsProfiler

        prof = FlopsProfiler(self.config.flops_profiler)
        if batch is None and self._last_batch is not None:
            batch = jax.tree.map(lambda x: x[0], self._last_batch)
        if batch is None:
            logger.warning("flops_profile: no batch seen yet")
            return None
        self._rng, r = jax.random.split(self._rng)
        step_time = float(np.mean(self._step_times[-5:])) if self._step_times else 0.0
        prof.profile(lambda p, b: self._loss(p, b, r)[0],
                     (self.state.params, batch), params=self.state.params,
                     step_time=step_time)
        prof.print_model_profile(depth=depth, top_modules=top_modules,
                                 output_file=output_file)
        self.flops_profiler = prof
        return prof.total_flops

    # ------------------------------------------------------------------
    @property
    def _last_metrics(self) -> Dict[str, float]:
        """Host view of the latest step metrics (syncs on first access)."""
        if self._metrics_host is None:
            m = {k: float(np.asarray(v)) for k, v in self._metrics_dev.items()}
            if m.pop("overflow", 0.0):
                m["skipped"] = 1.0
            self._metrics_host = m
        return self._metrics_host

    @property
    def skipped_steps(self) -> int:
        """fp16 overflow-skipped step count (reference ``engine.skipped_steps``).
        Reads a device-side counter, so accessing it synchronizes."""
        return self._skipped_base + int(self._skipped_dev)

    @skipped_steps.setter
    def skipped_steps(self, value: int):
        self._skipped_base = int(value)
        self._skipped_dev = jnp.zeros([], jnp.int32)

    @property
    def loss_scale(self) -> float:
        return float(np.asarray(self.state.loss_scale.scale))

    def get_lr(self):
        return [float(np.asarray(self.lr_schedule(self.state.step)))]

    def get_global_grad_norm(self) -> float:
        return self._last_metrics.get("grad_norm", 0.0)

    def zero_stage(self) -> int:
        return self.rules.stage

    def throughput(self) -> Dict[str, float]:
        """samples/sec + step latency (reference ``ThroughputTimer``,
        ``utils/timer.py:199``)."""
        if not self._step_times:
            return {}
        recent = self._step_times[-20:]
        dt = float(np.mean(recent))
        return {"step_time_s": dt, "samples_per_sec": self.train_batch_size / dt}

    # state offload (reference ``engine.offload_states:3720``) ----------
    def offload_states(self, include=("optimizer_state",), device: str = "cpu",
                       nvme_path: Optional[str] = None):
        """Move engine state off-device between training phases: ``cpu`` =
        host RAM (numpy), ``nvme`` = SSD via the native aio swap tier
        (``runtime/zero/swapper.py``). Training is invalid until
        ``reload_states`` — same contract as the reference."""
        self._offloaded = getattr(self, "_offloaded", {})
        for raw_kind in include:
            kind = self._canonical_kind(raw_kind)
            if kind in self._offloaded:
                continue
            tree, sh = self._state_part(kind)
            if device == "nvme":
                sw = self._get_swapper(nvme_path)
                sw.swap_out(kind, tree)
                sw.synchronize(kind)
                placeholder = jax.tree.map(
                    lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree)
                self._set_state_part(kind, placeholder)
                # keep the owning swapper with the entry: its in-memory
                # manifest is the only way back to this data
                self._offloaded[kind] = ("nvme", sh, sw)
            else:
                host_tree, _ = _to_host_memory(tree, sh, fallback="numpy")
                self._set_state_part(kind, host_tree)
                self._offloaded[kind] = ("cpu", sh, None)

    def reload_states(self):
        for kind, (where, sh, sw) in list(getattr(self, "_offloaded", {}).items()):
            if where == "nvme":
                tree = sw.swap_in(kind, shardings=sh, delete=True)
            else:
                tree, _ = self._state_part(kind)
                tree = jax.device_put(tree, sh)
            self._set_state_part(kind, tree)
            del self._offloaded[kind]

    @staticmethod
    def _canonical_kind(kind: str) -> str:
        if kind in ("optimizer_state", "optimizer"):
            return "optimizer_state"
        if kind in ("params", "fp32_params", "hp_params"):
            return "params"
        raise ValueError(f"unknown offload kind {kind!r} "
                         "(use 'optimizer_state' or 'params')")

    def _state_part(self, kind: str):
        if kind == "optimizer_state":
            return self.state.opt_state, self._opt_shardings
        return self.state.params, self._param_shardings

    def _set_state_part(self, kind: str, tree):
        if kind == "optimizer_state":
            self.state = self.state.replace(opt_state=tree)
        else:
            self.state = self.state.replace(params=tree)

    def _get_swapper(self, nvme_path: Optional[str]):
        path = nvme_path or self.config.zero_optimization.offload_optimizer.nvme_path
        if not path:
            raise ValueError(
                "offload to nvme needs a path: pass nvme_path= or set "
                "zero_optimization.offload_optimizer.nvme_path in the config")
        swappers = getattr(self, "_swappers", None)
        if swappers is None:
            swappers = self._swappers = {}
        if path not in swappers:
            from .zero.swapper import AsyncTensorSwapper

            aio = self.config.aio
            swappers[path] = AsyncTensorSwapper(
                os.path.join(path, "dstpu_swap"),
                num_threads=aio.thread_count, block_size=aio.block_size)
        return swappers[path]

    def should_stop(self) -> bool:
        """True once the resilience tier drained for a preemption: the final
        snapshot is durable and the training loop should exit so the grace
        window is not spent on steps that will be lost."""
        r = self.resilience
        return bool(r is not None and r.stop_requested)

    # checkpointing (delegates to checkpoint subsystem) -----------------
    def save_checkpoint(self, save_dir, tag=None, client_state=None, **kw):
        from ..checkpoint.engine import save_checkpoint as _save

        return _save(self, save_dir, tag=tag, client_state=client_state, **kw)

    def load_checkpoint(self, load_dir, tag=None, **kw):
        from ..checkpoint.engine import load_checkpoint as _load

        return _load(self, load_dir, tag=tag, **kw)


# ---------------------------------------------------------------------------


def _accepts_kw(fn, name: str) -> bool:
    try:
        sig = inspect.signature(fn)
        return name in sig.parameters or any(
            p.kind == p.VAR_KEYWORD for p in sig.parameters.values())
    except (TypeError, ValueError):
        return False


def _accepts_rng(fn) -> bool:
    try:
        sig = inspect.signature(fn)
        n_positional = sum(1 for p in sig.parameters.values()
                           if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD))
        return n_positional >= 3 or any(p.name in ("rng", "rngs", "key")
                                        for p in sig.parameters.values())
    except (TypeError, ValueError):
        return False


def _draw_from_iter(data_iter, gas):
    mbs = [next(data_iter) for _ in range(gas)]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *mbs)


_HOST_JIT_PROBE: Dict[Any, bool] = {}


def _host_memory_jit_supported(mesh) -> bool:
    """Whether COMPILED programs on this mesh can take/return pinned-host
    operands (the memories API). TPU yes; the multi-device CPU SPMD
    partitioner rejects the placement annotations ('side-effect ops cannot
    be replicated'), so the offload storage tier must probe before placing
    optimizer state in host memory — host-resident inputs to a jit that
    cannot express them would crash the first train step."""
    # stable key (id() could be recycled after GC): platform + device ids
    key = (mesh.devices.flat[0].platform,
           tuple(d.id for d in mesh.devices.flat))
    if key not in _HOST_JIT_PROBE:
        try:
            sh = NamedSharding(mesh, P()).with_memory_kind("pinned_host")  # spec-ok: pinned-host capability probe, single scalar
            x = jax.device_put(jnp.zeros((1,), jnp.float32), sh)
            jax.jit(lambda v: v + 1, in_shardings=sh, out_shardings=sh)(x)
            _HOST_JIT_PROBE[key] = True
        except Exception:
            _HOST_JIT_PROBE[key] = False
    return _HOST_JIT_PROBE[key]


def _to_host_memory(tree, shardings, fallback: str = "keep"):
    """Move a pytree to pinned host memory (ZeRO-Offload tier; reference
    ``offload_optimizer.device=cpu``). Returns ``(tree, shardings)`` with the
    shardings updated to the actual residency, so later device_puts (e.g.
    ``reload_states``) restore the same memory kind. When the backend has no
    pinned_host space: ``fallback='keep'`` leaves the leaf on device,
    ``'numpy'`` fetches it to host RAM."""
    flat, treedef = jax.tree.flatten(tree)
    shs = jax.tree.leaves(shardings)
    out_leaves, out_shs = [], []
    for x, sh in zip(flat, shs):
        try:
            host_sh = sh.with_memory_kind("pinned_host")
            out_leaves.append(jax.device_put(x, host_sh))
            out_shs.append(host_sh)
        except Exception:
            # sync-ok: offload fallback when pinned-host memory is absent
            out_leaves.append(x if fallback == "keep" else jax.device_get(x))
            out_shs.append(sh)
    return (jax.tree.unflatten(treedef, out_leaves),
            jax.tree.unflatten(treedef, out_shs))


def initialize(args=None,
               model: Optional[Callable] = None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               distributed_port=None,
               mpu=None,
               dist_init_required=None,
               config=None,
               config_params=None,
               topology: Optional[Topology] = None,
               param_specs=None,
               batch_spec=None,
               **kwargs):
    """Create an engine (reference ``deepspeed.initialize``,
    ``deepspeed/__init__.py:69``; same signature vocabulary).

    ``model`` is a pure loss function ``loss = f(params, batch[, rng])`` or a
    flax module whose ``apply`` returns the loss; ``model_parameters`` is the
    initial parameter pytree (fp32) — or, for the ``zero.Init`` analogue
    (shard-at-creation, reference ``partition_parameters.py:816``), a
    zero-arg closure returning that pytree (e.g.
    ``lambda: flax_model.init(key, dummy)["params"]``): each leaf then
    materializes directly into its ZeRO shard and no full-size copy of the
    model ever exists on host or any single device.
    Returns ``(engine, optimizer_proxy, dataloader, lr_scheduler_proxy)`` to
    match the reference tuple.
    """
    raw_cfg = config if config is not None else config_params
    from ..autotuning.autotuner import AUTOTUNE_CONFIG_ENV

    if os.environ.get(AUTOTUNE_CONFIG_ENV) and raw_cfg is not None:
        from ..autotuning.autotuner import apply_autotune_env_overrides

        if isinstance(raw_cfg, str):  # config file path: load, then overlay
            with open(raw_cfg) as f:
                raw_cfg = json.load(f)
        elif not isinstance(raw_cfg, dict):  # typed config object
            raw_cfg = raw_cfg.to_dict()
        raw_cfg = apply_autotune_env_overrides(raw_cfg)
    cfg = load_config(raw_cfg)
    dist.init_distributed()
    if topology is None:
        spec = TopologySpec(pp=cfg.pipeline.stages if cfg.pipeline.stages else 1,
                            ep=cfg.moe.ep_size if cfg.moe.enabled else 1,
                            sp=cfg.sequence_parallel_size,
                            tp=cfg.tensor_parallel.tp_size if cfg.tensor_parallel.enabled else 1)
        topology = Topology(spec)
    set_topology(topology)
    # latency-hiding collective matmul: the runtime knob flips the fleet-wide
    # default the model wiring reads (model configs can also opt in per-model
    # via TransformerConfig.overlap_collective_matmul)
    from ..ops.collective_matmul import set_overlap_enabled
    set_overlap_enabled(bool(cfg.tensor_parallel.overlap_collective_matmul))

    loss_fn = model
    if hasattr(model, "apply") and hasattr(model, "init"):  # flax module
        mod = model

        def loss_fn(params, batch, rng=None):
            kw = {"rngs": {"dropout": rng}} if rng is not None else {}
            return mod.apply({"params": params}, batch, **kw)

        from ..models.transformer import TransformerLM
        # TransformerLM reads the topology itself; any other flax module is
        # a foreign model and must bring specs when tp > 1 (the engine
        # raises ForeignModelShardingError instead of replicating densely)
        loss_fn._sharding_native = isinstance(mod, TransformerLM)

    engine = DeepSpeedTPUEngine(loss_fn=loss_fn, params=model_parameters, config=cfg,
                                topology=topology, param_specs=param_specs,
                                batch_spec=batch_spec, optimizer=optimizer,
                                lr_scheduler=lr_scheduler,
                                donate_state=kwargs.get("donate_state", True),
                                autotp_example_batch=kwargs.get(
                                    "autotp_example_batch"),
                                frozen_params=kwargs.get("frozen_params"))
    dist.configure(comms_logger=cfg.comms_logger)

    dataloader = None
    if training_data is not None:
        from .data_pipeline.data_sampler import build_curriculum_sampler
        from .dataloader import DeepSpeedDataLoader

        # metric-file-driven curriculum selection (DataAnalyzer outputs →
        # DeepSpeedDataSampler; reference deepspeed_io + data_sampler.py).
        # Selection happens at the loader; the engine's seqlen hook still
        # truncates independently when a seqlen curriculum is configured.
        sampler = None
        if cfg.data_efficiency.enabled:
            sampler = build_curriculum_sampler(
                cfg.data_efficiency.data_sampling,
                batch_size=cfg.train_micro_batch_size_per_gpu,
                seed=cfg.data_efficiency.seed,
                draws_per_opt_step=engine.gas)
            if sampler is not None and sampler.n_samples != len(training_data):
                raise ConfigError(
                    f"curriculum metric files cover {sampler.n_samples} "
                    f"samples but training_data has {len(training_data)} — "
                    "the DataAnalyzer output must come from this corpus")
        engine.data_sampler = sampler  # checkpointed with the engine state
        dataloader = DeepSpeedDataLoader(training_data,
                                         batch_size=cfg.train_micro_batch_size_per_gpu,
                                         sampler=sampler)
    if dataloader is not None:
        # the control plane's halve_micro_batch actuator must not change
        # the engine's batch split while a fixed-shape loader feeds it
        engine._train_dataloader = dataloader
    if dataloader is not None and engine.resilience is not None:
        # resumable data stream: the loader's position rides in snapshot
        # meta, and a restore (which already happened at engine init)
        # fast-forwards it so the post-restore batch sequence matches an
        # uninterrupted run
        engine.resilience.register_dataloader(dataloader)
    return engine, engine.tx, dataloader, engine.lr_schedule
