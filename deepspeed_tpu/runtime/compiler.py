"""Compile integration (reference ``deepspeed/runtime/compiler.py``).

The reference gates ``torch.compile`` support behind a version probe and
wires a backend into the engine. Under XLA the engine's train step IS a
compiled program — there is no opt-in. What remains useful from the
reference surface is ahead-of-time compilation: ``engine.compile(batch)``
lowers and compiles the train step eagerly so the first ``train_batch``
doesn't pay the (20-40 s on TPU) JIT cost inside the training loop.
"""


def is_compile_supported() -> bool:
    """Always true: jit is the execution model, not an optional backend."""
    return True
