"""Silent-corruption integrity tier: fingerprints, replay, quarantine.

Every failure the resilience stack handles elsewhere is *loud* — crashes,
hangs, stragglers, torn writes. A host that computes wrong bits without
crashing is worse: the corruption lands in replicated params, gets
snapshotted as "valid", and poisons every later restore. This module is
the detection tier for that failure class (SDC — silent data corruption),
default-off behind ``resilience.integrity:`` and bitwise invisible when
off.

Three mechanisms, cheapest first:

1. **cross-rank fingerprints** — every ``interval_steps`` the engine's
   DP-replicated state is folded to a tiny ``uint32[chunks]`` digest by a
   jitted, position-weighted modular reduction (:func:`make_fingerprint_fn`).
   Replicated leaves MUST be bitwise identical across data-parallel ranks,
   so ANY digest divergence is corruption (or lost determinism — equally
   fatal). The digest stays on device at issue time and is fetched one step
   later (the PR 4 sentinel-metrics contract), so the hot path never
   host-syncs. Ranks exchange digests through a :class:`FingerprintStore`
   (shared-dir JSON, the heartbeat-transport idiom) and a doctor-style
   majority vote names the minority rank.
2. **shadow-step replay** — on divergence (or a periodic audit cadence) the
   last fingerprinted step is re-executed from the retained pre-step state,
   optionally on a rotated device, and re-fingerprinted. A replay that
   matches the majority means the live execution suffered a one-shot flip
   (``transient``); a replay that still diverges means the corruption is in
   the input state or the host computes wrong repeatedly (``sticky``) —
   that host gets quarantined, not retried.
3. **verified snapshots** — :class:`IntegrityMonitor.snapshot_stamp` is the
   commit-time callable the :class:`~.snapshot.SnapshotManager` consults:
   manifest entries gain ``{"fingerprint": ..., "verified": bool}`` and a
   snapshot taken inside the taint window (divergence detected but not yet
   rolled back) — or after the last known-clean fingerprint step once a
   divergence IS known — is never stamped verified, so
   ``latest_valid(prefer_verified=True)`` cannot resurrect poisoned state.

Actuation is NOT here: the monitor only *publishes* verdicts
(:meth:`IntegrityMonitor.pending_verdicts`); the flap-guarded control
supervisor's ``integrity`` rule (``control/policy.py``) decides rollback /
quarantine, so SDC response obeys the same hysteresis, cooldown, and
budget as every other automated action.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.fs import fsync_write_json
from ...utils.logging import log_dist, logger

__all__ = ["make_fingerprint_fn", "fingerprint_hex", "flip_bit",
           "FingerprintStore", "IntegrityMonitor"]

# multiplier folding per-leaf digests into the running chunk accumulator;
# odd (invertible mod 2^32) so no leaf's contribution can be erased
_FOLD = np.uint32(1000003)


# ---------------------------------------------------------------------------
# fingerprint kernel
# ---------------------------------------------------------------------------

def _leaf_digest(x: jnp.ndarray, chunks: int) -> jnp.ndarray:
    """``uint32[chunks]`` position-weighted modular digest of one leaf.

    The leaf is bitcast to a matching-width unsigned int (so the digest
    sees the exact bit pattern, not float semantics — ``-0.0`` vs ``0.0``
    and NaN payloads all count), widened to uint32, padded to a multiple of
    ``chunks``, and reduced per chunk as ``sum(w_i * v_i) mod 2^32`` with
    odd weights ``w_i = 2*i + 1``. An odd weight times any nonzero delta is
    nonzero mod 2^32, so every single-bit flip anywhere in the leaf changes
    its chunk's digest — the property the whole tier rests on."""
    if x.dtype == jnp.bool_:
        u = x.astype(jnp.uint32)
    elif jnp.issubdtype(x.dtype, jnp.integer) or jnp.issubdtype(
            x.dtype, jnp.floating):
        nbits = x.dtype.itemsize * 8
        u = jax.lax.bitcast_convert_type(
            x, jnp.dtype(f"uint{nbits}")).astype(jnp.uint32)
    else:  # complex etc.: view through float32 pairs is overkill; sum bits
        u = jnp.abs(x).astype(jnp.uint32)
    flat = u.reshape(-1)
    n = flat.shape[0]
    cols = -(-n // chunks)  # ceil
    pad = chunks * cols - n
    flat = jnp.pad(flat, (0, pad))
    mat = flat.reshape(chunks, cols)
    w = (jnp.arange(cols, dtype=jnp.uint32) * jnp.uint32(2)
         + jnp.uint32(1))
    return jnp.sum(mat * w[None, :], axis=1, dtype=jnp.uint32)


def make_fingerprint_fn(chunks: int = 8) -> Callable[[Any], jnp.ndarray]:
    """Jitted ``pytree -> uint32[chunks]`` digest (device-resident result).

    Call it, keep the device array, and fetch it a step later — issuing is
    async like any other jitted computation, so the hot path pays only the
    dispatch."""

    def fp(tree) -> jnp.ndarray:
        acc = jnp.zeros((chunks,), jnp.uint32)
        for leaf in jax.tree_util.tree_leaves(tree):
            if not hasattr(leaf, "dtype") or leaf.size == 0:
                continue
            acc = acc * _FOLD + _leaf_digest(jnp.asarray(leaf), chunks)
        return acc

    return jax.jit(fp)


def fingerprint_hex(fp_host: np.ndarray) -> str:
    """Canonical wire form of a fetched digest (8 hex chars per chunk)."""
    return "".join(f"{int(v):08x}" for v in np.asarray(fp_host, np.uint32))


def flip_bit(tree, *, bit: int = 17, leaf_index: int = 0):
    """Flip one bit of one element of the ``leaf_index``-th array leaf —
    the seeded SDC the chaos classes inject and the drills assert on.
    Pure function of the tree; returns a new tree."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    arrays = [i for i, l in enumerate(leaves)
              if hasattr(l, "dtype") and getattr(l, "size", 0)
              and jnp.issubdtype(l.dtype, jnp.floating)]
    if not arrays:
        return tree
    i = arrays[leaf_index % len(arrays)]
    leaf = leaves[i]
    nbits = leaf.dtype.itemsize * 8
    udt = jnp.dtype(f"uint{nbits}")
    flat = jax.lax.bitcast_convert_type(leaf, udt).reshape(-1)
    mask = jnp.zeros_like(flat).at[0].set(
        jnp.asarray(1 << (bit % nbits), udt))
    flipped = jax.lax.bitcast_convert_type(
        (flat ^ mask).reshape(leaf.shape), leaf.dtype)
    leaves[i] = flipped
    return jax.tree_util.tree_unflatten(treedef, leaves)


# ---------------------------------------------------------------------------
# cross-rank exchange
# ---------------------------------------------------------------------------

class FingerprintStore:
    """Shared-directory fingerprint exchange: one ``fp-<rank>.json`` per
    rank (atomic replace, bounded history), readable by every peer and by
    the doctor. The object-store heartbeat idiom, minus the liveness
    semantics: records are append-mostly and re-published only to attach a
    replay verdict."""

    KEEP = 64  # records retained per rank file

    def __init__(self, root: str, rank: int, world: int):
        self.root = root
        self.rank = int(rank)
        self.world = int(world)
        self._records: Dict[int, dict] = {}  # own records by step
        self._lock = threading.Lock()
        os.makedirs(root, exist_ok=True)

    def _path(self, rank: int) -> str:
        return os.path.join(self.root, f"fp-{rank}.json")

    def publish(self, step: int, fp_hex: str, *,
                verdict: Optional[str] = None) -> None:
        """Write (or revise, when attaching a verdict) our step record."""
        with self._lock:
            rec = self._records.setdefault(
                int(step), {"step": int(step), "fp": fp_hex})
            rec["fp"] = fp_hex
            if verdict is not None:
                rec["verdict"] = verdict
            keep = sorted(self._records)[-self.KEEP:]
            self._records = {s: self._records[s] for s in keep}
            body = {"rank": self.rank, "world": self.world,
                    "records": [self._records[s] for s in keep]}
        try:
            fsync_write_json(self._path(self.rank), body)
        except OSError as e:  # a torn publish is a missed vote, not a crash
            logger.warning(f"integrity: fingerprint publish failed: {e}")

    def read(self, step: int) -> Dict[int, dict]:
        """``rank -> record`` for every peer that has published ``step``."""
        out: Dict[int, dict] = {}
        for r in range(max(1, self.world)):
            try:
                with open(self._path(r)) as f:
                    body = json.load(f)
            except (OSError, ValueError):
                continue
            for rec in body.get("records", []):
                if rec.get("step") == int(step):
                    out[r] = rec
                    break
        return out


def vote(sigs: Dict[int, str]) -> Tuple[Optional[str], List[int]]:
    """Doctor-style majority vote over ``rank -> fp``: returns
    ``(majority_fp or None, minority_ranks)``. No strict majority (a tie,
    or a single rank) yields ``(None, [])`` — corruption cannot be
    localized without a quorum, only detected."""
    if len(sigs) < 2:
        return None, []
    freq: Dict[str, int] = {}
    for s in sigs.values():
        freq[s] = freq.get(s, 0) + 1
    majority = max(freq, key=lambda k: freq[k])
    if freq[majority] <= len(sigs) - freq[majority]:
        return None, []
    return majority, sorted(r for r, s in sigs.items() if s != majority)


# ---------------------------------------------------------------------------
# the monitor
# ---------------------------------------------------------------------------

class IntegrityMonitor:
    """Owned by :class:`~.supervisor.ResilienceManager`; all hooks run on
    the training thread. Detection only — verdicts are queued for the
    control supervisor's ``integrity`` rule, and the snapshot stamp is a
    pure read of the taint state."""

    def __init__(self, engine, cfg, *, store: Optional[FingerprintStore] = None,
                 emit: Optional[Callable[[dict], None]] = None,
                 replay_corrupt_fn: Optional[Callable] = None):
        self.engine = engine
        self.cfg = cfg
        ar = getattr(engine, "artifact_rank", 0)
        self.rank = (int(cfg.rank) if int(cfg.rank) >= 0
                     else int(ar() if callable(ar) else (ar or 0)))
        self.world = int(cfg.world)
        root = cfg.dir
        if not root:
            base = getattr(getattr(engine, "resilience", None),
                           "snapshot_dir", None) or "."
            root = os.path.join(base, "integrity")
        self.store = store or FingerprintStore(root, self.rank, self.world)
        self._emit = emit or (lambda ev: None)
        self._replay_corrupt_fn = replay_corrupt_fn
        self._fp_fn = make_fingerprint_fn(int(cfg.chunks))
        # pending device digest awaiting its one-step-delayed fetch:
        # (step, device uint32[chunks])
        self._pending: Optional[Tuple[int, Any]] = None
        # steps published but not yet quorum-compared: step -> publish step_i
        self._unresolved: Dict[int, int] = {}
        # divergences awaiting the minority rank's replay verdict
        self._unclassified: Dict[int, dict] = {}
        self._verdicts: List[dict] = []   # drained by the control rule
        self.divergences: List[dict] = []  # full history (flight dumps)
        self._recipes: Dict[int, dict] = {}  # step -> replay recipe
        self._steps_seen = 0
        self.last_fp: Optional[str] = None
        self.last_fp_step: Optional[int] = None
        self.last_clean_step: Optional[int] = None
        self.tainted_since: Optional[int] = None
        self.checks = 0
        self.replays = 0
        self.quarantined: List[int] = []  # ranks the supervisor demoted
        # True from the moment a divergence is DETECTED until a rollback
        # restores verified state: the window in which a committed
        # snapshot may hold corruption newer than the last clean
        # fingerprint and must not be stamped verified
        self._dirty = False
        self._counters = self._bind_counters()

    # -- wiring ---------------------------------------------------------
    def _bind_counters(self):
        try:
            from ...telemetry import get_registry, telemetry_active

            if telemetry_active():
                reg = get_registry()
                return {
                    "checks": reg.counter(
                        "dstpu_integrity_checks_total",
                        "cross-rank fingerprint comparisons performed"),
                    "divergence": reg.counter(
                        "dstpu_integrity_divergence_total",
                        "fingerprint divergences detected"),
                    "replays": reg.counter(
                        "dstpu_integrity_replays_total",
                        "shadow-step replays executed"),
                }
        except Exception:
            pass  # swallow-ok: telemetry is optional; detection must not depend on it
        return {}

    def _count(self, key: str, **labels) -> None:
        c = self._counters.get(key)
        if c is not None:
            try:
                c.inc(**labels) if labels else c.inc()
            except TypeError:
                c.inc()

    # -- cadence --------------------------------------------------------
    def due(self, step: int) -> bool:
        n = max(1, int(self.cfg.interval_steps))
        return step % n == 0

    @property
    def tainted(self) -> bool:
        return self.tainted_since is not None

    # -- hooks ----------------------------------------------------------
    def pre_step(self, step: int) -> None:
        """Retain a pre-step state copy when ``step`` will be fingerprinted
        — the replay recipe's input. One live retention at a time (plus any
        pinned by an unresolved divergence); the copy is device-resident
        and freed as soon as its step resolves clean."""
        if not self.due(step):
            return
        try:
            pre = jax.tree_util.tree_map(
                lambda x: jnp.copy(x) if hasattr(x, "dtype") else x,
                self.engine.state)
        except Exception as e:
            logger.warning(f"integrity: pre-step retention failed: {e}")
            return
        self._recipes[step] = {"pre_state": pre}
        self._gc_recipes(keep=step)

    def post_step(self, step: int) -> None:
        """Called once per executed step ``step`` (post-state is live in
        ``engine.state``): harvest last round's digest, re-poll unresolved
        votes, and issue this round's digest if due. Only the harvest
        touches the host, and only for a ``chunks``-word array issued a
        full step earlier."""
        self._steps_seen += 1
        self._harvest()
        self._poll_unresolved()
        if self.due(step):
            try:
                dev = self._fp_fn(self.engine.state)
                if hasattr(dev, "copy_to_host_async"):
                    dev.copy_to_host_async()
                self._pending = (step, dev)
            except Exception as e:
                logger.warning(f"integrity: fingerprint issue failed: {e}")
                self._pending = None
            rec = self._recipes.get(step)
            if rec is not None:
                rec["batch"] = getattr(self.engine, "_last_batch", None)
                rec["rng"] = getattr(self.engine, "_last_step_rng", None)
                rec["key"] = getattr(self.engine, "_last_step_key", None)

    def note_rollback(self, step: int) -> None:
        """The actuation that ends a taint window: state was restored from
        a verified snapshot, so divergence bookkeeping resets."""
        if self.tainted:
            self._emit({"Train/Integrity/rollback_clear": step})
        self.tainted_since = None
        self._dirty = False
        self._unclassified.clear()
        self._verdicts.clear()
        self._recipes.clear()
        self._pending = None
        self._unresolved.clear()

    # -- verdict queue (control rule reads) -----------------------------
    def pending_verdicts(self) -> List[dict]:
        return list(self._verdicts)

    def drain_verdicts(self) -> List[dict]:
        out, self._verdicts = self._verdicts, []
        return out

    # -- snapshot stamping ----------------------------------------------
    def snapshot_stamp(self, step: int) -> dict:
        """Commit-time integrity stamp for a snapshot of post-``step``
        state. NOT verified when (a) a divergence is live (taint window),
        (b) a vote for some step <= ``step`` is still unresolved (the
        snapshot may hold exactly the corruption we have not finished
        checking), or (c) we have diverged before and ``step`` is past the
        last known-clean fingerprint."""
        unresolved = [s for s in self._unresolved if s <= step]
        unresolved += [s for s in self._unclassified if s <= step]
        verified = not self.tainted and not unresolved
        if verified and self._dirty:
            # detected-but-not-yet-rolled-back: only steps at or before the
            # last KNOWN-clean fingerprint may still be stamped (the
            # corruption may predate its detection by up to an interval)
            verified = (self.last_clean_step is not None
                        and step <= self.last_clean_step)
        if verified and self.rank in self.quarantined:
            # a quarantined rank no longer votes, so its own digests can
            # never be re-proven clean — nothing it writes is verified
            verified = False
        return {"fingerprint": self.last_fp, "fingerprint_step":
                self.last_fp_step, "verified": bool(verified)}

    # -- internals ------------------------------------------------------
    def _harvest(self) -> None:
        if self._pending is None:
            return
        step, dev = self._pending
        self._pending = None
        try:
            host = np.asarray(dev)  # sync-ok: one-step-delayed 8-word digest fetch, the sentinel-metrics contract
        except Exception as e:
            logger.warning(f"integrity: fingerprint fetch failed: {e}")
            return
        fp = fingerprint_hex(host)
        self.last_fp, self.last_fp_step = fp, step
        self._emit({"Train/Integrity/fingerprint_step": step})
        if self.world >= 2:
            self.store.publish(step, fp)
            self._unresolved[step] = self._steps_seen
        else:
            # single-rank world: nothing to vote against; the digest still
            # rides snapshots and flight dumps as forensic evidence
            self.last_clean_step = step
            self._recipes.pop(step, None)

    def _poll_unresolved(self) -> None:
        # quarantined ranks' fingerprints no longer count: a demoted host's
        # stale (or still-corrupt) store records must not re-taint the
        # survivors replaying steps after the post-quarantine rollback
        quarantined = set(self.quarantined)
        eff_world = sum(1 for r in range(max(1, self.world))
                        if r not in quarantined)
        for step in sorted(self._unresolved):
            sigs = {r: rec for r, rec in self.store.read(step).items()
                    if r not in quarantined}
            timeout = (self._steps_seen - self._unresolved[step]
                       >= max(1, int(self.cfg.resolve_timeout_steps)))
            if len(sigs) < eff_world and not (timeout and len(sigs) >= 2):
                continue
            del self._unresolved[step]
            if len(sigs) < 2:
                # nobody left to vote against (quarantine shrank the
                # electorate): the digest stays forensic evidence only
                self.last_clean_step = step
                self._recipes.pop(step, None)
                continue
            self._compare(step, sigs)
        for step in sorted(self._unclassified):
            self._classify_peer(step)

    def _compare(self, step: int, recs: Dict[int, dict]) -> None:
        self.checks += 1
        self._count("checks")
        sigs = {r: rec["fp"] for r, rec in recs.items()}
        if len(set(sigs.values())) == 1:
            self.last_clean_step = step
            self._recipes.pop(step, None)
            return
        majority, minority = vote(sigs)
        if majority is None or not minority:
            # divergence without a localizable minority (tie / 2-world)
            minority = sorted(sigs)
            majority = None
        div = {"step": step, "sigs": {str(r): s for r, s in sigs.items()},
               "minority": minority, "majority_fp": majority,
               "self_minority": self.rank in minority, "verdict": None}
        self._count("divergence")
        self._dirty = True
        self.tainted_since = (step if self.tainted_since is None
                              else min(self.tainted_since, step))
        log_dist(f"integrity: fingerprint divergence at step {step}: "
                 f"minority rank(s) {minority} vs {len(sigs)} voters")
        self._emit({"Train/Integrity/divergence_step": step})
        if div["self_minority"] and majority is not None:
            div["verdict"] = self._replay_verdict(step, majority)
            self.store.publish(step, sigs[self.rank],
                              verdict=div["verdict"])
            self._finish_divergence(div)
        elif majority is not None:
            # wait (bounded) for the minority rank's replay verdict
            self._unclassified[step] = div
            div["_deadline"] = self._steps_seen + max(
                1, int(self.cfg.resolve_timeout_steps))
            self._classify_peer(step)
        else:
            div["verdict"] = "unlocalized"
            self._finish_divergence(div)

    def _classify_peer(self, step: int) -> None:
        div = self._unclassified.get(step)
        if div is None:
            return
        recs = self.store.read(step)
        for r in div["minority"]:
            v = recs.get(r, {}).get("verdict")
            if v:
                div["verdict"] = v
                break
        else:
            if self._steps_seen < div["_deadline"]:
                return
            # a host too corrupt to publish its own verdict is sticky
            div["verdict"] = "sticky"
        del self._unclassified[step]
        div.pop("_deadline", None)
        self._finish_divergence(div)

    def _finish_divergence(self, div: dict) -> None:
        self.divergences.append(div)
        self._verdicts.append(div)
        self._emit({"Train/Integrity/verdict": div})

    def _replay_verdict(self, step: int, majority_fp: str) -> str:
        """Shadow-step replay: re-execute ``step`` from the retained
        pre-step state and bitwise-compare the digest with the majority.
        Match -> the live run suffered a one-shot flip (``transient``);
        mismatch -> the corruption is in the inputs or the host repeats it
        (``sticky``). Best-effort: a replay that cannot run classifies
        conservatively as sticky."""
        if not self.cfg.shadow_replay:
            return "sticky"
        rec = self._recipes.get(step)
        if not rec or rec.get("batch") is None or rec.get("rng") is None:
            return "sticky"
        try:
            step_fn = self.engine._train_steps.get(rec.get("key"))
            if step_fn is None:
                return "sticky"
            pre = self._rotate(rec["pre_state"])
            out_state, _ = step_fn(pre, rec["batch"], rec["rng"])
            if self._replay_corrupt_fn is not None:
                out_state = self._replay_corrupt_fn(step, out_state)
            self.replays += 1
            self._count("replays")
            host = np.asarray(self._fp_fn(out_state))  # sync-ok: off-hot-path divergence forensics, not the step loop
            replay_fp = fingerprint_hex(host)
            return "transient" if replay_fp == majority_fp else "sticky"
        except Exception as e:
            logger.warning(f"integrity: shadow replay failed: {e}")
            return "sticky"

    def _rotate(self, tree):
        """Re-home the replay input on a different local device when the
        state is single-device and the host has spares — a flip pinned to
        one core then cannot reproduce. On sharded state this is a
        documented no-op: rotation would need a cross-host reshard, and the
        sticky/transient call falls back to pure re-execution."""
        try:
            devs = jax.local_devices()
            if len(devs) < 2:
                return tree
            leaves = jax.tree_util.tree_leaves(tree)
            homes = {d for l in leaves if hasattr(l, "devices")
                     for d in l.devices()}
            if len(homes) != 1:
                return tree
            (home,) = homes
            alt = devs[(devs.index(home) + 1) % len(devs)]
            return jax.device_put(tree, alt)
        except Exception:
            return tree  # swallow-ok: rotation is opportunistic; replay still classifies without it

    def _gc_recipes(self, keep: int) -> None:
        pinned = set(self._unresolved) | set(self._unclassified) | {keep}
        for s in [s for s in self._recipes if s not in pinned]:
            del self._recipes[s]

    # -- forensic surfaces ----------------------------------------------
    def snapshot(self) -> dict:
        """Rides flight dumps (``extra["integrity"]``) and the doctor."""
        return {"enabled": True, "rank": self.rank, "world": self.world,
                "interval_steps": int(self.cfg.interval_steps),
                "checks": self.checks, "replays": self.replays,
                "last_fp": self.last_fp, "last_fp_step": self.last_fp_step,
                "last_clean_step": self.last_clean_step,
                "tainted_since": self.tainted_since,
                "quarantined": list(self.quarantined),
                "divergences": list(self.divergences[-16:])}
