"""Full-stack chaos engine: deterministic fault schedules across
transport / serving / control.

:class:`~.faults.FaultPlan` proved the pattern for the *training* loop —
every failure mode a scheduled, repeatable, one-shot-audited event — but
its injection surface stops at loss/grad metrics and snapshot hooks. The
serving tier, the non-collective transports (heartbeat beacons over the
object store, the plan cache, the snapshot manifest commit), and the
control plane's health signals had no drill harness at all: their failure
handling was only exercised by real failures. :class:`ChaosSchedule`
generalizes the plan to the whole stack:

transport layer
    ``transport_put_error`` / ``transport_get_error`` — transient
    object-store PUT/GET failures (retried by ``utils/retry.py``);
    ``torn_beacon`` — a beacon body truncated mid-PUT (readers must treat
    it as absent); ``plan_cache_error`` — transient plan-cache read
    errors; ``snapshot_io_error`` — transient snapshot-commit I/O errors.

serving layer
    ``replica_kill`` — a replica dies at serving step N (engine thread
    stops, beacon goes stale; the router's dead-replica takeover must
    resume its work); ``kv_exhaustion`` — the admission pool reads dry for
    a few cycles; ``slow_prefill`` — a stalled/slow prefill step;
    ``drop_token`` — a sampled token's stream delivery is lost (the
    delivered-token dedup cursor must re-deliver it exactly once);
    ``replica_spawn_fail`` — a fleet scale-out's replica bring-up fails
    before the server exists (the FleetManager must reap the half-spawned
    handle, never leak a WARMING router entry); ``replica_slow_warm`` — a
    joining replica's warm-up stalls ``param`` seconds (the router's warm
    gate must keep traffic off it the whole time).

control layer
    ``stale_health`` — a health-table refresh returns the previous rows
    (stale data the flap guard must ride out); ``flap_straggler`` — a
    rank's straggler verdict flaps on alternate reads.

Each :class:`ChaosEvent` arms at the ``at``-th call of its injection site
and fires ``count`` consecutive times, exactly once per event — the
``fired`` audit trail records what actually happened (and rides
``chaos-schedule.json`` so ``python -m deepspeed_tpu.doctor`` can name
every injected fault in its post-mortem). Schedules are seeded:
:meth:`ChaosSchedule.generate` derives the ``at`` indices from a
``random.Random(seed)``, so the same seed replays the same chaos.

Training-layer injections (NaN loss, grad spikes, preemption, torn
snapshot writes, hangs, stragglers, beacon loss) ride along unchanged as a
nested :class:`~.faults.FaultPlan` (``ChaosSchedule.training``), which the
``ResilienceManager`` adopts when the ``chaos:`` block carries one.

Injection sites consult the process-global schedule through
:func:`get_chaos`; with no ``chaos:`` block configured the global is None
and every hook is a single attribute test — the stack is bitwise identical
to a tree without the subsystem.

Stdlib-only (no jax import): drill scripts and the stdlib transports
(``heartbeat.py``) import this without touching a backend.
"""

import json
import os
import random
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .faults import FaultPlan

try:
    from ...utils.logging import logger
except ImportError:  # loaded standalone (file-path import in drill scripts)
    import logging

    logger = logging.getLogger("deepspeed_tpu.chaos")

#: fault class -> layer (the taxonomy the docs/doctor report by)
FAULT_CLASSES: Dict[str, str] = {
    "transport_put_error": "transport",
    "transport_get_error": "transport",
    "torn_beacon": "transport",
    "plan_cache_error": "transport",
    "snapshot_io_error": "transport",
    "replica_kill": "serving",
    "kv_exhaustion": "serving",
    "slow_prefill": "serving",
    "drop_token": "serving",
    "replica_spawn_fail": "serving",
    "replica_slow_warm": "serving",
    "stale_health": "control",
    "flap_straggler": "control",
    # silent-data-corruption drills: ride the nested training FaultPlan
    # (sdc_transient_at_steps / sdc_sticky_from_step), not poll() sites —
    # registered here so the taxonomy, manifest validation, and the
    # doctor's named-fault evidence cover them like every other class
    "sdc_bitflip_transient": "training",
    "sdc_bitflip_sticky": "training",
}

#: per-class defaults for seeded generation: (count, param)
_GENERATE_DEFAULTS: Dict[str, Any] = {
    "transport_put_error": (2, 0.0),
    "transport_get_error": (2, 0.0),
    "torn_beacon": (1, 0.0),
    "plan_cache_error": (2, 0.0),
    "snapshot_io_error": (2, 0.0),
    "replica_kill": (1, 0.0),
    "kv_exhaustion": (3, 0.0),
    "slow_prefill": (1, 0.05),
    "drop_token": (1, 0.0),
    "replica_spawn_fail": (1, 0.0),
    "replica_slow_warm": (1, 0.05),
    "stale_health": (1, 0.0),
    "flap_straggler": (4, 0.0),
}

MANIFEST_NAME = "chaos-schedule.json"


class ChaosInjectedError(OSError):
    """A scheduled transient transport error (never raised outside chaos
    schedules). An OSError so the retry classification treats it exactly
    like the real failure it stands in for."""


@dataclass
class ChaosEvent:
    """One scheduled fault: arms at the ``at``-th call of a matching
    injection site, then fires ``count`` consecutive times."""
    kind: str
    site: str = ""        # "" matches every site consulting this kind
    at: int = 0           # 0-based index of the arming call
    count: int = 1        # consecutive firings once armed
    param: float = 0.0    # class-specific magnitude (sleep seconds, rank..)
    # runtime state (not part of the schedule identity)
    armed: bool = field(default=False, compare=False)
    remaining: int = field(default=0, compare=False)

    def to_dict(self) -> dict:
        return {"kind": self.kind, "site": self.site, "at": self.at,
                "count": self.count, "param": self.param}


class ChaosSchedule:
    """Seeded, one-shot-audited fault schedule across the whole stack."""

    def __init__(self, events: List[ChaosEvent], *, seed: int = 0,
                 training: Optional[FaultPlan] = None):
        for ev in events:
            if ev.kind not in FAULT_CLASSES:
                raise ValueError(
                    f"unknown chaos fault class {ev.kind!r}; "
                    f"choose from {sorted(FAULT_CLASSES)}")
        self.seed = int(seed)
        self.events = list(events)
        self.training = training
        self.fired: List[dict] = []   # (kind/site/at/layer/param) audit trail
        self._calls: Dict[tuple, int] = {}
        self._lock = threading.Lock()
        self._by_kind: Dict[str, List[ChaosEvent]] = {}
        for ev in self.events:
            self._by_kind.setdefault(ev.kind, []).append(ev)

    # -- construction ----------------------------------------------------
    @classmethod
    def generate(cls, seed: int, classes: List[str], *, horizon: int = 64,
                 events_per_class: int = 1,
                 sites: Optional[Dict[str, str]] = None,
                 training: Optional[FaultPlan] = None) -> "ChaosSchedule":
        """Seeded schedule: for each listed fault class, draw
        ``events_per_class`` arming indices uniformly over ``[0, horizon)``
        from ``random.Random(seed)``. Same seed => same schedule."""
        rng = random.Random(int(seed))
        events: List[ChaosEvent] = []
        for kind in classes:
            if kind not in FAULT_CLASSES:
                raise ValueError(f"unknown chaos fault class {kind!r}")
            count, param = _GENERATE_DEFAULTS.get(kind, (1, 0.0))
            for _ in range(max(1, int(events_per_class))):
                events.append(ChaosEvent(
                    kind=kind, site=(sites or {}).get(kind, ""),
                    at=rng.randrange(max(1, int(horizon))),
                    count=count, param=param))
        return cls(events, seed=seed, training=training)

    @classmethod
    def from_config(cls, cfg) -> "ChaosSchedule":
        """Build from a ``chaos:`` config block (``runtime/config.py``
        ChaosConfig): explicit ``events`` dicts first, then the seeded
        ``classes`` auto-generation, plus the nested training FaultPlan."""
        events = []
        for e in (getattr(cfg, "events", None) or []):
            if not isinstance(e, dict) or "kind" not in e:
                raise ValueError(
                    f"chaos.events entries are dicts with a 'kind' key "
                    f"(one of {sorted(FAULT_CLASSES)}); got {e!r}")
            events.append(ChaosEvent(kind=e["kind"], site=e.get("site", ""),
                                     at=int(e.get("at", 0)),
                                     count=int(e.get("count", 1)),
                                     param=float(e.get("param", 0.0))))
        training = None
        tr = getattr(cfg, "training", None)
        if tr is not None and getattr(tr, "enabled", False):
            training = FaultPlan.from_config(tr)
        classes = list(getattr(cfg, "classes", None) or [])
        if classes:
            gen = cls.generate(getattr(cfg, "seed", 0), classes,
                               horizon=getattr(cfg, "horizon", 64),
                               events_per_class=getattr(
                                   cfg, "events_per_class", 1))
            events.extend(gen.events)
        return cls(events, seed=getattr(cfg, "seed", 0), training=training)

    # -- the injection-site API ------------------------------------------
    def poll(self, kind: str, site: str) -> Optional[ChaosEvent]:
        """One consult from an injection site: increments the (kind, site)
        call counter, arms any matching event whose ``at`` index this call
        reaches (audited ONCE into ``fired``), and returns the event while
        it still has firings left — else None."""
        with self._lock:
            key = (kind, site)
            idx = self._calls.get(key, 0)
            self._calls[key] = idx + 1
            matching = [ev for ev in self._by_kind.get(kind, ())
                        if not ev.site or ev.site == site]
            # arm FIRST, for every matching event: an event whose `at`
            # index lands inside an earlier event's firing window must
            # still arm this call — the call counter never revisits an
            # index, so skipping the arming here would silently drop the
            # injection (and undercount the audited schedule)
            for ev in matching:
                if not ev.armed and idx == ev.at:
                    ev.armed = True
                    ev.remaining = max(1, ev.count)
                    self.fired.append({
                        "kind": kind, "site": site, "at": idx,
                        "count": ev.count, "param": ev.param,
                        "layer": FAULT_CLASSES[kind]})
                    logger.warning(f"chaos: {kind}@{site} armed at call "
                                   f"{idx} (x{ev.count})")
            for ev in matching:
                if ev.armed and ev.remaining > 0:
                    ev.remaining -= 1
                    return ev
        return None

    def fire(self, kind: str, site: str) -> bool:
        """One-shot boolean consult (serving/control sites)."""
        return self.poll(kind, site) is not None

    def value(self, kind: str, site: str) -> Optional[float]:
        """Like :meth:`fire` but returns the event's ``param`` (sleep
        seconds, target rank, ...) when it fires."""
        ev = self.poll(kind, site)
        return None if ev is None else ev.param

    def maybe_raise(self, kind: str, site: str) -> None:
        """Transport sites: raise a transient :class:`ChaosInjectedError`
        while the matching event fires (the retry loop absorbs it)."""
        ev = self.poll(kind, site)
        if ev is not None:
            raise ChaosInjectedError(f"chaos[{kind}@{site}]")

    def mangle_bytes(self, kind: str, site: str, data: bytes) -> bytes:
        """Torn-write sites: truncate the payload mid-body while the
        matching event fires (a reader must see garbage, never half-new)."""
        ev = self.poll(kind, site)
        if ev is None:
            return data
        return data[:max(1, len(data) // 2)]

    # -- audit / manifest ------------------------------------------------
    def all_fired(self) -> List[dict]:
        """The full audit trail including the nested training plan's
        ``fired`` entries (as ``site="training"`` rows)."""
        out = list(self.fired)
        if self.training is not None:
            out += [{"kind": kind, "site": "training", "at": step,
                     "layer": "training"}
                    for step, kind in self.training.fired]
        return out

    def classes_fired(self) -> List[str]:
        return sorted({e["kind"] for e in self.all_fired()})

    def to_manifest(self) -> dict:
        return {"version": 1, "seed": self.seed,
                "events": [ev.to_dict() for ev in self.events],
                "fired": self.all_fired()}

    def dump(self, directory: str) -> str:
        """Write ``chaos-schedule.json`` beside the fleet's other crash
        artifacts so the doctor's post-mortem can name every injected
        fault. Returns the path."""
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, MANIFEST_NAME)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_manifest(), f, indent=1)
        os.replace(tmp, path)
        return path


# ---------------------------------------------------------------------------
# process-global schedule: injection sites consult this; None = chaos off
# and every hook is a single attribute test (bitwise off-identity)
# ---------------------------------------------------------------------------

_CHAOS: Optional[ChaosSchedule] = None
_FROM_CONFIG = False   # provenance: installed by an engine's chaos: block?


def configure_chaos(schedule: Optional[ChaosSchedule]
                    ) -> Optional[ChaosSchedule]:
    """Install (or clear, with None) the process-wide chaos schedule.
    Schedules installed this way (benches, tests) are MANUAL: an engine
    built from a chaos-FREE config leaves them alone (the caller owns the
    lifecycle), while an engine whose config carries its own enabled
    ``chaos:`` block installs that schedule instead — an explicit config
    always wins over an ambient manual install."""
    global _CHAOS, _FROM_CONFIG
    _CHAOS = schedule
    _FROM_CONFIG = False
    return schedule


def _training_identity(plan: Optional[FaultPlan]):
    """The *schedule* identity of a training FaultPlan (runtime state —
    ``fired``/``_spent`` — excluded): what two configs must agree on for
    their chaos blocks to count as the same drill."""
    if plan is None:
        return None
    return (plan.nan_loss_at_steps, plan.grad_spike_at_steps,
            plan.spike_magnitude, plan.preempt_at_step,
            plan.torn_write_at_steps, plan.crash_before_commit_at_steps,
            plan.hang_at_step, plan.slow_rank, plan.slow_step_s,
            plan.heartbeat_loss_at_steps, plan.sdc_transient_at_steps,
            plan.sdc_sticky_from_step, plan.sdc_rank, plan.sdc_bit)


def install_chaos_from_config(cfg) -> ChaosSchedule:
    """Engine-init install path for the ``chaos:`` config block. Building
    several engines from the SAME drill config (the autotuner's probe
    engines, a restart in-process) must not reset the one-shot audit
    trail and re-arm already-fired events — when a config-installed
    schedule with the same seed+events+training plan is already live, it
    is kept (counters and ``fired`` intact) instead of being rebuilt. A
    config that differs in ANY schedule dimension (including only the
    nested training block) replaces the live schedule."""
    global _CHAOS, _FROM_CONFIG
    new = ChaosSchedule.from_config(cfg)
    cur = _CHAOS
    if (_FROM_CONFIG and cur is not None and cur.seed == new.seed
            and [e.to_dict() for e in cur.events]
            == [e.to_dict() for e in new.events]
            and _training_identity(cur.training)
            == _training_identity(new.training)):
        return cur
    _CHAOS = new
    _FROM_CONFIG = True
    return new


def clear_config_chaos() -> None:
    """Engine-init path for configs WITHOUT a chaos block: clears a
    previously config-installed schedule (the off-identity contract is
    per-config), but never touches a manually-installed one — a bench
    mid-drill may legitimately build chaos-free reference engines."""
    global _CHAOS, _FROM_CONFIG
    if _FROM_CONFIG:
        _CHAOS = None
        _FROM_CONFIG = False


def get_chaos() -> Optional[ChaosSchedule]:
    return _CHAOS


def chaos_active() -> bool:
    return _CHAOS is not None
