"""Cross-host heartbeats: per-host beacons → dead-host / straggler verdicts.

The watchdog (:mod:`watchdog`) notices *this* host's step not finishing; the
heartbeat table is the complementary fleet view — every host periodically
publishes a small beacon (rank, step, recent step time), and any reader can
derive:

- **dead host** — beacon older than ``dead_after_s`` (the host stopped
  publishing: wedged, preempted, or gone);
- **straggler** — a host whose reported step time exceeds ``factor`` × the
  fleet median (the EQuARX/TPU-pod failure mode where one slow host drags
  every collective; a straggler is *detectable* here long before the
  watchdog's absolute deadline trips).

Transport is pluggable via the two-method protocol of
:class:`FileHeartbeatTransport` (``write(rank, payload)`` /
``read_all() -> {rank: payload}``); the default is beacon files in a shared
directory (GCS-fuse / NFS on real pods, tmpdir in tests) written via
temp + ``os.replace`` so readers never observe a torn beacon.

Stdlib-only (no jax import) for the same reason as :mod:`watchdog`: the
launcher and standalone drill scripts import it without touching a backend.
"""

import copy
import json
import os
import socket
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

try:
    from ...utils.logging import logger
except ImportError:  # loaded standalone (file-path import in drill scripts)
    import logging

    logger = logging.getLogger("deepspeed_tpu.heartbeat")

try:
    from ...utils.retry import RetryError, RetryPolicy, retry_call
except ImportError:  # standalone load: degrade to single-attempt calls
    RetryError = OSError

    def retry_call(fn, **_kw):
        return fn()

    RetryPolicy = None

try:
    from .chaos import get_chaos
except ImportError:  # standalone load: chaos drills need the package

    def get_chaos():
        return None


# beacons are small and frequent: short backoffs, tight deadline — a PUT
# that cannot land within a couple of beacon intervals should fail (the
# beater retries next interval anyway)
_BEACON_RETRY = (RetryPolicy(max_attempts=4, base_s=0.02, cap_s=0.5,
                             deadline_s=5.0)
                 if RetryPolicy is not None else None)
# GETs ride synchronous read paths (HealthTable.read sits under the
# router's submit/alive_ids): immediate zero-backoff re-reads only — a
# sleeping per-key backoff on a degraded store would head-of-line block
# client traffic, and an absent beacon is already tolerated (the next
# periodic read retries naturally)
_BEACON_GET_RETRY = (RetryPolicy(max_attempts=3, base_s=0.0, cap_s=0.0,
                                 deadline_s=1.0)
                     if RetryPolicy is not None else None)

_BEACON_PREFIX = "hb-"


class FileHeartbeatTransport:
    """Beacon files ``hb-<rank>.json`` in a shared directory."""

    def __init__(self, directory: str):
        self.dir = os.path.abspath(directory)
        os.makedirs(self.dir, exist_ok=True)

    def write(self, rank: int, payload: dict) -> None:
        path = os.path.join(self.dir, f"{_BEACON_PREFIX}{int(rank)}.json")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)  # readers see old-or-new, never torn

    def read_all(self) -> Dict[int, dict]:
        out: Dict[int, dict] = {}
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for name in names:
            if not (name.startswith(_BEACON_PREFIX) and name.endswith(".json")):
                continue
            try:
                rank = int(name[len(_BEACON_PREFIX):-len(".json")])
                with open(os.path.join(self.dir, name)) as f:
                    doc = json.load(f)
            except (ValueError, OSError, json.JSONDecodeError):
                continue  # partially-deleted or foreign file: not a beacon
            if isinstance(doc, dict):  # a torn/garbage body reads as absent
                out[rank] = doc
        return out


class _LocalBucketStub:
    """Minimal object-store client over a local directory, with BUCKET
    semantics: whole-object PUT/GET only (a reader never observes a partial
    write — PUT lands atomically), last-writer-wins per key, flat key
    namespace under a prefix. Stands in for a GCS/S3 bucket in tests and on
    dev boxes; a real deployment passes any client object with the same
    three methods (``put_object``/``get_object``/``list_objects``) to
    :class:`ObjectStoreHeartbeatTransport` instead."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        # keys are opaque bucket paths; map separators into the local tree
        safe = key.strip("/").replace("/", os.sep)
        return os.path.join(self.root, safe)

    def put_object(self, key: str, data: bytes) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.put.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)  # the atomic whole-object PUT

    def get_object(self, key: str) -> bytes:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except OSError:
            raise KeyError(key)

    def list_objects(self, prefix: str):
        base = self._path(prefix)
        try:
            names = os.listdir(base)
        except OSError:
            return []
        pfx = prefix.strip("/")
        return [f"{pfx}/{n}" for n in sorted(names)
                if not n.split(os.sep)[-1].startswith(".")
                and ".put." not in n]


class ObjectStoreHeartbeatTransport:
    """The :class:`FileHeartbeatTransport` write/read_all protocol against a
    shared-bucket key/value layout (``<prefix>/hb-<rank>.json`` objects), so
    multi-slice fleets heartbeat through the object store they already have
    instead of needing a shared POSIX filesystem (slices rarely cross-mount
    one). Bucket contract: whole-object PUT/GET (no partial reads — a
    beacon decodes completely or reads as absent) and last-writer-wins per
    rank key (each rank owns its key; concurrent PUTs of the same key
    resolve to the newest, which is exactly beacon semantics).

    ``store`` is either a directory path (a :class:`_LocalBucketStub` is
    built over it) or any client exposing ``put_object(key, bytes)``,
    ``get_object(key) -> bytes`` and ``list_objects(prefix) -> [keys]``.

    Real buckets fail transiently (throttles, timeouts, 5xx): every PUT/GET
    runs under ``utils/retry.py`` (decorrelated-jitter backoff, deadline
    budget, ``dstpu_retry_total{site=heartbeat.*}``), so one EAGAIN never
    reads as a dead host. A beacon that decodes to garbage — a torn PUT
    observed mid-read on a store without whole-object semantics — reads as
    *absent*, never raises out of a :class:`HealthTable` refresh.
    """

    def __init__(self, store, prefix: str = "heartbeats",
                 retry: Optional["RetryPolicy"] = None,
                 get_retry: Optional["RetryPolicy"] = None):
        self.client = (_LocalBucketStub(store) if isinstance(store, str)
                       else store)
        self.prefix = prefix.strip("/")
        self.retry = retry or _BEACON_RETRY
        self.get_retry = get_retry or _BEACON_GET_RETRY

    def _key(self, rank: int) -> str:
        return f"{self.prefix}/{_BEACON_PREFIX}{int(rank)}.json"

    def write(self, rank: int, payload: dict) -> None:
        key = self._key(rank)
        data = json.dumps(payload).encode("utf-8")
        chaos = get_chaos()
        if chaos is not None:
            data = chaos.mangle_bytes("torn_beacon", "heartbeat.put", data)

        def _put():
            if chaos is not None:
                chaos.maybe_raise("transport_put_error", "heartbeat.put")
            self.client.put_object(key, data)

        retry_call(_put, site="heartbeat.put", policy=self.retry)

    def read_all(self) -> Dict[int, dict]:
        out: Dict[int, dict] = {}
        chaos = get_chaos()
        for key in self.client.list_objects(self.prefix):
            name = key.rsplit("/", 1)[-1]
            if not (name.startswith(_BEACON_PREFIX)
                    and name.endswith(".json")):
                continue

            def _get(key=key):
                if chaos is not None:
                    chaos.maybe_raise("transport_get_error", "heartbeat.get")
                return self.client.get_object(key)

            try:
                rank = int(name[len(_BEACON_PREFIX):-len(".json")])
                raw = retry_call(_get, site="heartbeat.get",
                                 policy=self.get_retry)
                doc = json.loads(raw)
            except (ValueError, KeyError, RetryError, OSError):
                # foreign object / deleted between list and get / retries
                # exhausted / torn or non-UTF-8 body: absent, not an error
                continue
            if isinstance(doc, dict):  # garbage-but-valid-JSON: absent too
                out[rank] = doc
        return out


class HeartbeatWriter:
    """Publishes this host's beacon. ``clock`` is injectable so tests can
    fabricate beacon ages deterministically."""

    def __init__(self, transport, rank: int,
                 clock: Callable[[], float] = time.time):
        self.transport = transport
        self.rank = int(rank)
        self.clock = clock
        self.beats = 0

    def beat(self, step: int, step_time_s: Optional[float] = None) -> None:
        self.transport.write(self.rank, {
            "rank": self.rank,
            "step": int(step),
            "step_time_s": None if step_time_s is None else float(step_time_s),
            "wall_time": float(self.clock()),
            "pid": os.getpid(),
            "host": socket.gethostname(),
        })
        self.beats += 1


@dataclass
class HostHealth:
    """One row of the fleet health table."""
    rank: int
    step: int
    step_time_s: Optional[float]
    age_s: float
    alive: bool
    straggler: bool
    ratio: float  # step_time / fleet median (1.0 when undefined)


def _median(xs: List[float]) -> float:
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])


class HealthTable:
    """Derives per-host verdicts from the beacon set.

    A host is a straggler only relative to *peers*: each host is compared
    against the median of the OTHER live hosts' step times (leave-one-out —
    an all-hosts median would let a 2-host fleet's straggler drag the
    reference up and cap its own ratio below 2×, making the verdict
    unreachable). With no live peer reporting a step time there is no
    reference and no straggler verdict.
    """

    def __init__(self, transport, *, dead_after_s: float = 60.0,
                 straggler_factor: float = 3.0,
                 clock: Callable[[], float] = time.time):
        self.transport = transport
        self.dead_after_s = float(dead_after_s)
        self.straggler_factor = float(straggler_factor)
        self.clock = clock
        self._last_rows: Optional[List[HostHealth]] = None  # chaos staleness

    def read(self) -> List[HostHealth]:
        chaos = get_chaos()
        if chaos is not None and chaos.fire("stale_health", "health.read"):
            # control-layer drill: this refresh returns the PREVIOUS rows
            # (a reader seeing stale data); consumers' flap guards must
            # ride it out instead of acting on one stale verdict. On a
            # first-ever read the previous state is the pre-warm-up empty
            # view — injecting that (rather than skipping but still
            # auditing the event) keeps the fired trail truthful.
            return copy.deepcopy(self._last_rows) if self._last_rows \
                else []
        beacons = self.transport.read_all()
        now = self.clock()
        rows: List[HostHealth] = []
        for rank in sorted(beacons):
            b = beacons[rank]
            age = max(0.0, now - float(b.get("wall_time", 0.0)))
            alive = age <= self.dead_after_s
            st = b.get("step_time_s")
            rows.append(HostHealth(rank=rank, step=int(b.get("step", -1)),
                                   step_time_s=st, age_s=age, alive=alive,
                                   straggler=False, ratio=1.0))
        reporting = [r for r in rows if r.alive and r.step_time_s is not None]
        if len(reporting) >= 2:
            for row in reporting:
                peers = [float(r.step_time_s) for r in reporting if r is not row]
                ref = _median(peers)
                if ref > 0:
                    row.ratio = float(row.step_time_s) / ref
                    row.straggler = row.ratio > self.straggler_factor
        if chaos is not None:
            ev = chaos.poll("flap_straggler", "health.read")
            if ev is not None and (ev.count - ev.remaining) % 2 == 1:
                # flapping signal: the target rank reads as a straggler on
                # alternate refreshes — the supervisor's trigger/clear
                # streaks must absorb it instead of re-planning every flap
                for row in rows:
                    if row.rank == int(ev.param):
                        row.straggler = True
                        row.ratio = max(row.ratio,
                                        self.straggler_factor + 1.0)
        self._last_rows = rows
        return rows

    def verdicts(self) -> Dict[str, List[int]]:
        """Condensed view: ``{"dead": [ranks], "stragglers": [ranks]}``."""
        rows = self.read()
        return {"dead": [r.rank for r in rows if not r.alive],
                "stragglers": [r.rank for r in rows if r.straggler]}
