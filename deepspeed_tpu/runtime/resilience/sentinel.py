"""In-loop divergence sentinel: NaN streaks and grad-norm spikes.

A production run dies two ways the loss curve can warn about: a NaN/inf
loss that persists (data corruption, fp16 blow-up past the skip gate, a
bad node) and a gradient-norm explosion that precedes divergence. The
sentinel watches the per-step metrics the engine already computes and trips
a configurable policy — ``rollback`` (restore last-good snapshot, optionally
dropping the LR), ``warn``, or ``halt``.

Transient single-step wobble is expected (fp16's loss-scale skip gate
already handles one-off overflow); the sentinel fires on *streaks*.
"""

from collections import deque
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from ...utils.logging import logger


class SentinelHalt(RuntimeError):
    """Raised when the sentinel trips under ``policy: halt``."""


@dataclass
class SentinelEvent:
    step: int
    kind: str        # "nan_loss" | "grad_spike"
    value: float
    action: str      # "rollback" | "warn" | "halt"
    detail: str = ""


@dataclass
class Sentinel:
    """Streak detectors over (loss, grad_norm) step metrics.

    ``observe`` returns the policy action when a detector trips, else None.
    The caller (ResilienceManager) executes the action and then calls
    ``reset`` so a rollback does not instantly re-trip on stale streaks.
    """

    nan_streak: int = 3          # consecutive non-finite steps before tripping
    spike_factor: float = 10.0   # grad_norm > factor * rolling median
    spike_streak: int = 2        # consecutive spike steps before tripping
    spike_window: int = 64       # rolling history length
    min_history: int = 8         # no spike verdicts before this many samples
    policy: str = "rollback"     # rollback | warn | halt

    events: List[SentinelEvent] = field(default_factory=list)
    _nan_run: int = 0
    _spike_run: int = 0
    _norms: deque = field(default_factory=lambda: deque(maxlen=64))

    def __post_init__(self):
        if self.policy not in ("rollback", "warn", "halt"):
            raise ValueError(f"sentinel policy {self.policy!r}: use "
                             "'rollback', 'warn', or 'halt'")
        self._norms = deque(maxlen=int(self.spike_window))

    def observe(self, step: int, loss: float, grad_norm: float
                ) -> Optional[str]:
        loss = float(loss)
        grad_norm = float(grad_norm)
        if not (np.isfinite(loss) and np.isfinite(grad_norm)):
            self._nan_run += 1
            if self._nan_run >= self.nan_streak:
                return self._trip(step, "nan_loss", loss,
                                  f"{self._nan_run} consecutive non-finite steps")
            return None
        self._nan_run = 0

        spiking = (len(self._norms) >= self.min_history
                   and grad_norm > self.spike_factor * float(
                       np.median(self._norms)))
        if spiking:
            self._spike_run += 1
            if self._spike_run >= self.spike_streak:
                return self._trip(
                    step, "grad_spike", grad_norm,
                    f"grad_norm {grad_norm:.3g} > {self.spike_factor}x "
                    f"median {float(np.median(self._norms)):.3g} "
                    f"for {self._spike_run} steps")
        else:
            self._spike_run = 0
            # only healthy norms feed the baseline: a spike streak must not
            # drag the median up and grant itself amnesty
            self._norms.append(grad_norm)
        return None

    def _trip(self, step: int, kind: str, value: float, detail: str) -> str:
        ev = SentinelEvent(step=step, kind=kind, value=float(value),
                           action=self.policy, detail=detail)
        self.events.append(ev)
        logger.warning(f"sentinel tripped at step {step}: {kind} ({detail}) "
                       f"-> {self.policy}")
        if self.policy == "halt":
            raise SentinelHalt(f"sentinel: {kind} at step {step} ({detail})")
        return self.policy

    def reset(self) -> None:
        """Clear streaks and history (after a rollback restored older state
        the old baseline no longer describes)."""
        self._nan_run = 0
        self._spike_run = 0
        self._norms.clear()
