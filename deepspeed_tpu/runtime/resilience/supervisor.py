"""Restore-on-restart + the per-step orchestration glue.

:class:`ResilienceManager` is the one object the engine talks to: it owns
the :class:`~.snapshot.SnapshotManager`, the :class:`~.sentinel.Sentinel`,
the :class:`~.preempt.PreemptionWatcher`, and the optional
:class:`~.faults.FaultPlan`, and exposes exactly three hooks —
``maybe_restore()`` at engine init, ``post_step()`` after every
``train_batch``, and ``drain()`` (also reachable via SIGTERM). With the
``resilience:`` block disabled none of this is constructed and the engine
is bit-identical to a tree without the subsystem.

Elastic restarts: a relaunch that comes back on a *different* chip count
calls :func:`resolve_restore` before building the engine — it resolves the
latest valid snapshot AND (when elasticity is configured) the
:class:`~...elasticity.elastic_agent.RescaleDecision` for the capacity
actually available, so the engine is built at a valid world and the batch
schedule stays consistent. The snapshot itself holds logical-global host
arrays, so restoring onto the new mesh is just ``device_put`` with the new
engine's shardings — the same resharding-by-construction the checkpoint
tier relies on.
"""

import time
from typing import Any, Optional, Tuple

import jax
import numpy as np

from ...utils.logging import log_dist, logger
from ..config_utils import ConfigError
from .faults import FaultPlan
from .preempt import PreemptionWatcher
from .sentinel import Sentinel
from .snapshot import SnapshotManager


def resolve_restore(snapshot_dir: str, ds_config=None,
                    available: Optional[int] = None
                    ) -> Tuple[Optional[dict], Optional[Any]]:
    """Pre-engine restart resolution: (latest valid snapshot entry or None,
    RescaleDecision or None).

    Call this FIRST in a restart script: the decision tells you what world
    (and batch schedule) to build the engine at; the entry tells you whether
    a restore will happen. Torn/corrupt newest snapshots are already skipped
    by manifest validation."""
    entry = SnapshotManager(snapshot_dir).latest_valid()
    decision = None
    if ds_config is not None and available is not None:
        elastic = getattr(ds_config, "elasticity", None)
        if elastic is not None and getattr(elastic, "enabled", False):
            from ...elasticity.elastic_agent import decide_world

            decision = decide_world(elastic, available)
            log_dist(f"elastic restore: {available} chips available -> "
                     f"world {decision.world_size} "
                     f"(batch {decision.final_batch}, "
                     f"micro {decision.micro_batch})")
    return entry, decision


class ResilienceManager:
    """Wires snapshots, sentinel, preemption, and fault injection into one
    engine. Constructed only when ``config.resilience.enabled``."""

    def __init__(self, engine, cfg):
        if not cfg.snapshot_dir:
            raise ConfigError(
                "resilience.enabled needs resilience.snapshot_dir — the "
                "subsystem is defined by having somewhere durable to "
                "snapshot to")
        self.engine = engine
        self.cfg = cfg
        self.faults: Optional[FaultPlan] = (
            FaultPlan.from_config(cfg.faults) if cfg.faults.enabled else None)
        self.snap = SnapshotManager(
            cfg.snapshot_dir, keep=cfg.keep_snapshots,
            use_async=cfg.async_snapshot, shard_mb=cfg.shard_mb,
            fault_hook=self.faults.snapshot_hook if self.faults else None)
        sc = cfg.sentinel
        self.sentinel: Optional[Sentinel] = None
        if sc.enabled:
            self.sentinel = Sentinel(
                nan_streak=sc.nan_streak, spike_factor=sc.spike_factor,
                spike_streak=sc.spike_streak, spike_window=sc.spike_window,
                min_history=sc.min_history, policy=sc.policy)
        if (self.sentinel is not None and sc.lr_drop_factor != 1.0
                and getattr(engine, "_client_optimizer", False)):
            logger.warning(
                "sentinel.lr_drop_factor is set but the engine was built "
                "with a CLIENT optimizer, which never sees the engine's LR "
                "schedule — rollbacks will report a dropped LR in metrics "
                "while the client optimizer keeps applying its own; wire "
                "engine.lr_schedule into the client optimizer (or use the "
                "config optimizer) for the drop to take effect")
        pc = cfg.preemption
        self.watcher: Optional[PreemptionWatcher] = None
        if pc.enabled:
            self.watcher = PreemptionWatcher(
                signals=tuple(pc.signals), probe_file=pc.probe_file,
                install=pc.install_signal_handler)
        if jax.process_count() > 1:
            logger.warning(
                "resilience snapshots fetch logical-global arrays to host "
                "(jax.device_get) and are wired for single-controller "
                "worlds; on this multi-host mesh use the checkpoint tier "
                "(orbax coordinates multi-host writes) for recovery")
        if getattr(engine, "_host_adam", None) is not None:
            logger.warning(
                "resilience snapshots cover the device TrainState only; the "
                "host-Adam offload tier's CPU optimizer state is NOT "
                "snapshotted — a restore re-seeds fp32 masters from params "
                "(use checkpoint save/load for exact host-Adam recovery)")
        self.rollbacks = 0
        self.restores = 0
        self.stop_requested = False
        self.drained = False
        # (step, metrics_dev) awaiting processing: the sentinel reads each
        # step's metrics one step LATE, off an async D2H copy started the
        # step before — post_step never stalls the dispatch pipeline on a
        # device sync (the engine's metrics-stay-on-device design holds
        # with resilience enabled)
        self._pending_metrics = None

    # ------------------------------------------------------------------
    # engine hooks
    # ------------------------------------------------------------------
    def maybe_restore(self) -> Optional[str]:
        """Engine-init hook: restore the latest valid snapshot, if any.
        Returns the restored tag or None."""
        entry = self.snap.latest_valid()
        if entry is None:
            return None
        self._restore(entry)
        self.restores += 1
        log_dist(f"resilience: restored snapshot {entry['tag']} "
                 f"(global_steps={self.engine.global_steps}"
                 f"{', preempted run' if entry['meta'].get('final') else ''})")
        return entry["tag"]

    def post_step(self) -> None:
        """Per-step hook (engine.train_batch, after the step was DISPATCHED).

        Order matters: a pending preemption wins over everything (the grace
        window is short); then the sentinel rules on the PREVIOUS step's
        metrics — read one step late off an async copy started last time,
        so no device sync serializes the dispatch pipeline; injections
        rewrite those observed metrics; a cadence snapshot only fires while
        no NaN streak is live, and the snapshot writer independently
        refuses to commit non-finite state (closing the one-step window in
        which a just-diverged state could otherwise pose as last-good)."""
        engine = self.engine
        step = engine.global_steps
        if self.faults is not None and self.faults.preempt_now(step):
            if self.watcher is not None:
                self.watcher.request("injected preemption")
            else:
                self.drain()
                return
        if self.watcher is not None and self.watcher.requested():
            self.drain()
            return

        prev, self._pending_metrics = self._pending_metrics, \
            (step, engine._metrics_dev)
        for leaf in jax.tree.leaves(engine._metrics_dev):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()  # lands before next post_step
        if prev is not None and self.sentinel is not None:
            pstep, pm = prev
            loss = float(np.asarray(pm["loss"]))
            grad_norm = float(np.asarray(pm["grad_norm"]))
            if self.faults is not None:
                loss = self.faults.observe_loss(pstep, loss)
                grad_norm = self.faults.observe_grad_norm(pstep, grad_norm)
            action = self.sentinel.observe(pstep, loss, grad_norm)
            if action == "rollback":
                self._rollback()
                return
            # "warn" already logged inside the sentinel; "halt" raised
        streak_live = (self.sentinel is not None
                       and self.sentinel._nan_run > 0)
        if not streak_live and self.cfg.snapshot_interval > 0 \
                and step % self.cfg.snapshot_interval == 0:
            self.take_snapshot()

    def drain(self) -> None:
        """Preemption path: retire in-flight device work, land any async
        checkpoint commit, force a synchronous final snapshot, and tell the
        training loop to stop (``engine.should_stop()``)."""
        if self.drained:
            self.stop_requested = True
            return
        engine = self.engine
        reason = self.watcher.reason if self.watcher else "drain()"
        log_dist(f"resilience: draining for preemption ({reason})")
        jax.block_until_ready(engine.state)
        pending = getattr(engine, "_ckpt_commit_thread", None)
        if pending is not None and pending.is_alive():
            pending.join()
        self.take_snapshot(final=True)
        self.snap.wait()
        self.drained = True
        self.stop_requested = True
        self._emit([("Resilience/preempt_drain", 1.0, engine.global_steps)])
        log_dist(f"resilience: final snapshot committed at step "
                 f"{engine.global_steps}; safe to terminate")

    # ------------------------------------------------------------------
    def take_snapshot(self, final: bool = False) -> str:
        engine = self.engine
        t0 = time.perf_counter()
        tag = self.snap.snapshot(
            engine.state, step=engine.global_steps,
            meta={"global_steps": engine.global_steps,
                  "skipped_steps": engine.skipped_steps,
                  "lr_scale": getattr(engine, "_lr_scale", 1.0),
                  "final": bool(final),
                  "topology": {"pp": engine.topo.pp_size,
                               "dp": engine.topo.dp_size,
                               "ep": engine.topo.ep_size,
                               "sp": engine.topo.sp_size,
                               "tp": engine.topo.tp_size},
                  "world_devices": engine.topo.n_devices},
            final=final)
        call_ms = (time.perf_counter() - t0) * 1e3
        self._emit([
            ("Resilience/snapshot_call_ms", call_ms, engine.global_steps),
            ("Resilience/snapshot_d2h_ms", self.snap.stats["d2h_ms"],
             engine.global_steps),
            ("Resilience/snapshot_bytes", self.snap.stats["bytes"],
             engine.global_steps)])
        return tag

    def _restore(self, entry: dict) -> None:
        engine = self.engine
        host_tree, entry = self.snap.restore_tree(engine.state, entry)
        engine.state = jax.device_put(host_tree, engine._state_shardings)
        meta = entry.get("meta", {})
        engine.global_steps = int(meta.get("global_steps", entry["step"]))
        engine.skipped_steps = int(meta.get("skipped_steps", 0))
        host_adam = getattr(engine, "_host_adam", None)
        if host_adam is not None:
            host_adam.reseed_masters(jax.device_get(engine.state.params))
        saved_scale = float(meta.get("lr_scale", 1.0))
        if saved_scale != getattr(engine, "_lr_scale", 1.0):
            engine._lr_scale = saved_scale
            self._invalidate_compiled_steps()

    def _rollback(self) -> None:
        engine = self.engine
        tripped_at = engine.global_steps
        self.snap.wait()  # an in-flight async write may BE the last-good
        entry = self.snap.latest_valid()
        if entry is None:
            logger.warning(
                "sentinel rollback requested but no valid snapshot exists "
                "yet — continuing without rollback (raise "
                "snapshot_interval coverage or pre-seed with a snapshot)")
            if self.sentinel is not None:
                self.sentinel.reset()
            return
        self._restore(entry)
        self._pending_metrics = None  # metrics of the rolled-away step
        drop = float(self.cfg.sentinel.lr_drop_factor)
        if drop != 1.0:
            engine._lr_scale = getattr(engine, "_lr_scale", 1.0) * drop
            self._invalidate_compiled_steps()
        self.rollbacks += 1
        if self.sentinel is not None:
            self.sentinel.reset()
        self._emit([("Resilience/rollback", 1.0, tripped_at),
                    ("Resilience/lr_scale",
                     getattr(engine, "_lr_scale", 1.0), tripped_at)])
        log_dist(f"resilience: rolled back from step {tripped_at} to "
                 f"snapshot {entry['tag']} (global_steps="
                 f"{engine.global_steps}, lr_scale="
                 f"{getattr(engine, '_lr_scale', 1.0):g})")

    def _invalidate_compiled_steps(self) -> None:
        """An LR-scale change is a trace-time constant: drop every compiled
        step so the next call retraces with the new scale. Rollbacks are
        rare; a recompile is the honest cost of changing the schedule."""
        engine = self.engine
        engine._train_steps = {(None, None): engine._make_train_step(None)}
        engine._train_step = engine._train_steps[(None, None)]
        engine._aot_step = None
        engine._apply_fn = None
        engine._micro_step_fn = None

    def _emit(self, events) -> None:
        if getattr(self.engine, "monitor", None) is not None:
            self.engine.monitor.write_events(events)

    def close(self) -> None:
        self.snap.close()
