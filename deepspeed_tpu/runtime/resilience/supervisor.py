"""Restore-on-restart + the per-step orchestration glue.

:class:`ResilienceManager` is the one object the engine talks to: it owns
the :class:`~.snapshot.SnapshotManager`, the :class:`~.sentinel.Sentinel`,
the :class:`~.preempt.PreemptionWatcher`, and the optional
:class:`~.faults.FaultPlan`, and exposes exactly three hooks —
``maybe_restore()`` at engine init, ``post_step()`` after every
``train_batch``, and ``drain()`` (also reachable via SIGTERM). With the
``resilience:`` block disabled none of this is constructed and the engine
is bit-identical to a tree without the subsystem.

Elastic restarts: a relaunch that comes back on a *different* chip count
calls :func:`resolve_restore` before building the engine — it resolves the
latest valid snapshot AND (when elasticity is configured) the
:class:`~...elasticity.elastic_agent.RescaleDecision` for the capacity
actually available, so the engine is built at a valid world and the batch
schedule stays consistent. The snapshot itself holds logical-global host
arrays, so restoring onto the new mesh is just ``device_put`` with the new
engine's shardings — the same resharding-by-construction the checkpoint
tier relies on.
"""

import os
import threading
import time
from collections import deque
from typing import Any, Optional, Tuple

import jax
import numpy as np

from ...telemetry.spans import span
from ...utils.logging import log_dist, logger
from ..config_utils import ConfigError
from .faults import FaultPlan
from .preempt import PreemptionWatcher
from .sentinel import Sentinel
from .snapshot import SnapshotManager
from .watchdog import StepWatchdog

# exit code a drained (preempted) run should hand back to the launcher so
# the restart policy can tell "wait out the preemption" from "crash";
# mirrored in launcher/launch.py (which must not import this jax-bound tier)
PREEMPT_EXIT_CODE = 82


def resolve_restore(snapshot_dir: str, ds_config=None,
                    available: Optional[int] = None
                    ) -> Tuple[Optional[dict], Optional[Any]]:
    """Pre-engine restart resolution: (latest valid snapshot entry or None,
    RescaleDecision or None).

    Call this FIRST in a restart script: the decision tells you what world
    (and batch schedule) to build the engine at; the entry tells you whether
    a restore will happen. Torn/corrupt newest snapshots are already skipped
    by manifest validation."""
    entry = SnapshotManager(snapshot_dir).latest_valid()
    decision = None
    if ds_config is not None and available is not None:
        elastic = getattr(ds_config, "elasticity", None)
        if elastic is not None and getattr(elastic, "enabled", False):
            from ...elasticity.elastic_agent import decide_world

            decision = decide_world(elastic, available)
            log_dist(f"elastic restore: {available} chips available -> "
                     f"world {decision.world_size} "
                     f"(batch {decision.final_batch}, "
                     f"micro {decision.micro_batch})")
    return entry, decision


class ResilienceManager:
    """Wires snapshots, sentinel, preemption, and fault injection into one
    engine. Constructed only when ``config.resilience.enabled``."""

    def __init__(self, engine, cfg):
        if not cfg.snapshot_dir:
            raise ConfigError(
                "resilience.enabled needs resilience.snapshot_dir — the "
                "subsystem is defined by having somewhere durable to "
                "snapshot to")
        self.engine = engine
        self.cfg = cfg
        self.faults: Optional[FaultPlan] = (
            FaultPlan.from_config(cfg.faults) if cfg.faults.enabled else None)
        if self.faults is None:
            # a chaos schedule (the `chaos:` block, installed before this
            # manager is built) may carry training-layer injections: adopt
            # its FaultPlan so one schedule drills the whole stack
            from .chaos import get_chaos

            chaos = get_chaos()
            if chaos is not None and chaos.training is not None:
                self.faults = chaos.training
        self.snap = SnapshotManager(
            cfg.snapshot_dir, keep=cfg.keep_snapshots,
            use_async=cfg.async_snapshot, shard_mb=cfg.shard_mb,
            fault_hook=self.faults.snapshot_hook if self.faults else None)
        sc = cfg.sentinel
        self.sentinel: Optional[Sentinel] = None
        if sc.enabled:
            self.sentinel = Sentinel(
                nan_streak=sc.nan_streak, spike_factor=sc.spike_factor,
                spike_streak=sc.spike_streak, spike_window=sc.spike_window,
                min_history=sc.min_history, policy=sc.policy)
        if (self.sentinel is not None and sc.lr_drop_factor != 1.0
                and getattr(engine, "_client_optimizer", False)):
            logger.warning(
                "sentinel.lr_drop_factor is set but the engine was built "
                "with a CLIENT optimizer, which never sees the engine's LR "
                "schedule — rollbacks will report a dropped LR in metrics "
                "while the client optimizer keeps applying its own; wire "
                "engine.lr_schedule into the client optimizer (or use the "
                "config optimizer) for the drop to take effect")
        pc = cfg.preemption
        self.watcher: Optional[PreemptionWatcher] = None
        if pc.enabled:
            self.watcher = PreemptionWatcher(
                signals=tuple(pc.signals), probe_file=pc.probe_file,
                install=pc.install_signal_handler)
        if jax.process_count() > 1:
            logger.warning(
                "resilience snapshots fetch logical-global arrays to host "
                "(jax.device_get) and are wired for single-controller "
                "worlds; on this multi-host mesh use the checkpoint tier "
                "(orbax coordinates multi-host writes) for recovery")
        if getattr(engine, "_host_adam", None) is not None:
            logger.warning(
                "resilience snapshots cover the device TrainState only; the "
                "host-Adam offload tier's CPU optimizer state is NOT "
                "snapshotted — a restore re-seeds fp32 masters from params "
                "(use checkpoint save/load for exact host-Adam recovery)")
        self.rollbacks = 0
        self.restores = 0
        self.stop_requested = False
        self.drained = False
        # (step, metrics_dev) awaiting processing: the sentinel reads each
        # step's metrics one step LATE, off an async D2H copy started the
        # step before — post_step never stalls the dispatch pipeline on a
        # device sync (the engine's metrics-stay-on-device design holds
        # with resilience enabled)
        self._pending_metrics = None

        # -- fleet-robustness tier (watchdog / heartbeat / degraded mode) --
        # the engine's artifact rank (DSTPU_PROCESS_ID-aware) keeps hangdump
        # and beacon filenames consistent with the telemetry tier's
        # flightdumps — the doctor joins all three by rank
        self._rank = getattr(engine, "artifact_rank", None)
        if self._rank is None:
            self._rank = jax.process_index()
        wc = cfg.watchdog
        self.watchdog: Optional[StepWatchdog] = None
        if wc.enabled:
            self.watchdog = StepWatchdog(
                wc.dump_dir or cfg.snapshot_dir, factor=wc.factor,
                floor_s=wc.floor_s, cap_s=wc.cap_s, window=wc.window,
                rank=self._rank)
        hc = cfg.heartbeat
        self.heartbeat = None
        self.health = None
        if hc.enabled:
            from .heartbeat import (FileHeartbeatTransport, HealthTable,
                                    HeartbeatWriter)

            transport = FileHeartbeatTransport(
                hc.dir or os.path.join(cfg.snapshot_dir, "heartbeats"))
            self.heartbeat = HeartbeatWriter(transport, rank=self._rank)
            self.health = HealthTable(transport,
                                      dead_after_s=hc.dead_after_s,
                                      straggler_factor=hc.straggler_factor)
        # newest HealthTable rows (refreshed each heartbeat tick): the
        # control plane reads THESE instead of issuing its own per-step
        # transport read
        self.last_health = None
        self.degraded = False
        # -- silent-corruption integrity tier (ISSUE 20) -------------------
        ic = cfg.integrity
        self.integrity = None
        if ic.enabled:
            from .integrity import FingerprintStore, IntegrityMonitor

            irank = int(ic.rank) if int(ic.rank) >= 0 else int(self._rank)
            root = ic.dir or os.path.join(cfg.snapshot_dir, "integrity")
            store = FingerprintStore(root, irank, int(ic.world))

            def _int_emit(ev: dict) -> None:
                step = self.engine.global_steps
                self._emit([(k, v if isinstance(v, (int, float)) else 1.0,
                             step) for k, v in ev.items()])

            self.integrity = IntegrityMonitor(
                engine, ic, store=store, emit=_int_emit,
                replay_corrupt_fn=self._replay_corrupt)
            # commit-time verified stamping: the snapshot writer consults
            # the monitor's taint view at manifest commit, so a divergence
            # detected while a write sat queued still denies the stamp
            self.snap.integrity_stamp = self.integrity.snapshot_stamp
        # set by TelemetryManager.attach_resilience: flight dumps ride the
        # watchdog expiry / rollback / drain paths, resilience events land
        # in the metrics registry. None = telemetry off, zero overhead.
        self._telemetry = None
        # set by ControlSupervisor.attach_engine: rollbacks feed the
        # control plane's rollback-rate signal. None = control off.
        self._control = None
        self._rollback_times: "deque[float]" = deque(maxlen=64)
        self._recent_step_times: "deque[float]" = deque(maxlen=16)
        self._step_t0: Optional[float] = None
        self._hang_release = threading.Event()
        self._dataloader = None
        self._restored_data_state = None
        # transport retries (utils/retry.py) surface as Resilience/* events
        # while this manager is live: "host X retried the bucket 14x" must
        # be visible in the same timeline as the dead verdict it preceded.
        # The sink holds only a WEAK reference to this manager (many
        # engines are built and abandoned without close() — autotuner
        # probes, serial ds.initialize calls — and a strong bound method
        # in the module-global registry would pin each whole engine
        # forever); the finalizer drops the registry entry when the
        # manager is collected, and close() drops it eagerly. The sink
        # object is materialized ONCE because the registry keys by id().
        import weakref

        from ...utils.retry import add_retry_monitor, remove_retry_monitor

        wself = weakref.ref(self)

        def _retry_sink(site, attempt, err, final):
            mgr = wself()
            if mgr is not None:
                mgr._on_transport_retry(site, attempt, err, final)

        self._retry_sink = _retry_sink
        add_retry_monitor(_retry_sink)
        weakref.finalize(self, remove_retry_monitor, _retry_sink)

    def _on_transport_retry(self, site: str, attempt: int, err: str,
                            final: bool) -> None:
        self._emit([(f"Resilience/retry/{site}", float(attempt),
                     self.engine.global_steps)])

    # ------------------------------------------------------------------
    # silent-data-corruption drills (chaos classes sdc_bitflip_*)
    # ------------------------------------------------------------------
    def _sdc_rank(self) -> int:
        """SDC drills target the integrity-tier rank when one is configured
        (in-process multi-engine drills give each engine its own virtual
        rank), else the process rank."""
        if self.integrity is not None:
            return self.integrity.rank
        ic = self.cfg.integrity
        return int(ic.rank) if int(ic.rank) >= 0 else int(self._rank)

    def _maybe_inject_sdc(self, step: int) -> None:
        f = self.faults
        if f is None or (not f.sdc_transient_at_steps
                         and f.sdc_sticky_from_step is None):
            return
        rank = self._sdc_rank()
        t = f.sdc_transient_now(step, rank)
        s = f.sdc_sticky_now(step, rank)
        if t or s:
            from .integrity import flip_bit

            self.engine.state = flip_bit(self.engine.state, bit=f.sdc_bit)
            if t:
                self._emit([("Resilience/fault/sdc_bitflip_transient",
                             1.0, step)])

    def _replay_corrupt(self, step: int, state):
        """Re-apply a STICKY chaos flip to a shadow-replay output: a broken
        host corrupts the replay too, which is exactly how the monitor
        tells sticky from transient (a one-shot transient flip is already
        spent and does NOT reproduce)."""
        f = self.faults
        if (f is not None and f.sdc_sticky_from_step is not None
                and f._sdc_rank_match(self._sdc_rank())
                and int(step) >= int(f.sdc_sticky_from_step)):
            from .integrity import flip_bit

            return flip_bit(state, bit=f.sdc_bit)
        return state

    def integrity_rollback(self) -> bool:
        """Control-plane actuator (``policy.rule_integrity``): roll back to
        the newest VERIFIED snapshot taken at or before the last
        known-clean fingerprint step. Returns True when a restore actually
        happened."""
        mx = (self.integrity.last_clean_step
              if self.integrity is not None else None)
        n = self.rollbacks
        with span("resilience/rollback"):
            self._rollback(max_step=mx, reason="integrity")
        return self.rollbacks > n

    # ------------------------------------------------------------------
    # engine hooks
    # ------------------------------------------------------------------
    def maybe_restore(self) -> Optional[str]:
        """Engine-init hook: restore the latest valid snapshot, if any.
        Returns the restored tag or None."""
        entry = self.snap.latest_valid()
        if entry is None:
            return None
        self._restore(entry)
        self.restores += 1
        meta = entry.get("meta", {})
        if meta.get("degraded_collectives"):
            # the run had already fallen back to exact collectives when this
            # snapshot was taken: a restart inherits the degraded mode (only
            # an operator's clear_degraded() re-escalates)
            self.enter_degraded(persist=False,
                                reason="inherited from snapshot meta")
        self._restored_data_state = meta.get("data_state")
        if self._restored_data_state and self._dataloader is not None:
            self._apply_data_state()
        log_dist(f"resilience: restored snapshot {entry['tag']} "
                 f"(global_steps={self.engine.global_steps}"
                 f"{', preempted run' if entry['meta'].get('final') else ''})")
        return entry["tag"]

    def register_dataloader(self, loader) -> None:
        """Attach the training dataloader so its position rides in snapshot
        meta (``state_dict``) and a restart fast-forwards it
        (``load_state_dict``) — the post-restore batch sequence then matches
        an uninterrupted run. Called by ``initialize()``; loaders without
        the state protocol are ignored."""
        if loader is None or not hasattr(loader, "state_dict"):
            return
        self._dataloader = loader
        if self._restored_data_state:
            self._apply_data_state()

    def _apply_data_state(self) -> None:
        state, self._restored_data_state = self._restored_data_state, None
        try:
            self._dataloader.load_state_dict(state)
            log_dist(f"resilience: data stream fast-forwarded to epoch "
                     f"{state.get('epoch')}, batch {state.get('batch_in_epoch')}")
        except Exception as e:
            logger.warning(f"resilience: could not restore data-stream "
                           f"state ({e}); the loader restarts from scratch")

    def pre_step(self) -> None:
        """Per-step hook BEFORE dispatch: arm the watchdog around the step
        (the deadline covers dispatch plus every blocking sync post_step
        performs — exactly the window a wedged collective hangs in)."""
        if self.watchdog is not None:
            self.watchdog.arm(self.engine.global_steps)
        if self.integrity is not None:
            # +1 pairs the pre-step retention with post_step's numbering
            # (the engine increments global_steps between the two hooks)
            self.integrity.pre_step(self.engine.global_steps + 1)
        self._step_t0 = time.monotonic()

    def abort_step(self) -> None:
        """Exception escape hatch for an armed step (engine.train_batch):
        the step never reached post_step, so disarm WITHOUT recording — an
        aborted step is neither a hang nor a step-time sample, and the
        caller may legitimately catch the exception and idle."""
        if self.watchdog is not None:
            self.watchdog.disarm(record=False)
        self._step_t0 = None

    def post_step(self) -> None:
        """Per-step hook (engine.train_batch, after the step was DISPATCHED).

        The fleet injections run first (a slow rank sleeps, a hang spins —
        both while the watchdog is still armed, so the drill exercises the
        REAL detection path); the inner logic then runs under a finally that
        disarms the watchdog and publishes the heartbeat, so a rollback's
        early return can't leave the deadline armed across non-step work."""
        if self.faults is not None:
            s = self.faults.slow_now(self.engine.global_steps, self._rank)
            if s > 0:
                time.sleep(s)
            if self.faults.hang_now(self.engine.global_steps):
                self._simulate_hang()
        try:
            self._post_step_inner()
        finally:
            dt = None
            if self.watchdog is not None:
                dt = self.watchdog.disarm()
            elif self._step_t0 is not None:
                dt = time.monotonic() - self._step_t0
            if dt is not None:
                self._recent_step_times.append(dt)
            self._step_t0 = None
            self._heartbeat_tick()

    def _post_step_inner(self) -> None:
        """Order matters: a pending preemption wins over everything (the
        grace window is short); then the sentinel rules on the PREVIOUS
        step's metrics — read one step late off an async copy started last
        time, so no device sync serializes the dispatch pipeline; injections
        rewrite those observed metrics; a cadence snapshot only fires while
        no NaN streak is live, and the snapshot writer independently
        refuses to commit non-finite state (closing the one-step window in
        which a just-diverged state could otherwise pose as last-good)."""
        engine = self.engine
        step = engine.global_steps
        if self.faults is not None and self.faults.preempt_now(step):
            if self.watcher is not None:
                self.watcher.request("injected preemption")
            else:
                self.drain()
                return
        if self.watcher is not None and self.watcher.requested():
            self.drain()
            return

        # SDC drills corrupt the post-step state BEFORE the fingerprint is
        # issued — detection sees exactly what a flipped ALU would leave
        self._maybe_inject_sdc(step)
        if self.integrity is not None:
            with span("integrity/check"):
                self.integrity.post_step(step)

        prev, self._pending_metrics = self._pending_metrics, \
            (step, engine._metrics_dev)
        for leaf in jax.tree.leaves(engine._metrics_dev):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()  # lands before next post_step
        if prev is not None and self.sentinel is not None:
            pstep, pm = prev
            loss = float(np.asarray(pm["loss"]))
            grad_norm = float(np.asarray(pm["grad_norm"]))
            if self.faults is not None:
                loss = self.faults.observe_loss(pstep, loss)
                grad_norm = self.faults.observe_grad_norm(pstep, grad_norm)
            action = self.sentinel.observe(pstep, loss, grad_norm)
            if action == "rollback":
                with span("resilience/rollback"):
                    self._rollback()
                    self._maybe_degrade()
                return
            # "warn" already logged inside the sentinel; "halt" raised
        streak_live = (self.sentinel is not None
                       and self.sentinel._nan_run > 0)
        if not streak_live and self.cfg.snapshot_interval > 0 \
                and step % self.cfg.snapshot_interval == 0:
            self.take_snapshot()

    def drain(self) -> None:
        """Preemption path: retire in-flight device work, land any async
        checkpoint commit, force a synchronous final snapshot, and tell the
        training loop to stop (``engine.should_stop()``)."""
        if self.drained:
            self.stop_requested = True
            return
        if self.watchdog is not None:
            # the drain's block_until_ready + sync snapshot legitimately
            # exceed a per-step deadline; do not let the watchdog call it a hang
            self.watchdog.disarm(record=False)
        engine = self.engine
        reason = self.watcher.reason if self.watcher else "drain()"
        log_dist(f"resilience: draining for preemption ({reason})")
        if self._telemetry is not None:
            # the flight record of a run about to vanish: dump BEFORE the
            # sync work below, while the timeline still shows why we drain
            self._telemetry.flight_dump("preempt_drain", {"why": reason})
            self._telemetry.count("preempt_drain")
        jax.block_until_ready(engine.state)
        pending = getattr(engine, "_ckpt_commit_thread", None)
        if pending is not None and pending.is_alive():
            pending.join()
        self.take_snapshot(final=True)
        self.snap.wait()
        self.drained = True
        self.stop_requested = True
        self._emit([("Resilience/preempt_drain", 1.0, engine.global_steps)])
        log_dist(f"resilience: final snapshot committed at step "
                 f"{engine.global_steps}; safe to terminate (exit with "
                 f"suggested_exit_code={self.suggested_exit_code} so the "
                 f"launcher classifies this as a preempt-drain)")

    @property
    def suggested_exit_code(self) -> int:
        """What the training script should ``sys.exit`` with once
        ``engine.should_stop()`` turns true: :data:`PREEMPT_EXIT_CODE` after
        a preemption drain (the launcher's restart policy then waits out the
        preemption without charging the crash-loop budget), 0 otherwise."""
        return PREEMPT_EXIT_CODE if self.drained else 0

    # ------------------------------------------------------------------
    # fleet tier: heartbeat, hang drill, degraded-mode fallback
    # ------------------------------------------------------------------
    def _heartbeat_tick(self) -> None:
        if self.heartbeat is None:
            return
        step = self.engine.global_steps
        hc = self.cfg.heartbeat
        if step % max(1, hc.interval_steps) != 0:
            return
        lost = (self.faults is not None
                and self.faults.heartbeat_lost(step))
        if not lost:
            st = (sum(self._recent_step_times) / len(self._recent_step_times)
                  if self._recent_step_times else None)
            try:
                self.heartbeat.beat(step, step_time_s=st)
            except Exception as e:
                # a beacon that cannot land (retries exhausted on a dead
                # bucket, full disk) must degrade to an ABSENT beacon —
                # peers will age it out — never abort the training step
                # this tick rides on
                logger.warning(f"resilience: heartbeat write failed: {e!r}")
        if self.health is not None:
            events = []
            self.last_health = rows = self.health.read()
            for row in rows:
                if not row.alive:
                    events.append(("Resilience/dead_host",
                                   float(row.rank), step))
                elif row.straggler:
                    events.append(("Resilience/straggler",
                                   float(row.rank), step))
                    events.append(("Resilience/straggler_ratio",
                                   row.ratio, step))
            if events:
                self._emit(events)

    def _simulate_hang(self) -> None:
        """``faults.hang_at_step`` drill: spin until the armed watchdog fires
        (its default action dumps stacks and kills the process; a test
        overrides ``on_expire`` and calls :meth:`release_hang`)."""
        if self.watchdog is None:
            logger.warning("faults.hang_at_step fired but the watchdog is "
                           "disabled — skipping the hang (nothing would "
                           "ever detect it)")
            return
        log_dist("resilience: injected hang — spinning until the watchdog "
                 "deadline expires")
        self._hang_release.clear()
        while not self._hang_release.wait(0.02):
            pass

    def release_hang(self) -> None:
        """Unblock a simulated hang (test hook, typically from
        ``watchdog.on_expire``)."""
        self._hang_release.set()

    def _maybe_degrade(self) -> None:
        """After the configured number of rollbacks inside the window, stop
        trusting the approximate collectives: repeated divergence with int8
        transports on the hot path is exactly the signature EQuARX-style
        compression failing on this model/data — fall back to exact XLA
        collectives instead of rolling back forever."""
        dm = self.cfg.degraded_mode
        now = time.monotonic()
        self._rollback_times.append(now)
        if not dm.enabled or self.degraded:
            return
        recent = [t for t in self._rollback_times if now - t <= dm.window_s]
        if len(recent) >= dm.rollback_threshold:
            self.enter_degraded(
                reason=f"{len(recent)} rollbacks within {dm.window_s:g}s")

    def enter_degraded(self, persist: bool = True,
                       reason: str = "operator") -> None:
        """Override every approximate-collective knob back to exact XLA
        collectives: fleet compression state off, planner off, and the
        engine's resolved DP-grad implementation cleared; compiled steps are
        invalidated so the next call retraces on the exact paths. With
        ``persist`` a snapshot is taken immediately so the flag rides in
        snapshot meta and restarts inherit it."""
        if self.degraded:
            return
        engine = self.engine
        from ...comm.compressed import configure_compression
        from ...comm.planner import configure_planner

        configure_compression("none")
        configure_planner("off")
        self._saved_dp_impl = (engine._compressed_dp, engine._dp_grad_impl)
        engine._compressed_dp = False
        engine._dp_grad_impl = None
        # the DCN-compressed program's error-feedback residual belongs to
        # the abandoned compressed trajectory: zero it (structure kept — the
        # retraced exact step just carries the zeros) so a later operator
        # clear_degraded() cannot re-inject a stale correction; the keyed
        # registry residuals of out-of-engine callers are dropped outright
        engine.state = engine.state.replace(
            comm_feedback=jax.tree.map(jax.numpy.zeros_like,
                                       engine.state.comm_feedback))
        from ...comm.compressed import clear_feedback

        clear_feedback()
        engine._degraded_collectives = True
        self.degraded = True
        if self._telemetry is not None:
            self._telemetry.count("degraded")
        self._invalidate_compiled_steps()
        self._emit([("Resilience/degraded_mode", 1.0, engine.global_steps)])
        logger.warning(
            f"resilience: entering DEGRADED MODE ({reason}) — compressed/"
            "planned collectives are overridden to exact XLA collectives; "
            "re-escalate only via ResilienceManager.clear_degraded()")
        if persist:
            self.take_snapshot()
            self.snap.wait()

    def clear_degraded(self) -> None:
        """Operator re-escalation: restore the config-derived collective
        knobs (the only way out of degraded mode — an automatic re-escalation
        would re-enter the very divergence loop that triggered the fallback)."""
        if not self.degraded:
            return
        engine = self.engine
        cc = engine.config.compressed_collectives
        from ...comm.compressed import configure_compression
        from ...comm.planner import configure_from_config

        configure_compression(cc.mode, block=cc.block,
                              hierarchical=cc.hierarchical,
                              sites=cc.site_map())
        configure_from_config(engine.config, topology=engine.topo)
        engine._compressed_dp, engine._dp_grad_impl = self._saved_dp_impl
        engine._degraded_collectives = False
        self.degraded = False
        self._rollback_times.clear()
        self._invalidate_compiled_steps()
        self._emit([("Resilience/degraded_mode", 0.0, engine.global_steps)])
        log_dist("resilience: degraded mode cleared by operator — config "
                 "collective knobs restored (next step retraces)")

    # ------------------------------------------------------------------
    def take_snapshot(self, final: bool = False) -> str:
        with span("resilience/snapshot"):
            return self._take_snapshot(final)

    def _take_snapshot(self, final: bool = False) -> str:
        engine = self.engine
        t0 = time.perf_counter()
        if self._telemetry is not None:
            self._telemetry.count("snapshot")
        data_state = None
        if self._dataloader is not None:
            try:
                data_state = self._dataloader.state_dict()
            except Exception as e:
                logger.warning(f"resilience: dataloader state_dict failed "
                               f"({e}); snapshot carries no data position")
        tag = self.snap.snapshot(
            engine.state, step=engine.global_steps,
            meta={"global_steps": engine.global_steps,
                  "skipped_steps": engine.skipped_steps,
                  "lr_scale": getattr(engine, "_lr_scale", 1.0),
                  "degraded_collectives": self.degraded,
                  "data_state": data_state,
                  "final": bool(final),
                  "topology": {"pp": engine.topo.pp_size,
                               "dp": engine.topo.dp_size,
                               "ep": engine.topo.ep_size,
                               "sp": engine.topo.sp_size,
                               "tp": engine.topo.tp_size},
                  "world_devices": engine.topo.n_devices},
            final=final)
        call_ms = (time.perf_counter() - t0) * 1e3
        self._emit([
            ("Resilience/snapshot_call_ms", call_ms, engine.global_steps),
            ("Resilience/snapshot_d2h_ms", self.snap.stats["d2h_ms"],
             engine.global_steps),
            ("Resilience/snapshot_bytes", self.snap.stats["bytes"],
             engine.global_steps)])
        return tag

    def _restore(self, entry: dict) -> None:
        engine = self.engine
        host_tree, entry = self.snap.restore_tree(engine.state, entry)
        engine.state = jax.device_put(host_tree, engine._state_shardings)
        meta = entry.get("meta", {})
        engine.global_steps = int(meta.get("global_steps", entry["step"]))
        engine.skipped_steps = int(meta.get("skipped_steps", 0))
        host_adam = getattr(engine, "_host_adam", None)
        if host_adam is not None:
            host_adam.reseed_masters(jax.device_get(engine.state.params))
        saved_scale = float(meta.get("lr_scale", 1.0))
        if saved_scale != getattr(engine, "_lr_scale", 1.0):
            engine._lr_scale = saved_scale
            self._invalidate_compiled_steps()

    def _rollback(self, *, max_step: Optional[int] = None,
                  reason: str = "sentinel") -> None:
        engine = self.engine
        tripped_at = engine.global_steps
        if self._telemetry is not None:
            # the steps that LED INTO the divergence are exactly what the
            # ring still holds — dump before the restore rewinds everything
            self._telemetry.flight_dump("rollback", {"tripped_at": tripped_at})
            self._telemetry.count("rollback")
        if self.watchdog is not None:
            # restore + retrace legitimately exceed a per-step deadline
            self.watchdog.disarm(record=False)
        self.snap.wait()  # an in-flight async write may BE the last-good
        entry = self.snap.latest_valid(max_step=max_step)
        if entry is None:
            logger.warning(
                f"{reason} rollback requested but no valid snapshot exists "
                "yet — continuing without rollback (raise "
                "snapshot_interval coverage or pre-seed with a snapshot)")
            if self.sentinel is not None:
                self.sentinel.reset()
            return
        self._restore(entry)
        if self.integrity is not None:
            # a restore from a verified snapshot ends the taint window
            self.integrity.note_rollback(tripped_at)
        self._pending_metrics = None  # metrics of the rolled-away step
        drop = float(self.cfg.sentinel.lr_drop_factor)
        if drop != 1.0:
            engine._lr_scale = getattr(engine, "_lr_scale", 1.0) * drop
            self._invalidate_compiled_steps()
        self.rollbacks += 1
        if self._control is not None:
            self._control.note_rollback(tripped_at)
        if self.sentinel is not None:
            self.sentinel.reset()
        self._emit([("Resilience/rollback", 1.0, tripped_at),
                    ("Resilience/lr_scale",
                     getattr(engine, "_lr_scale", 1.0), tripped_at)])
        log_dist(f"resilience: rolled back from step {tripped_at} to "
                 f"snapshot {entry['tag']} (global_steps="
                 f"{engine.global_steps}, lr_scale="
                 f"{getattr(engine, '_lr_scale', 1.0):g})")

    def _invalidate_compiled_steps(self) -> None:
        """An LR-scale change is a trace-time constant: drop every compiled
        step so the next call retraces with the new scale. Rollbacks are
        rare; a recompile is the honest cost of changing the schedule.
        Delegates to the engine's own invalidation (shared with the
        control-plane actuators)."""
        self.engine.invalidate_compiled_steps()

    def _emit(self, events) -> None:
        if getattr(self.engine, "monitor", None) is not None:
            self.engine.monitor.write_events(events)

    def close(self) -> None:
        from ...utils.retry import remove_retry_monitor

        remove_retry_monitor(self._retry_sink)
        if self.watchdog is not None:
            self.watchdog.stop()
        self.snap.close()
