"""Async double-buffered device→host snapshots with torn-write immunity.

The checkpoint tier (``checkpoint/engine.py``) is the durable, reshardable,
orbax-backed store a user points at object storage. Snapshots are the
*recovery* tier underneath it: small, frequent, local, and cheap enough to
take every N steps, so a NaN spike or a preemption loses minutes — not the
hours since the last user checkpoint. Reference analogue: the DataStates/
Nebula async checkpoint engines layered under DeepSpeed's save path.

Design:

- **double-buffered, off the step path** — ``snapshot()`` fetches the state
  to host (the only device-synchronizing part) and hands the host tree to a
  background writer thread; one snapshot may be queued while another is
  being written, so training overlaps the disk write. A third request
  blocks until a buffer frees (backpressure rather than unbounded RAM).
- **checksummed shards** — leaves are packed into ``shard_NNN.bin`` files
  (raw little-endian bytes, ~``shard_mb`` each); each shard's SHA-256 goes
  in the manifest, so restore *verifies* before it trusts.
- **atomic commit** — shards are written into a dot-temp directory which is
  ``os.replace``d to ``step_<N>/`` only when fully written; the manifest
  (``MANIFEST.json``, the single source of valid tags) is then rewritten
  via write-temp + fsync + rename. A crash at ANY point leaves either the
  previous manifest (new snapshot invisible) or the new one (snapshot fully
  durable) — never a pointer to garbage.
- **restore skips torn writes** — ``latest_valid()`` walks manifest entries
  newest-first and returns the first whose shards all exist and hash clean.
"""

import hashlib
import json
import os
import queue
import shutil
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ...utils.fs import fsync_dir, fsync_write_json
from ...utils.logging import logger
from ...utils.retry import RetryPolicy, retry_call
from .chaos import get_chaos

MANIFEST = "MANIFEST.json"

# the manifest commit is the snapshot's point of no return: a transient
# write error (shared-FS hiccup, NFS EAGAIN) must not discard minutes of
# shard writes, so it retries under the shared backoff before giving up
_COMMIT_RETRY = RetryPolicy(max_attempts=5, base_s=0.05, cap_s=1.0,
                            deadline_s=30.0)


def _keystr(kp) -> str:
    return jax.tree_util.keystr(kp)


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class SnapshotError(RuntimeError):
    pass


class SnapshotManager:
    """Owns one snapshot directory: write, prune, validate, load.

    ``fault_hook(stage, step) -> Optional[str]`` is the fault-injection
    seam (``faults.FaultPlan.snapshot_hook``): called at ``"post_data"``
    (shards written, data dir committed) and ``"pre_manifest"`` (about to
    commit the manifest); returning ``"torn"`` corrupts the newest shard,
    ``"crash"`` raises :class:`faults.InjectedCrash`.
    """

    def __init__(self, directory: str, keep: int = 2, use_async: bool = True,
                 shard_mb: int = 256,
                 fault_hook: Optional[Callable[[str, int], Optional[str]]] = None,
                 integrity_stamp: Optional[Callable[[int], dict]] = None):
        self.dir = os.path.abspath(directory)
        os.makedirs(self.dir, exist_ok=True)
        self.keep = max(1, int(keep))
        self.use_async = bool(use_async)
        self.shard_bytes = max(1, int(shard_mb)) << 20
        self.fault_hook = fault_hook
        # commit-time integrity stamp (integrity.IntegrityMonitor
        # .snapshot_stamp): consulted on the WRITER thread at manifest
        # commit, so a divergence detected while the write was queued still
        # denies the `verified` stamp. None (integrity off) leaves the
        # manifest byte-identical to the pre-integrity format.
        self.integrity_stamp = integrity_stamp
        self.stats: Dict[str, float] = {"snapshots": 0, "bytes": 0,
                                        "d2h_ms": 0.0, "write_ms": 0.0}
        self._err: Optional[BaseException] = None
        self._queue: "queue.Queue[Optional[Tuple[list, int, dict]]]" = \
            queue.Queue(maxsize=1)
        # queued + in-progress jobs, counted under a condition variable:
        # incremented BEFORE the (possibly blocking) put, decremented by the
        # writer when a job fully finishes — wait() sleeps on the condition,
        # immune to the set-then-clear race an Event would have
        self._inflight = 0
        self._cond = threading.Condition()
        self._writer: Optional[threading.Thread] = None

    # -- write path -----------------------------------------------------
    def snapshot(self, tree: Any, step: int, meta: Optional[dict] = None,
                 final: bool = False) -> str:
        """Fetch ``tree`` to host and commit it as tag ``step_<step>``.

        Returns the tag. Async mode returns as soon as the host copy exists
        and the write job is enqueued; ``final=True`` (the preemption drain)
        waits for the write to land before returning.
        """
        self._raise_pending()
        t0 = time.perf_counter()
        flat = [(_keystr(kp), np.asarray(jax.device_get(leaf)))
                for kp, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]]
        self.stats["d2h_ms"] = (time.perf_counter() - t0) * 1e3
        job = (flat, int(step), dict(meta or {}))
        if not self.use_async or final:
            self.wait()
            self._write(*job)
        else:
            self._ensure_writer()
            with self._cond:
                self._inflight += 1
            self._queue.put(job)  # blocks when both buffers are in flight
        self._raise_pending()
        return f"step_{int(step)}"

    def wait(self) -> None:
        """Drain queued + in-progress writes; re-raise a writer failure."""
        with self._cond:
            while self._inflight > 0:
                self._cond.wait()
        self._raise_pending()

    def close(self) -> None:
        if self._writer is not None:
            self._queue.join()
            self._queue.put(None)
            self._writer.join()
            self._writer = None

    def _raise_pending(self):
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    def _ensure_writer(self):
        if self._writer is None or not self._writer.is_alive():
            self._writer = threading.Thread(target=self._writer_loop,
                                            daemon=True,
                                            name="dstpu-snapshot-writer")
            self._writer.start()

    def _writer_loop(self):
        while True:
            job = self._queue.get()
            if job is None:
                self._queue.task_done()
                return
            try:
                self._write(*job)
            except BaseException as e:  # surfaced on the next snapshot()/wait()
                self._err = e
            finally:
                self._queue.task_done()
                with self._cond:
                    self._inflight -= 1
                    self._cond.notify_all()

    def _write(self, flat: List[Tuple[str, np.ndarray]], step: int, meta: dict):
        from .faults import InjectedCrash  # local: avoid import cycle

        t0 = time.perf_counter()
        tag = f"step_{step}"
        # a snapshot IS the rollback target: refuse to commit non-finite
        # state (the sentinel's health view is one step delayed, so a
        # just-diverged state could otherwise land as "last-good"). Runs on
        # the writer thread — the step path never pays for this scan.
        for key, arr in flat:
            try:
                bad = (np.issubdtype(np.asarray(arr).dtype, np.floating)
                       and not np.isfinite(np.asarray(arr, np.float32)).all())
            except (TypeError, ValueError):  # exotic dtype: trust it
                bad = False
            if bad:
                logger.warning(
                    f"snapshot step_{step}: leaf {key!r} contains non-finite "
                    "values — refusing to commit (the previous valid "
                    "snapshot stays the restore target)")
                return
        final_dir = os.path.join(self.dir, tag)
        tmp_dir = os.path.join(self.dir, f".tmp.{tag}.{os.getpid()}")
        shutil.rmtree(tmp_dir, ignore_errors=True)
        os.makedirs(tmp_dir)

        # group leaves into ~shard_bytes raw-byte shards; the manifest holds
        # (key, dtype, shape, offset, size) per leaf so restore needs no
        # pickle and bf16 (no native numpy serialization) rides as bytes
        shards: List[dict] = []
        cur: List[dict] = []
        cur_arrays: List[np.ndarray] = []
        cur_size = 0
        groups: List[Tuple[dict, List[np.ndarray]]] = []

        def flush():
            nonlocal cur, cur_arrays, cur_size
            if cur:
                shard = {"file": f"shard_{len(shards):03d}.bin", "leaves": cur}
                shards.append(shard)
                groups.append((shard, cur_arrays))
                cur, cur_arrays, cur_size = [], [], 0

        for key, arr in flat:
            nbytes = int(arr.nbytes)
            if cur_size and cur_size + nbytes > self.shard_bytes:
                flush()
            cur.append({"key": key, "dtype": str(arr.dtype),
                        "shape": list(arr.shape), "offset": cur_size,
                        "size": nbytes})
            cur_arrays.append(arr)
            cur_size += nbytes
        flush()

        total = 0
        for shard, arrays in groups:
            p = os.path.join(tmp_dir, shard["file"])
            h = hashlib.sha256()
            with open(p, "wb") as f:
                for arr in arrays:
                    # stream leaf-by-leaf to disk and into the hash: peak
                    # extra memory is ONE leaf's byte copy, never the whole
                    # state (tobytes, not memoryview — ml_dtypes leaves like
                    # bf16 reject the buffer protocol)
                    raw = arr.tobytes()
                    f.write(raw)
                    h.update(raw)
                f.flush()
                os.fsync(f.fileno())
            shard["sha256"] = h.hexdigest()
            total += os.path.getsize(p)
        # a stale tag dir can legally exist here (crash-before-commit left
        # its data unmanifested; a rollback re-reached the same step) —
        # os.replace onto a non-empty dir raises, so clear it first
        if os.path.isdir(final_dir):
            shutil.rmtree(final_dir)
        os.replace(tmp_dir, final_dir)  # data commit (atomic on one fs)
        fsync_dir(self.dir)

        action = self.fault_hook("post_data", step) if self.fault_hook else None
        if action == "torn":
            # deterministic torn write: flip bytes in the newest shard AFTER
            # its checksum was recorded — the manifest will name it valid,
            # restore's verification must prove otherwise
            victim = os.path.join(final_dir, shards[-1]["file"])
            with open(victim, "r+b") as f:
                f.write(b"\xde\xad\xbe\xef")
        if self.fault_hook and self.fault_hook("pre_manifest", step) == "crash":
            raise InjectedCrash(f"injected crash before manifest commit of {tag}")

        entry = {"tag": tag, "step": step, "meta": meta, "shards": shards,
                 "bytes": total, "wall_time": time.time()}
        # the non-finite scan above catches loud divergence; this catches
        # the SILENT kind — a fingerprint divergence detected but not yet
        # rolled back must deny the `verified` stamp, or the corrupt state
        # resurrects as the preferred restore target (ISSUE 20 bugfix)
        if self.integrity_stamp is not None:
            try:
                stamp = dict(self.integrity_stamp(step) or {})
            except Exception as e:
                logger.warning(f"snapshot {tag}: integrity stamp failed: {e}")
                stamp = {"verified": False, "error": str(e)}
            entry["integrity"] = stamp
            if not stamp.get("verified", False):
                logger.warning(
                    f"snapshot {tag}: committed UNVERIFIED (divergence "
                    "detected or unresolved at commit time) — "
                    "latest_valid() will prefer older verified entries")
        man = self.manifest()
        man["entries"] = [e for e in man.get("entries", [])
                          if e["tag"] != tag] + [entry]
        man["entries"].sort(key=lambda e: e["step"])
        pruned = man["entries"][:-self.keep]
        man["entries"] = man["entries"][-self.keep:]
        man_path = os.path.join(self.dir, MANIFEST)
        chaos = get_chaos()

        def _commit():
            if chaos is not None:
                chaos.maybe_raise("snapshot_io_error", "snapshot.commit")
            fsync_write_json(man_path, man, indent=2)

        retry_call(_commit, site="snapshot.commit", policy=_COMMIT_RETRY)
        for old in pruned:
            shutil.rmtree(os.path.join(self.dir, old["tag"]),
                          ignore_errors=True)
        self.stats["snapshots"] += 1
        self.stats["bytes"] = total
        self.stats["write_ms"] = (time.perf_counter() - t0) * 1e3

    # -- read path ------------------------------------------------------
    def manifest(self) -> dict:
        p = os.path.join(self.dir, MANIFEST)
        if not os.path.exists(p):
            return {"entries": []}
        try:
            with open(p) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            # the manifest itself is written atomically, so a parse failure
            # means external damage; treat as empty rather than crashing
            logger.warning(f"unreadable snapshot manifest {p}; ignoring")
            return {"entries": []}

    def _entry_valid(self, entry: dict) -> bool:
        d = os.path.join(self.dir, entry["tag"])
        for shard in entry.get("shards", []):
            p = os.path.join(d, shard["file"])
            if not os.path.exists(p) or _sha256(p) != shard["sha256"]:
                return False
        return True

    def latest_valid(self, *, prefer_verified: bool = True,
                     max_step: Optional[int] = None) -> Optional[dict]:
        """Newest manifest entry whose shards all exist and hash clean.

        Two passes when the manifest carries integrity stamps: first the
        newest entry that is BOTH checksum-clean and stamped
        ``verified`` (its in-HBM source had a clean cross-rank fingerprint
        — checksums only prove the *write* landed intact, not that the
        state written was worth keeping), then — only if no verified entry
        survives — any checksum-clean entry, so restore still works for
        manifests written before the integrity tier existed. ``max_step``
        (the rollback-on-corruption path passes the last known-clean
        fingerprint step) additionally excludes entries taken after the
        corruption window opened."""
        entries = [e for e in reversed(self.manifest().get("entries", []))
                   if max_step is None or e.get("step", 0) <= max_step]
        if prefer_verified:
            for entry in entries:
                if not entry.get("integrity", {}).get("verified", False):
                    continue
                if self._entry_valid(entry):
                    return entry
                logger.warning(
                    f"snapshot {entry['tag']} fails checksum validation "
                    "(torn write?) — falling back to the previous entry")
        for entry in entries:
            if self._entry_valid(entry):
                if (prefer_verified
                        and entry.get("integrity", {}).get("verified")
                        is False):
                    logger.warning(
                        f"snapshot {entry['tag']} restores UNVERIFIED "
                        "state (no verified entry survives) — treat the "
                        "resumed run as suspect")
                return entry
            logger.warning(
                f"snapshot {entry['tag']} fails checksum validation "
                "(torn write?) — falling back to the previous entry")
        return None

    def load(self, entry: Optional[dict] = None) -> Tuple[Dict[str, np.ndarray], dict]:
        """Read one snapshot into ``{keystr: np.ndarray}`` (host)."""
        if entry is None:
            entry = self.latest_valid()
        if entry is None:
            raise SnapshotError(f"no valid snapshot in {self.dir}")
        out: Dict[str, np.ndarray] = {}
        d = os.path.join(self.dir, entry["tag"])
        for shard in entry["shards"]:
            with open(os.path.join(d, shard["file"]), "rb") as f:
                blob = f.read()
            for leaf in shard["leaves"]:
                import jax.numpy as jnp  # ml_dtypes-aware dtype resolution
                dt = np.dtype(jnp.dtype(leaf["dtype"]))
                raw = blob[leaf["offset"]:leaf["offset"] + leaf["size"]]
                out[leaf["key"]] = np.frombuffer(raw, dtype=dt).reshape(
                    leaf["shape"])
        return out, entry

    def restore_tree(self, template: Any, entry: Optional[dict] = None
                     ) -> Tuple[Any, dict]:
        """Rebuild a pytree shaped like ``template`` from a snapshot. Arrays
        are logical-global host copies, so the caller can ``device_put`` them
        onto ANY sharding — restore onto a different world count is the same
        code path as same-world restore."""
        flat_keys, entry = self.load(entry)
        leaves, treedef = [], None
        flat, treedef = jax.tree_util.tree_flatten_with_path(template)
        for kp, leaf in flat:
            key = _keystr(kp)
            if key not in flat_keys:
                raise SnapshotError(
                    f"snapshot {entry['tag']} has no leaf {key!r} — the "
                    "training state structure changed since it was taken")
            arr = flat_keys[key]
            if tuple(arr.shape) != tuple(np.shape(leaf)):
                raise SnapshotError(
                    f"snapshot leaf {key!r} has shape {arr.shape}, "
                    f"engine expects {np.shape(leaf)}")
            leaves.append(arr)
        return jax.tree_util.tree_unflatten(treedef, leaves), entry
