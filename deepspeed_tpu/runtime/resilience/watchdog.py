"""Step watchdog: turn a hung collective into a restartable failure.

On TPU pods the dominant fleet failure is not a crash but a *wedge*: one
host stops making progress and every collective the others issue blocks
forever — no exception, no exit code, nothing for a supervisor to act on.
The reference stack leans on NCCL's ``TORCH_NCCL_HEARTBEAT_TIMEOUT_SEC`` /
flight-recorder machinery for this; XLA has no equivalent surface, so the
detection must live in the runtime.

:class:`StepWatchdog` is a monitor thread armed around each engine step:

- ``arm(step)`` sets a deadline derived from a **rolling median** of recent
  step times (``factor`` × median, clamped to ``[floor_s, cap_s]``). Before
  any history exists the deadline is ``cap_s`` — the first step legitimately
  includes XLA compilation.
- ``disarm()`` clears the deadline and feeds the observed step time into
  the history.
- on expiry the watchdog dumps **all-thread stacks** to
  ``<dump_dir>/hangdump-<rank>.txt`` (via :mod:`faulthandler`, so even
  C-blocked threads show their Python frames) and terminates the process
  with :data:`WATCHDOG_EXIT_CODE` via ``os._exit`` — a hung collective
  cannot be unwound with an exception, and the *supervisor* (launcher
  ``_supervise``) is the layer that knows how to restart. Tests override
  ``on_expire`` to observe the firing without dying.

This module is deliberately stdlib-only (no jax import) so the launcher and
standalone drill scripts can load it without touching an accelerator
backend.
"""

import faulthandler
import os
import statistics
import threading
import time
from collections import deque
from typing import Callable, Optional

try:
    from ...utils.logging import logger
except ImportError:  # loaded standalone (file-path import in drill scripts)
    import logging

    logger = logging.getLogger("deepspeed_tpu.watchdog")

# Distinctive exit code the launcher's restart policy maps to the
# "watchdog-hang" class (deliberately outside the 1/2/126-165 shell range).
# Mirrored in launcher/launch.py: the launcher must classify this without
# importing the resilience tier.
WATCHDOG_EXIT_CODE = 83


def hangdump_path(dump_dir: str, rank: int) -> str:
    return os.path.join(dump_dir, f"hangdump-{rank}.txt")


def write_hangdump(dump_dir: str, rank: int, step: Optional[int],
                   deadline_s: Optional[float]) -> str:
    """Dump all-thread stacks to ``hangdump-<rank>.txt`` and return the path.

    Append mode: a restart loop that wedges repeatedly accumulates evidence
    instead of overwriting the first (often most informative) dump."""
    os.makedirs(dump_dir, exist_ok=True)
    path = hangdump_path(dump_dir, rank)
    with open(path, "a") as f:
        f.write(f"==== watchdog hangdump rank={rank} pid={os.getpid()} "
                f"step={step} deadline_s={deadline_s} "
                f"wall={time.time():.3f} ====\n")
        faulthandler.dump_traceback(file=f, all_threads=True)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    return path


class StepWatchdog:
    """Deadline monitor armed around each engine step.

    ``on_expire(step)`` replaces the default kill action when set (tests,
    custom supervisors); the default writes the hangdump and exits the
    process with ``exit_code``. ``pre_dump`` (settable after construction)
    runs FIRST on expiry regardless of ``on_expire`` — the telemetry tier's
    flight recorder hooks it so the exit-83 post-mortem includes the last N
    steps' span timeline (which phase hung), not just thread stacks; it is
    exception-guarded so a failing dump can never mask the kill.
    """

    def __init__(self, dump_dir: str, *, factor: float = 8.0,
                 floor_s: float = 30.0, cap_s: float = 600.0,
                 window: int = 32, rank: int = 0,
                 on_expire: Optional[Callable[[Optional[int]], None]] = None,
                 exit_code: int = WATCHDOG_EXIT_CODE):
        if cap_s < floor_s:
            raise ValueError(f"watchdog cap_s ({cap_s}) < floor_s ({floor_s})")
        self.dump_dir = dump_dir
        self.factor = float(factor)
        self.floor_s = float(floor_s)
        self.cap_s = float(cap_s)
        self.rank = int(rank)
        self.on_expire = on_expire
        self.pre_dump: Optional[Callable[[], None]] = None
        self.exit_code = int(exit_code)
        self.fired = False
        self.fired_step: Optional[int] = None
        self._times: "deque[float]" = deque(maxlen=max(1, int(window)))
        self._cond = threading.Condition()
        self._deadline: Optional[float] = None  # monotonic, None = disarmed
        self._armed_at: Optional[float] = None
        self._armed_deadline_s: Optional[float] = None
        self._step: Optional[int] = None
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="dstpu-step-watchdog")
        self._thread.start()

    # -- deadline policy -------------------------------------------------
    def deadline_s(self) -> float:
        """Current per-step deadline: ``factor`` × rolling median, clamped to
        ``[floor_s, cap_s]``; ``cap_s`` while no history exists (compile)."""
        with self._cond:
            times = list(self._times)
        if not times:
            return self.cap_s
        med = statistics.median(times)
        return min(self.cap_s, max(self.floor_s, self.factor * med))

    # -- arm/disarm (the per-step hot path: one lock, no syscalls) -------
    def arm(self, step: Optional[int] = None) -> None:
        d = self.deadline_s()
        with self._cond:
            self._step = step
            self._armed_at = time.monotonic()
            self._armed_deadline_s = d
            self._deadline = self._armed_at + d
            self._cond.notify_all()

    def disarm(self, record: bool = True) -> Optional[float]:
        """Clear the deadline; with ``record`` feed the observed step time
        into the rolling history (pass ``record=False`` around known-slow
        non-step work like rollbacks and drains). Returns the observed
        step time, if armed."""
        with self._cond:
            dt = None
            if self._armed_at is not None:
                dt = time.monotonic() - self._armed_at
                if record:
                    self._times.append(dt)
            self._armed_at = None
            self._armed_deadline_s = None
            self._deadline = None
            self._step = None
            self._cond.notify_all()
            return dt

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=5.0)

    # -- monitor thread --------------------------------------------------
    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._stop and self._deadline is None:
                    self._cond.wait()
                if self._stop:
                    return
                remaining = self._deadline - time.monotonic()
                if remaining > 0:
                    self._cond.wait(remaining)
                    continue
                step = self._step
                deadline_s = self._armed_deadline_s
                self._deadline = None
                self._armed_at = None
                self.fired = True
                self.fired_step = step
            self._fire(step, deadline_s)
            if self.on_expire is None:
                return  # unreachable after os._exit; keeps tests honest

    def _fire(self, step: Optional[int], deadline_s: Optional[float]) -> None:
        if self.pre_dump is not None:
            try:
                self.pre_dump()  # flight record first: richest evidence
            except Exception as e:
                logger.error(f"watchdog: pre_dump failed ({e}); proceeding")
        try:
            path = write_hangdump(self.dump_dir, self.rank, step, deadline_s)
            logger.error(
                f"watchdog: step {step} exceeded its {deadline_s:.1f}s "
                f"deadline — all-thread stacks dumped to {path}; "
                f"{'notifying on_expire' if self.on_expire else f'exiting with code {self.exit_code} for the supervisor to restart'}")
        except Exception as e:  # the dump must never mask the kill
            logger.error(f"watchdog: hangdump failed ({e}); proceeding")
        if self.on_expire is not None:
            self.on_expire(step)
            return
        # A hung collective holds locks and C frames no exception can unwind;
        # os._exit skips atexit/finalizers by design — the snapshot tier's
        # atomic manifest commit makes that safe (a torn write is skipped).
        os._exit(self.exit_code)
