"""Deterministic fault injection: the test harness the subsystem is sworn to.

A resilience layer that is only exercised by real failures is untested by
definition. This module turns each failure mode into a scheduled, repeatable
event so the suite can prove end-to-end recovery:

- ``nan_loss_at_steps`` — the step's observed loss becomes NaN (a streak of
  N consecutive steps trips the sentinel deterministically);
- ``grad_spike_at_steps`` — the observed grad norm is multiplied by
  ``spike_magnitude``;
- ``preempt_at_step`` — the preemption watcher's flag is raised as if
  SIGTERM had arrived;
- ``torn_write_at_steps`` — the snapshot taken at that step has its newest
  shard corrupted AFTER checksumming (restore must detect and skip it);
- ``crash_before_commit_at_steps`` — the snapshot writer raises
  :class:`InjectedCrash` after the data directory lands but before the
  manifest commit (restore must resolve the previous tag);
- ``hang_at_step`` — the step never completes (the post-step hook spins
  until released), so the armed step watchdog must fire: hangdump +
  distinctive exit code + supervised restart;
- ``slow_rank`` — the named rank sleeps ``slow_step_s`` every step (a
  steady straggler the heartbeat table must call out);
- ``heartbeat_loss_at_steps`` — the host's beacon write is suppressed at
  those steps (peers must derive a dead-host verdict once the beacon ages
  past the threshold);
- ``sdc_transient_at_steps`` / ``sdc_sticky_from_step`` — a seeded bit
  flip in ``sdc_rank``'s params (one-shot at the listed steps, or on EVERY
  step from the sticky threshold — a broken host stays broken); the
  integrity tier's cross-rank fingerprints must detect it, the shadow
  replay must call transient vs sticky, and the supervisor must quarantine
  (chaos classes ``sdc_bitflip_transient`` / ``sdc_bitflip_sticky``).

Loss/grad injections rewrite the *observed* metrics fed to the sentinel,
not the device state — the rollback that follows is the real code path
(restore last-good snapshot, continue), executed on healthy arrays so the
test can assert training actually continues.

Each scheduled injection fires ONCE: a rollback rewinds the step counter
past an already-fired step, and a transient fault that re-fired on every
replay would turn the recovery test into an infinite loop. The ``fired``
audit trail records what actually happened.
"""

from dataclasses import dataclass, field
from typing import Optional, Set, Tuple


class InjectedCrash(RuntimeError):
    """A scheduled crash-before-commit (never raised outside fault plans)."""


def _steps(v) -> Tuple[int, ...]:
    if v is None:
        return ()
    if isinstance(v, int):
        return (v,)
    return tuple(int(s) for s in v)


@dataclass
class FaultPlan:
    nan_loss_at_steps: Tuple[int, ...] = ()
    grad_spike_at_steps: Tuple[int, ...] = ()
    spike_magnitude: float = 1e6
    preempt_at_step: Optional[int] = None
    torn_write_at_steps: Tuple[int, ...] = ()
    crash_before_commit_at_steps: Tuple[int, ...] = ()
    hang_at_step: Optional[int] = None
    slow_rank: Optional[int] = None
    slow_step_s: float = 0.25
    heartbeat_loss_at_steps: Tuple[int, ...] = ()
    sdc_transient_at_steps: Tuple[int, ...] = ()
    sdc_sticky_from_step: Optional[int] = None
    sdc_rank: int = -1
    sdc_bit: int = 17

    fired: list = field(default_factory=list)  # (step, kind) audit trail
    _spent: Set[Tuple[int, str]] = field(default_factory=set)

    @classmethod
    def from_config(cls, cfg) -> "FaultPlan":
        """Build from a ``resilience.faults`` config block (or any object
        with the same attribute names)."""
        return cls(
            nan_loss_at_steps=_steps(getattr(cfg, "nan_loss_at_steps", ())),
            grad_spike_at_steps=_steps(getattr(cfg, "grad_spike_at_steps", ())),
            spike_magnitude=float(getattr(cfg, "spike_magnitude", 1e6)),
            preempt_at_step=getattr(cfg, "preempt_at_step", None),
            torn_write_at_steps=_steps(getattr(cfg, "torn_write_at_steps", ())),
            crash_before_commit_at_steps=_steps(
                getattr(cfg, "crash_before_commit_at_steps", ())),
            hang_at_step=getattr(cfg, "hang_at_step", None),
            slow_rank=getattr(cfg, "slow_rank", None),
            slow_step_s=float(getattr(cfg, "slow_step_s", 0.25)),
            heartbeat_loss_at_steps=_steps(
                getattr(cfg, "heartbeat_loss_at_steps", ())),
            sdc_transient_at_steps=_steps(
                getattr(cfg, "sdc_transient_at_steps", ())),
            sdc_sticky_from_step=getattr(cfg, "sdc_sticky_from_step", None),
            sdc_rank=int(getattr(cfg, "sdc_rank", -1)),
            sdc_bit=int(getattr(cfg, "sdc_bit", 17)),
        )

    def _fire(self, step: int, kind: str, scheduled) -> bool:
        if step not in _steps(scheduled) or (step, kind) in self._spent:
            return False
        self._spent.add((step, kind))
        self.fired.append((step, kind))
        return True

    # -- metric injections (consumed by ResilienceManager.post_step) -----
    def observe_loss(self, step: int, loss: float) -> float:
        if self._fire(step, "nan_loss", self.nan_loss_at_steps):
            return float("nan")
        return loss

    def observe_grad_norm(self, step: int, grad_norm: float) -> float:
        if self._fire(step, "grad_spike", self.grad_spike_at_steps):
            return float(grad_norm) * self.spike_magnitude
        return grad_norm

    def preempt_now(self, step: int) -> bool:
        return self._fire(step, "preempt", self.preempt_at_step)

    # -- fleet injections (consumed by ResilienceManager.post_step) ------
    def hang_now(self, step: int) -> bool:
        """One-shot: this step wedges (the manager spins until released or
        the watchdog kills the process)."""
        return self._fire(step, "hang", self.hang_at_step)

    def slow_now(self, step: int, rank: int) -> float:
        """Per-step straggler sleep for ``slow_rank`` (seconds; 0 elsewhere).
        Deliberately NOT one-shot — a straggler is a *steady* condition the
        heartbeat median must surface; only the first firing is audited."""
        if self.slow_rank is None or int(rank) != int(self.slow_rank):
            return 0.0
        if ("slow", "slow") not in self._spent:
            self._spent.add(("slow", "slow"))
            self.fired.append((step, "slow"))
        return float(self.slow_step_s)

    def _sdc_rank_match(self, rank: int) -> bool:
        return self.sdc_rank < 0 or int(rank) == int(self.sdc_rank)

    def sdc_transient_now(self, step: int, rank: int) -> bool:
        """One-shot bit flip in this rank's post-step state (chaos class
        ``sdc_bitflip_transient``): the hardware glitched once; the flipped
        bit persists in params until a rollback heals it."""
        return self._sdc_rank_match(rank) and self._fire(
            step, "sdc_bitflip_transient", self.sdc_transient_at_steps)

    def sdc_sticky_now(self, step: int, rank: int) -> bool:
        """Sticky-host SDC (chaos class ``sdc_bitflip_sticky``): from the
        scheduled step onward EVERY step on ``sdc_rank`` computes a flipped
        bit. Deliberately NOT one-shot — a broken ALU stays broken and a
        shadow replay on the same host must reproduce the corruption (the
        sticky verdict); only the first firing is audited."""
        if (self.sdc_sticky_from_step is None
                or not self._sdc_rank_match(rank)
                or int(step) < int(self.sdc_sticky_from_step)):
            return False
        if ("sdc_sticky", "sdc_sticky") not in self._spent:
            self._spent.add(("sdc_sticky", "sdc_sticky"))
            self.fired.append((step, "sdc_bitflip_sticky"))
        return True

    def heartbeat_lost(self, step: int) -> bool:
        """One-shot per scheduled step: suppress this step's beacon write."""
        return self._fire(step, "heartbeat_loss", self.heartbeat_loss_at_steps)

    # -- snapshot write hook (SnapshotManager.fault_hook) ----------------
    def snapshot_hook(self, stage: str, step: int) -> Optional[str]:
        if stage == "post_data" and self._fire(step, "torn_write",
                                               self.torn_write_at_steps):
            return "torn"
        if stage == "pre_manifest" and self._fire(
                step, "crash_before_commit", self.crash_before_commit_at_steps):
            return "crash"
        return None
