"""Preemption watcher: SIGTERM / maintenance-event → drain → final snapshot.

Preemptible TPU capacity announces eviction ahead of time — Cloud delivers
SIGTERM to the workload, and TPU maintenance events surface through the
metadata server (operationally often relayed as a touched sentinel file or
an env-named flag). Either way the job gets a grace window; spending it on
one more snapshot turns an eviction from "lose everything since the last
checkpoint" into "lose nothing".

The watcher only *records* the request (signal handlers must stay tiny and
async-signal-safe); the engine's post-step hook notices it at the next step
boundary — a natural drain point, since the in-flight compiled step has then
retired — and the ResilienceManager forces a synchronous final snapshot.

Signal installation reuses the launcher's plumbing
(:func:`deepspeed_tpu.launcher.launch.install_signal_handlers`) with
``chain=True``, so a supervising launcher's own SIGTERM forwarding keeps
working underneath this watcher.
"""

import os
import signal as _signal
import time
from typing import Callable, Iterable, Optional

from ...utils.logging import logger

# operational escape hatch: if this env names a path and the path exists,
# the watcher treats it as a maintenance notice (k8s preStop hooks and TPU
# maintenance relays can `touch` it without knowing anything about us)
PREEMPT_FILE_ENV = "DSTPU_PREEMPT_FILE"


def _resolve_signals(names: Iterable) -> tuple:
    out = []
    for n in names:
        if isinstance(n, int):
            out.append(n)
        else:
            sig = getattr(_signal, str(n).upper(), None)
            if sig is None:
                raise ValueError(f"unknown signal name {n!r}")
            out.append(sig)
    return tuple(out)


class PreemptionWatcher:
    """Flag-carrier between the grace-window notice and the step loop.

    ``probes`` are zero-arg callables polled by :meth:`requested`; any
    returning truthy raises the flag (pluggable: scheduler APIs, metadata
    servers). A ``probe_file`` (or the ``DSTPU_PREEMPT_FILE`` env) adds the
    touched-file probe.
    """

    def __init__(self, signals: Iterable = ("SIGTERM",),
                 probe_file: Optional[str] = None,
                 probes: Iterable[Callable[[], bool]] = (),
                 install: bool = True):
        self._flag = False
        self.reason: Optional[str] = None
        self.requested_at: Optional[float] = None
        self.probes = list(probes)
        probe_file = probe_file or os.environ.get(PREEMPT_FILE_ENV)
        if probe_file:
            self.probes.append(
                lambda p=probe_file: os.path.exists(p) and f"probe file {p}")
        self.installed_signals = ()
        if install:
            from ...launcher.launch import install_signal_handlers

            sigs = _resolve_signals(signals)
            installed = install_signal_handlers(self._on_signal, signals=sigs,
                                                chain=True)
            self.installed_signals = tuple(installed)

    # handler body stays minimal: set flags, no I/O, no allocation-heavy work
    def _on_signal(self, signum, frame):
        self._flag = True
        if self.reason is None:
            self.reason = f"signal {signum}"
            self.requested_at = time.time()

    def request(self, reason: str = "programmatic") -> None:
        """Raise the flag from code (fault injection, scheduler callbacks)."""
        self._flag = True
        if self.reason is None:
            self.reason = reason
            self.requested_at = time.time()

    def requested(self) -> bool:
        """Poll: signal already seen, or any probe reporting eviction."""
        if self._flag:
            return True
        for probe in self.probes:
            try:
                hit = probe()
            except Exception as e:  # a broken probe must not kill the step loop
                logger.warning(f"preemption probe raised {e!r}; ignoring")
                continue
            if hit:
                self.request(hit if isinstance(hit, str) else "probe")
                return True
        return False
