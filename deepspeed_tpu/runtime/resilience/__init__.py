"""Resilience subsystem: survive preemption, divergence, and torn writes.

PRs 1-3 made the stack fast; this layer makes a run *outlive* the fleet it
runs on (the reference ships elasticity + pluggable checkpoint engines for
the same reason — production training happens on preemptible capacity):

- :mod:`snapshot` — double-buffered async device→host snapshots on a
  background writer thread; checksummed shards, write-temp + atomic-rename
  commit, a JSON manifest of valid tags so torn writes are skipped.
- :mod:`sentinel` — in-loop health monitor: NaN/inf-loss streaks and
  grad-norm spikes trip a configurable policy (rollback to last-good,
  optionally dropping the LR).
- :mod:`preempt` — SIGTERM / maintenance-event watcher reusing the
  launcher's signal plumbing; drains in-flight steps and forces a final
  snapshot.
- :mod:`faults` — deterministic fault injection for tests (NaN at step N,
  simulated preemption, torn write, crash-before-commit).
- :mod:`chaos` — the full-stack generalization: seeded, one-shot-audited
  fault schedules across transport (object-store errors, torn beacons,
  plan-cache / snapshot-commit I/O), serving (replica kill, KV
  exhaustion, slow prefill, dropped token delivery), and control (stale
  health rows, flapping straggler verdicts), consulted by injection sites
  through a process-global that is None — and cost-free — by default.
- :mod:`supervisor` — restore-on-restart: resolve the latest *valid*
  manifest entry and (with elasticity enabled) the world to restart at, so
  a resume onto a different chip count reshards correctly.
- :mod:`watchdog` — per-step deadline monitor (rolling-median-derived):
  a hung collective becomes hangdump + distinctive exit code + supervised
  restart instead of an eternal silent stall.
- :mod:`heartbeat` — per-host beacons in a shared dir; readers derive
  dead-host and straggler verdicts (step-time vs fleet median).
- :mod:`integrity` — silent-corruption tier: cadenced cross-rank
  fingerprints of DP-replicated state (bitwise-equal by construction, so
  any divergence is corruption), shadow-step replay to call transient vs
  sticky SDC, verified snapshot stamping, and quarantine verdicts for the
  control supervisor's ``integrity`` rule.

Everything is gated behind the ``resilience:`` config block; with it off
(the default) no hook exists and engine stepping is bit-identical.
"""

from .chaos import (FAULT_CLASSES, ChaosEvent, ChaosInjectedError,
                    ChaosSchedule, chaos_active, configure_chaos, get_chaos)
from .faults import FaultPlan, InjectedCrash
from .heartbeat import (FileHeartbeatTransport, HealthTable, HeartbeatWriter,
                        HostHealth, ObjectStoreHeartbeatTransport)
from .integrity import (FingerprintStore, IntegrityMonitor, fingerprint_hex,
                        flip_bit, make_fingerprint_fn)
from .preempt import PreemptionWatcher
from .sentinel import Sentinel, SentinelEvent, SentinelHalt
from .snapshot import SnapshotManager
from .supervisor import PREEMPT_EXIT_CODE, ResilienceManager, resolve_restore
from .watchdog import WATCHDOG_EXIT_CODE, StepWatchdog

__all__ = ["SnapshotManager", "Sentinel", "SentinelEvent", "SentinelHalt",
           "PreemptionWatcher", "FaultPlan", "InjectedCrash",
           "ResilienceManager", "resolve_restore", "StepWatchdog",
           "WATCHDOG_EXIT_CODE", "PREEMPT_EXIT_CODE", "HeartbeatWriter",
           "HealthTable", "HostHealth", "FileHeartbeatTransport",
           "ObjectStoreHeartbeatTransport",
           "ChaosSchedule", "ChaosEvent", "ChaosInjectedError",
           "FAULT_CLASSES", "configure_chaos", "get_chaos", "chaos_active",
           "IntegrityMonitor", "FingerprintStore", "make_fingerprint_fn",
           "fingerprint_hex", "flip_bit"]
