"""LR schedules (reference ``runtime/lr_schedules.py``).

Each builder returns ``step -> lr`` as a jnp-traceable callable so schedules
can live inside the compiled train step; the reference's per-step Python
scheduler ``step()`` loop collapses into a pure function of the step counter.

Reference classes: ``LRRangeTest:273``, ``OneCycle:371``, ``WarmupLR:633``,
``WarmupDecayLR:723``, ``WarmupCosineLR:774``.
"""

import math
from typing import Callable, Optional

import jax.numpy as jnp

Schedule = Callable[[jnp.ndarray], jnp.ndarray]

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
WARMUP_COSINE_LR = "WarmupCosineLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR, WARMUP_COSINE_LR]


def lr_range_test(lr_range_test_min_lr: float = 1e-3,
                  lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False, **_) -> Schedule:
    def sched(step):
        s = jnp.maximum(step.astype(jnp.float32) - 1, 0.0)
        interval = s / lr_range_test_step_size
        if lr_range_test_staircase:
            interval = jnp.floor(interval)
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)

    return sched


def one_cycle(cycle_min_lr: float = 1e-3, cycle_max_lr: float = 1e-2,
              cycle_first_step_size: int = 2000, cycle_second_step_size: Optional[int] = None,
              decay_step_size: int = 0, decay_lr_rate: float = 0.0, **_) -> Schedule:
    second = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
    total_cycle = cycle_first_step_size + second

    def sched(step):
        s = jnp.maximum(step.astype(jnp.float32) - 1, 0.0)
        up = jnp.clip(s / cycle_first_step_size, 0.0, 1.0)
        down = jnp.clip((s - cycle_first_step_size) / second, 0.0, 1.0)
        in_cycle_lr = jnp.where(s <= cycle_first_step_size,
                                cycle_min_lr + (cycle_max_lr - cycle_min_lr) * up,
                                cycle_max_lr - (cycle_max_lr - cycle_min_lr) * down)
        if decay_step_size > 0:
            decay_steps = jnp.maximum(s - total_cycle, 0.0) / decay_step_size
            decayed = cycle_min_lr / (1.0 + decay_steps * decay_lr_rate)
            return jnp.where(s > total_cycle, decayed, in_cycle_lr)
        return in_cycle_lr

    return sched


def warmup_lr(warmup_min_lr: float = 0.0, warmup_max_lr: float = 1e-3,
              warmup_num_steps: int = 1000, warmup_type: str = "log", **_) -> Schedule:
    warmup_num_steps = max(2, warmup_num_steps)

    def sched(step):
        s = jnp.clip(step.astype(jnp.float32), 1.0, float(warmup_num_steps))
        if warmup_type == "log":
            gamma = jnp.log(s) / math.log(warmup_num_steps)
        else:
            gamma = s / warmup_num_steps
        lr = warmup_min_lr + (warmup_max_lr - warmup_min_lr) * gamma
        return jnp.where(step >= warmup_num_steps, warmup_max_lr, lr)

    return sched


def warmup_decay_lr(total_num_steps: int, warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 1e-3, warmup_num_steps: int = 1000,
                    warmup_type: str = "log", **_) -> Schedule:
    base = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)
    warmup_num_steps_ = max(2, warmup_num_steps)

    def sched(step):
        s = step.astype(jnp.float32)
        decay = jnp.clip((total_num_steps - s) / max(1.0, total_num_steps - warmup_num_steps_),
                         0.0, 1.0)
        return jnp.where(s < warmup_num_steps_, base(step), warmup_max_lr * decay)

    return sched


def warmup_cosine_lr(total_num_steps: int, warmup_min_ratio: float = 0.01,
                     warmup_num_steps: int = 1000, cos_min_ratio: float = 0.0001,
                     warmup_type: str = "linear", base_lr: float = 1.0, **_) -> Schedule:
    warmup_num_steps_ = max(2, warmup_num_steps)

    def sched(step):
        s = jnp.clip(step.astype(jnp.float32), 1.0, None)
        if warmup_type == "log":
            gamma = jnp.log(jnp.clip(s, 1.0, warmup_num_steps_)) / math.log(warmup_num_steps_)
        else:
            gamma = jnp.clip(s / warmup_num_steps_, 0.0, 1.0)
        warm = warmup_min_ratio + (1.0 - warmup_min_ratio) * gamma
        progress = jnp.clip((s - warmup_num_steps_) / max(1.0, total_num_steps - warmup_num_steps_),
                            0.0, 1.0)
        cos_ratio = cos_min_ratio + (1.0 - cos_min_ratio) * 0.5 * (1.0 + jnp.cos(jnp.pi * progress))
        return base_lr * jnp.where(s < warmup_num_steps_, warm, cos_ratio)

    return sched


def build_lr_schedule(sched_type: Optional[str], params: dict, base_lr: float = 1e-3) -> Schedule:
    """Config ``scheduler`` section -> schedule callable. ``None`` -> constant
    base_lr (the optimizer's own lr)."""
    if sched_type is None:
        return lambda step: jnp.asarray(base_lr, jnp.float32)
    if sched_type == LR_RANGE_TEST:
        return lr_range_test(**params)
    if sched_type == ONE_CYCLE:
        return one_cycle(**params)
    if sched_type == WARMUP_LR:
        return warmup_lr(**params)
    if sched_type == WARMUP_DECAY_LR:
        return warmup_decay_lr(**params)
    if sched_type == WARMUP_COSINE_LR:
        return warmup_cosine_lr(**params)
    raise ValueError(f"Unknown scheduler type {sched_type}; valid: {VALID_LR_SCHEDULES}")
