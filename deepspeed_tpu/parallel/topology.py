"""Device mesh topology: the TPU-native replacement for process groups.

The reference builds arbitrary rank-subset process groups
(``deepspeed/utils/groups.py``, ``runtime/pipe/topology.py:12``
``ProcessTopology``). On TPU, groups are *named mesh axes* of a
``jax.sharding.Mesh``; a collective "over the data-parallel group" is a
collective over the ``dp`` axis (or the ``('dp_outer','ep')`` axis tuple when
expert parallelism splits it).

Axis order is chosen for ICI locality: ``pp`` outermost (cross-slice / DCN
friendly), then data parallel, then sequence parallel, with ``tp`` innermost
(fastest-varying → physically adjacent chips).
"""

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# canonical axis names
PP_AXIS = "pp"
DP_OUTER_AXIS = "dp_outer"
EP_AXIS = "ep"
SP_AXIS = "sp"
TP_AXIS = "tp"


@dataclasses.dataclass(frozen=True)
class TopologySpec:
    """Logical parallelism degrees. dp is inferred from the device count."""
    pp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1
    dp: Optional[int] = None  # None => infer

    def resolve_dp(self, n_devices: int) -> int:
        denom = self.pp * self.sp * self.tp
        if n_devices % denom != 0:
            raise ValueError(f"world size {n_devices} not divisible by pp*sp*tp={denom}")
        dp = n_devices // denom
        if self.dp is not None and self.dp != dp:
            raise ValueError(f"data_parallel_size={self.dp} inconsistent with "
                             f"world={n_devices}, pp*sp*tp={denom}")
        if dp % self.ep != 0:
            raise ValueError(f"expert parallel size {self.ep} must divide dp size {dp}")
        return dp


class Topology:
    """A resolved mesh topology.

    Mesh axes: ``(pp, dp_outer, ep, sp, tp)`` — always all five, size-1 axes
    included, so sharding rules can be written once. The data-parallel "group"
    is the axis pair ``(dp_outer, ep)``.
    """

    def __init__(self, spec: TopologySpec = TopologySpec(),
                 devices: Optional[Sequence[jax.Device]] = None):
        if devices is None:
            devices = jax.devices()
        self.spec = spec
        self.n_devices = len(devices)
        dp = spec.resolve_dp(self.n_devices)
        self.pp_size, self.sp_size, self.tp_size = spec.pp, spec.sp, spec.tp
        self.ep_size = spec.ep
        self.dp_size = dp
        self.dp_outer_size = dp // spec.ep

        shape = (spec.pp, self.dp_outer_size, spec.ep, spec.sp, spec.tp)
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_device_mesh(shape, devices=list(devices))
        except Exception:
            dev_array = np.asarray(list(devices)).reshape(shape)
        self.mesh = Mesh(dev_array,
                         axis_names=(PP_AXIS, DP_OUTER_AXIS, EP_AXIS, SP_AXIS, TP_AXIS))

    # ---- group-like accessors (reference: deepspeed/utils/groups.py) -----
    @property
    def dp_axes(self) -> Tuple[str, ...]:
        return (DP_OUTER_AXIS, EP_AXIS)

    @property
    def fsdp_axes(self) -> Tuple[str, ...]:
        """Axes over which ZeRO shards params/grads/optimizer state.

        Sequence-parallel ranks replicate data-parallel state in the reference
        (Ulysses composes with ZeRO-3 via ``seq_data_parallel_group``,
        ``engine.py:1198``) — so ZeRO shards over dp *and* sp axes to match.
        """
        return (DP_OUTER_AXIS, EP_AXIS, SP_AXIS)

    @property
    def all_axes(self) -> Tuple[str, ...]:
        return (PP_AXIS, DP_OUTER_AXIS, EP_AXIS, SP_AXIS, TP_AXIS)

    def axis_size(self, *names: str) -> int:
        s = 1
        for n in names:
            s *= self.mesh.shape[n]
        return s

    def sharding(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def filter_spec(self, spec: P, shape) -> P:
        """Drop spec entries whose dim doesn't divide the mesh axes — e.g.
        GQA kv-head dims smaller than tp (reference AutoTP replicates such
        weights, ``module_inject/tp_shard.py``)."""
        entries = list(spec) + [None] * (len(shape) - len(spec))

        def ok(i, entry):
            if entry is None:
                return False
            names = entry if isinstance(entry, tuple) else (entry,)
            return shape[i] % self.axis_size(*names) == 0

        return P(*[e if ok(i, e) else None for i, e in enumerate(entries)])  # spec-ok: mechanical surgery: drop axes that do not divide the dim

    def filter_spec_tree(self, spec_tree, tree):
        """``filter_spec`` over a pytree of PartitionSpecs + matching arrays."""
        return jax.tree.map(lambda s, x: self.filter_spec(s, x.shape), spec_tree, tree,
                            is_leaf=lambda x: isinstance(x, P))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())  # spec-ok: replicated() helper, the trivial spec

    def __repr__(self):
        return (f"Topology(pp={self.pp_size}, dp={self.dp_size} (outer={self.dp_outer_size},"
                f" ep={self.ep_size}), sp={self.sp_size}, tp={self.tp_size},"
                f" devices={self.n_devices})")


# Global topology, set by initialize() (reference: groups module globals).
_TOPOLOGY: Optional[Topology] = None


def set_topology(topo: Topology) -> None:
    global _TOPOLOGY
    _TOPOLOGY = topo


def get_topology() -> Topology:
    global _TOPOLOGY
    if _TOPOLOGY is None:
        _TOPOLOGY = Topology()
    return _TOPOLOGY


def reset_topology() -> None:
    global _TOPOLOGY
    _TOPOLOGY = None
