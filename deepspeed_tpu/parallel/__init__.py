from .topology import (DP_OUTER_AXIS, EP_AXIS, PP_AXIS, SP_AXIS, TP_AXIS, Topology,
                       TopologySpec, get_topology, reset_topology, set_topology)
