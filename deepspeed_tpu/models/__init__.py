from .bert import (BertConfig, BertEncoder, BertForMaskedLM,
                   BertForQuestionAnswering, mlm_loss_fn, qa_loss_fn)
from .transformer import (TransformerConfig, TransformerLM, init_params,
                          make_loss_fn, param_specs)

__all__ = ["TransformerConfig", "TransformerLM", "init_params", "make_loss_fn",
           "param_specs", "BertConfig", "BertEncoder", "BertForMaskedLM",
           "BertForQuestionAnswering", "mlm_loss_fn", "qa_loss_fn"]
